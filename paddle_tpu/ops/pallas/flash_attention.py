"""Flash attention (forward + backward) as Pallas TPU kernels.

Reference analog: the reference vendors third_party/flashattn (CUDA) behind
python/paddle/nn/functional/flash_attention.py. TPU-first redesign: an online-softmax
tiled kernel on the MXU — q blocks stream against k/v blocks held in VMEM, softmax state
(m, l) carried in fp32, O(S) memory instead of the O(S^2) probs matrix. Backward follows
the flash-attention-2 recomputation scheme (saved LSE + per-row delta), emitted as two
kernels (dq; dk/dv per q-head with a GQA group-sum outside).

Layout contract: paddle's (batch, seq, num_heads, head_dim); internally (B, H, S, D).
"""
from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = np.float32(-1e30)


def _i32(x):
    return jnp.asarray(x, jnp.int32)


def _cdiv_i32(a, b):
    # explicit int32 lax arithmetic: jnp operator promotion recurses inside the
    # pallas kernel trace under x64 mode on some jax versions
    return jax.lax.div(jax.lax.add(a, _i32(b - 1)), _i32(b))


def _interpret():
    if os.environ.get("PADDLE_TPU_PALLAS_INTERPRET"):
        return True
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:
        return True


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_q, block_k, seq_k, causal_offset=0):
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale          # (Bq, D)
    d = q.shape[-1]

    num_kv = seq_k // block_k
    if causal:
        # only blocks at or before the diagonal contribute
        hi = _cdiv_i32(jax.lax.add(
            jax.lax.mul(jax.lax.add(qi, _i32(1)), _i32(block_q)),
            _i32(causal_offset)), block_k)
        hi = jnp.minimum(hi, _i32(num_kv))
    else:
        hi = num_kv

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(jax.lax.mul(j, _i32(block_k)), block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(jax.lax.mul(j, _i32(block_k)), block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # (Bq, Bk)
        if causal:
            rows = jax.lax.mul(qi, _i32(block_q)) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.mul(j, _i32(block_k)) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows + _i32(causal_offset) >= cols, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(_i32(0), _i32(hi), body, (m0, l0, acc0))

    l_safe = jnp.maximum(l, np.float32(1e-30))
    o_ref[0, 0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # full 2-D store: a scalar-indexed lse_ref[0,0,:,0] store lowers through a
    # strided-store path that infinitely recurses in Mosaic (i64->i32 convert)
    lse_ref[0, 0] = (m + jnp.log(l_safe))[:, None]


def _fwd(q, k, v, scale, causal, block_q, block_k):
    B, Hq, Sq, D = q.shape
    _, Hkv, Sk, _ = k.shape
    rep = Hq // Hkv
    grid = (B, Hq, Sq // block_q)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_k=Sk, causal_offset=Sk - Sq)

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, np.int32(0))),
            pl.BlockSpec((1, 1, Sk, D), lambda b, h, i, rep=rep: (b, jax.lax.div(h, np.int32(rep)), np.int32(0), np.int32(0))),
            pl.BlockSpec((1, 1, Sk, D), lambda b, h, i, rep=rep: (b, jax.lax.div(h, np.int32(rep)), np.int32(0), np.int32(0))),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, np.int32(0))),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (b, h, i, np.int32(0))),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hq, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B, Hq, Sq, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                   scale, causal, block_q, block_k, seq_k, causal_offset=0):
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0][:, 0]                             # (Bq,)
    delta = delta_ref[0, 0][:, 0]                         # (Bq,)
    d = q.shape[-1]

    num_kv = seq_k // block_k
    if causal:
        hi = _cdiv_i32(jax.lax.add(
            jax.lax.mul(jax.lax.add(qi, _i32(1)), _i32(block_q)),
            _i32(causal_offset)), block_k)
        hi = jnp.minimum(hi, _i32(num_kv))
    else:
        hi = num_kv

    def body(j, dq):
        k = k_ref[0, 0, pl.ds(jax.lax.mul(j, _i32(block_k)), block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(jax.lax.mul(j, _i32(block_k)), block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = jax.lax.mul(qi, _i32(block_q)) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.mul(j, _i32(block_k)) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows + _i32(causal_offset) >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])                     # (Bq, Bk)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(_i32(0), _i32(hi), body,
                           jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, block_q, block_k, seq_q,
                    causal_offset=0):
    ki = pl.program_id(2)
    k = k_ref[0, 0].astype(jnp.float32)                   # (Bk, D)
    v = v_ref[0, 0].astype(jnp.float32)
    d = k.shape[-1]

    num_q = seq_q // block_q
    if causal:
        lo = jax.lax.div(
            jnp.maximum(jax.lax.sub(jax.lax.mul(ki, _i32(block_k)),
                                    _i32(causal_offset)), _i32(0)),
            _i32(block_q))
    else:
        lo = 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(jax.lax.mul(i, _i32(block_q)), block_q), :].astype(jnp.float32)
        do = do_ref[0, 0, pl.ds(jax.lax.mul(i, _i32(block_q)), block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(jax.lax.mul(i, _i32(block_q)), block_q), :][:, 0]
        delta = delta_ref[0, 0, pl.ds(jax.lax.mul(i, _i32(block_q)), block_q), :][:, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = jax.lax.mul(i, _i32(block_q)) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.mul(ki, _i32(block_k)) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows + _i32(causal_offset) >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])                     # (Bq, Bk)
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(_i32(lo), _i32(num_q), body, (dk0, dv0))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, res, g):
    q, k, v, out, lse = res
    do = g
    B, Hq, Sq, D = q.shape
    _, Hkv, Sk, _ = k.shape
    rep = Hq // Hkv

    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1,
                    keepdims=True)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_k=Sk,
                          causal_offset=Sk - Sq),
        grid=(B, Hq, Sq // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, np.int32(0))),
            pl.BlockSpec((1, 1, Sk, D), lambda b, h, i, rep=rep: (b, jax.lax.div(h, np.int32(rep)), np.int32(0), np.int32(0))),
            pl.BlockSpec((1, 1, Sk, D), lambda b, h, i, rep=rep: (b, jax.lax.div(h, np.int32(rep)), np.int32(0), np.int32(0))),
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, np.int32(0))),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (b, h, i, np.int32(0))),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (b, h, i, np.int32(0))),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, np.int32(0))),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    # dk/dv computed per q-head, then group-summed over the GQA repeat factor
    dk_rep, dv_rep = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_q=Sq,
                          causal_offset=Sk - Sq),
        grid=(B, Hq, Sk // block_k),
        in_specs=[
            pl.BlockSpec((1, 1, Sq, D), lambda b, h, i: (b, h, np.int32(0), np.int32(0))),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, rep=rep: (b, jax.lax.div(h, np.int32(rep)), i, np.int32(0))),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, rep=rep: (b, jax.lax.div(h, np.int32(rep)), i, np.int32(0))),
            pl.BlockSpec((1, 1, Sq, D), lambda b, h, i: (b, h, np.int32(0), np.int32(0))),
            pl.BlockSpec((1, 1, Sq, 1), lambda b, h, i: (b, h, np.int32(0), np.int32(0))),
            pl.BlockSpec((1, 1, Sq, 1), lambda b, h, i: (b, h, np.int32(0), np.int32(0))),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i: (b, h, i, np.int32(0))),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i: (b, h, i, np.int32(0))),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hq, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((B, Hq, Sk, D), v.dtype),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    if rep > 1:
        dk = dk_rep.reshape(B, Hkv, rep, Sk, D).sum(axis=2).astype(k.dtype)
        dv = dv_rep.reshape(B, Hkv, rep, Sk, D).sum(axis=2).astype(v.dtype)
    else:
        dk, dv = dk_rep, dv_rep
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry (custom VJP over the kernels)
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, block_q, block_k):
    out, _ = _fwd(q, k, v, scale, causal, block_q, block_k)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, res, g):
    return _bwd(scale, causal, block_q, block_k, res, g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention_fwd(q, k, v, causal=False, scale=None,
                        block_q=None, block_k=None):
    """(B, S, H, D) flash attention entry used by F.scaled_dot_product_attention.

    Differentiable (custom VJP); raises ValueError on unsupported shapes so the
    caller can fall back to the math path.
    """
    B, Sq, Hq, D = q.shape
    Sk, Hkv = k.shape[1], k.shape[2]
    # 512x512 measured best on v5e at bench shapes (S=2048, D=128): 0.594 MFU
    # vs 0.458 at 128x128 — bigger q/k tiles amortize the loop and fill the MXU
    if block_q is None:
        block_q = int(os.environ.get("PADDLE_TPU_FLASH_BLOCK_Q", "512"))
    if block_k is None:
        block_k = int(os.environ.get("PADDLE_TPU_FLASH_BLOCK_K", "512"))
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    # shrink to a divisor rather than fail: Sq=1920 should still run flash at
    # block 128 instead of silently degrading to the O(S^2) math path
    while block_q > 16 and Sq % block_q != 0:
        block_q //= 2
    while block_k > 16 and Sk % block_k != 0:
        block_k //= 2
    if Sq % block_q != 0 or Sk % block_k != 0:
        raise ValueError(f"sequence lengths ({Sq},{Sk}) not divisible by "
                         f"blocks ({block_q},{block_k})")
    if Hq % Hkv != 0:
        raise ValueError(f"GQA head counts {Hq}/{Hkv} not divisible")
    if causal and Sq > Sk:
        # rows past Sk attend to nothing: forward would emit zeros and the
        # p=exp(s-lse) trick in the dk/dv kernel would add exp(0)=1 garbage terms
        raise ValueError(f"causal flash attention requires Sq<=Sk, got ({Sq},{Sk})")
    s = np.float32(scale if scale is not None else 1.0 / np.sqrt(D))
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = _flash(qt, kt, vt, s, bool(causal), block_q, block_k)
    return jnp.swapaxes(out, 1, 2)
