"""HeterPS: accelerator-resident hot-embedding cache over the PS tables.

Reference analog: paddle/fluid/framework/fleet/heter_ps/ (PSGPU — a GPU
hashtable that caches hot sparse-feature rows between the trainer and the
parameter-server tables, so most pulls/pushes never leave the device).

TPU-first form: the cache is ONE device array of shape (capacity, dim) —
gathers and scatter-adds are what the hardware does well — with a host-side
id->slot map and an LRU clock. A batch pull

1. splits ids into hits (resident) and misses,
2. fetches miss rows from the PSClient in one RPC,
3. installs them into free/least-recently-used slots with one scatter,
4. returns one device gather over the slots.

Gradients accumulate into a device-side (capacity, dim) buffer via
scatter-add; ``flush()`` ships the accumulated rows to the server in one
push RPC (the reference's pull/push aggregation in heter_comm).
"""
from __future__ import annotations

import numpy as np

__all__ = ["HeterPSCache"]


class HeterPSCache:
    def __init__(self, client, table_name, dim, capacity=4096,
                 dtype="float32"):
        import jax.numpy as jnp

        self._jnp = jnp
        self.client = client
        self.table_name = table_name
        self.dim = int(dim)
        self.capacity = int(capacity)
        self._rows = jnp.zeros((self.capacity, self.dim), dtype)
        self._grad = jnp.zeros((self.capacity, self.dim), jnp.float32)
        self._slot_of = {}        # id -> slot
        self._id_of = {}          # slot -> id
        self._clock = 0
        self._last_used = np.zeros(self.capacity, np.int64)
        self._dirty = set()       # slots with unflushed grads
        self._free = list(range(self.capacity - 1, -1, -1))
        self.stats = {"hits": 0, "misses": 0, "evictions": 0, "flushes": 0}

    # -- slot management ----------------------------------------------------

    def _take_slot(self, pinned=()):
        """A free or LRU-evicted slot; `pinned` slots (the current batch's
        rows, including ones installed a moment ago) are never victims."""
        if self._free:
            return self._free.pop()
        # evict the least-recently-used CLEAN slot; flush first if every
        # evictable slot is dirty (grads must reach the server first)
        order = np.argsort(self._last_used)
        for s in order:
            if int(s) not in self._dirty and int(s) not in pinned:
                self._evict(int(s))
                return int(s)
        self.flush()
        for s in order:
            if int(s) not in pinned:
                self._evict(int(s))
                return int(s)
        raise RuntimeError(
            f"heter_ps cache capacity {self.capacity} is smaller than one "
            "batch's unique id count — raise capacity")

    def _evict(self, slot):
        old = self._id_of.pop(slot, None)
        if old is not None:
            del self._slot_of[old]
            self.stats["evictions"] += 1

    # -- pull/push ----------------------------------------------------------

    def pull(self, ids):
        """Device (n, dim) array of rows for ``ids`` (hits never leave the
        accelerator; misses arrive in one PS RPC)."""
        jnp = self._jnp
        ids = np.asarray(ids, np.int64).ravel()
        uniq = list(dict.fromkeys(int(i) for i in ids))
        missing = [i for i in uniq if i not in self._slot_of]
        if missing:
            self.stats["misses"] += len(missing)
            rows = self.client.pull_sparse(self.table_name, missing)
            pinned = {self._slot_of[i] for i in uniq if i in self._slot_of}
            slots = []
            for i in missing:
                s = self._take_slot(pinned)
                self._slot_of[i] = s
                self._id_of[s] = i
                pinned.add(s)
                slots.append(s)
            self._rows = self._rows.at[jnp.asarray(slots)].set(
                jnp.asarray(np.asarray(rows, np.float32),
                            self._rows.dtype))
        self.stats["hits"] += len(uniq) - len(missing)
        self._clock += 1
        for i in uniq:
            self._last_used[self._slot_of[i]] = self._clock
        gather = jnp.asarray([self._slot_of[int(i)] for i in ids])
        return self._rows[gather]

    def push_grad(self, ids, grads, lr=None):
        """Accumulate grads on-device; rows must be resident (grads come
        from a pull in the same step). ``lr`` (the trainer's current
        scheduled rate) is remembered so an eviction-forced flush applies
        the pending grads at the right rate, not the table default."""
        jnp = self._jnp
        if lr is not None:
            self._pending_lr = float(lr)
        ids = np.asarray(ids, np.int64).ravel()
        slots = []
        for i in ids:
            s = self._slot_of.get(int(i))
            if s is None:
                raise KeyError(
                    f"push_grad for id {int(i)} with no resident row — "
                    "pull() it first (heter_ps keeps grad slots device-side)")
            slots.append(s)
            self._dirty.add(s)
        g = jnp.asarray(np.asarray(grads, np.float32)).reshape(
            len(slots), self.dim)
        self._grad = self._grad.at[jnp.asarray(slots)].add(g)

    def flush(self, lr=None):
        """One push RPC with every accumulated grad; clears the buffer and
        refreshes the affected resident rows from the server."""
        if not self._dirty:
            return 0
        if lr is None:
            lr = getattr(self, "_pending_lr", None)
        jnp = self._jnp
        slots = sorted(self._dirty)
        ids = [self._id_of[s] for s in slots]
        g = np.asarray(self._grad[jnp.asarray(slots)])
        self.client.push_sparse(self.table_name, ids, g, lr=lr)
        self._grad = self._grad.at[jnp.asarray(slots)].set(0.0)
        # server applied the optimizer: re-pull so the cache serves the
        # stepped values
        fresh = self.client.pull_sparse(self.table_name, ids)
        self._rows = self._rows.at[jnp.asarray(slots)].set(
            jnp.asarray(np.asarray(fresh, np.float32), self._rows.dtype))
        n = len(slots)
        self._dirty.clear()
        self.stats["flushes"] += 1
        return n

    def n_resident(self):
        return len(self._slot_of)
