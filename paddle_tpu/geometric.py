"""paddle.geometric: graph message passing + segment reductions.

Reference analog: python/paddle/geometric/ (message_passing/send_recv.py
send_u_recv/send_ue_recv, math segment_{sum,mean,max,min}, sampling) over
dedicated scatter CUDA kernels.

TPU-first: every primitive is a jax segment op (ops.segment_sum et al. lower
to sorted-scatter HLO), so message passing fuses with the surrounding model.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .framework.core import Tensor
from .ops._apply import defop


@defop("geometric.segment_reduce")
def _segment_reduce(data, segment_ids, num_segments=0, pool_type="sum"):
    n = int(num_segments)
    ids = segment_ids.astype(jnp.int32)
    if pool_type == "sum":
        return jax.ops.segment_sum(data, ids, n)
    if pool_type == "mean":
        s = jax.ops.segment_sum(data, ids, n)
        cnt = jax.ops.segment_sum(jnp.ones((data.shape[0],), data.dtype), ids, n)
        return s / jnp.maximum(cnt, 1.0)[(...,) + (None,) * (data.ndim - 1)]
    if pool_type == "max":
        return jax.ops.segment_max(data, ids, n)
    if pool_type == "min":
        return jax.ops.segment_min(data, ids, n)
    raise ValueError(f"unknown pool_type {pool_type!r}")


def _segments_from(ids, count):
    """Static segment count: the caller's `count`, or max(ids)+1 host-computed
    when ids is concrete. Under a trace, XLA needs a compile-time output size —
    raise a clear error asking for `count` instead of crashing on int(tracer)."""
    if count is not None:
        return int(count)
    ids_val = ids.value if isinstance(ids, Tensor) else ids
    if isinstance(ids_val, jax.core.Tracer):
        raise ValueError(
            "segment ops inside a traced/compiled region need a static "
            "segment count: pass count=<num_segments>")
    return int(jnp.max(ids_val)) + 1


def segment_sum(data, segment_ids, count=None, name=None):
    return _segment_reduce(data, segment_ids,
                           num_segments=_segments_from(segment_ids, count),
                           pool_type="sum")


def segment_mean(data, segment_ids, count=None, name=None):
    return _segment_reduce(data, segment_ids,
                           num_segments=_segments_from(segment_ids, count),
                           pool_type="mean")


def segment_max(data, segment_ids, count=None, name=None):
    return _segment_reduce(data, segment_ids,
                           num_segments=_segments_from(segment_ids, count),
                           pool_type="max")


def segment_min(data, segment_ids, count=None, name=None):
    return _segment_reduce(data, segment_ids,
                           num_segments=_segments_from(segment_ids, count),
                           pool_type="min")


@defop("geometric.send_u_recv")
def _send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=0):
    msgs = x[src_index]                      # gather source features
    n = int(out_size) if out_size else x.shape[0]
    ids = dst_index.astype(jnp.int32)
    if reduce_op == "sum":
        return jax.ops.segment_sum(msgs, ids, n)
    if reduce_op == "mean":
        s = jax.ops.segment_sum(msgs, ids, n)
        cnt = jax.ops.segment_sum(jnp.ones((msgs.shape[0],), msgs.dtype),
                                  ids, n)
        return s / jnp.maximum(cnt, 1.0)[(...,) + (None,) * (msgs.ndim - 1)]
    if reduce_op == "max":
        return jax.ops.segment_max(msgs, ids, n)
    if reduce_op == "min":
        return jax.ops.segment_min(msgs, ids, n)
    raise ValueError(f"unknown reduce_op {reduce_op!r}")


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src] and scatter-reduce onto dst
    (reference send_recv.py send_u_recv)."""
    return _send_u_recv(x, src_index, dst_index, reduce_op=reduce_op,
                        out_size=int(out_size) if out_size else 0)


@defop("geometric.send_ue_recv")
def _send_ue_recv(x, e, src_index, dst_index, message_op="add",
                  reduce_op="sum", out_size=0):
    msgs = x[src_index]
    if message_op == "add":
        msgs = msgs + e
    elif message_op == "mul":
        msgs = msgs * e
    else:
        raise ValueError(f"unknown message_op {message_op!r}")
    n = int(out_size) if out_size else x.shape[0]
    ids = dst_index.astype(jnp.int32)
    if reduce_op == "sum":
        return jax.ops.segment_sum(msgs, ids, n)
    if reduce_op == "mean":
        s = jax.ops.segment_sum(msgs, ids, n)
        cnt = jax.ops.segment_sum(jnp.ones((msgs.shape[0],), msgs.dtype),
                                  ids, n)
        return s / jnp.maximum(cnt, 1.0)[(...,) + (None,) * (msgs.ndim - 1)]
    if reduce_op == "max":
        return jax.ops.segment_max(msgs, ids, n)
    raise ValueError(f"unknown reduce_op {reduce_op!r}")


def send_ue_recv(x, e, src_index, dst_index, message_op="add", reduce_op="sum",
                 out_size=None, name=None):
    """Edge-featured message passing (reference send_recv.py send_ue_recv)."""
    return _send_ue_recv(x, e, src_index, dst_index, message_op=message_op,
                         reduce_op=reduce_op,
                         out_size=int(out_size) if out_size else 0)


@defop("graph_send_uv")
def _send_uv(x, y, src_index, dst_index, message_op="add"):
    xs = x[src_index.astype(jnp.int32)]
    yd = y[dst_index.astype(jnp.int32)]
    if message_op == "add":
        return xs + yd
    if message_op == "sub":
        return xs - yd
    if message_op == "mul":
        return xs * yd
    if message_op == "div":
        return xs / yd
    raise ValueError(f"unknown message_op {message_op!r}")


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from source and destination node features
    (reference geometric/message_passing/send_recv.py:413 send_uv):
    out[i] = op(x[src_index[i]], y[dst_index[i]])."""
    return _send_uv(x, y, src_index, dst_index, message_op=message_op)


def _reindex(x_np, neigh_np, count_np=None):
    """Renumber: input nodes first, then neighbors by first appearance.
    Returns (reindex_src, reindex_dst, out_nodes); reindex_dst is None when
    count_np is (the heterogeneous caller builds its own per-type repeat)."""
    import numpy as np

    mapping = {int(v): i for i, v in enumerate(x_np)}
    out_nodes = list(x_np)
    reindex_src = np.empty(len(neigh_np), np.int64)
    for i, v in enumerate(neigh_np):
        v = int(v)
        idx = mapping.get(v)
        if idx is None:
            idx = len(out_nodes)
            mapping[v] = idx
            out_nodes.append(v)
        reindex_src[i] = idx
    reindex_dst = (np.repeat(np.arange(len(x_np), dtype=np.int64), count_np)
                   if count_np is not None else None)
    return reindex_src, reindex_dst, np.asarray(out_nodes, x_np.dtype)


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Renumber sampled subgraph nodes from 0 (reference reindex.py:34):
    input nodes first, then neighbors in order of first appearance. Returns
    (reindex_src, reindex_dst, out_nodes). Host-side by nature — the output
    size is data-dependent (graph sampling is a data-pipeline step)."""
    import numpy as np

    x_np = np.asarray(getattr(x, "value", x)).reshape(-1)
    neigh_np = np.asarray(getattr(neighbors, "value", neighbors)).reshape(-1)
    count_np = np.asarray(getattr(count, "value", count)).reshape(-1)
    rs, rd, out = _reindex(x_np, neigh_np, count_np)
    return Tensor(jnp.asarray(rs)), Tensor(jnp.asarray(rd)), \
        Tensor(jnp.asarray(out))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """reference reindex.py:153 — reindex a heterogeneous sampled graph:
    per-edge-type neighbor/count lists share ONE node renumbering."""
    import numpy as np

    x_np = np.asarray(getattr(x, "value", x)).reshape(-1)
    neighs = [np.asarray(getattr(n, "value", n)).reshape(-1)
              for n in neighbors]
    counts = [np.asarray(getattr(c, "value", c)).reshape(-1) for c in count]
    # each edge type carries its own per-input-node count vector; the dst
    # index is the per-type repeat, concatenated in type order
    rd = np.concatenate([
        np.repeat(np.arange(len(x_np), dtype=np.int64), c) for c in counts])
    rs, _, out = _reindex(x_np, np.concatenate(neighs))
    return Tensor(jnp.asarray(rs)), Tensor(jnp.asarray(rd)), \
        Tensor(jnp.asarray(out))


def _sample_from_csc(row, colptr, input_nodes, sample_size, eids=None,
                     weights=None, seed=None):
    import numpy as np

    rng = np.random.RandomState(seed)
    out_n, out_c, out_e = [], [], []
    for node in input_nodes:
        beg, end = int(colptr[node]), int(colptr[node + 1])
        idx = np.arange(beg, end)
        if 0 <= sample_size < len(idx):
            if weights is not None:
                w = np.asarray(weights[beg:end], np.float64)
                p = w / w.sum() if w.sum() > 0 else None
                idx = rng.choice(idx, size=sample_size, replace=False, p=p)
            else:
                idx = rng.choice(idx, size=sample_size, replace=False)
        out_n.append(row[idx])
        out_c.append(len(idx))
        if eids is not None:
            out_e.append(eids[idx])
    dt = np.asarray(row).dtype
    neigh = np.concatenate(out_n) if out_n else np.empty(0, dt)
    cnt = np.asarray(out_c, dt)
    es = None
    if eids is not None:  # empty input_nodes still yields an empty eids
        es = np.concatenate(out_e) if out_e else np.empty(0, dt)
    return neigh, cnt, es


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniform neighbor sampling over a CSC graph (reference
    sampling/neighbors.py:30). Returns (out_neighbors, out_count[, out_eids]).
    Host-side: output size is data-dependent."""
    import numpy as np

    if return_eids and eids is None:
        raise ValueError("return_eids=True needs eids")
    row_np = np.asarray(getattr(row, "value", row)).reshape(-1)
    col_np = np.asarray(getattr(colptr, "value", colptr)).reshape(-1)
    in_np = np.asarray(getattr(input_nodes, "value", input_nodes)).reshape(-1)
    e_np = (np.asarray(getattr(eids, "value", eids)).reshape(-1)
            if eids is not None else None)
    neigh, cnt, es = _sample_from_csc(row_np, col_np, in_np,
                                      int(sample_size), e_np)
    outs = (Tensor(jnp.asarray(neigh)), Tensor(jnp.asarray(cnt)))
    if return_eids:
        return (*outs, Tensor(jnp.asarray(es)))
    return outs


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weight-proportional neighbor sampling without replacement (reference
    sampling/neighbors.py weighted_sample_neighbors)."""
    import numpy as np

    if return_eids and eids is None:
        raise ValueError("return_eids=True needs eids")
    row_np = np.asarray(getattr(row, "value", row)).reshape(-1)
    col_np = np.asarray(getattr(colptr, "value", colptr)).reshape(-1)
    w_np = np.asarray(getattr(edge_weight, "value", edge_weight)).reshape(-1)
    in_np = np.asarray(getattr(input_nodes, "value", input_nodes)).reshape(-1)
    e_np = (np.asarray(getattr(eids, "value", eids)).reshape(-1)
            if eids is not None else None)
    neigh, cnt, es = _sample_from_csc(row_np, col_np, in_np,
                                      int(sample_size), e_np, weights=w_np)
    outs = (Tensor(jnp.asarray(neigh)), Tensor(jnp.asarray(cnt)))
    if return_eids:
        return (*outs, Tensor(jnp.asarray(es)))
    return outs
