"""AMP auto-cast.

Reference analog: the AMP logic injected into every generated op
(fluid/eager/auto_code_generator/generator/eager_gen.py:645 AMP_LOGIC_TEMPLATE,
imperative/amp_auto_cast.cc controller) and python/paddle/amp/auto_cast.py. The op
dispatcher (ops/_apply.py) calls amp_cast_inputs() on every op when an amp context is
active: O1 casts white-list op inputs to the low-precision dtype and black-list op inputs to
fp32; O2 casts everything except the black list. bf16 is the TPU-native choice; fp16 is kept
for API parity.
"""
from __future__ import annotations

import contextlib

import numpy as np

import jax.numpy as jnp

from ..framework import dtype as dtype_mod
from ..framework.core import Tensor
from . import amp_lists

_STATE = []


class _AmpState:
    __slots__ = ("enable", "dtype", "level", "custom_white", "custom_black")

    def __init__(self, enable, dtype, level, custom_white, custom_black):
        self.enable = enable
        self.dtype = np.dtype(dtype_mod.convert_dtype(dtype))
        self.level = level
        self.custom_white = set(custom_white or [])
        self.custom_black = set(custom_black or [])


def _amp_state():
    return _STATE[-1] if _STATE else None


def amp_state():
    return _amp_state()


_MON = None  # (monitor._state, amp-cast counter), bound on first cast


def _mon():
    global _MON
    if _MON is None:
        from .. import monitor as _m

        _MON = (_m._state, _m.counter("paddle_tpu_dispatch_amp_casts_total"))
    return _MON


def amp_cast_inputs(opdef, args, kwargs):
    state = _amp_state()
    if state is None or not state.enable:
        return args, kwargs
    name = opdef.name
    if name == "cast" or opdef.amp_category == "skip":
        # dtype-control ops are never themselves AMP-cast: under O2 the
        # hook would cast `cast`'s input via another cast, recursing forever
        return args, kwargs
    white = (name in amp_lists.WHITE_LIST or name in state.custom_white
             or opdef.amp_category == "white")
    black = (name in amp_lists.BLACK_LIST or name in state.custom_black
             or opdef.amp_category == "black")
    if name in state.custom_white:
        black = False
    if state.level == "O2":
        target = np.dtype(np.float32) if black else state.dtype
    else:  # O1
        if white and not black:
            target = state.dtype
        elif black:
            target = np.dtype(np.float32)
        else:
            return args, kwargs

    mon = _mon()

    def cast_leaf(x):
        if isinstance(x, Tensor) and dtype_mod.is_floating(x.dtype) and np.dtype(x.dtype) != target:
            # cast through the op layer so autograd casts the grad back
            from ..ops.manipulation import cast

            if mon[0].on:
                mon[1].inc()
            return cast(x, target)
        return x

    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs),
                                                 is_leaf=lambda x: isinstance(x, Tensor))
    leaves = [cast_leaf(l) for l in leaves]
    return jax.tree_util.tree_unflatten(treedef, leaves)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None, level="O1",
              dtype="float16", use_promote=True):
    """paddle.amp.auto_cast (python/paddle/amp/auto_cast.py:1006)."""
    if level not in ("O0", "O1", "O2", "OD"):
        raise ValueError(f"level must be O0/OD/O1/O2, got {level}")
    if level == "O0":
        enable = False
    state = _AmpState(enable, dtype, "O1" if level == "OD" else level,
                      custom_white_list, custom_black_list)
    _STATE.append(state)
    try:
        yield
    finally:
        _STATE.pop()


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="float16", master_weight=None,
             save_dtype=None, master_grad=False, excluded_layers=None):
    """paddle.amp.decorate (auto_cast.py:1091): O2 casts model params to low precision and
    lets the optimizer keep fp32 master weights."""
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        d = dtype_mod.convert_dtype(dtype)
        for m in model_list:
            for p in m.parameters():
                if dtype_mod.is_floating(p.dtype) and np.dtype(p.dtype) == np.float32:
                    p._replace_value(p.value.astype(d))
        if optimizers is not None:
            opt_list = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
            for opt in opt_list:
                if hasattr(opt, "_use_master_weights"):
                    opt._use_master_weights = True if master_weight is None else master_weight
                if hasattr(opt, "_use_master_grad"):
                    opt._use_master_grad = bool(master_grad)
        # fp32 master gradients end to end: backward re-linearizes
        # reduced-precision ops in fp32 (autograd/tape.py master grad) and
        # the optimizer upcasts any reduced grad before its update. Every
        # O2 decorate SETS the mode from its master_grad argument, so
        # decorate(master_grad=False) restores the default instead of
        # inheriting a stale process-wide True from an earlier decorate.
        from ..autograd import tape as _tape

        _tape.set_master_grad(bool(master_grad))
        _amp_global_state.use_master_grad = bool(master_grad)
    if optimizers is None:
        return models
    return models, optimizers


def is_auto_cast_enabled():
    s = _amp_state()
    return bool(s and s.enable)


def get_amp_dtype():
    s = _amp_state()
    return dtype_mod.dtype_name(s.dtype) if s else "float32"


class AMPGlobalState:
    """Mirror of amp/auto_cast.py:122 AMPGlobalState (master-grad bookkeeping)."""

    def __init__(self):
        self.model_parameters = []
        self.use_master_grad = False
        self.already_register_final_backward_hook = False


_amp_global_state = AMPGlobalState()


def amp_global_state():
    return _amp_global_state
