"""Filesystem helpers: paddle.distributed.fleet.utils.{LocalFS, HDFSClient}.

Reference analog: python/paddle/distributed/fleet/utils/fs.py:134 LocalFS
(and the hadoop-CLI-backed HDFSClient). LocalFS is fully functional;
HDFSClient shells out to the configured ``hadoop fs`` binary exactly like
the reference and therefore needs one in PATH (this environment has none —
construction succeeds, operations raise with a clear message if the CLI is
absent)."""
from __future__ import annotations

import os
import shutil
import subprocess

__all__ = ["LocalFS", "HDFSClient", "FSFileExistsError", "FSFileNotExistsError"]


class FSFileExistsError(RuntimeError):
    pass


class FSFileNotExistsError(RuntimeError):
    pass


class LocalFS:
    """reference fs.py:134 — local filesystem with the FS API shape."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            (dirs if os.path.isdir(os.path.join(fs_path, name))
             else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def delete(self, fs_path):
        if os.path.isdir(fs_path):
            shutil.rmtree(fs_path, ignore_errors=True)
        elif os.path.exists(fs_path):
            os.remove(fs_path)

    def need_upload_download(self):
        return False

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True):
        if os.path.exists(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        open(fs_path, "a").close()

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not overwrite and os.path.exists(dst_path):
            raise FSFileExistsError(dst_path)
        if test_exists and not os.path.exists(src_path):
            raise FSFileNotExistsError(src_path)
        shutil.move(src_path, dst_path)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]


class HDFSClient:
    """reference fs.py HDFSClient: every operation is one ``hadoop fs``
    CLI call with the configs rendered as -D options."""

    def __init__(self, hadoop_home=None, configs=None, time_out=300,
                 sleep_inter=1000):
        self._hadoop = (os.path.join(hadoop_home, "bin", "hadoop")
                        if hadoop_home else "hadoop")
        self._configs = configs or {}
        self._timeout = time_out

    def _cmd(self, *args, check=False):
        base = [self._hadoop, "fs"]
        for k, v in self._configs.items():
            base += ["-D", f"{k}={v}"]
        try:
            proc = subprocess.run(base + list(args), capture_output=True,
                                  text=True, timeout=self._timeout)
        except FileNotFoundError as e:
            raise RuntimeError(
                f"hadoop CLI not found ({self._hadoop!r}); HDFSClient needs "
                "a hadoop installation (pass hadoop_home=)") from e
        if check and proc.returncode != 0:
            # the reference raises ExecuteError on CLI failure — silent
            # success on a failed transfer corrupts the caller's world
            raise RuntimeError(
                f"hadoop fs {' '.join(args)} failed "
                f"(rc={proc.returncode}): {proc.stderr[-500:]}")
        return proc

    def is_exist(self, fs_path):
        return self._cmd("-test", "-e", fs_path).returncode == 0

    def is_dir(self, fs_path):
        return self._cmd("-test", "-d", fs_path).returncode == 0

    def is_file(self, fs_path):
        return (self.is_exist(fs_path) and not self.is_dir(fs_path))

    def ls_dir(self, fs_path):
        out = self._cmd("-ls", fs_path)
        dirs, files = [], []
        for line in out.stdout.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = parts[-1].rsplit("/", 1)[-1]
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        self._cmd("-mkdir", "-p", fs_path, check=True)

    def delete(self, fs_path):
        self._cmd("-rm", "-r", "-f", fs_path, check=True)

    def upload(self, local_path, fs_path, multi_processes=1, overwrite=False):
        if overwrite:
            self.delete(fs_path)
        self._cmd("-put", local_path, fs_path, check=True)

    def download(self, fs_path, local_path, multi_processes=1,
                 overwrite=False):
        if overwrite and os.path.exists(local_path):
            LocalFS().delete(local_path)
        self._cmd("-get", fs_path, local_path, check=True)

    def need_upload_download(self):
        return True

    def mv(self, fs_src_path, fs_dst_path, overwrite=False):
        if overwrite:
            self.delete(fs_dst_path)
        self._cmd("-mv", fs_src_path, fs_dst_path, check=True)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path) and not exist_ok:
            raise FSFileExistsError(fs_path)
        self._cmd("-touchz", fs_path, check=True)
