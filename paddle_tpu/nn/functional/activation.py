"""Activation functionals (reference: python/paddle/nn/functional/activation.py).

All map to jax.nn / jnp primitives; XLA fuses them into surrounding matmuls, which is the
TPU replacement for the reference's fused activation CUDA kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops._apply import defop
from ...framework.core import Tensor

relu = defop("relu")(lambda x: jax.nn.relu(x))
relu6 = defop("relu6")(lambda x: jax.nn.relu6(x))
sigmoid = defop("sigmoid_fn")(lambda x: jax.nn.sigmoid(x))
tanh = defop("tanh_fn")(lambda x: jnp.tanh(x))
silu = defop("silu")(lambda x: jax.nn.silu(x))
swish = silu
mish = defop("mish")(lambda x: jax.nn.mish(x))
hardswish = defop("hardswish")(lambda x: x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0)
hardsigmoid = defop("hardsigmoid")(lambda x, slope=1.0 / 6, offset=0.5: jnp.clip(slope * x + offset, 0.0, 1.0))
hardtanh = defop("hardtanh")(lambda x, min=-1.0, max=1.0: jnp.clip(x, min, max))  # noqa: A002
tanhshrink = defop("tanhshrink")(lambda x: x - jnp.tanh(x))
softsign = defop("softsign")(lambda x: jax.nn.soft_sign(x))
selu = defop("selu")(
    lambda x, scale=1.0507009873554805, alpha=1.6732632423543772:
    scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))
)
celu = defop("celu")(lambda x, alpha=1.0: jax.nn.celu(x, alpha))
elu = defop("elu")(lambda x, alpha=1.0: jax.nn.elu(x, alpha))


@defop("leaky_relu")
def _leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(x, negative_slope)


def leaky_relu(x, negative_slope=0.01, name=None):
    return _leaky_relu(x, negative_slope=float(negative_slope))


@defop("prelu_op")
def _prelu(x, weight, data_format="NCHW"):
    if weight.size == 1:
        w = weight.reshape(())
    else:
        axis = 1 if data_format == "NCHW" else x.ndim - 1
        shape = [1] * x.ndim
        shape[axis] = weight.shape[0]
        w = weight.reshape(shape)
    return jnp.where(x > 0, x, w * x)


def prelu(x, weight, data_format="NCHW", name=None):
    return _prelu(x, weight, data_format=data_format)


@defop("gelu")
def _gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


def gelu(x, approximate=False, name=None):
    return _gelu(x, approximate=bool(approximate))


@defop("softmax", amp_category="black")
def _softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


def softmax(x, axis=-1, dtype=None, name=None):
    from ...ops.manipulation import cast

    if dtype is not None:
        x = cast(x, dtype)
    return _softmax(x, axis=int(axis))


@defop("log_softmax", amp_category="black")
def _log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...ops.manipulation import cast

    if dtype is not None:
        x = cast(x, dtype)
    return _log_softmax(x, axis=int(axis))


@defop("softplus")
def _softplus(x, beta=1.0, threshold=20.0):
    scaled = beta * x
    return jnp.where(scaled > threshold, x, jax.nn.softplus(scaled) / beta)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return _softplus(x, beta=float(beta), threshold=float(threshold))


@defop("softshrink")
def _softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold, jnp.where(x < -threshold, x + threshold, 0.0))


def softshrink(x, threshold=0.5, name=None):
    return _softshrink(x, threshold=float(threshold))


@defop("hardshrink")
def _hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, jnp.zeros_like(x))


def hardshrink(x, threshold=0.5, name=None):
    return _hardshrink(x, threshold=float(threshold))


@defop("thresholded_relu")
def _thresholded_relu(x, threshold=1.0, value=0.0):
    return jnp.where(x > threshold, x, jnp.asarray(value, x.dtype))


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return _thresholded_relu(x, threshold=float(threshold), value=float(value))


@defop("maxout")
def _maxout(x, groups, axis=1):
    c = x.shape[axis]
    new_shape = list(x.shape)
    new_shape[axis] = c // groups
    new_shape.insert(axis + 1, groups)
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


def maxout(x, groups, axis=1, name=None):
    return _maxout(x, groups=int(groups), axis=int(axis))


@defop("glu")
def _glu(x, axis=-1):
    return jax.nn.glu(x, axis=axis)


def glu(x, axis=-1, name=None):
    return _glu(x, axis=int(axis))


@defop("swiglu")
def _swiglu(x, y=None):
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


def swiglu(x, y=None, name=None):
    """Fused SwiGLU (reference: python/paddle/incubate/nn/functional/swiglu.py)."""
    return _swiglu(x, y)


def relu_(x):
    out = relu(x)
    x._replace_value(out.value)
    x._grad_node, x._out_index, x.stop_gradient = out._grad_node, out._out_index, out.stop_gradient
    return x


def softmax_(x, axis=-1, dtype=None, name=None):
    out = softmax(x, axis, dtype)
    x._replace_value(out.value)
    x._grad_node, x._out_index, x.stop_gradient = out._grad_node, out._out_index, out.stop_gradient
    return x
