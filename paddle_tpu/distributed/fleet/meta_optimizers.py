"""Fleet meta-optimizers: strategy-driven optimizer wrapping.

Reference analog: python/paddle/distributed/fleet/meta_optimizers/ —
GradientMergeOptimizer (apply every k steps, accumulating in between),
LambOptimizer (swap the inner optimizer for Lamb). The GPU-era members
(DGC sparse-compressed allreduce, LocalSGD) have no TPU analog: gradient
reduction is compiler-emitted over ICI, so there is no NCCL ring to
compress or desynchronize (`DistributedStrategy` accepts the flags as
documented no-ops, like the reference's own knobs on unsupported
hardware).
"""
from __future__ import annotations

from ...framework.core import Tensor


class GradientMergeOptimizer:
    """reference meta_optimizers/gradient_merge_optimizer.py: accumulate
    gradients for ``k_steps`` micro-steps, then apply the inner optimizer
    once on the (optionally averaged) sum. Between applies, ``step()``
    only banks the gradients and ``clear_grad()`` clears the per-micro
    grads as usual."""

    def __init__(self, inner, k_steps=1, avg=True):
        self._inner = inner
        self._k = max(1, int(k_steps))
        self._avg = bool(avg)
        self._step_n = 0
        self._acc = {}  # id(param) -> accumulated raw grad value

    def __getattr__(self, name):
        return getattr(self._inner, name)

    @property
    def k_steps(self):
        return self._k

    def step(self):
        self._step_n += 1
        params = self._inner._parameter_list_flat()
        for p in params:
            if p.grad is None:
                continue
            a = self._acc.get(id(p))
            gv = p.grad.value
            self._acc[id(p)] = gv if a is None else a + gv
        if self._step_n % self._k:
            return  # accumulation micro-step: no parameter update
        for p in params:
            a = self._acc.pop(id(p), None)
            if a is None:
                continue
            p.grad = Tensor(a / self._k if self._avg else a)
        self._inner.step()

    def clear_grad(self, set_to_zero=True):
        self._inner.clear_grad(set_to_zero)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """Backward + MERGED step. Inner.minimize would call the inner
        step() directly and silently bypass the merge; in static capture it
        also registers the train hook, which must point at this wrapper."""
        from ...framework import capture

        prog = capture.active()
        if prog is not None:
            out = self._inner.minimize(loss, startup_program=startup_program,
                                       parameters=parameters,
                                       no_grad_set=no_grad_set)
            prog.retarget_train_hook(self._inner, self)
            return out
        if parameters is not None:
            self._inner._param_groups[0]["params"] = list(parameters)
        loss.backward()
        self.step()
        return None, None

    def _amp_train_step(self, live_loss):
        """Executor train-hook entry (static/__init__.py): defined on the
        CLASS so the executor routes through the MERGED step — __getattr__
        delegation would otherwise hand it the inner static-amp wrapper's
        hook and apply k unmerged updates per intended merged one. Dynamic
        fp16 loss scaling cannot compose with banking (the scale changes
        between banked micro-steps); bf16 static AMP (no scaler) and plain
        static programs both route here."""
        scaler = None
        obj = self._inner
        seen = set()
        while obj is not None and id(obj) not in seen:
            seen.add(id(obj))
            scaler = getattr(obj, "_scaler", None) or scaler
            obj = getattr(obj, "_inner", None) or getattr(obj, "_inner_opt",
                                                          None)
        if scaler is not None:
            raise NotImplementedError(
                "gradient_merge + fp16 dynamic loss scaling in static mode "
                "is unsupported (the loss scale would change between banked "
                "micro-steps); use bf16 static AMP or eager mode")
        live_loss.backward()
        self.step()
        self.clear_grad()

    # -- checkpointing: the banked gradients and the micro-step counter are
    # training state (an elastic resume mid-accumulation must not lose the
    # already-banked micro-batches) -----------------------------------------
    def state_dict(self):
        import numpy as np

        sd = dict(self._inner.state_dict())
        params = self._inner._parameter_list_flat()
        acc = {i: np.asarray(self._acc[id(p)])
               for i, p in enumerate(params) if id(p) in self._acc}
        sd["_gradient_merge"] = {"step_n": self._step_n, "acc": acc}
        return sd

    def set_state_dict(self, sd):
        import jax.numpy as jnp

        sd = dict(sd)
        gm = sd.pop("_gradient_merge", None)
        self._inner.set_state_dict(sd)
        if gm:
            self._step_n = int(gm.get("step_n", 0))
            params = self._inner._parameter_list_flat()
            self._acc = {id(params[int(i)]): jnp.asarray(v)
                         for i, v in (gm.get("acc") or {}).items()}
        else:
            # a checkpoint without merge state (e.g. from a plain inner
            # optimizer): keeping pre-load banked grads would pollute the
            # loaded weights with discarded training
            self._step_n = 0
            self._acc = {}


def apply_inner_meta_optimizers(optimizer, strategy):
    """Meta-optimizers that REPLACE the inner optimizer (applied before
    hybrid wrapping, so HybridParallelOptimizer's setattr hooks — clip
    replacement, ZeRO shard fn — land on the real optimizer)."""
    if getattr(strategy, "lamb", False):
        from ...optimizer.optimizer import Lamb

        if not isinstance(optimizer, Lamb):
            cfg = dict(getattr(strategy, "lamb_configs", {}) or {})
            # carry the inner optimizer's training contract over: the live
            # LR scheduler object (not a frozen float), grad clip, master
            # weights, and the param groups with their per-group options
            optimizer = Lamb(
                learning_rate=optimizer._learning_rate,
                parameters=[dict(g) for g in optimizer._param_groups],
                grad_clip=optimizer._grad_clip,
                multi_precision=optimizer._use_master_weights,
                lamb_weight_decay=float(cfg.get("lamb_weight_decay", 0.01)))
    return optimizer


def apply_outer_meta_optimizers(optimizer, strategy):
    """Meta-optimizers that WRAP the (possibly hybrid) optimizer: gradient
    merge goes outermost so global-norm clipping and sharding act on the
    MERGED gradients, and so the hybrid wrapper's attribute hooks were
    already installed on the true inner optimizer."""
    if getattr(strategy, "gradient_merge", False):
        cfg = dict(getattr(strategy, "gradient_merge_configs", {}) or {})
        optimizer = GradientMergeOptimizer(
            optimizer, k_steps=cfg.get("k_steps", 1),
            avg=cfg.get("avg", True))
    return optimizer
