"""Flagship model families, TPU-first.

Reference analogs: PaddleNLP-style LLaMA/GPT used by the reference's auto-parallel
end-to-end tests (test/auto_parallel/hybrid_strategy/semi_auto_parallel_llama_model.py,
test/collective/fleet hybrid suites). These are the models the framework's parallelism
stack is validated and benchmarked on.
"""
from .llama import (  # noqa: F401
    LlamaConfig,
    LlamaForCausalLM,
    LlamaForCausalLMPipe,
    LlamaModel,
    LlamaPretrainingCriterion,
)
from .bert import (  # noqa: F401
    BertConfig,
    BertForPretraining,
    BertModel,
    BertPretrainingCriterion,
    ErnieConfig,
    ErnieForPretraining,
    ErnieModel,
)
from .gpt import (  # noqa: F401
    GPTConfig,
    GPTForCausalLM,
    GPTModel,
    GPTPretrainingCriterion,
)
from .llama_decode import LlamaDecodeEngine  # noqa: F401
from .radix_cache import PrefixCache  # noqa: F401
from .serving import (AdmissionTimeout, ContinuousBatchingEngine,  # noqa: F401
                      StaticBatchEngine)
