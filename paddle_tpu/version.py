"""paddle.version parity surface.

Reference analog: python/paddle/version/__init__.py (generated at build time by
setup.py write_version_py — full_version/major/minor/rc/commit plus the
capability probes show()/cuda()/cudnn()/xpu()). Here the capability probes
answer for the TPU build: there is no CUDA/cuDNN; the accelerator is whatever
PJRT exposes.
"""
from __future__ import annotations

full_version = "0.3.0"
major = "0"
minor = "3"
patch = "0"
rc = "0"
nccl_version = "0"
cuda_version = "False"
cudnn_version = "False"
tensorrt_version = "False"
xpu_version = "False"
xpu_xccl_version = "False"
xpu_xhpc_version = "False"
istaged = False
commit = "unknown"
with_pip_cuda_libraries = "OFF"
with_pip_tensorrt = "OFF"

__all__ = ["cuda", "cudnn", "nccl", "show", "xpu", "xpu_xccl", "xpu_xhpc",
           "tpu"]


def show():
    """Print the version/build info (reference version.show)."""
    if istaged:
        print("full_version:", full_version)
        print("major:", major)
        print("minor:", minor)
        print("patch:", patch)
        print("rc:", rc)
    else:
        print("commit:", commit)
    print("cuda:", cuda_version)
    print("cudnn:", cudnn_version)
    print("nccl:", nccl_version)
    print("xpu:", xpu_version)
    print("tpu:", tpu())


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version


def nccl():
    return nccl_version


def xpu():
    return xpu_version


def xpu_xccl():
    return xpu_xccl_version


def xpu_xhpc():
    return xpu_xhpc_version


def tensorrt():
    return tensorrt_version


def tpu():
    """TPU generation string via PJRT, or "False" off-device (TPU analog of
    version.cuda())."""
    try:
        import jax

        d = jax.devices()[0]
        if d.platform == "tpu":
            return getattr(d, "device_kind", "tpu")
    except Exception:  # noqa: BLE001 - version probe must never raise
        pass
    return "False"
