"""paddle.distributed.checkpoint: flat-shard distributed checkpoint format.

Reference analog: python/paddle/distributed/checkpoint/ (metadata.py:41 global
offsets, save_state_dict.py:48 async save, load_state_dict.py:526 redistribution).
"""
from .metadata import LocalTensorIndex, LocalTensorMetadata, Metadata  # noqa: F401
from .save_state_dict import (  # noqa: F401
    flatten_state_dict,
    save_state_dict,
    unflatten_state_dict,
    wait_async_save,
)
from .load_state_dict import load_merged_state_dict, load_state_dict  # noqa: F401
