"""Optimizers.

Reference analog: python/paddle/optimizer/optimizer.py base + per-optimizer phi kernels
(adamw_kernel etc.). TPU-first: each optimizer defines a pure per-leaf update rule; step()
executes ONE jitted function over the whole parameter pytree (the fused multi-tensor apply —
XLA fuses all per-param updates into one executable, replacing the reference's
multi_tensor_adam CUDA path). Master weights (AMP O2) keep an fp32 shadow per low-precision
param, matching optimizer.py:318 _master_weights.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework import dtype as dtype_mod
from ..framework.core import Parameter, Tensor
from .lr import LRScheduler


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class Optimizer:
    _rule_name = "base"

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        self._static_bind = False
        if parameters is None:
            from ..framework import capture

            if capture.active() is None:
                from ..framework.enforce import InvalidArgumentError

                raise InvalidArgumentError(
                    "parameters is required in eager mode: pass "
                    "model.parameters()")
            # static-mode construction (reference optimizer collects params
            # from the Program): bind the builder-registered parameters at
            # minimize() time
            self._static_bind = True
            parameters = []
        # param groups (reference optimizer.py supports dict groups)
        self._param_groups = []
        params = list(parameters)
        if params and isinstance(params[0], dict):
            for g in params:
                grp = dict(g)
                grp["params"] = list(g["params"])
                self._param_groups.append(grp)
        else:
            self._param_groups.append({"params": params})
        self._learning_rate = learning_rate
        if isinstance(weight_decay, (L2Decay,)):
            self._weight_decay = weight_decay.coeff
            self._coupled_decay = True
        elif isinstance(weight_decay, L1Decay):
            self._weight_decay = weight_decay.coeff
            self._coupled_decay = "l1"
        else:
            self._weight_decay = float(weight_decay) if weight_decay else 0.0
            self._coupled_decay = True
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._use_master_weights = multi_precision
        self._use_master_grad = False
        self._accumulators = {}  # id(param) -> state dict
        self._master_weights = {}  # id(param) -> fp32 jax array
        self._step_count = 0
        self._jit_cache = {}
        # ZeRO-style state placement hook (set by dist.shard_optimizer / fleet sharding):
        # called as _shard_fn(state_name, param, accumulator_tensor) -> sharded tensor
        self._shard_fn = None
        self._is_dist = False

    # -- lr ------------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    @staticmethod
    def _as_value(x):
        return x.value if isinstance(x, Tensor) else x

    def _parameter_list_flat(self):
        return [p for g in self._param_groups for p in g["params"]]

    @property
    def _parameter_list(self):
        return self._parameter_list_flat()

    # -- state ---------------------------------------------------------------
    def _init_state(self, p):
        """Return dict of state arrays for param p (fp32)."""
        return {}

    def _apply_shard_fn(self, p, state):
        """Run the ZeRO placement hook (if installed) over a state dict — the
        single path every state-creation site (step, set_state_dict, DistModel
        pre-init) must go through so placements never diverge."""
        if self._shard_fn is None:
            return state
        return {k: self._as_value(self._shard_fn(k, p, Tensor(v)))
                for k, v in state.items()}

    def _init_sharded_state(self, p):
        return self._apply_shard_fn(p, self._init_state(p))

    def _rule(self, p, g, state, lr, **hyper):
        """Pure update: (p32, g32, state, lr) -> (new_p32, new_state)."""
        raise NotImplementedError

    def _hyper(self, group):
        return {}

    def _precompute(self, step, hyper):
        """Per-STEP shared subexpressions of the update rule, computed
        ONCE per fused apply and passed to every per-param ``_rule``
        call. Adam's bias corrections ``1 - beta^t`` were traced once
        per PARAMETER before this hook — the duplicate-subexpression
        shape graftir's GI004 flags (and graftopt's CSE rewrite would
        fold); hoisting them here burns the finding at the source."""
        return {}

    # -- step ----------------------------------------------------------------
    @jax.named_scope("optimizer_step")
    def step(self):
        self._step_count += 1
        lr_scalar = jnp.asarray(self.get_lr(), jnp.float32)
        for group in self._param_groups:
            params = [p for p in group["params"] if not p.stop_gradient or p.grad is not None]
            # plain Tensors (stop_gradient=False) are accepted alongside Parameters
            pg = [(p, p.grad) for p in params
                  if p.grad is not None and getattr(p, "trainable", True)]
            if not pg:
                continue
            if self._grad_clip is not None:
                pg = self._grad_clip(pg)
            hyper = self._hyper(group)
            wd = group.get("weight_decay", self._weight_decay)
            if isinstance(wd, (L2Decay, L1Decay)):
                wd = wd.coeff
            # gather values + states
            p_vals, g_vals, states, masters = [], [], [], []
            for p, g in pg:
                if id(p) not in self._accumulators:
                    self._accumulators[id(p)] = self._init_sharded_state(p)
                    if self._use_master_weights and np.dtype(p.dtype) in (
                        np.dtype(np.float16), np.dtype(jnp.bfloat16)
                    ):
                        self._master_weights[id(p)] = p.value.astype(jnp.float32)
                states.append(self._accumulators[id(p)])
                masters.append(self._master_weights.get(id(p)))
                p_vals.append(p.value)
                gv = g.value
                if self._use_master_grad and np.dtype(gv.dtype) in (
                    np.dtype(np.float16), np.dtype(jnp.bfloat16)
                ):
                    # master grad contract: updates consume fp32 gradients
                    # even when a producer handed over a reduced-precision
                    # one (the fused apply would upcast anyway; doing it
                    # here keeps the jit signature honest about it)
                    gv = gv.astype(jnp.float32)
                g_vals.append(gv)
            new_ps, new_states, new_masters = self._fused_apply(
                p_vals, g_vals, states, masters, lr_scalar, float(wd), hyper,
                [getattr(p, "optimize_attr", {}).get("learning_rate", 1.0) for p, _ in pg],
            )
            for (p, _), np_, ns, nm in zip(pg, new_ps, new_states, new_masters):
                p._replace_value(np_)
                self._accumulators[id(p)] = ns
                if nm is not None:
                    self._master_weights[id(p)] = nm

    def _fused_apply(self, p_vals, g_vals, states, masters, lr, wd, hyper, lr_mults):
        """One jitted call updating every parameter (fused multi-tensor apply)."""
        key = (len(p_vals), tuple(v.shape for v in p_vals),
               tuple(str(v.dtype) for v in p_vals), tuple(sorted(hyper.items())), wd,
               tuple(lr_mults), tuple(m is not None for m in masters))
        fn = self._jit_cache.get(key)
        if fn is None:
            rule = self._rule
            precompute = self._precompute
            coupled = self._coupled_decay

            def apply_all(p_vals, g_vals, states, masters, lr, step):
                outs, out_states, out_masters = [], [], []
                shared = precompute(step, hyper)
                for pv, gv, st, mw, mult in zip(p_vals, g_vals, states, masters,
                                                list(lr_mults)):
                    p32 = mw if mw is not None else pv.astype(jnp.float32)
                    g32 = gv.astype(jnp.float32)
                    if wd and coupled is True:
                        g32 = g32 + wd * p32
                    elif wd and coupled == "l1":
                        g32 = g32 + wd * jnp.sign(p32)
                    new_p32, new_st = rule(p32, g32, st, lr * mult, step=step, wd=wd,
                                           **hyper, **shared)
                    outs.append(new_p32.astype(pv.dtype))
                    out_states.append(new_st)
                    out_masters.append(new_p32 if mw is not None else None)
                return outs, out_states, out_masters

            fn = jax.jit(apply_all)
            self._jit_cache[key] = fn
        step_arr = jnp.asarray(self._step_count, jnp.float32)
        return fn(p_vals, g_vals, states, masters, lr, step_arr)

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list_flat():
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..framework import capture

        prog = capture.active()
        # minimize's parameters= narrows the optimized set (reference
        # parameter_list semantics) on any path, not just static binding
        if parameters is not None:
            self._param_groups[0]["params"] = list(parameters)
        if prog is not None:
            # static capture (program_guard): the reference appends backward +
            # update ops to the Program; here Executor.run performs
            # backward+step on the replayed loss each run() call
            if self._static_bind:
                if parameters is None and getattr(prog, "_parameters", None):
                    self._param_groups[0]["params"] = prog.all_parameters()
                if not self._param_groups[0]["params"]:
                    from ..framework.enforce import InvalidArgumentError

                    raise InvalidArgumentError(
                        "minimize() found no parameters: pass parameters= "
                        "or build the net with static.nn builders (which "
                        "register their parameters on the Program)")
            prog._train_hooks.append((loss, self))
            return None, None
        loss.backward()
        self.step()
        return None, None

    # -- persistence ----------------------------------------------------------
    def state_dict(self):
        state = {"LR_Scheduler": {}, "master_weights": {}}
        if isinstance(self._learning_rate, LRScheduler):
            state["LR_Scheduler"] = self._learning_rate.state_dict()
        for i, p in enumerate(self._parameter_list_flat()):
            name = p.name or f"param_{i}"
            acc = self._accumulators.get(id(p))
            if acc:
                for k, v in acc.items():
                    state[f"{name}_{k}"] = Tensor(v)
            if id(p) in self._master_weights:
                state["master_weights"][name] = Tensor(self._master_weights[id(p)])
        state["@step"] = self._step_count
        return state

    def set_state_dict(self, state):
        self._step_count = state.get("@step", 0)
        if isinstance(self._learning_rate, LRScheduler) and state.get("LR_Scheduler"):
            self._learning_rate.set_state_dict(state["LR_Scheduler"])
        for i, p in enumerate(self._parameter_list_flat()):
            name = p.name or f"param_{i}"
            acc = self._init_state(p)
            found = False
            for k in list(acc):
                sk = f"{name}_{k}"
                if sk in state:
                    v = state[sk]
                    acc[k] = v.value if isinstance(v, Tensor) else jnp.asarray(v)
                    found = True
            if found:
                # re-apply the ZeRO placement hook: loaded accumulators must come back
                # sharded exactly as freshly-created ones are in step()
                self._accumulators[id(p)] = self._apply_shard_fn(p, acc)
            mw = state.get("master_weights", {}).get(name)
            if mw is not None:
                self._master_weights[id(p)] = mw.value if isinstance(mw, Tensor) else mw

    load_state_dict = set_state_dict


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision)

    def _rule(self, p, g, state, lr, **kw):
        return p - lr * g, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, rescale_grad=1.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_state(self, p):
        return {"velocity": jnp.zeros(p.value.shape, jnp.float32)}

    def _hyper(self, group):
        return {"momentum": group.get("momentum", self._momentum),
                "nesterov": self._nesterov}

    def _rule(self, p, g, state, lr, momentum=0.9, nesterov=False, **kw):
        v = momentum * state["velocity"] + g
        if nesterov:
            p_new = p - lr * (g + momentum * v)
        else:
            p_new = p - lr * v
        return p_new, {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, use_multi_tensor=False, amsgrad=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._amsgrad = amsgrad

    def _init_state(self, p):
        st = {"moment1": jnp.zeros(p.value.shape, jnp.float32),
              "moment2": jnp.zeros(p.value.shape, jnp.float32)}
        if self._amsgrad:
            st["moment2_max"] = jnp.zeros(p.value.shape, jnp.float32)
        return st

    def _hyper(self, group):
        return {
            "beta1": group.get("beta1", self._beta1),
            "beta2": group.get("beta2", self._beta2),
            "eps": self._eps,
        }

    def _precompute(self, step, hyper):
        # bias corrections are functions of the STEP alone: one pair of
        # pow()s per apply, not one per parameter (GI004 duplicate-
        # subexpression burn; bit-identical — same ops, same order)
        beta1 = hyper.get("beta1", self._beta1)
        beta2 = hyper.get("beta2", self._beta2)
        return {"bias1": 1 - jnp.power(beta1, step),
                "bias2": 1 - jnp.power(beta2, step)}

    def _rule(self, p, g, state, lr, beta1=0.9, beta2=0.999, eps=1e-8, step=1.0,
              bias1=None, bias2=None, **kw):
        m = beta1 * state["moment1"] + (1 - beta1) * g
        v = beta2 * state["moment2"] + (1 - beta2) * jnp.square(g)
        mhat = m / (bias1 if bias1 is not None else 1 - jnp.power(beta1, step))
        vhat = v / (bias2 if bias2 is not None else 1 - jnp.power(beta2, step))
        new_state = {"moment1": m, "moment2": v}
        if self._amsgrad:
            vmax = jnp.maximum(state["moment2_max"], vhat)
            new_state["moment2_max"] = vmax
            vhat = vmax
        p_new = p - lr * mhat / (jnp.sqrt(vhat) + eps)
        return p_new, new_state


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False, amsgrad=False,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters, None, grad_clip,
                         lazy_mode, multi_precision, amsgrad=amsgrad)
        self._weight_decay = float(weight_decay) if weight_decay else 0.0
        self._coupled_decay = False  # decoupled
        self._apply_decay_param_fun = apply_decay_param_fun

    def _rule(self, p, g, state, lr, beta1=0.9, beta2=0.999, eps=1e-8, step=1.0, wd=0.0,
              **kw):
        p = p * (1 - lr * wd)
        # kw threads the hoisted bias1/bias2 through to Adam's rule
        return super()._rule(p, g, state, lr, beta1, beta2, eps, step=step, **kw)

    def step(self):
        # honor apply_decay_param_fun by zeroing decay for excluded params via groups
        if self._apply_decay_param_fun is not None:
            include, exclude = [], []
            for p in self._parameter_list_flat():
                (include if self._apply_decay_param_fun(p.name) else exclude).append(p)
            saved = self._param_groups
            self._param_groups = [
                {"params": include, "weight_decay": self._weight_decay},
                {"params": exclude, "weight_decay": 0.0},
            ]
            try:
                super().step()
            finally:
                self._param_groups = saved
        else:
            super().step()


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _init_state(self, p):
        return {"moment": jnp.zeros(p.value.shape, jnp.float32),
                "inf_norm": jnp.zeros(p.value.shape, jnp.float32)}

    def _hyper(self, group):
        return {"beta1": self._beta1, "beta2": self._beta2, "eps": self._eps}

    def _precompute(self, step, hyper):
        return {"bias1": 1 - jnp.power(hyper.get("beta1", self._beta1),
                                       step)}

    def _rule(self, p, g, state, lr, beta1=0.9, beta2=0.999, eps=1e-8, step=1.0,
              bias1=None, **kw):
        m = beta1 * state["moment"] + (1 - beta1) * g
        u = jnp.maximum(beta2 * state["inf_norm"], jnp.abs(g))
        bc = bias1 if bias1 is not None else 1 - jnp.power(beta1, step)
        p_new = p - lr / bc * m / (u + eps)
        return p_new, {"moment": m, "inf_norm": u}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None,
                 grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, p):
        return {"moment": jnp.full(p.value.shape, self._init_acc, jnp.float32)}

    def _hyper(self, group):
        return {"eps": self._eps}

    def _rule(self, p, g, state, lr, eps=1e-6, **kw):
        acc = state["moment"] + jnp.square(g)
        return p - lr * g / (jnp.sqrt(acc) + eps), {"moment": acc}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._eps, self._rho = epsilon, rho

    def _init_state(self, p):
        return {"avg_squared_grad": jnp.zeros(p.value.shape, jnp.float32),
                "avg_squared_update": jnp.zeros(p.value.shape, jnp.float32)}

    def _hyper(self, group):
        return {"eps": self._eps, "rho": self._rho}

    def _rule(self, p, g, state, lr, eps=1e-6, rho=0.95, **kw):
        eg = rho * state["avg_squared_grad"] + (1 - rho) * jnp.square(g)
        update = -jnp.sqrt(state["avg_squared_update"] + eps) / jnp.sqrt(eg + eps) * g
        eu = rho * state["avg_squared_update"] + (1 - rho) * jnp.square(update)
        return p + lr * update, {"avg_squared_grad": eg, "avg_squared_update": eu}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._eps, self._momentum, self._centered = rho, epsilon, momentum, centered

    def _init_state(self, p):
        return {"mean_square": jnp.zeros(p.value.shape, jnp.float32),
                "mean_grad": jnp.zeros(p.value.shape, jnp.float32),
                "momentum_acc": jnp.zeros(p.value.shape, jnp.float32)}

    def _hyper(self, group):
        return {"rho": self._rho, "eps": self._eps, "momentum": self._momentum,
                "centered": self._centered}

    def _rule(self, p, g, state, lr, rho=0.95, eps=1e-6, momentum=0.0, centered=False, **kw):
        ms = rho * state["mean_square"] + (1 - rho) * jnp.square(g)
        mg = rho * state["mean_grad"] + (1 - rho) * g if centered else state["mean_grad"]
        denom = ms - jnp.square(mg) if centered else ms
        mom = momentum * state["momentum_acc"] + lr * g / jnp.sqrt(denom + eps)
        return p - mom, {"mean_square": ms, "mean_grad": mg, "momentum_acc": mom}


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, parameters=None, grad_clip=None, exclude_from_weight_decay_fn=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn
        self._coupled_decay = False

    def _init_state(self, p):
        return {"moment1": jnp.zeros(p.value.shape, jnp.float32),
                "moment2": jnp.zeros(p.value.shape, jnp.float32)}

    def _hyper(self, group):
        return {"beta1": self._beta1, "beta2": self._beta2, "eps": self._eps,
                "lamb_wd": self._lamb_wd}

    def _rule(self, p, g, state, lr, beta1=0.9, beta2=0.999, eps=1e-6, lamb_wd=0.01,
              step=1.0, **kw):
        m = beta1 * state["moment1"] + (1 - beta1) * g
        v = beta2 * state["moment2"] + (1 - beta2) * jnp.square(g)
        mhat = m / (1 - jnp.power(beta1, step))
        vhat = v / (1 - jnp.power(beta2, step))
        r = mhat / (jnp.sqrt(vhat) + eps) + lamb_wd * p
        w_norm = jnp.linalg.norm(p)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * trust * r, {"moment1": m, "moment2": v}


class NAdam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 momentum_decay=0.004, parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._psi = momentum_decay

    def _init_state(self, p):
        return {"moment1": jnp.zeros(p.value.shape, jnp.float32),
                "moment2": jnp.zeros(p.value.shape, jnp.float32),
                "mu_product": jnp.ones((), jnp.float32)}

    def _hyper(self, group):
        return {"beta1": self._beta1, "beta2": self._beta2, "eps": self._eps,
                "psi": self._psi}

    def _rule(self, p, g, state, lr, beta1=0.9, beta2=0.999, eps=1e-8, psi=0.004, step=1.0,
              **kw):
        mu_t = beta1 * (1 - 0.5 * jnp.power(0.96, step * psi))
        mu_t1 = beta1 * (1 - 0.5 * jnp.power(0.96, (step + 1) * psi))
        mu_prod = state["mu_product"] * mu_t
        m = beta1 * state["moment1"] + (1 - beta1) * g
        v = beta2 * state["moment2"] + (1 - beta2) * jnp.square(g)
        mhat = mu_t1 * m / (1 - mu_prod * mu_t1) + (1 - mu_t) * g / (1 - mu_prod)
        vhat = v / (1 - jnp.power(beta2, step))
        return p - lr * mhat / (jnp.sqrt(vhat) + eps), {
            "moment1": m, "moment2": v, "mu_product": mu_prod
        }


class RAdam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _init_state(self, p):
        return {"moment1": jnp.zeros(p.value.shape, jnp.float32),
                "moment2": jnp.zeros(p.value.shape, jnp.float32)}

    def _hyper(self, group):
        return {"beta1": self._beta1, "beta2": self._beta2, "eps": self._eps}

    def _rule(self, p, g, state, lr, beta1=0.9, beta2=0.999, eps=1e-8, step=1.0, **kw):
        m = beta1 * state["moment1"] + (1 - beta1) * g
        v = beta2 * state["moment2"] + (1 - beta2) * jnp.square(g)
        mhat = m / (1 - jnp.power(beta1, step))
        rho_inf = 2.0 / (1 - beta2) - 1
        beta2t = jnp.power(beta2, step)
        rho_t = rho_inf - 2 * step * beta2t / (1 - beta2t)

        def rect_update():
            r = jnp.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf)
                         / ((rho_inf - 4) * (rho_inf - 2) * rho_t))
            vhat = jnp.sqrt(v / (1 - beta2t))
            return p - lr * r * mhat / (vhat + eps)

        p_new = jnp.where(rho_t > 5.0, rect_update(), p - lr * mhat)
        return p_new, {"moment1": m, "moment2": v}


class ASGD(Optimizer):
    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision)
        self._batch_num = batch_num

    def _init_state(self, p):
        return {"d": jnp.zeros(p.value.shape, jnp.float32),
                "ys": jnp.zeros((self._batch_num,) + tuple(p.value.shape), jnp.float32)}

    def _hyper(self, group):
        return {"batch_num": self._batch_num}

    def _rule(self, p, g, state, lr, batch_num=1, step=1.0, **kw):
        idx = (jnp.asarray(step, jnp.int32) - 1) % batch_num
        y_old = state["ys"][idx]
        d = state["d"] - y_old + g
        ys = state["ys"].at[idx].set(g)
        n = jnp.minimum(step, float(batch_num))
        return p - lr * d / n, {"d": d, "ys": ys}


class Rprop(Optimizer):
    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, multi_precision)
        self._lr_range = learning_rate_range
        self._etas = etas

    def _init_state(self, p):
        return {"prev_grad": jnp.zeros(p.value.shape, jnp.float32),
                "lrs": jnp.full(p.value.shape, self.get_lr(), jnp.float32)}

    def _hyper(self, group):
        return {"eta_neg": self._etas[0], "eta_pos": self._etas[1],
                "lr_min": self._lr_range[0], "lr_max": self._lr_range[1]}

    def _rule(self, p, g, state, lr, eta_neg=0.5, eta_pos=1.2, lr_min=1e-5, lr_max=50.0,
              **kw):
        sign = jnp.sign(g * state["prev_grad"])
        factor = jnp.where(sign > 0, eta_pos, jnp.where(sign < 0, eta_neg, 1.0))
        lrs = jnp.clip(state["lrs"] * factor, lr_min, lr_max)
        g_eff = jnp.where(sign < 0, 0.0, g)
        return p - lrs * jnp.sign(g_eff), {"prev_grad": g_eff, "lrs": lrs}


class LBFGS(Optimizer):
    """L-BFGS (reference: python/paddle/optimizer/lbfgs.py). Runs closure-based full-batch
    optimization; history kept on host."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None, tolerance_grad=1e-7,
                 tolerance_change=1e-9, history_size=100, line_search_fn=None,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._max_iter = max_iter
        self._hist = history_size
        self._tol_g = tolerance_grad
        self._tol_c = tolerance_change
        self._s, self._y = [], []
        self._prev_flat_g = None
        self._prev_flat_x = None

    def _flat(self, vals):
        return jnp.concatenate([v.reshape(-1).astype(jnp.float32) for v in vals])

    def _unflat(self, flat):
        outs, off = [], 0
        for p in self._parameter_list_flat():
            n = int(np.prod(p.value.shape)) if p.value.shape else 1
            outs.append(flat[off : off + n].reshape(p.value.shape).astype(p.value.dtype))
            off += n
        return outs

    def step(self, closure=None):
        if closure is None:
            raise ValueError("LBFGS.step requires a closure returning the loss")
        loss = closure()
        params = self._parameter_list_flat()
        g = self._flat([p.grad.value for p in params])
        x = self._flat([p.value for p in params])  # pre-update iterate
        if self._prev_flat_g is not None:
            s = x - self._prev_flat_x
            y = g - self._prev_flat_g
            if float(jnp.dot(s, y)) > 1e-10:
                self._s.append(s)
                self._y.append(y)
                if len(self._s) > self._hist:
                    self._s.pop(0)
                    self._y.pop(0)
        q = g
        alphas = []
        for s, y in zip(reversed(self._s), reversed(self._y)):
            rho = 1.0 / jnp.dot(y, s)
            a = rho * jnp.dot(s, q)
            q = q - a * y
            alphas.append((a, rho))
        if self._s:
            gamma = jnp.dot(self._s[-1], self._y[-1]) / jnp.dot(self._y[-1], self._y[-1])
            q = gamma * q
        for (a, rho), s, y in zip(reversed(alphas), self._s, self._y):
            b = rho * jnp.dot(y, q)
            q = q + (a - b) * s
        direction = -q
        lr = self.get_lr()
        new_x = x + lr * direction
        for p, nv in zip(params, self._unflat(new_x)):
            p._replace_value(nv)
        self._prev_flat_g = g
        self._prev_flat_x = x  # curvature pair s = x_{k+1} - x_k needs the PRE-update x
        return loss
