"""paddle.text.datasets: classic NLP benchmark dataset readers.

Reference analog: python/paddle/text/datasets/{uci_housing,imikolov,imdb}.py —
same on-disk formats (whitespace housing table, PTB-style token files, the
aclImdb tar layout) parsed from local files; downloading is disabled in this
build, so `data_file` (or the relevant path argument) is required.
"""
from __future__ import annotations

import re
import tarfile

import numpy as np

from .io import Dataset

UCI_FEATURE_NAMES = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE",
    "DIS", "RAD", "TAX", "PTRATIO", "B", "LSTAT", "convert",
]


def _no_download(cls, arg):
    raise ValueError(
        f"{cls}: automatic download is disabled in this build; pass {arg} "
        "pointing at a local copy of the dataset")


class UCIHousing(Dataset):
    """Whitespace 14-column table; features min-max/avg normalized, 80/20
    train/test split (uci_housing.py:135)."""

    def __init__(self, data_file=None, mode="train", dtype="float32"):
        if data_file is None:
            _no_download("UCIHousing", "data_file")
        self.mode = mode.lower()
        self.dtype = dtype
        data = np.fromfile(data_file, sep=" ")
        data = data.reshape(data.shape[0] // 14, 14)
        maxs, mins = data.max(axis=0), data.min(axis=0)
        avgs = data.sum(axis=0) / data.shape[0]
        for i in range(13):  # label column stays raw
            rng = maxs[i] - mins[i]
            data[:, i] = (data[:, i] - avgs[i]) / (rng if rng else 1.0)
        offset = int(data.shape[0] * 0.8)
        self.data = data[:offset] if self.mode == "train" else data[offset:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return (row[:-1].astype(self.dtype), row[-1:].astype(self.dtype))

    def __len__(self):
        return len(self.data)


class Imikolov(Dataset):
    """PTB-style n-gram dataset (imikolov.py): builds a frequency-cutoff word
    dict from the train split, yields n-gram windows of word ids."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50):
        if data_file is None:
            _no_download("Imikolov", "data_file")
        self.data_type = data_type.upper()
        self.window_size = window_size
        self.mode = mode.lower()
        self.min_word_freq = min_word_freq
        with tarfile.open(data_file, "r:*") as tf:
            names = {m.name: m for m in tf.getmembers()}

            def read(which):
                for name, m in names.items():
                    if which in name and name.endswith(".txt"):
                        return tf.extractfile(m).read().decode().splitlines()
                raise ValueError(f"no {which} split found in {data_file}")

            train_lines = read("train")
            lines = train_lines if self.mode == "train" else read("valid")
        self.word_idx = self._build_dict(train_lines)
        self.data = self._to_samples(lines)

    def _build_dict(self, lines):
        freq = {}
        for ln in lines:
            for w in ln.strip().split():
                freq[w] = freq.get(w, 0) + 1
        freq = {w: c for w, c in freq.items() if c >= self.min_word_freq}
        freq.pop("<unk>", None)
        ordered = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
        word_idx = {w: i for i, (w, _) in enumerate(ordered)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _to_samples(self, lines):
        unk = self.word_idx["<unk>"]
        out = []
        for ln in lines:
            if self.data_type == "NGRAM":
                toks = ["<s>"] + ln.strip().split() + ["<e>"]
                ids = [self.word_idx.get(w, unk) for w in toks]
                for i in range(self.window_size, len(ids) + 1):
                    out.append(np.array(ids[i - self.window_size:i], "int64"))
            else:  # SEQ
                toks = ln.strip().split()
                ids = [self.word_idx.get(w, unk) for w in toks]
                src = [self.word_idx.get("<s>", unk)] + ids
                trg = ids + [self.word_idx.get("<e>", unk)]
                out.append((np.array(src, "int64"), np.array(trg, "int64")))
        return out

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """aclImdb sentiment tarball (imdb.py): word dict from train pos/neg docs
    with frequency cutoff, samples = (ids, label) with pos=0, neg=1."""

    def __init__(self, data_file=None, mode="train", cutoff=150):
        if data_file is None:
            _no_download("Imdb", "data_file")
        self.data_file = data_file
        self.mode = mode.lower()
        self.word_idx = self._build_word_dict(cutoff)
        self.docs, self.labels = self._load_anno()

    def _tokenize(self, pattern):
        docs = []
        with tarfile.open(self.data_file, "r:*") as tf:
            for m in tf.getmembers():
                if pattern.match(m.name):
                    text = tf.extractfile(m).read().decode(errors="replace")
                    docs.append(text.rstrip("\n\r").translate(
                        str.maketrans("", "", "!\"#$%&'()*+,-./:;<=>?@[]^_`{|}~")
                    ).lower().split())
        return docs

    def _build_word_dict(self, cutoff):
        pattern = re.compile(r"aclImdb/train/((pos)|(neg))/.*\.txt$")
        freq = {}
        for doc in self._tokenize(pattern):
            for w in doc:
                freq[w] = freq.get(w, 0) + 1
        freq = {w: c for w, c in freq.items() if c > cutoff}
        ordered = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
        word_idx = {w: i for i, (w, _) in enumerate(ordered)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load_anno(self):
        unk = self.word_idx["<unk>"]
        docs, labels = [], []
        for label, tag in [(0, "pos"), (1, "neg")]:
            pattern = re.compile(rf"aclImdb/{self.mode}/{tag}/.*\.txt$")
            for doc in self._tokenize(pattern):
                docs.append(np.array(
                    [self.word_idx.get(w, unk) for w in doc], "int64"))
                labels.append(label)
        return docs, labels

    def __getitem__(self, idx):
        return self.docs[idx], np.int64(self.labels[idx])

    def __len__(self):
        return len(self.docs)


__all__ = ["UCIHousing", "Imikolov", "Imdb", "Movielens", "WMT14",
           "WMT16", "Conll05st", "MovieInfo", "UserInfo",
           "UCI_FEATURE_NAMES"]


AGE_TABLE = [1, 18, 25, 35, 45, 50, 56]


class MovieInfo:
    """movielens.py MovieInfo (id, categories, title)."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, movie_title_dict):
        return [
            [self.index],
            [categories_dict[c] for c in self.categories],
            [movie_title_dict[w.lower()] for w in self.title.split()],
        ]


class UserInfo:
    """movielens.py UserInfo (id, gender, age bucket, job)."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = AGE_TABLE.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [[self.index], [0 if self.is_male else 1], [self.age],
                [self.job_id]]


class Movielens(Dataset):
    """ml-1m zip reader (movielens.py): '::'-separated movies/users/ratings
    tables; samples = user fields + movie fields + [rating*2-5]."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0):
        import re
        import zipfile

        if data_file is None:
            _no_download("Movielens", "data_file")
        self.mode = mode.lower()
        rng = np.random.RandomState(rand_seed)
        pattern = re.compile(r"^(.*)\((\d+)\)$")
        self.movie_info, self.user_info = {}, {}
        title_words, categories = set(), set()
        with zipfile.ZipFile(data_file) as z:
            with z.open("ml-1m/movies.dat") as f:
                for line in f:
                    mid, title, cats = line.decode("latin").strip().split("::")
                    cats = cats.split("|")
                    categories.update(cats)
                    m = pattern.match(title)
                    title = m.group(1) if m else title
                    title_words.update(w.lower() for w in title.split())
                    self.movie_info[int(mid)] = MovieInfo(mid, cats, title)
            self.movie_title_dict = {w: i
                                     for i, w in enumerate(sorted(title_words))}
            self.categories_dict = {c: i
                                    for i, c in enumerate(sorted(categories))}
            with z.open("ml-1m/users.dat") as f:
                for line in f:
                    uid, gender, age, job, _ = \
                        line.decode("latin").strip().split("::")
                    self.user_info[int(uid)] = UserInfo(uid, gender, age, job)
            self.data = []
            is_test = self.mode == "test"
            with z.open("ml-1m/ratings.dat") as f:
                for line in f:
                    if (rng.random_sample() < test_ratio) != is_test:
                        continue
                    uid, mid, rating, _ = \
                        line.decode("latin").strip().split("::")
                    usr = self.user_info[int(uid)]
                    mov = self.movie_info[int(mid)]
                    self.data.append(
                        usr.value()
                        + mov.value(self.categories_dict,
                                    self.movie_title_dict)
                        + [[float(rating) * 2 - 5.0]])

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


_WMT_START, _WMT_END, _WMT_UNK_IDX = "<s>", "<e>", 2


class WMT14(Dataset):
    """wmt14.py: tarball with {mode}/{mode} tab-separated parallel text and
    src.dict/trg.dict vocab files; yields (src_ids, trg_ids, trg_ids_next)
    with <s>/<e> framing and the reference's 80-token length cap."""

    def __init__(self, data_file=None, mode="train", dict_size=30000):
        if data_file is None:
            _no_download("WMT14", "data_file")
        self.mode = mode.lower()
        self.dict_size = dict_size
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(data_file, "r:*") as tf:
            def to_dict(suffix):
                names = [m.name for m in tf.getmembers()
                         if m.name.endswith(suffix)]
                if len(names) != 1:
                    raise ValueError(f"expected one {suffix} in the archive")
                out = {}
                for i, line in enumerate(tf.extractfile(names[0])):
                    if i >= self.dict_size:
                        break
                    out[line.strip().decode()] = i
                return out

            self.src_dict = to_dict("src.dict")
            self.trg_dict = to_dict("trg.dict")
            data_names = [m.name for m in tf.getmembers()
                          if m.name.endswith(f"{self.mode}/{self.mode}")]
            for name in data_names:
                for line in tf.extractfile(name):
                    parts = line.decode().strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src = [self.src_dict.get(w, _WMT_UNK_IDX)
                           for w in [_WMT_START, *parts[0].split(), _WMT_END]]
                    trg = [self.trg_dict.get(w, _WMT_UNK_IDX)
                           for w in parts[1].split()]
                    if len(src) > 80 or len(trg) > 80:
                        continue
                    self.trg_ids_next.append(
                        np.array([*trg, self.trg_dict[_WMT_END]], "int64"))
                    self.trg_ids.append(
                        np.array([self.trg_dict[_WMT_START], *trg], "int64"))
                    self.src_ids.append(np.array(src, "int64"))

    def __getitem__(self, idx):
        return (self.src_ids[idx], self.trg_ids[idx], self.trg_ids_next[idx])

    def __len__(self):
        return len(self.src_ids)


class WMT16(WMT14):
    """wmt16.py: same sample shape; vocab built from archive dict files
    named wmt16/{src,trg}.dict (the reference builds them on first use)."""

    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en"):
        super().__init__(data_file=data_file, mode=mode,
                         dict_size=max(src_dict_size, trg_dict_size))


class Conll05st(Dataset):
    """conll05.py: semantic-role-labeling corpus; this reader consumes the
    preprocessed tarball layout (conll05st-release/{mode} files with
    word/predicate/label columns) plus word/verb/label dict files."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="train"):
        if data_file is None:
            _no_download("Conll05st", "data_file")
        self.mode = mode.lower()

        def load_dict(path):
            with open(path) as f:
                return {ln.strip(): i for i, ln in enumerate(f)
                        if ln.strip()}

        self.word_dict = load_dict(word_dict_file)
        self.verb_dict = load_dict(verb_dict_file)
        self.label_dict = load_dict(target_dict_file)
        unk = self.word_dict.get("<unk>", 0)
        self.samples = []
        # one sentence per line: "w1 w2 ... ||| verb ||| l1 l2 ..."
        with open(data_file) as f:
            for line in f:
                parts = [p.strip() for p in line.split("|||")]
                if len(parts) != 3:
                    continue
                words, verb, labels = (parts[0].split(), parts[1],
                                       parts[2].split())
                self.samples.append((
                    np.array([self.word_dict.get(w, unk) for w in words],
                             "int64"),
                    np.int64(self.verb_dict.get(verb, 0)),
                    np.array([self.label_dict.get(l, 0) for l in labels],
                             "int64"),
                ))

    def get_dict(self):
        return self.word_dict, self.verb_dict, self.label_dict

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)
