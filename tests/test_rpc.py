"""paddle.distributed.rpc: agent, sync/async invocation, worker infos.

Mirrors the reference's RPC tests (test/rpc/): multi-worker processes invoke
module-level functions on each other by worker name. Here: a 2-process run
(the real wire path — separate interpreters, pickle-by-reference resolution
through an importable module) plus single-process API-shape checks.
"""
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestSingleWorker:
    def test_self_rpc_and_infos(self):
        from paddle_tpu.distributed import rpc

        rpc.init_rpc("solo", rank=0, world_size=1)
        try:
            info = rpc.get_current_worker_info()
            assert info.name == "solo" and info.rank == 0
            assert rpc.get_all_worker_infos() == [info]
            assert rpc.get_worker_info("solo") is info
            assert rpc.rpc_sync("solo", max, args=(3, 7)) == 7
            fut = rpc.rpc_async("solo", pow, args=(2, 10))
            assert fut.wait() == 1024
        finally:
            rpc.shutdown()

    def test_remote_exception_propagates(self):
        from paddle_tpu.distributed import rpc

        rpc.init_rpc("solo2", rank=0, world_size=1)
        try:
            with pytest.raises(ZeroDivisionError):
                rpc.rpc_sync("solo2", divmod, args=(1, 0))
        finally:
            rpc.shutdown()


_WORKER = """
import os, sys
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import rpc_helpers  # the shared module remote fns resolve through
from paddle_tpu.distributed import rpc

rank = int(os.environ["PADDLE_TRAINER_ID"])
rpc.init_rpc(f"worker{rank}", rank=rank, world_size=2,
             master_endpoint=os.environ["PADDLE_MASTER"])
if rank == 0:
    # sync call runs on worker1's interpreter (its pid differs)
    remote_pid = rpc.rpc_sync("worker1", rpc_helpers.get_pid)
    assert remote_pid != os.getpid()
    assert rpc.rpc_sync("worker1", rpc_helpers.add, args=(2, 3)) == 5
    futs = [rpc.rpc_async("worker1", rpc_helpers.add, args=(i, i))
            for i in range(8)]
    assert [f.wait() for f in futs] == [2 * i for i in range(8)]
    # remote state mutation sticks between calls
    rpc.rpc_sync("worker1", rpc_helpers.set_value, args=(42,))
    assert rpc.rpc_sync("worker1", rpc_helpers.get_value) == 42
    infos = rpc.get_all_worker_infos()
    assert [w.name for w in infos] == ["worker0", "worker1"]
    print("RPC_OK", flush=True)
rpc.shutdown()
"""

_HELPERS = """
import os

_VALUE = [None]

def get_pid():
    return os.getpid()

def add(a, b):
    return a + b

def set_value(v):
    _VALUE[0] = v

def get_value():
    return _VALUE[0]
"""


class TestTwoProcess:
    def test_cross_process_rpc(self, tmp_path):
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        (tmp_path / "rpc_helpers.py").write_text(_HELPERS)
        (tmp_path / "worker.py").write_text(_WORKER)
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.update({
            "PADDLE_MASTER": f"127.0.0.1:{port}",
            "PADDLE_TPU_PLATFORM": "cpu",
            "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        })
        procs = []
        try:
            for rank in range(2):
                procs.append(subprocess.Popen(
                    [sys.executable, str(tmp_path / "worker.py")],
                    env=dict(env, PADDLE_TRAINER_ID=str(rank)),
                    cwd=str(tmp_path), stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT, text=True))
            out0, _ = procs[0].communicate(timeout=300)
            out1, _ = procs[1].communicate(timeout=300)
            assert procs[0].returncode == 0, out0
            assert procs[1].returncode == 0, out1
            assert "RPC_OK" in out0
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
