"""Wiring: a graftpilot controller over a live serving fleet.

:func:`fleet_telemetry` builds the controller's ``telemetry_fn`` — ONE
host-readable snapshot per tick over a
:class:`~paddle_tpu.serving.fleet.FleetRouter`:

- replica counts + aggregate queue depth (``replica_snapshot`` rows);
- arrival rate and TTFT quantiles from the router's rolling
  ``recent_arrivals`` / ``recent_ttft_ms`` deques (host counters —
  present with the monitor off);
- the /perfz queue-wait component (``timeline.ttft_decomposition`` p50)
  when tracing is on, refreshed at most every ``perf_interval_s``;
- SLO burn state (max fast burn + the alerting series) when the fleet
  wired a tracker;
- the GI003 live HBM estimate via ``hbm_fn`` when provided.

Every value is JSON-able: the snapshot goes into the decision record
verbatim, which is what makes a recorded run replayable offline.

:func:`build_serving_controller` binds the declared knobs to their real
setters (``scale_to``, ``hedge_after_s``, ``set_engine_knobs``) and
assembles the default rule set (``rules.serving_rules``).
"""
from __future__ import annotations

import time

from .controller import Controller
from .knobs import Knob
from .rules import serving_rules

__all__ = ["fleet_telemetry", "build_serving_controller", "quantile"]


def quantile(values, q):
    """Nearest-rank quantile of a sequence (None when empty)."""
    vals = sorted(values)
    if not vals:
        return None
    idx = min(len(vals) - 1, max(0, int(round(q * (len(vals) - 1)))))
    return float(vals[idx])


def fleet_telemetry(fleet, *, window_s=5.0, perf_interval_s=0.5,
                    hbm_fn=None, now_fn=None):
    """Build a ``telemetry_fn`` over ``fleet`` (see module docstring).

    ``hbm_fn`` (optional) returns ``(live_bytes, budget_bytes)`` — e.g.
    the GI003 estimate of the engine's step program against the
    declared ``hbm_budget`` — feeding the HBM-pressure guard.
    """
    now = now_fn if now_fn is not None else time.monotonic
    cache = {"perf_t": None, "queue_wait_ms": None}

    def collect():
        t = float(now())
        rows = fleet.replica_snapshot()
        active = fleet.active_replicas()
        depth = sum(int(r["inflight"]) for r in rows)
        arrivals = list(fleet.recent_arrivals)
        horizon = time.monotonic() - float(window_s)
        rate = sum(1 for a in arrivals if a >= horizon) / float(window_s)
        ttfts = list(fleet.recent_ttft_ms)
        snap = {
            "t": t,
            "replicas_total": len(rows),
            "replicas_active": active,
            "queue_depth": depth,
            "arrival_rate_rps": round(rate, 4),
            "ttft_p50_ms": quantile(ttfts, 0.50),
            "ttft_p95_ms": quantile(ttfts, 0.95),
            "queue_wait_ms": cache["queue_wait_ms"],
            "burn_fast_max": None,
            "slo_alerting": [],
            "hbm_live_bytes": None,
            "hbm_budget_bytes": None,
        }
        from ..monitor import timeline as _timeline
        from ..monitor import trace as _trace

        if _trace._state.on and (cache["perf_t"] is None
                                 or t - cache["perf_t"]
                                 >= float(perf_interval_s)):
            cache["perf_t"] = t
            try:
                dec = _timeline.ttft_decomposition(
                    _trace.span_dump(tail=2048)["spans"])
                if dec["requests"]:
                    cache["queue_wait_ms"] = dec["p50_ms"]["queue_wait_ms"]
            except Exception:  # noqa: BLE001 - analytics never fail a tick
                pass
            snap["queue_wait_ms"] = cache["queue_wait_ms"]
        slo = getattr(fleet, "_slo", None)
        if slo is not None:
            scan = slo.scan(min_interval_s=min(1.0, float(window_s)))
            agg = [r["fast_burn"] for r in scan if not r["tenant"]]
            snap["burn_fast_max"] = round(max(agg), 4) if agg else 0.0
            snap["slo_alerting"] = sorted(
                (f'{r["objective"]}/{r["tenant"]}' if r["tenant"]
                 else r["objective"])
                for r in scan if r["alerting"])
        if hbm_fn is not None:
            try:
                live, budget = hbm_fn()
                snap["hbm_live_bytes"] = None if live is None \
                    else int(live)
                snap["hbm_budget_bytes"] = None if budget is None \
                    else int(budget)
            except Exception:  # noqa: BLE001 - a failing estimator
                pass           # holds the guard, never kills the tick
        return snap

    return collect


def build_serving_controller(fleet, *, rules=None, interval_s=0.25,
                             window_s=5.0, perf_interval_s=0.5,
                             hbm_fn=None, replan=None, now_fn=None,
                             drain_timeout=10.0, register=True,
                             **controller_kw):
    """A :class:`~paddle_tpu.control.controller.Controller` actuating a
    live :class:`~paddle_tpu.serving.fleet.FleetRouter`:

    - ``fleet.replicas`` -> :meth:`FleetRouter.scale_to` (lossless
      drain/resume);
    - ``fleet.hedge_after_s`` -> the router's public hedging threshold;
    - ``engine.chunk_size`` / ``engine.decode_burst`` /
      ``engine.max_queue`` -> staged on every replica engine via
      :meth:`FleetRouter.set_engine_knobs`, applied at step boundaries.

    ``replan`` (optional) is the HBM guard's budget-remat hook
    (``analysis.jaxpr.planner.make_replan_hook``). The controller is
    returned STOPPED — call ``.start()`` to run the loop, or drive
    ``.tick()`` yourself (the bench does).
    """
    eng = fleet.replicas[0].engine
    hedge0 = fleet.hedge_after_s if fleet.hedge_after_s is not None \
        else 30.0

    def set_hedge(v):
        fleet.hedge_after_s = float(v)

    knobs = [
        Knob("fleet.replicas", fleet.active_replicas(),
             setter=lambda v: fleet.scale_to(v,
                                             drain_timeout=drain_timeout)),
        Knob("fleet.hedge_after_s", hedge0, setter=set_hedge),
        Knob("engine.chunk_size", eng.chunk_size,
             setter=lambda v: fleet.set_engine_knobs(chunk_size=v)),
        Knob("engine.decode_burst", eng.decode_burst,
             setter=lambda v: fleet.set_engine_knobs(decode_burst=v)),
        Knob("engine.max_queue",
             eng.max_queue if eng.max_queue is not None else 4096,
             setter=lambda v: fleet.set_engine_knobs(max_queue=v)),
    ]
    hooks = {} if replan is None else {"replan": replan}
    return Controller(
        rules if rules is not None else serving_rules(),
        knobs,
        telemetry_fn=fleet_telemetry(fleet, window_s=window_s,
                                     perf_interval_s=perf_interval_s,
                                     hbm_fn=hbm_fn, now_fn=now_fn),
        interval_s=interval_s, now_fn=now_fn, hooks=hooks,
        register=register, **controller_kw)
