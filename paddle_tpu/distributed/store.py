"""TCPStore: the bootstrap key-value store for multi-process rendezvous.

Reference analog: paddle/phi/core/distributed/store/tcp_store.cc (master/client KV
with blocking waits and counter-barriers; pybind at fluid/pybind/communication.cc:124).

TPU-first note: the *collectives* never go through this store — they ride XLA's
ICI/DCN collectives inside compiled programs. The store exists for what sits around
them: rank rendezvous before `jax.distributed.initialize`, exchanging the
coordinator address, cross-process barriers in tests and the launcher's health
bookkeeping. Implementation is a small length-prefixed binary protocol over TCP
(master holds a dict; clients block on waits), stdlib-only.

Protocol: 1-byte command, then length-prefixed key/value byte strings.
Commands: SET, GET (blocking), ADD (atomic add, returns new value), WAIT (block
until key exists), DELETE, NUM_KEYS.
"""
from __future__ import annotations

import os
import socket
import struct
import threading
import time

_CMD_SET = 0
_CMD_GET = 1
_CMD_ADD = 2
_CMD_WAIT = 3
_CMD_DELETE = 4
_CMD_NUM_KEYS = 5


def _send_bytes(sock, data: bytes):
    sock.sendall(struct.pack("<I", len(data)) + data)


def _recv_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("TCPStore peer closed")
        buf += chunk
    return buf


def _recv_bytes(sock) -> bytes:
    (n,) = struct.unpack("<I", _recv_exact(sock, 4))
    return _recv_exact(sock, n)


class _MasterDaemon(threading.Thread):
    """Serves the KV dict; one handler thread per client connection."""

    def __init__(self, host, port):
        super().__init__(daemon=True)
        self._kv = {}
        self._cv = threading.Condition()
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen(128)
        self.port = self._server.getsockname()[1]
        self._stop = False

    def run(self):
        while not self._stop:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                cmd = _recv_exact(conn, 1)[0]
                if cmd == _CMD_SET:
                    key = _recv_bytes(conn)
                    val = _recv_bytes(conn)
                    with self._cv:
                        self._kv[key] = val
                        self._cv.notify_all()
                    _send_bytes(conn, b"ok")
                elif cmd in (_CMD_GET, _CMD_WAIT):
                    key = _recv_bytes(conn)
                    (timeout_ms,) = struct.unpack("<I", _recv_exact(conn, 4))
                    deadline = time.monotonic() + timeout_ms / 1000.0
                    with self._cv:
                        while key not in self._kv:
                            remaining = deadline - time.monotonic()
                            if remaining <= 0:
                                break
                            self._cv.wait(remaining)
                        if key not in self._kv:
                            conn.sendall(b"\x01")
                        else:
                            conn.sendall(b"\x00")
                            _send_bytes(
                                conn,
                                b"" if cmd == _CMD_WAIT else self._kv[key])
                elif cmd == _CMD_ADD:
                    key = _recv_bytes(conn)
                    (delta,) = struct.unpack("<q", _recv_exact(conn, 8))
                    with self._cv:
                        cur = int(self._kv.get(key, b"0"))
                        cur += delta
                        self._kv[key] = str(cur).encode()
                        self._cv.notify_all()
                    conn.sendall(struct.pack("<q", cur))
                elif cmd == _CMD_DELETE:
                    key = _recv_bytes(conn)
                    with self._cv:
                        existed = self._kv.pop(key, None) is not None
                    conn.sendall(b"\x01" if existed else b"\x00")
                elif cmd == _CMD_NUM_KEYS:
                    with self._cv:
                        n = len(self._kv)
                    conn.sendall(struct.pack("<q", n))
                else:
                    raise ValueError(f"bad TCPStore command {cmd}")
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def shutdown(self):
        self._stop = True
        try:
            self._server.close()
        except OSError:
            pass


class TCPStore:
    """Client (and optionally host) of the rendezvous KV store.

    Matches the reference constructor shape (tcp_store.cc / communication.cc:124):
    ``TCPStore(host, port, is_master, world_size, timeout)``.
    """

    def __init__(self, host="127.0.0.1", port=0, is_master=False, world_size=1,
                 timeout=900):
        self.host = host
        self.is_master = is_master
        self.world_size = world_size
        self.timeout = timeout
        self._daemon = None
        if is_master:
            self._daemon = _MasterDaemon(host if host else "0.0.0.0", port)
            self._daemon.start()
            port = self._daemon.port
        self.port = port
        self._sock = None
        self._lock = threading.Lock()
        self._connect()

    def _connect(self):
        deadline = time.monotonic() + self.timeout
        last_err = None
        while time.monotonic() < deadline:
            try:
                self._sock = socket.create_connection((self.host, self.port),
                                                      timeout=self.timeout)
                self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                return
            except OSError as e:  # master not up yet
                last_err = e
                time.sleep(0.05)
        raise ConnectionError(
            f"TCPStore could not reach master at {self.host}:{self.port}: {last_err}")

    # -- KV API (reference: Store::set/get/add/wait) -------------------------
    def set(self, key: str, value):
        if isinstance(value, str):
            value = value.encode()
        with self._lock:
            self._sock.sendall(bytes([_CMD_SET]))
            _send_bytes(self._sock, key.encode())
            _send_bytes(self._sock, bytes(value))
            _recv_bytes(self._sock)

    def _blocking_request(self, cmd, key, timeout):
        """GET/WAIT block server-side until the key exists; run them on their own
        connection so a waiting thread doesn't hold the shared socket's lock and
        deadlock a concurrent set() from another thread of this process."""
        t = int((timeout if timeout is not None else self.timeout) * 1000)
        sock = socket.create_connection((self.host, self.port),
                                        timeout=max(t / 1000.0 + 5.0, 10.0))
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.sendall(bytes([cmd]))
            _send_bytes(sock, key.encode())
            sock.sendall(struct.pack("<I", t))
            status = _recv_exact(sock, 1)
            if status == b"\x01":
                op = "get" if cmd == _CMD_GET else "wait"
                raise TimeoutError(f"TCPStore.{op}({key!r}) timed out")
            return _recv_bytes(sock)
        finally:
            sock.close()

    def get(self, key: str, timeout=None) -> bytes:
        return self._blocking_request(_CMD_GET, key, timeout)

    def add(self, key: str, delta: int) -> int:
        with self._lock:
            self._sock.sendall(bytes([_CMD_ADD]))
            _send_bytes(self._sock, key.encode())
            self._sock.sendall(struct.pack("<q", int(delta)))
            (val,) = struct.unpack("<q", _recv_exact(self._sock, 8))
            return val

    def wait(self, key: str, timeout=None):
        self._blocking_request(_CMD_WAIT, key, timeout)

    def delete_key(self, key: str) -> bool:
        with self._lock:
            self._sock.sendall(bytes([_CMD_DELETE]))
            _send_bytes(self._sock, key.encode())
            return _recv_exact(self._sock, 1) == b"\x01"

    def num_keys(self) -> int:
        with self._lock:
            self._sock.sendall(bytes([_CMD_NUM_KEYS]))
            (val,) = struct.unpack("<q", _recv_exact(self._sock, 8))
            return val

    def barrier(self, name="_barrier", timeout=None):
        """Counter barrier over all world_size participants."""
        arrived = self.add(f"{name}/count", 1)
        round_key = f"{name}/release/{(arrived - 1) // self.world_size}"
        if arrived % self.world_size == 0:
            self.set(round_key, b"1")
        self.wait(round_key, timeout=timeout)

    def shutdown(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        if self._daemon is not None:
            self._daemon.shutdown()
            self._daemon = None


_GLOBAL_STORE = [None]


def create_or_get_global_tcp_store():
    """Build the process-global store from launcher env vars
    (reference: parallel.py:1134 core.create_or_get_global_tcp_store)."""
    if _GLOBAL_STORE[0] is not None:
        return _GLOBAL_STORE[0]
    # the early bootstrap (paddle_tpu._bootstrap) may already hold the store —
    # it loads this file as a shadow module before the package is importable,
    # and a second master would fail to bind the listening rendezvous port
    try:
        from paddle_tpu._bootstrap import _STORE

        if _STORE[0] is not None:
            _GLOBAL_STORE[0] = _STORE[0]
            return _GLOBAL_STORE[0]
    except ImportError:
        pass
    master = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ADDR", "127.0.0.1")
    if ":" in master:
        host, port = master.rsplit(":", 1)
        port = int(port)
    else:
        host, port = master, int(os.environ.get("MASTER_PORT", "6170"))
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    _GLOBAL_STORE[0] = TCPStore(host, port, is_master=(rank == 0),
                                world_size=world,
                                timeout=float(os.environ.get("PADDLE_STORE_TIMEOUT", "900")))
    return _GLOBAL_STORE[0]
