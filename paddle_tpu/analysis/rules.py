"""graftlint rules GL001–GL011: framework-aware static checks.

Each rule encodes one invariant the runtime cannot cheaply enforce —
trace purity, host-sync hygiene, registry/doc consistency, lock
discipline, metric-name contract, span-name contract, lock-order
consistency, recompile hygiene, mutable-global capture, unguarded
shared state, guarded-by consistency — as a pure AST/text check. Rules
receive
the whole :class:`~paddle_tpu.analysis.core.Project` so cross-file rules
(GL003, GL005, GL006) see registrations and their catalogs together, and
the interprocedural rules (GL001/GL002/GL004 propagation, GL007, GL008,
and the GL010/GL011 lockset analysis in
:mod:`~paddle_tpu.analysis.locksets`) share one
:class:`~paddle_tpu.analysis.callgraph.CallGraph` per run via
``project.callgraph()``.

The rationale for each rule lives in docs/static_analysis.md; the short
form is on the rule class.
"""
from __future__ import annotations

import ast
import re

from .core import Finding, dotted_name


class Rule:
    id = "GL000"
    name = "base"
    rationale = ""

    def check(self, project):
        raise NotImplementedError

    def finding(self, srcfile, node, message, chain=()):
        return Finding(self.id, srcfile.relpath,
                       getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0),
                       message, scope=srcfile.scope_of(node), chain=chain)

    def strict_problems(self, project, findings=None):
        """Aggregator semantics (tools/run_static_checks.py): this one rule
        with NO baseline, inline suppressions honored. Pass ``findings`` to
        reuse an existing engine run."""
        from .core import partition, run

        if findings is None:
            findings = run(project, [self])
        else:
            findings = [f for f in findings if f.rule == self.id]
        new, _base, _supp = partition(project, findings, ())
        return [f"{f.path}:{f.line}: {f.message}" for f in new]


def _contains(node, pred):
    return any(pred(n) for n in ast.walk(node))


def _decorator_tag(dec):
    """'to_static' / 'defop' / 'jit' when the decorator compiles the body
    into a traced program, else None. Handles bare names, dotted paths,
    parameterized forms (@to_static(...)), and functools.partial(jax.jit)."""
    if isinstance(dec, ast.Call):
        fn = dotted_name(dec.func)
        if fn and fn.rsplit(".", 1)[-1] == "partial" and dec.args:
            return _decorator_tag(dec.args[0])
        dec = dec.func
    name = dotted_name(dec)
    if name is None:
        return None
    last = name.rsplit(".", 1)[-1]
    if last == "to_static" or last.endswith("defop"):
        return last if last == "to_static" else "defop"
    if name in ("jax.jit", "jit") or name.endswith(".jax.jit"):
        return "jit"
    return None


class TraceImpurity(Rule):
    """GL001: host-impure calls inside traced function bodies.

    A function compiled by ``to_static``/``defop``/``jax.jit`` runs its
    Python body ONCE, at trace time (jit/api.py:32 graph-break contract):
    ``time.time()``, ``datetime.now()``, ``np.random.*`` and file I/O
    evaluate to one concrete value that is then baked into the compiled
    program for every later call — a silent wrong-result bug, not a crash.
    Use ``monitor.now_ns`` outside the traced region for timing and the
    framework RNG (``paddle.seed`` / keyed ``jax.random``) for randomness.
    """

    id = "GL001"
    name = "trace-impurity"
    rationale = ("impure host calls in traced bodies run once and bake "
                 "their value into the compiled program")

    IMPURE_EXACT = {
        "time.time", "time.time_ns", "time.perf_counter",
        "time.perf_counter_ns", "time.monotonic", "time.monotonic_ns",
        "datetime.now", "datetime.utcnow", "datetime.datetime.now",
        "datetime.datetime.utcnow", "os.urandom", "uuid.uuid4",
        "open", "input",
    }
    IMPURE_PREFIX = ("np.random.", "numpy.random.", "random.")

    def _impure(self, call):
        name = dotted_name(call.func)
        if name is None:
            return None
        if name in self.IMPURE_EXACT:
            return name
        for p in self.IMPURE_PREFIX:
            if name.startswith(p):
                return name
        return None

    @staticmethod
    def _traced_functions(srcfile):
        """{FunctionDef: tag} for every function the file compiles into a
        traced program — decorator form (@to_static/@defop/@jax.jit) AND
        call form (``jax.jit(run, ...)`` / ``to_static(fn)``), which is
        how the serving engine builds its cached programs. Call-form
        targets resolve to the def with the same name in the same
        enclosing scope (two methods may each define a local ``run``).
        Memoized per file: three rules (GL001, GL002 interproc, GL008)
        share one computation."""
        memo = getattr(srcfile, "_traced_functions_memo", None)
        if memo is not None:
            return memo
        traced = {}
        defs = {}
        for n in srcfile.walk():
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault((n.name, srcfile.scope_of(n)), []).append(n)
                tags = [t for t in map(_decorator_tag, n.decorator_list)
                        if t]
                if tags:
                    traced.setdefault(n, tags[0])
        for call in srcfile.walk():
            if not isinstance(call, ast.Call) or not call.args:
                continue
            tag = _decorator_tag(call)
            arg = call.args[0]
            if tag and isinstance(arg, ast.Name):
                cands = defs.get((arg.id, srcfile.scope_of(call)), ())
                if len(cands) == 1:
                    traced.setdefault(cands[0], tag)
        srcfile._traced_functions_memo = traced
        return traced

    def check(self, project):
        out = []
        cg = project.callgraph()
        for f in project.files:
            if f.tree is None:
                continue
            for fn, tag in self._traced_functions(f).items():
                for call in ast.walk(fn):
                    if not isinstance(call, ast.Call):
                        continue
                    name = self._impure(call)
                    if name:
                        out.append(self.finding(
                            f, call,
                            f"trace-impure call {name}() inside "
                            f"@{tag} function '{fn.name}': evaluated "
                            "once at trace time and baked into the "
                            "compiled program"))
                        continue
                    # interprocedural: the impurity hides behind a helper
                    fi = cg.info_for_node(fn)
                    if fi is None:
                        continue
                    tgt = cg.resolve(f, fi.qualname, call)
                    if tgt is None or tgt == fi.key:
                        continue
                    entry = cg.callee_summary(tgt, "impure")
                    if entry is None:
                        continue
                    eff = entry[0]
                    via = " -> ".join(cg.chain_names(tgt, "impure"))
                    out.append(self.finding(
                        f, call,
                        f"call into trace-impure helper reaches "
                        f"{eff.detail} (via {via}) inside @{tag} "
                        f"function '{fn.name}': evaluated once at trace "
                        "time and baked into the compiled program",
                        chain=cg.chain(tgt, "impure")))
        return out


class HostSync(Rule):
    """GL002: device→host syncs in the dispatch/serving hot paths.

    ``.item()`` / ``.numpy()`` / ``float(jnp...)`` / ``np.asarray(jnp...)``
    each block until the device value materializes on host — one hidden
    round-trip per call, which serializes the async dispatch pipeline when
    it sits in an op wrapper or a decode loop. The documented exception is
    the API-normalization idiom guarded by ``isinstance(x, Tensor)`` /
    ``hasattr(x, "numpy")`` (Tensor-valued shape/axis arguments are a
    graph-break point by contract, jit/api.py:32).
    """

    id = "GL002"
    name = "host-sync-in-hot-path"
    rationale = ("each host read blocks the async device pipeline; hot "
                 "paths must batch or hoist them")

    SCOPES = ("paddle_tpu/ops/", "paddle_tpu/models/")
    CASTS = {"float", "int", "bool"}
    NP_COPIES = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
    # dtype/shape introspection runs on host metadata — no device value is
    # ever materialized, so casting these is not a sync
    METADATA = {"jnp.issubdtype", "jnp.promote_types", "jnp.result_type",
                "jnp.iinfo", "jnp.finfo", "jnp.dtype", "jnp.ndim",
                "jnp.shape"}
    METADATA_PREFIX = ("jax.tree_util.", "jax.errors.")

    @staticmethod
    def _is_guard_call(n):
        if not isinstance(n, ast.Call):
            return False
        fname = dotted_name(n.func)
        if fname == "isinstance" and len(n.args) == 2:
            return _contains(
                n.args[1],
                lambda m: (isinstance(m, ast.Name)
                           and m.id in ("Tensor", "ndarray"))
                or (isinstance(m, ast.Attribute)
                    and m.attr in ("Tensor", "ndarray")))
        if fname in ("hasattr", "getattr") and len(n.args) >= 2:
            arg = n.args[1]
            return (isinstance(arg, ast.Constant)
                    and arg.value in ("numpy", "value", "item"))
        return False

    @classmethod
    def _guard_polarity(cls, test):
        """True when the test asserts the guard (``isinstance(x, Tensor)``
        → the BODY branch is the guarded one), False when negated
        (``not isinstance(...)`` → the ORELSE branch is), None when the
        test is no guard at all."""
        for n in ast.walk(test):
            if cls._is_guard_call(n):
                negs = sum(1 for m in ast.walk(test)
                           if isinstance(m, ast.UnaryOp)
                           and isinstance(m.op, ast.Not)
                           and _contains(m.operand, cls._is_guard_call))
                return negs % 2 == 0
        return None

    def _guarded(self, srcfile, node):
        """True when `node` sits in the branch an isinstance/hasattr guard
        actually selects — a sync in the OTHER branch (the else of
        ``if isinstance(x, Tensor):``) is exactly the unguarded case."""
        child = node
        for anc in srcfile.ancestors(node):
            if isinstance(anc, (ast.If, ast.IfExp)):
                polarity = self._guard_polarity(anc.test)
                if polarity is not None:
                    branch = anc.body if polarity else anc.orelse
                    nodes = branch if isinstance(branch, list) else [branch]
                    if any(child is b for b in nodes):
                        return True
            child = anc
        return False

    @classmethod
    def _has_device_expr(cls, node):
        def pred(n):
            if isinstance(n, ast.Call):
                name = dotted_name(n.func)
                if name and (name.startswith("jnp.")
                             or name.startswith("jax.")) \
                        and name not in cls.METADATA \
                        and not name.startswith(cls.METADATA_PREFIX):
                    return True
            return False

        return _contains(node, pred)

    def check(self, project):
        out = []
        for f in project.files:
            if f.tree is None or not f.relpath.startswith(self.SCOPES):
                continue
            for call in f.walk():
                if not isinstance(call, ast.Call):
                    continue
                msg = self._classify(f, call)
                if msg:
                    out.append(self.finding(f, call, msg))
        out.extend(self._interprocedural(project))
        return out

    def _interprocedural(self, project):
        """Syncs hiding behind helper calls. Two propagation surfaces:

        1. a hot-path function (``SCOPES``) calling a helper OUTSIDE the
           hot-path scopes whose body (transitively) host-syncs — the sync
           site itself is not directly flagged, so the call site is;
        2. a traced (``to_static``/``defop``/``jit``) body calling a
           syncing helper anywhere — a host read under the trace is a
           concretization error at runtime; the lint catches it at review
           time.

        Suppressed or isinstance-guarded syncs never propagate (the
        callgraph drops them at effect collection)."""
        cg = project.callgraph()
        out = []
        seen = set()

        def emit(f, call, tgt, context):
            entry = cg.callee_summary(tgt, "hostsync")
            if entry is None:
                return
            key = (f.relpath, call.lineno, call.col_offset, tgt)
            if key in seen:
                return
            seen.add(key)
            eff = entry[0]
            via = " -> ".join(cg.chain_names(tgt, "hostsync"))
            out.append(self.finding(
                f, call,
                f"call into host-syncing helper reaches {eff.detail} "
                f"(via {via}) {context}",
                chain=cg.chain(tgt, "hostsync")))

        for fi in cg.functions.values():
            if not fi.path.startswith(self.SCOPES):
                continue
            f = fi.srcfile
            for (call, tgt, _disp) in fi.calls:
                if tgt is None or tgt == fi.key:
                    continue
                if cg.functions[tgt].path.startswith(self.SCOPES):
                    continue    # the sync site is directly flagged there
                if self._classify(f, call) or self._guarded(f, call):
                    continue
                emit(f, call, tgt,
                     "in a hot path; hoist the read out or keep the "
                     "reduction on device")

        from .callgraph import body_walk

        for f in project.files:
            if f.tree is None:
                continue
            for fn, tag in TraceImpurity._traced_functions(f).items():
                fi = cg.info_for_node(fn)
                if fi is None:
                    continue
                for call in body_walk(fn):
                    if not isinstance(call, ast.Call):
                        continue
                    tgt = cg.resolve(f, fi.qualname, call)
                    if tgt is None or tgt == fi.key:
                        continue
                    emit(f, call, tgt,
                         f"inside @{tag} function '{fn.name}': a host "
                         "read under the trace is a concretization "
                         "error; hoist it out of the compiled region")
        return out

    def _classify(self, srcfile, call):
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in ("item", "numpy"):
            # .numpy().item(): one sync, one finding (at the .numpy())
            recv = call.func.value
            if isinstance(recv, ast.Call) \
                    and isinstance(recv.func, ast.Attribute) \
                    and recv.func.attr == "numpy":
                return None
            if self._guarded(srcfile, call):
                return None
            return (f".{call.func.attr}() forces a device→host sync in a "
                    "hot path; hoist it out of the loop or guard it with "
                    "the isinstance(x, Tensor) normalization idiom")
        name = dotted_name(call.func)
        if name in self.CASTS and len(call.args) == 1 \
                and self._has_device_expr(call.args[0]) \
                and not self._guarded(srcfile, call):
            return (f"{name}(<device expr>) concretizes a jax value on "
                    "host (hidden sync); keep the reduction on device or "
                    "hoist the read out of the hot path")
        if name in self.NP_COPIES and call.args \
                and self._has_device_expr(call.args[0]) \
                and not self._guarded(srcfile, call):
            return (f"{name}(<device expr>) copies a device value to host "
                    "(hidden sync); compute it inside the compiled program "
                    "and transfer only the result")
        return None


class RegistryConsistency(Rule):
    """GL003: the defop registry, docs/ops.md, and AMP metadata agree.

    ``defop`` registrations ARE the op registry (ops/_apply.py:429);
    docs/ops.md is its generated, reviewed rendering. An op registered in
    source but absent from the doc (or carrying a different AMP category)
    means the doc — which the AMP auto-cast policy and reviewers read — is
    stale. Dynamic registrations (f-string names) make the reverse
    direction undecidable statically, so stale-row checks only run on
    trees with fully-literal registration.
    """

    id = "GL003"
    name = "registry-consistency"
    rationale = ("docs/ops.md and AMP categories must track the defop "
                 "registry or reviewers act on stale op metadata")

    AMP_CATEGORIES = {"white", "black", "fp32"}
    DOC = "docs/ops.md"
    _ROW = re.compile(r"^\|\s*`([^`]+)`\s*\|")
    _COUNT = re.compile(r"^(\d+) ops registered")

    @staticmethod
    def _reg_call(call):
        """(kind, name_node) for defop/register_op calls; plumbing
        (the generic call inside the defop/register_op definitions) is
        excluded by the caller via scope."""
        name = dotted_name(call.func)
        if name is None:
            return None
        last = name.rsplit(".", 1)[-1]
        if last.endswith("defop") or last == "register_op":
            return last
        return None

    def check(self, project):
        doc_text = project.read_optional(self.DOC)
        if doc_text is None:
            return []
        doc_rows, doc_count, count_line = self._parse_doc(doc_text)

        regs = []        # (srcfile, call, name, amp or None, amp_known)
        dynamic = []
        for f in project.files:
            if f.tree is None:
                continue
            for call in f.walk():
                if not isinstance(call, ast.Call) or not self._reg_call(call):
                    continue
                scope = f.scope_of(call)
                if scope.rsplit(".", 1)[-1] in ("defop", "register_op",
                                                "deco"):
                    continue  # the registry plumbing itself
                if not call.args or not isinstance(call.args[0], ast.Constant) \
                        or not isinstance(call.args[0].value, str):
                    dynamic.append((f, call))
                    continue
                amp, amp_known = None, True
                for kw in call.keywords:
                    if kw.arg == "amp_category":
                        if isinstance(kw.value, ast.Constant):
                            amp = kw.value.value
                        else:
                            amp_known = False
                regs.append((f, call, call.args[0].value, amp, amp_known))

        out = []
        seen = {}
        for f, call, name, amp, amp_known in regs:
            if name in seen:
                out.append(self.finding(
                    f, call,
                    f"op '{name}' registered twice (also at "
                    f"{seen[name]}); the registry is a name-keyed "
                    "contract, the second registration silently wins"))
            else:
                seen[name] = f"{f.relpath}:{call.lineno}"
            if amp is not None and amp not in self.AMP_CATEGORIES:
                out.append(self.finding(
                    f, call,
                    f"op '{name}' has unknown amp_category {amp!r} "
                    f"(expected one of {sorted(self.AMP_CATEGORIES)})"))
            if name not in doc_rows:
                out.append(self.finding(
                    f, call,
                    f"op '{name}' registered here but has no row in "
                    f"{self.DOC} — regenerate it with "
                    "`python -m paddle_tpu.ops.optable`"))
            elif amp_known and (amp or "-") != doc_rows[name][1]:
                out.append(self.finding(
                    f, call,
                    f"op '{name}' amp_category={(amp or '-')!r} here but "
                    f"{self.DOC} says {doc_rows[name][1]!r} — stale doc, "
                    "regenerate it"))
        if not dynamic:
            for name, (line, _amp) in sorted(doc_rows.items()):
                if name not in seen:
                    out.append(Finding(
                        self.id, self.DOC, line, 0,
                        f"doc row for op '{name}' has no registration in "
                        "the source tree — stale doc, regenerate it"))
        if doc_count is not None and doc_count != len(doc_rows):
            out.append(Finding(
                self.id, self.DOC, count_line, 0,
                f"doc header claims {doc_count} ops but the table has "
                f"{len(doc_rows)} rows — regenerate it"))
        return out

    def _parse_doc(self, text):
        rows, count, count_line = {}, None, 0
        for i, line in enumerate(text.splitlines(), 1):
            m = self._ROW.match(line)
            if m and m.group(1) != "op":
                cols = [c.strip() for c in line.strip().strip("|").split("|")]
                amp = cols[-1] if len(cols) >= 4 else "-"
                rows[m.group(1)] = (i, amp)
                continue
            m = self._COUNT.match(line)
            if m:
                count, count_line = int(m.group(1)), i
        return rows, count, count_line


class LockDiscipline(Rule):
    """GL004: no device dispatch or blocking wait inside a lock body.

    ``with self._lock:`` bodies must be short, host-only critical
    sections: a ``jax.*``/``jnp.*`` call under the lock can block on
    device execution (or worse, re-enter instrumented dispatch that takes
    the same lock), and ``time.sleep``/``.join()``/``.wait()`` turn the
    metric registry or serving engine into a convoy. Move device work and
    waits outside, keep only the state mutation inside.
    """

    id = "GL004"
    name = "lock-discipline"
    rationale = ("device dispatch or blocking waits under a lock convoy "
                 "every other thread touching that lock")

    BLOCKING_ATTRS = {"join", "wait", "acquire", "result"}
    BLOCKING_EXACT = {"time.sleep"}

    @staticmethod
    def _lock_ctx(item):
        name = dotted_name(item.context_expr)
        return name is not None and name.rsplit(".", 1)[-1].lower().endswith(
            "lock")

    def check(self, project):
        out = []
        for f in project.files:
            if f.tree is None:
                continue
            for w in f.walk():
                if not isinstance(w, ast.With) \
                        or not any(self._lock_ctx(i) for i in w.items):
                    continue
                lock = next(dotted_name(i.context_expr) for i in w.items
                            if self._lock_ctx(i))
                for call in ast.walk(w):
                    msg = self._classify(call, lock)
                    if msg:
                        out.append(self.finding(f, call, msg))
        # interprocedural: a helper called under the lock blocks/dispatches
        cg = project.callgraph()
        for fi in cg.functions.values():
            f = fi.srcfile
            for (lockkey, _w, _inner, calls) in fi.lock_regions:
                lock = lockkey.split(":", 1)[-1]
                for (call, tgt, disp) in calls:
                    if tgt == fi.key:
                        continue
                    if self._classify(call, lock):
                        continue    # directly flagged above
                    entry = cg.callee_summary(tgt, "blocking")
                    if entry is None:
                        continue
                    eff = entry[0]
                    via = " -> ".join(cg.chain_names(tgt, "blocking"))
                    out.append(self.finding(
                        f, call,
                        f"call into blocking helper reaches {eff.detail} "
                        f"(via {via}) inside `with {lock}:` — every other "
                        "thread touching the lock convoys behind it; move "
                        "the call outside the critical section",
                        chain=cg.chain(tgt, "blocking")))
        return out

    @classmethod
    def _blocking_attr_call(cls, call):
        """True for ``.join()``/``.wait()``/``.acquire()``/``.result()``
        shapes that actually block: zero args or a single numeric timeout.
        ``os.path.join(a, b)`` / ``sep.join(parts)`` take value arguments
        and are pure — the arity is the distinguisher."""
        if not isinstance(call.func, ast.Attribute) \
                or call.func.attr not in cls.BLOCKING_ATTRS \
                or isinstance(call.func.value, ast.Constant):
            return False
        if call.keywords:
            return True         # .wait(timeout=...) etc.
        if len(call.args) == 0:
            return True
        if len(call.args) == 1:  # numeric literal = a timeout, not a value
            a = call.args[0]
            return isinstance(a, ast.Constant) \
                and isinstance(a.value, (int, float))
        return False

    def _classify(self, call, lock):
        if not isinstance(call, ast.Call):
            return None
        name = dotted_name(call.func)
        if name and (name.startswith("jax.") or name.startswith("jnp.")):
            return (f"device dispatch {name}() inside `with {lock}:` can "
                    "block on the device (or re-enter instrumented "
                    "dispatch) while every other thread waits on the lock")
        if name in self.BLOCKING_EXACT:
            return (f"{name}() sleeps while holding `{lock}` — every "
                    "other thread touching the lock convoys behind it")
        if self._blocking_attr_call(call):
            return (f".{call.func.attr}() blocks while holding `{lock}`; "
                    "wait outside the critical section")
        return None


class MetricNameContract(Rule):
    """GL005: the telemetry metric-name contract (absorbs
    tools/check_metric_names.py, whose CLI stays as a thin shim).

    Every ``paddle_tpu_*`` metric registered anywhere in the tree must be
    declared in ``paddle_tpu/monitor/catalog.py`` and follow the
    ``paddle_tpu_<subsystem>_<name>`` convention (counters end ``_total``)
    — dashboards and artifact validators key on these exact strings, so an
    undeclared or misnamed metric is a contract break, not a style issue.
    """

    id = "GL005"
    name = "metric-name-contract"
    rationale = ("metric names are a dashboard-facing contract; "
                 "undeclared or misnamed series break consumers silently")

    CATALOG = "paddle_tpu/monitor/catalog.py"
    REG_FUNCS = {"counter", "gauge", "histogram"}
    KINDS = ("counter", "gauge", "histogram")

    @staticmethod
    def load_catalog(path):
        """Execute the (dependency-free by design) catalog module by file
        path — shared with the tools/check_metric_names.py shim."""
        import importlib.util

        spec = importlib.util.spec_from_file_location("_graftlint_catalog",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def strict_problems(self, project, findings=None):
        """The PR 1 check_metric_names semantics, in one place for both
        the shim CLI and the run_static_checks aggregator: no baseline,
        inline suppressions honored, and a MISSING catalog is a failure
        (the rule itself skips quietly on catalog-less fixture trees).
        Pass ``findings`` to reuse an existing engine run."""
        from .core import partition, run

        if project.read_optional(self.CATALOG) is None:
            return [f"{self.CATALOG}: catalog not found under "
                    f"{project.root} — the metric-name contract cannot "
                    "be checked"]
        if findings is None:
            findings = run(project, [self])
        else:
            findings = [f for f in findings if f.rule == self.id]
        new, _base, _supp = partition(project, findings, ())
        return [f"{f.path}:{f.line}: {f.message}" for f in new]

    def check(self, project):
        if project.read_optional(self.CATALOG) is None:
            return []
        import os

        cat = self.load_catalog(os.path.join(project.root, self.CATALOG))
        name_re = re.compile(cat.NAME_PATTERN)
        out = []
        catfile = next((f for f in project.files
                        if f.relpath == self.CATALOG), None)

        def cat_line(name):
            if catfile is None:
                return 0
            for i, line in enumerate(catfile.lines, 1):
                if f'"{name}"' in line:
                    return i
            return 0

        for name, (kind, _labels, help_text) in sorted(cat.METRICS.items()):
            loc = cat_line(name)
            if not name_re.match(name):
                out.append(Finding(
                    self.id, self.CATALOG, loc, 0,
                    f"catalog name {name} does not match paddle_tpu_"
                    f"<{'|'.join(cat.SUBSYSTEMS)}>_<name>"))
            if kind == "counter" and not name.endswith("_total"):
                out.append(Finding(
                    self.id, self.CATALOG, loc, 0,
                    f"catalog counter {name} must end in _total"))
            if kind not in self.KINDS:
                out.append(Finding(
                    self.id, self.CATALOG, loc, 0,
                    f"catalog name {name} has unknown type {kind!r}"))
            if not help_text:
                out.append(Finding(
                    self.id, self.CATALOG, loc, 0,
                    f"catalog name {name} has no help text"))

        declared = set(cat.METRICS)
        for f in project.files:
            if f.tree is None:
                continue
            for call in f.walk():
                if not isinstance(call, ast.Call) or not call.args:
                    continue
                fname = dotted_name(call.func)
                if fname is None \
                        or fname.rsplit(".", 1)[-1] not in self.REG_FUNCS:
                    continue
                arg = call.args[0]
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)
                        and arg.value.startswith("paddle_tpu_")):
                    continue
                name = arg.value
                if name not in declared:
                    out.append(self.finding(
                        f, call,
                        f"metric {name} registered but not declared in "
                        f"{self.CATALOG}"))
                elif not name_re.match(name):
                    out.append(self.finding(
                        f, call,
                        f"metric {name} violates the naming convention "
                        f"{cat.NAME_PATTERN}"))
        return out


class SpanNameContract(Rule):
    """GL006: the trace span-name contract (the GL005 of the span layer).

    Every span the framework emits (``monitor/trace.py``) must be declared
    in ``paddle_tpu/monitor/catalog.py`` ``SPANS`` and follow the
    ``<subsystem>.<name>`` convention — trace viewers, flight-recorder
    consumers and the hang-dump workflow key on the exact strings, so an
    undeclared or misnamed span is a contract break, not a style issue.
    """

    id = "GL006"
    name = "span-name-contract"
    rationale = ("span names are a trace-viewer/hang-dump contract; "
                 "undeclared or misnamed spans break consumers silently")

    CATALOG = "paddle_tpu/monitor/catalog.py"
    # functions whose first string-literal argument is a span name
    EMIT_FUNCS = {"span", "start_span", "record_span"}

    load_catalog = staticmethod(MetricNameContract.load_catalog)

    def strict_problems(self, project, findings=None):
        """Aggregator semantics (tools/run_static_checks.py): no baseline,
        inline suppressions honored, and a catalog without a SPANS table is
        a failure (the rule itself skips quietly on span-less fixture
        trees). Pass ``findings`` to reuse an existing engine run."""
        from .core import partition, run

        if project.read_optional(self.CATALOG) is None:
            return [f"{self.CATALOG}: catalog not found under "
                    f"{project.root} — the span-name contract cannot "
                    "be checked"]
        import os

        cat = self.load_catalog(os.path.join(project.root, self.CATALOG))
        if getattr(cat, "SPANS", None) is None:
            return [f"{self.CATALOG}: no SPANS table — the span-name "
                    "contract cannot be checked"]
        if findings is None:
            findings = run(project, [self])
        else:
            findings = [f for f in findings if f.rule == self.id]
        new, _base, _supp = partition(project, findings, ())
        return [f"{f.path}:{f.line}: {f.message}" for f in new]

    def check(self, project):
        if project.read_optional(self.CATALOG) is None:
            return []
        import os

        cat = self.load_catalog(os.path.join(project.root, self.CATALOG))
        spans = getattr(cat, "SPANS", None)
        if spans is None:
            return []   # metric-only fixture catalog: nothing to enforce
        subsystems = tuple(getattr(cat, "SPAN_SUBSYSTEMS", ()))
        name_re = re.compile(getattr(
            cat, "SPAN_PATTERN",
            r"^(" + "|".join(subsystems) + r")(\.[a-z][a-z0-9_]*)+$"))
        out = []
        catfile = next((f for f in project.files
                        if f.relpath == self.CATALOG), None)

        def cat_line(name):
            if catfile is None:
                return 0
            for i, line in enumerate(catfile.lines, 1):
                if f'"{name}"' in line:
                    return i
            return 0

        for name, help_text in sorted(spans.items()):
            loc = cat_line(name)
            if not name_re.match(name):
                out.append(Finding(
                    self.id, self.CATALOG, loc, 0,
                    f"catalog span {name} does not match "
                    f"<{'|'.join(subsystems)}>.<name>"))
            if not help_text:
                out.append(Finding(
                    self.id, self.CATALOG, loc, 0,
                    f"catalog span {name} has no help text"))

        declared = set(spans)
        for f in project.files:
            if f.tree is None:
                continue
            for call in f.walk():
                if not isinstance(call, ast.Call) or not call.args:
                    continue
                fname = dotted_name(call.func)
                if fname is not None:
                    last = fname.rsplit(".", 1)[-1]
                elif isinstance(call.func, ast.Attribute):
                    # non-dotted receivers too (mon[5].record_span(...) —
                    # the lazily-bound handle tuples of the instrument
                    # sites): the method name alone identifies an emitter
                    last = call.func.attr
                else:
                    continue
                if last not in self.EMIT_FUNCS:
                    continue
                arg = call.args[0]
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)
                        and "." in arg.value
                        and arg.value.split(".", 1)[0] in subsystems):
                    continue    # dynamic names / foreign span() calls
                name = arg.value
                if name not in declared:
                    out.append(self.finding(
                        f, call,
                        f"span {name} emitted but not declared in "
                        f"{self.CATALOG} SPANS"))
                elif not name_re.match(name):
                    out.append(self.finding(
                        f, call,
                        f"span {name} violates the naming convention "
                        f"{name_re.pattern}"))
        return out


class LockOrder(Rule):
    """GL007: lock-order inversion across the runtime stack.

    The serving engine, watchdog scanner, dataloader producer and monitor
    exporters run as concurrent threads sharing a handful of locks. A
    deadlock needs no bug in any single function — only two call paths
    acquiring the same two locks in opposite orders. The call graph makes
    the acquisition order static: ``with lockA:`` whose body (transitively,
    through helpers) acquires ``lockB`` is an A→B edge; any cycle in that
    graph is a potential deadlock and every participating order must be
    made consistent. The runtime twin is graftsan's lock-order witness
    (``analysis/sanitizers.py``), which checks the ACTUAL acquisition
    orders the process performs.
    """

    id = "GL007"
    name = "lock-order-inversion"
    rationale = ("two paths acquiring the same locks in opposite orders "
                 "deadlock under the right interleaving; the acquisition "
                 "graph must stay acyclic")

    def check(self, project):
        cg = project.callgraph()
        edges = {}   # (a, b) -> (srcfile, node, via text, chain)
        for fi in cg.functions.values():
            for (lockkey, w, inner, calls) in fi.lock_regions:
                if fi.srcfile.suppressed(self.id, w.lineno):
                    continue
                for (k, line) in inner:
                    if k != lockkey:
                        edges.setdefault((lockkey, k), (
                            fi.srcfile, w,
                            f"{fi.qualname} nests the acquisitions",
                            (f"{fi.qualname} acquires "
                             f"{_lk(lockkey)} at {fi.path}:{w.lineno} then "
                             f"{_lk(k)} at {fi.path}:{line}",)))
                for (call, tgt, disp) in calls:
                    if tgt == fi.key:
                        continue
                    for k in cg.transitive_acquires(tgt):
                        if k == lockkey:
                            continue
                        hops = cg.chain(tgt, "acquire:" + k)
                        edges.setdefault((lockkey, k), (
                            fi.srcfile, call,
                            f"{fi.qualname} calls {disp}",
                            (f"{fi.qualname} holds {_lk(lockkey)} and "
                             f"calls {disp} at {fi.path}:{call.lineno}",)
                            + tuple(hops)))
        return self._cycle_findings(edges)

    def _cycle_findings(self, edges):
        adj = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)
        out = []
        reported = set()
        for (a, b) in sorted(edges):
            if (b, a) not in edges:
                continue
            pair = tuple(sorted((a, b)))
            if pair in reported:
                continue
            reported.add(pair)
            f1, n1, via1, chain1 = edges[(pair[0], pair[1])]
            f2, n2, via2, chain2 = edges[(pair[1], pair[0])]
            out.append(Finding(
                self.id, f1.relpath, getattr(n1, "lineno", 0),
                getattr(n1, "col_offset", 0),
                f"lock-order inversion: {_lk(pair[0])} -> {_lk(pair[1])} "
                f"({via1}) but {_lk(pair[1])} -> {_lk(pair[0])} ({via2}) — "
                "a deadlock under the right interleaving; pick one order "
                "and make every path follow it",
                scope=f1.scope_of(n1),
                chain=tuple(chain1) + ("-- versus --",) + tuple(chain2)))
        # longer cycles: walk each simple cycle not already covered by a
        # pairwise inversion (rotation-canonical so each reports once)
        for cyc in self._simple_cycles(adj):
            if len(cyc) == 2:
                continue
            canon = tuple(sorted(cyc))
            if canon in reported:
                continue
            reported.add(canon)
            first = min(cyc)
            i = cyc.index(first)
            order = cyc[i:] + cyc[:i]
            f1, n1, _via, _chain = edges[(order[0], order[1])]
            chain = []
            for x, y in zip(order, order[1:] + order[:1]):
                chain.extend(edges[(x, y)][3])
            out.append(Finding(
                self.id, f1.relpath, getattr(n1, "lineno", 0),
                getattr(n1, "col_offset", 0),
                "lock-order cycle: "
                + " -> ".join(_lk(k) for k in order + (order[0],))
                + " — a deadlock under the right interleaving; break the "
                "cycle by fixing one global acquisition order",
                scope=f1.scope_of(n1), chain=tuple(chain)))
        out.sort(key=lambda x: (x.path, x.line))
        return out

    @staticmethod
    def _simple_cycles(adj):
        """Bounded DFS enumeration of simple cycles (the lock graph is tiny
        — a handful of nodes — so exhaustive search is fine)."""
        cycles = []
        seen = set()
        nodes = sorted(adj)

        def dfs(start, cur, path):
            for nxt in sorted(adj.get(cur, ())):
                if nxt == start and len(path) > 1:
                    canon = tuple(sorted(path))
                    if canon not in seen:
                        seen.add(canon)
                        cycles.append(tuple(path))
                elif nxt not in path and nxt > start and len(path) < 8:
                    dfs(start, nxt, path + [nxt])

        for n in nodes:
            dfs(n, n, [n])
        return cycles


def _lk(lockkey):
    """Human form of a lock key (drop the file prefix when unambiguous)."""
    return lockkey.split(":", 1)[-1]


class RecompileHazard(Rule):
    """GL008: recompile storms visible from the source.

    Whole-program compilation makes compile count the hidden cost center
    (arxiv 2301.13062): each new signature pays a trace + XLA compile that
    dwarfs the step it serves. Three statically-visible hazard shapes, each
    a bug class this tree has actually shipped (PR 2 found the first by
    hand):

    1. **per-call registration** — a ``@defop`` inside a function body
       whose wrapper is called in that same body re-registers the op per
       call: a fresh OpDef identity per call defeats the per-signature vjp
       cache (every backward retraces) and churns the registry. Factories
       that REGISTER inside a helper but return the wrapper uncalled are
       fine (registration runs once at import).
    2. **shape/dtype branching in a jitted body** — ``if x.shape[0] > n:``
       inside a ``to_static``/``jax.jit`` body compiles one program per
       outcome; with unbucketed shapes that is one compile per distinct
       shape (the recompile storm the serving engine's prefill buckets
       exist to prevent). ``defop`` bodies are exempt: eager ops are
       per-signature cached by design and shape normalization there is the
       norm.
    3. **per-call-constructed static args** — passing a ``lambda`` (or a
       function defined in the calling function's body) to a compiled
       callable keys the program cache on the object's ``repr`` — a fresh
       address every call, so every call is a cache miss that compiles.

    The runtime twin is graftsan's recompile sentinel
    (``analysis/sanitizers.py``), which counts actual cache misses and
    trips past a threshold.
    """

    id = "GL008"
    name = "recompile-hazard"
    rationale = ("every avoidable signature is a trace+compile that dwarfs "
                 "the step it serves; registration, branching and cache "
                 "keys must be compile-stable")

    SHAPE_ATTRS = {"shape", "ndim", "dtype"}

    def check(self, project):
        out = []
        for f in project.files:
            if f.tree is None:
                continue
            out.extend(self._per_call_registration(f))
            out.extend(self._shape_branching(f))
            out.extend(self._weak_static_args(f))
        return out

    # -- pattern 1: per-call registration ------------------------------------
    def _per_call_registration(self, f):
        out = []
        for node in f.walk():
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not any(self._is_reg_decorator(d) for d in node.decorator_list):
                continue
            owner = self._enclosing_function(f, node)
            if owner is None:
                continue    # module/class level: registered once at import
            from .callgraph import body_walk

            called = any(
                isinstance(c, ast.Call) and isinstance(c.func, ast.Name)
                and c.func.id == node.name
                for c in body_walk(owner))
            if called:
                out.append(self.finding(
                    f, node,
                    f"op '{node.name}' is @defop-registered inside "
                    f"'{owner.name}' and called there: re-registered on "
                    "EVERY call — a fresh OpDef identity defeats the "
                    "per-signature vjp cache (each backward retraces) and "
                    "churns the registry; hoist the registration to module "
                    "level"))
        return out

    @staticmethod
    def _is_reg_decorator(dec):
        if isinstance(dec, ast.Call):
            dec = dec.func
        name = dotted_name(dec)
        return name is not None and (
            name.rsplit(".", 1)[-1].endswith("defop")
            or name.rsplit(".", 1)[-1] == "register_op")

    @staticmethod
    def _enclosing_function(f, node):
        for anc in f.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    # -- pattern 2: shape/dtype branching in jitted bodies -------------------
    def _shape_branching(self, f):
        from .callgraph import body_walk

        out = []
        for fn, tag in TraceImpurity._traced_functions(f).items():
            if tag == "defop":
                continue    # eager ops are per-signature cached by design
            params = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
            params.discard("self")
            for node in body_walk(fn):
                if not isinstance(node, (ast.If, ast.While, ast.IfExp)):
                    continue
                hit = self._shape_test(node.test, params)
                if hit:
                    out.append(self.finding(
                        f, node,
                        f"branch on {hit} inside @{tag} function "
                        f"'{fn.name}': one compiled program per outcome — "
                        "with unbucketed inputs, one compile per distinct "
                        "shape (recompile storm); pad/bucket the input or "
                        "use a device-side select"))
        return out

    def _shape_test(self, test, params):
        for n in ast.walk(test):
            if isinstance(n, ast.Attribute) and n.attr in self.SHAPE_ATTRS \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id in params:
                return f"{n.value.id}.{n.attr}"
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id == "len" and len(n.args) == 1 \
                    and isinstance(n.args[0], ast.Name) \
                    and n.args[0].id in params:
                return f"len({n.args[0].id})"
        return None

    # -- pattern 3: per-call-constructed static args -------------------------
    def _weak_static_args(self, f):
        out = []
        compiled = self._compiled_names(f)
        if not compiled:
            return out
        local_defs = {}
        for n in f.walk():
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local_defs.setdefault(n.name, []).append(f.scope_of(n))
        for call in f.walk():
            if not isinstance(call, ast.Call) \
                    or not isinstance(call.func, ast.Name) \
                    or call.func.id not in compiled:
                continue
            scope = f.scope_of(call)
            for arg in list(call.args) + [k.value for k in call.keywords]:
                if isinstance(arg, ast.Lambda):
                    out.append(self.finding(
                        f, call,
                        f"lambda argument to compiled callable "
                        f"'{call.func.id}': the program cache keys "
                        "non-hashable constants by repr — a fresh object "
                        "address every call, so EVERY call is a compile; "
                        "hoist the function to module level"))
                elif isinstance(arg, ast.Name) and scope:
                    # a def in the calling function (or an enclosing one):
                    # fresh function object per outer call
                    nested = [s for s in local_defs.get(arg.id, ())
                              if s and (scope == s
                                        or scope.startswith(s + "."))]
                    if nested:
                        out.append(self.finding(
                            f, call,
                            f"locally-defined function '{arg.id}' passed "
                            f"to compiled callable '{call.func.id}': a "
                            "fresh function object per enclosing call "
                            "keys a new signature each time (recompile "
                            "storm); hoist it to module level"))
        return out

    @staticmethod
    def _compiled_names(f):
        """Local names statically known to be compiled callables: defs
        decorated @to_static/@jax.jit (not @defop), and assignment targets
        of ``to_static(...)`` / ``jax.jit(...)`` results."""
        names = set()
        for n in f.walk():
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                tags = [t for t in map(_decorator_tag, n.decorator_list) if t]
                if tags and tags[0] in ("to_static", "jit"):
                    names.add(n.name)
            elif isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                tag = _decorator_tag(n.value)
                if tag in ("to_static", "jit"):
                    for tgt in n.targets:
                        if isinstance(tgt, ast.Name):
                            names.add(tgt.id)
        return names


class MutableGlobalCapture(Rule):
    """GL009: jitted/to_static bodies that close over a MUTABLE module
    global.

    A traced body runs its Python ONCE: reading a module-level list/
    dict/set bakes the values seen at trace time into the compiled
    program. Later mutations of the global are silently ignored — until
    an unrelated recompile (new shape, evicted cache) re-traces and
    picks them up, so behavior CHANGES at a point no code changed. That
    staleness-then-divergence is nastier than a plain wrong constant
    (GL001's territory) because it is green in every test that traces
    exactly once. Pass the value as an argument (retrace on change) or
    bind it to an immutable module constant.
    """

    id = "GL009"
    name = "mutable-global-capture"
    rationale = ("a traced body reading a mutable module global bakes "
                 "trace-time contents in; later mutations apply only "
                 "after an unrelated recompile")

    MUTABLE_CALLS = {"list", "dict", "set", "defaultdict", "OrderedDict",
                     "Counter", "deque", "bytearray"}

    def _mutable_globals(self, srcfile):
        """{name: kind} for module-level bindings whose value is a
        mutable container (display literal, comprehension, or a bare
        constructor call)."""
        out = {}
        for node in srcfile.tree.body:
            if isinstance(node, ast.Assign):
                targets = [t for t in node.targets
                           if isinstance(t, ast.Name)]
                value = node.value
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                targets = [node.target]
                value = node.value
            else:
                continue
            kind = None
            if isinstance(value, (ast.List, ast.ListComp)):
                kind = "list"
            elif isinstance(value, (ast.Dict, ast.DictComp)):
                kind = "dict"
            elif isinstance(value, (ast.Set, ast.SetComp)):
                kind = "set"
            elif isinstance(value, ast.Call):
                name = dotted_name(value.func)
                if name and name.rsplit(".", 1)[-1] in self.MUTABLE_CALLS:
                    kind = name.rsplit(".", 1)[-1]
            if kind:
                for t in targets:
                    out[t.id] = kind
        return out

    def check(self, project):
        out = []
        for f in project.files:
            if f.tree is None:
                continue
            mutables = self._mutable_globals(f)
            if not mutables:
                continue
            for fn, tag in TraceImpurity._traced_functions(f).items():
                # any name bound inside the function (params of every
                # kind, stores, comprehension targets, nested defs)
                # shadows the global
                bound = set()
                for n in ast.walk(fn):
                    a = getattr(n, "args", None)
                    if isinstance(a, ast.arguments):
                        for arg in (list(a.posonlyargs) + list(a.args)
                                    + list(a.kwonlyargs)
                                    + [x for x in (a.vararg, a.kwarg)
                                       if x is not None]):
                            bound.add(arg.arg)
                for n in ast.walk(fn):
                    if isinstance(n, ast.Name) \
                            and isinstance(n.ctx, (ast.Store, ast.Del)):
                        bound.add(n.id)
                    elif isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef,
                                        ast.ClassDef)) and n is not fn:
                        bound.add(n.name)
                seen = set()
                for n in ast.walk(fn):
                    if not (isinstance(n, ast.Name)
                            and isinstance(n.ctx, ast.Load)):
                        continue
                    kind = mutables.get(n.id)
                    if kind is None or n.id in bound or n.id in seen:
                        continue
                    seen.add(n.id)
                    out.append(self.finding(
                        f, n,
                        f"@{tag} function '{fn.name}' closes over "
                        f"mutable module-global '{n.id}' ({kind}): the "
                        "traced program bakes in the contents seen at "
                        "trace time, and later mutations apply only "
                        "after an unrelated recompile — pass it as an "
                        "argument or make it an immutable constant"))
        return out


class UnguardedSharedState(Rule):
    """GL010: a ``self.<attr>`` written under a lock anywhere in its
    class but accessed lock-free from a thread-reachable method.

    The write-under-lock is the author's own declaration that the field
    is shared mutable state; the lock-free access from a method another
    thread can reach is then a data race by the author's own contract.
    The static lockset at an access is the union of the enclosing
    ``with <lock>:`` regions, the locks provably held at every call site
    on the thread path (the ``*_locked`` helper convention), and any
    ``# guarded_by: <lock>`` annotation on the line. ``Finding.chain``
    carries the thread-entry chain — the ``Thread(target=...)`` spawn
    site and the call hops from it to the unguarded access — rendered by
    ``--explain`` exactly like the GL001/GL002 propagation chains.
    Deliberately lock-free fields (GIL-atomic monotonic counters,
    append-only telemetry) take ``# graftlint: disable=GL010`` with a
    rationale; externally synchronized ones take ``# guarded_by:``.
    """

    id = "GL010"
    name = "unguarded-shared-state"
    rationale = ("a field written under a lock is shared state by the "
                 "author's own declaration; touching it lock-free from "
                 "a thread-reachable method is a data race")

    def check(self, project):
        from .locksets import analysis_for

        out = []
        la = analysis_for(project)
        for (srcfile, access, cls, guard, root) in \
                la.unguarded_shared_state():
            method = la.cg.functions[access.method_key].qualname
            kind = "written" if access.write else "read"
            out.append(self.finding(
                srcfile, access.node,
                f"'self.{access.attr}' of class '{cls}' is written "
                f"under lock '{_lk(guard)}' elsewhere but {kind} "
                f"lock-free in '{method}', which runs on a thread "
                f"spawned via '{root}' — hold the lock here, or mark "
                "the line '# guarded_by: <lock>' if it is synchronized "
                "externally",
                chain=la.thread_chain(access.method_key)))
        return out


class GuardedByInconsistency(Rule):
    """GL011: lock/field associations that are internally contradictory.

    (a) the guarded writes of one attribute hold locksets with an empty
    common intersection — two sites each "hold a lock", but not the
    *same* lock, so neither excludes the other (this also catches a
    ``# guarded_by:`` annotation naming a lock the real writes never
    hold); (b) a mutable container built in ``__init__`` and mutated
    under a lock escapes that lock's region via a bare
    ``return self.<attr>`` / ``yield self.<attr>`` — the caller iterates
    or mutates the live object after the lock is released. Return a
    snapshot (``list(...)``, ``dict(...)``) instead.
    """

    id = "GL011"
    name = "guarded-by-inconsistency"
    rationale = ("a field guarded by different locks at different sites "
                 "is guarded by none; a mutable structure returned from "
                 "inside its lock region escapes the lock")

    def check(self, project):
        from .locksets import analysis_for

        out = []
        la = analysis_for(project)
        for (access, cls, menu, sites) in la.inconsistent_guards():
            fi = la.cg.functions[access.method_key]
            chain = tuple(
                f"write under {{{', '.join(_lk(l) for l in locks)}}} "
                f"at {fi.path}:{line}"
                for (line, locks) in sites)
            out.append(self.finding(
                fi.srcfile, access.node,
                f"'self.{access.attr}' of class '{cls}' is guarded by "
                f"different locks at different write sites "
                f"({', '.join(_lk(l) for l in menu)} — no common "
                "lock): every writer must hold the same lock for "
                "mutual exclusion to mean anything",
                chain=chain))
        for (srcfile, node, cls, attr, kind, lockkey) in \
                la.lock_region_escapes():
            out.append(self.finding(
                srcfile, node,
                f"mutable {kind} 'self.{attr}' of class '{cls}' "
                f"escapes the '{_lk(lockkey)}' region via a bare "
                "return/yield while being mutated under that lock "
                "elsewhere — the caller sees live unlocked state; "
                "return a copy instead"))
        return out


ALL_RULES = (TraceImpurity(), HostSync(), RegistryConsistency(),
             LockDiscipline(), MetricNameContract(), SpanNameContract(),
             LockOrder(), RecompileHazard(), MutableGlobalCapture(),
             UnguardedSharedState(), GuardedByInconsistency())

RULES_BY_ID = {r.id: r for r in ALL_RULES}
