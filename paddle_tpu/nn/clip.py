"""Gradient clipping.

Reference analog: python/paddle/nn/clip.py (ClipGradByValue/Norm/GlobalNorm). Clippers are
callables over (param, grad) lists used by optimizers pre-step; the distributed-aware
variant (cross-group norm allreduce) lives in distributed/fleet/hybrid_optimizer.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..framework.core import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):  # noqa: A002
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g.value, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            n = jnp.sqrt(jnp.sum(jnp.square(g.value)))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(n, 1e-12), 1.0)
            out.append((p, Tensor(g.value * scale)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def __call__(self, params_grads):
        sq = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            sq.append(jnp.sum(jnp.square(g.value.astype(jnp.float32))))
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor((g.value.astype(jnp.float32) * scale).astype(g.value.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    params = [parameters] if not isinstance(parameters, (list, tuple)) else list(parameters)
    grads = [p.grad for p in params if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g.value)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g.value.astype(jnp.float32)) ** norm_type) for g in grads]
        )) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in params:
        if p.grad is not None:
            p.grad._replace_value(p.grad.value * scale.astype(p.grad.value.dtype))
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    params = [parameters] if not isinstance(parameters, (list, tuple)) else list(parameters)
    for p in params:
        if p.grad is not None:
            p.grad._replace_value(jnp.clip(p.grad.value, -clip_value, clip_value))
