"""Composable distributed pass pipeline for the Engine d2s path.

Reference analog: python/paddle/distributed/passes/ (pass_base.py new_pass/
PassManager/PassContext; auto_parallel_amp.py, auto_parallel_fp16.py,
auto_parallel_recompute.py, auto_parallel_sharding.py,
auto_parallel_gradient_merge.py) composed in order by
auto_parallel/static/engine.py:_parallel_pir (:669): amp decorate before
autodiff, then recompute/sharding as program rewrites, then gradient-merge
and pipeline scheduling as optimization passes over one program.

TPU-first redesign: the reference's "program" is a PIR module each pass
rewrites; here the program is the ONE jax trace DistModel compiles, so a
pass is an ordered transformation of the StepContext — the model / loss /
optimizer / forward-scope guards / step-state extensions that the trace is
assembled from. Applying the pipeline then tracing produces the same single
XLA program the reference's pass stack hand-builds, with GSPMD doing the
partitioning and XLA the fusion:

  - auto_parallel_amp      -> dtype policy: amp.auto_cast guard around the
                              traced forward+loss, amp.decorate on model/
                              optimizer (O2 master weights)
  - auto_parallel_recompute-> forward segments rewritten under
                              jax.checkpoint (fleet.recompute)
  - auto_parallel_sharding -> ZeRO placements on optimizer state
                              (api.ShardingStage1/2/3 shard_fn)
  - auto_parallel_gradient_merge -> k-step gradient banking; the traced
                              step computes the update every micro-step and
                              SELECTS (jnp.where on the bank counter)
                              between banked and applied states — branchless
                              and jit-compatible, the optimizer-update FLOPs
                              being negligible next to fwd+bwd

Pass-order contract (PASS_ORDER): amp < recompute < sharding <
gradient_merge. PassManager sorts its passes by this order and refuses
unknown names, so a mis-ordered user list still applies correctly — the
reference enforces the same implicitly by _parallel_pir's phase structure.
"""
from __future__ import annotations

import fnmatch

__all__ = ["new_pass", "PassBase", "PassContext", "PassManager",
           "PASS_ORDER", "build_pipeline_from_strategy"]


# the explicit order contract (see module docstring for the why of each edge)
PASS_ORDER = (
    "auto_parallel_amp",
    "auto_parallel_fp16",        # alias lane: fp16 == amp at O2/fp16
    "auto_parallel_recompute",
    "auto_parallel_sharding",
    "auto_parallel_gradient_merge",
)

_REGISTRY: dict[str, type] = {}


def register_pass(name):
    def deco(cls):
        cls.name = name
        _REGISTRY[name] = cls
        return cls
    return deco


def new_pass(name, attrs=None):
    """reference pass_base.py new_pass(name, attrs): instantiate by name."""
    if name not in _REGISTRY:
        raise ValueError(
            f"unknown pass {name!r}; registered: {sorted(_REGISTRY)}")
    return _REGISTRY[name](attrs)


class PassContext:
    """What the pass pipeline transforms (reference PassContext carries the
    program + dist_context; here: the pieces the one-trace step is built
    from)."""

    def __init__(self, model=None, loss=None, optimizer=None, strategy=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.strategy = strategy
        # callables returning context managers, entered (in order) around
        # the traced forward+loss
        self.forward_guards = []
        # None or {"k_steps": int, "avg": bool} — consumed by DistModel
        self.gradient_merge = None
        self.applied = []           # pass names, in application order


class PassBase:
    name = None

    def __init__(self, attrs=None):
        self.attrs = dict(attrs or {})

    def check(self, ctx):  # noqa: ARG002 - subclass hook
        return True

    def apply(self, ctx):
        raise NotImplementedError


class PassManager:
    """Ordered application of a pass list (reference pass_base.py
    PassManager). Passes are sorted by PASS_ORDER; unknown passes raise."""

    def __init__(self, passes):
        for p in passes:
            if p.name not in PASS_ORDER:
                raise ValueError(
                    f"pass {p.name!r} has no position in PASS_ORDER; "
                    "register it there with an explicit ordering rationale")
        self._passes = sorted(passes, key=lambda p: PASS_ORDER.index(p.name))

    @property
    def names(self):
        return [p.name for p in self._passes]

    def apply(self, ctx):
        for p in self._passes:
            if not p.check(ctx):
                raise ValueError(f"pass {p.name} check failed on this context")
            p.apply(ctx)
            ctx.applied.append(p.name)
        return ctx


@register_pass("auto_parallel_amp")
class AMPPass(PassBase):
    """reference auto_parallel_amp.py: dtype-policy rewrite. O1 wraps compute
    in the cast policy; O2 additionally casts params low-precision with fp32
    master weights on the optimizer (amp/auto_cast.py decorate)."""

    def apply(self, ctx):
        from ...amp import auto_cast, decorate

        level = str(self.attrs.get("level", "O1")).upper()
        dtype = self.attrs.get("dtype", "bfloat16")
        if self.attrs.get("use_pure_fp16"):
            level, dtype = "O2", "float16"
        white = self.attrs.get("custom_white_list") or None
        black = self.attrs.get("custom_black_list") or None
        if level == "O2" and ctx.model is not None:
            decorate(ctx.model, ctx.optimizer, level="O2", dtype=dtype,
                     master_weight=self.attrs.get("master_weight"))
        ctx.forward_guards.append(
            lambda: auto_cast(True, custom_white_list=white,
                              custom_black_list=black, level=level,
                              dtype=dtype))


@register_pass("auto_parallel_fp16")
class FP16Pass(AMPPass):
    """reference auto_parallel_fp16.py — pure-fp16 lane of the amp pass."""

    def apply(self, ctx):
        self.attrs.setdefault("level", "O2")
        self.attrs.setdefault("dtype", "float16")
        super().apply(ctx)


@register_pass("auto_parallel_recompute")
class RecomputePass(PassBase):
    """reference auto_parallel_recompute.py: rewrite checkpointed segments so
    activations are rematerialized in backward. Here: wrap matching
    sublayers' forwards in fleet.recompute (jax.checkpoint)."""

    def apply(self, ctx):
        from ..fleet.recompute import recompute

        if ctx.model is None:
            return
        patterns = [p for p in (self.attrs.get("checkpoints") or []) if p]
        policy = self.attrs.get("checkpoint_policy")
        wrapped = 0
        for name, sub in ctx.model.named_sublayers():
            if getattr(sub, "_recompute_pass_wrapped", False):
                continue
            if patterns:
                if not any(fnmatch.fnmatch(name, pat) or name == pat
                           for pat in patterns):
                    continue
            else:
                # default segmentation: direct children that own parameters
                # (the reference's PipelineLayer-style per-block checkpoints)
                if "." in name or not any(
                        True for _ in sub.parameters()):
                    continue
            orig = sub.forward

            def make(fwd):
                def fwd_recompute(*a, **k):
                    if policy is not None:
                        k = dict(k, checkpoint_policy=policy)
                    return recompute(fwd, *a, **k)
                return fwd_recompute

            sub.forward = make(orig)
            sub._recompute_pass_wrapped = True
            wrapped += 1
        if patterns and not wrapped:
            raise ValueError(
                f"recompute pass: no sublayer matched checkpoints={patterns}")


@register_pass("auto_parallel_sharding")
class ShardingPass(PassBase):
    """reference auto_parallel_sharding.py: ZeRO. Stage 1/2 put Shard(0)
    placements on optimizer state (gradients reduce-scatter under GSPMD);
    stage 3 additionally shards the parameters themselves."""

    def apply(self, ctx):
        from ..api import (ShardingStage1, ShardingStage2, ShardingStage3,
                           shard_optimizer)

        if ctx.optimizer is None:
            return
        stage = int(self.attrs.get("stage", 1))
        cls = {1: ShardingStage1, 2: ShardingStage2, 3: ShardingStage3}.get(stage)
        if cls is None:
            raise ValueError(f"sharding stage must be 1/2/3, got {stage}")
        fn = cls(mesh=self.attrs.get("mesh"),
                 sharding_mesh_dim=self.attrs.get("sharding_mesh_dim"))
        inner = getattr(ctx.optimizer, "inner_opt", ctx.optimizer)
        shard_optimizer(inner, fn)
        if stage == 3 and ctx.model is not None:
            for p in ctx.model.parameters():
                # apply_to_param RETURNS a resharded Parameter (shard_tensor
                # builds a new one); swap the placement into the live param
                # or stage 3 would silently degrade to stage 1
                new = fn.apply_to_param(p)
                if new is not p:
                    p._replace_value(new.value)
                    p._dist_attr = new._dist_attr
                    p.is_distributed = True


@register_pass("auto_parallel_gradient_merge")
class GradientMergePass(PassBase):
    """reference auto_parallel_gradient_merge.py: accumulate grads k steps,
    apply once. Consumed by DistModel's trace as branchless select state
    (see module docstring) — this pass only records the config, which is
    why it must sort last: it changes WHEN the update applies, not what any
    earlier pass computes."""

    def apply(self, ctx):
        k = int(self.attrs.get("k_steps", 1))
        if k < 1:
            raise ValueError(f"gradient merge k_steps must be >= 1, got {k}")
        ctx.gradient_merge = {"k_steps": k,
                              "avg": bool(self.attrs.get("avg", True))}


def _knob(strategy, name):
    """(enabled, config-dict) for one knob, accepting BOTH strategy forms:
    the flat DistributedStrategy (bool + <name>_configs dict) and the
    auto_parallel Strategy's dot-access groups (truthiness == .enable,
    config fields on the group itself)."""
    val = getattr(strategy, name, False)
    if hasattr(val, "to_dict"):          # dot-access group
        cfg = {k: v for k, v in val.to_dict().items() if k != "enable"}
        return bool(val), cfg
    return bool(val), dict(getattr(strategy, f"{name}_configs", {}) or {})


def build_pipeline_from_strategy(strategy):
    """Map a DistributedStrategy/Strategy's enabled knobs onto the pass
    pipeline (the reference Engine does this wiring inside _parallel_pir)."""
    passes = []
    on, cfg = _knob(strategy, "amp")
    if on:
        if "level" not in cfg or not cfg.get("level"):
            cfg["level"] = "O2" if cfg.get("use_pure_fp16") else "O1"
        if "dtype" not in cfg or not cfg.get("dtype"):
            cfg["dtype"] = ("bfloat16" if cfg.get("use_bf16", True)
                            else "float16")
        passes.append(new_pass("auto_parallel_amp", cfg))
    on, cfg = _knob(strategy, "recompute")
    if on:
        passes.append(new_pass("auto_parallel_recompute", cfg))
    on, cfg = _knob(strategy, "sharding")
    if on:
        # ShardingPass reads stage/mesh/sharding_mesh_dim; the degree rides
        # on the MESH under GSPMD (flat consumers like Engine.cost get it
        # from the sharding_configs view), so it is dropped here
        cfg.pop("degree", None)
        passes.append(new_pass("auto_parallel_sharding", cfg))
    on, cfg = _knob(strategy, "gradient_merge")
    if on:
        passes.append(new_pass("auto_parallel_gradient_merge", cfg))
    return PassManager(passes)
