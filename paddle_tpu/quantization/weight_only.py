"""Weight-only quantization for TPU serving.

Reference analog: python/paddle/nn/quant/quantized_linear.py
(weight_quantize / weight_dequantize / weight_only_linear — the reference's
modern serving path) and quantization/quantize.py's inference conversion.

TPU-first: weights are stored int8 (or int4 packed in int8) with per-output-
channel fp scales; the matmul runs x @ dequant(w) — XLA fuses the dequant
multiply into the dot's epilogue, so HBM traffic drops by the quantization
ratio while the MXU still sees bf16/fp32 operands (the win on TPU is
bandwidth, not int8 math).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..framework.core import Tensor
from ..nn.layer.layers import Layer
from ..ops._apply import defop

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "WeightOnlyLinear", "quantize_for_inference"]


def weight_quantize(weight, algo="weight_only_int8", group_size=-1):
    """(quantized int8 tensor, per-channel fp32 scale) for a (in, out) weight.

    algo: "weight_only_int8" | "weight_only_int4" (int4 packed two-per-byte
    along the input dim). Matches quantized_linear.py weight_quantize."""
    w = np.asarray(weight.numpy() if isinstance(weight, Tensor) else weight,
                   np.float32)
    if group_size and group_size > 0 and algo != "weight_only_int8":
        raise NotImplementedError(
            "group_size is supported for weight_only_int8 only in this build")
    if algo == "weight_only_int8":
        if group_size and group_size > 0:
            k, n = w.shape
            if k % group_size:
                raise ValueError(
                    f"in_features {k} not divisible by group_size {group_size}")
            wg = w.reshape(-1, group_size, n)
            s = np.maximum(np.abs(wg).max(axis=1), 1e-8) / 127.0  # (groups, out)
            q = np.clip(np.round(wg / s[:, None, :]), -127, 127) \
                .astype(np.int8).reshape(k, n)
            return Tensor(jnp.asarray(q)), Tensor(jnp.asarray(s))
        s = np.maximum(np.abs(w).max(axis=0), 1e-8) / 127.0      # (out,)
        q = np.clip(np.round(w / s), -127, 127).astype(np.int8)
        return Tensor(jnp.asarray(q)), Tensor(jnp.asarray(s))
    if algo == "weight_only_int4":
        s = np.maximum(np.abs(w).max(axis=0), 1e-8) / 7.0
        q = np.clip(np.round(w / s), -7, 7).astype(np.int8)
        if q.shape[0] % 2:
            q = np.concatenate([q, np.zeros((1, q.shape[1]), np.int8)])
        lo = q[0::2] & 0x0F
        hi = (q[1::2] & 0x0F) << 4
        packed = (lo | hi).astype(np.int8)                       # (in/2, out)
        return Tensor(jnp.asarray(packed)), Tensor(jnp.asarray(s))
    raise ValueError(f"unsupported weight_quantize algo {algo!r}")


def _unpack_int4(packed, k):
    p = packed.astype(jnp.int32)
    lo = (p & 0x0F).astype(jnp.int8)
    hi = ((p >> 4) & 0x0F).astype(jnp.int8)
    # sign-extend 4-bit two's complement
    lo = jnp.where(lo > 7, lo - 16, lo)
    hi = jnp.where(hi > 7, hi - 16, hi)
    full = jnp.stack([lo, hi], axis=1).reshape(-1, packed.shape[1])
    return full[:k]


def weight_dequantize(quant_weight, scale, algo="weight_only_int8",
                      out_dtype="float32", k=None):
    """Inverse of weight_quantize (quantized_linear.py weight_dequantize)."""
    qv = quant_weight.value if isinstance(quant_weight, Tensor) else quant_weight
    sv = scale.value if isinstance(scale, Tensor) else scale
    if algo == "weight_only_int4":
        k = k if k is not None else qv.shape[0] * 2
        qv = _unpack_int4(qv, k)
    return Tensor(qv.astype(jnp.dtype(out_dtype)) * sv.astype(
        jnp.dtype(out_dtype)))


@defop("weight_only_linear", amp_category="white")
def _wol(x, qweight, scale, bias=None, algo="weight_only_int8", k=None):
    if algo == "weight_only_int4":
        w = _unpack_int4(qweight, k)
    else:
        w = qweight
    s = scale.astype(x.dtype)
    if s.ndim == 2:  # group-wise: (groups, out) -> per-row scales
        group = w.shape[0] // s.shape[0]
        s = jnp.repeat(s, group, axis=0)[:w.shape[0]]
    wd = w.astype(x.dtype) * s
    out = x @ wd
    return out + bias.astype(x.dtype) if bias is not None else out


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", group_size=-1, name=None):
    """quantized_linear.py weight_only_linear: x @ dequant(int weight)."""
    if weight_scale is None:
        raise ValueError(
            "weight_only_linear requires weight_scale (from weight_quantize)")
    algo = "weight_only_int4" if str(weight_dtype) == "int4" \
        else "weight_only_int8"
    return _wol(x, weight, weight_scale, bias, algo=algo,
                k=x.shape[-1])


class WeightOnlyLinear(Layer):
    """Inference Linear with int8/int4 weights (serving swap target)."""

    def __init__(self, linear, algo="weight_only_int8"):
        super().__init__()
        self.algo = algo
        self.in_features = int(linear.weight.shape[0])
        self.out_features = int(linear.weight.shape[1])
        qw, s = weight_quantize(linear.weight, algo=algo)
        # registered as buffers: persisted by state_dict, excluded from grads
        self.register_buffer("quant_weight", qw)
        self.register_buffer("weight_scale", s)
        self.bias = linear.bias

    def forward(self, x):
        return _wol(x, self.quant_weight, self.weight_scale, self.bias,
                    algo=self.algo, k=self.in_features)

    def extra_repr(self):
        return (f"in={self.in_features}, out={self.out_features}, "
                f"algo={self.algo}")


def quantize_for_inference(model, algo="weight_only_int8", min_features=0):
    """Swap every Linear for WeightOnlyLinear (quantize.py conversion role).

    min_features skips tiny layers (heads/gates) where quantization error
    outweighs the bandwidth saving. Returns the number of layers swapped."""
    from ..nn.layer.common import Linear

    count = 0
    for layer in model.sublayers(include_self=True):
        if type(layer).__name__ == "_QuantedWrapper":
            # QAT fake-quant wrappers read .inner.weight in forward — swapping
            # the Linear underneath them would break the wrapper; convert the
            # QAT model first (or quantize the float model)
            continue
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, Linear) and \
                    int(sub.weight.shape[0]) >= min_features:
                layer._sub_layers[name] = WeightOnlyLinear(sub, algo=algo)
                count += 1
    return count
