"""graftsan: opt-in runtime sanitizers for the hazards graftlint can only
approximate statically.

Five sanitizers, enabled via
``PADDLE_TPU_SANITIZE=lock,recompile,hostsync,race,numerics`` (or
``all``) at process start, or programmatically with :func:`enable`:

- **lock** — a lock-order witness (the dynamic twin of GL007): the stack's
  known locks are wrapped so every acquisition-while-holding records an
  ordered edge; acquiring B while holding A after some thread acquired A
  while holding B raises :class:`LockOrderInversion` *before blocking*,
  naming both first-witness acquisition stacks. :func:`check_wait` is the
  dynamic GL004: a declared blocking wait (dataloader queue get) trips if
  the calling thread holds any sanitized lock.
- **recompile** — a recompile sentinel (the dynamic twin of GL008): the
  jit program caches (``jit/api.py`` to_static, ``jit/sot.py`` captures,
  the serving engine's prefill/decode caches) report every cache miss via
  :func:`note_compile`; more misses for one callable than the threshold
  (``PADDLE_TPU_SANITIZE_RECOMPILE_THRESHOLD``, default 8) raises
  :class:`RecompileStorm` with the recent signature history — the
  shape-varying-loop storm caught while it is still cheap.
- **hostsync** — a host-sync tripwire: a ``Tensor`` concretization
  (``.numpy()`` / ``.item()`` / ``float()`` …) inside an active
  ``trace.training_step`` / ``serving`` span — or any
  :func:`protected_region` — raises :class:`HostSyncInProtectedRegion`.
  Reads wrapped in :func:`allow_host_sync` are sanctioned.
- **race** — a data-race witness (the dynamic twin of GL010): instrumented
  hot classes (the serving engine's stats/span tables, FleetRouter,
  SLOTracker, CheckpointManager) report field accesses via
  :func:`race_access`; an Eraser-style candidate-lockset intersection over
  the SanitizedLock held-set per (owner, field) raises :class:`DataRace`
  when a mutated field's candidate set empties — both conflicting access
  stacks named, no lucky-timing crash required. Enabling ``race`` makes
  :func:`new_lock` return sanitized locks (held-set maintenance) even when
  the order witness is off.
- **numerics** — numsan, the runtime twin of graftir's GI005–GI007: one
  compiled device-side all-finite reduction over the registered step
  outputs at every step/burst boundary (:func:`numsan_check`), ONE bool
  to the host per step — no per-op sync. A non-finite value raises
  :class:`NumericsTrip` naming the step and the first non-finite region
  tag (the registered regions are re-checked in order to localize it);
  drilled via the ``numsan.check`` fault point. This replaces the old
  flag-gated per-op host NaN scanner on the hot paths; the eager
  per-op checker in ``amp/debugging.py`` remains for interactive
  debugging and now shares numsan's compiled check.

Discipline matches monitor/trace: **disabled by default**, every guard is
one slot load on a preallocated ``_state`` object, nothing is wrapped or
hooked until enabled — bench.py stamps ``detail.sanitizer_overhead`` and
the tier-1 dispatch budget holds with sanitizers off.

Every trip also (best-effort) bumps
``paddle_tpu_monitor_sanitizer_trips_total``, records a
``monitor.sanitizer_trip`` span (``monitor.numsan_trip`` for numerics,
which carries the site/step/region attrs), and writes the trace
flight-recorder dump (the hang/post-mortem workflow of docs/tracing.md)
before raising.

This module is stdlib-only (no jax, no framework imports) like the rest of
``paddle_tpu.analysis``; runtime integration points import IT, and the
monitor/trace bindings — and numsan's jax half in ``numerics.py`` —
resolve lazily at trip/check time.
"""
from __future__ import annotations

import collections
import os
import threading
import traceback

__all__ = [
    "SanitizerError", "LockOrderInversion", "RecompileStorm",
    "HostSyncInProtectedRegion", "BlockingWaitUnderLock", "DataRace",
    "NumericsTrip",
    "enable", "disable", "enabled", "install_from_env", "reset",
    "SanitizedLock", "new_lock", "wrap_lock", "lock_order_edges",
    "check_wait",
    "note_compile", "compile_counts", "recompile_threshold",
    "set_recompile_threshold",
    "protected_region", "allow_host_sync", "trips",
    "race_access", "race_fields",
    "numsan_check", "numsan_counts",
]

_KINDS = ("lock", "recompile", "hostsync", "race", "numerics")


class SanitizerError(RuntimeError):
    """Base class: a graftsan sanitizer tripped."""


class LockOrderInversion(SanitizerError):
    """Two threads acquired the same two locks in opposite orders."""


class RecompileStorm(SanitizerError):
    """One callable crossed the compile-count threshold."""


class HostSyncInProtectedRegion(SanitizerError):
    """A device→host sync fired inside an active training/serving span."""


class BlockingWaitUnderLock(SanitizerError):
    """A declared blocking wait ran while holding a sanitized lock."""


class DataRace(SanitizerError):
    """An instrumented field's candidate lockset emptied while mutated —
    two threads touch it with no common lock."""


class NumericsTrip(SanitizerError):
    """A registered step-boundary region holds a non-finite value."""


class _State:
    """One slot load per guard when disabled — the monitor discipline.
    ``locktrack`` is the derived held-set-maintenance flag: on when the
    order witness OR the race witness needs to know which sanitized
    locks each thread holds."""

    __slots__ = ("on", "lock", "recompile", "hostsync", "race",
                 "numerics", "locktrack")

    def __init__(self):
        self.on = False
        self.lock = False
        self.recompile = False
        self.hostsync = False
        self.race = False
        self.numerics = False
        self.locktrack = False


_state = _state_singleton = _State()
_tls = threading.local()

# -- lock-order witness -------------------------------------------------------

_graph_lock = threading.Lock()
_edges = {}          # (held, acquired) -> first-witness stack (str)
_trips = []          # [(kind, message)] — test/postmortem introspection

# -- race witness -------------------------------------------------------------

_race_lock = threading.Lock()
_fields = {}         # (owner, field) -> _FieldAccess

# -- recompile sentinel -------------------------------------------------------

_recompile_lock = threading.Lock()
_compiles = {}       # label -> count
_signatures = {}     # label -> deque of recent signature reprs
_DEFAULT_THRESHOLD = 8
_threshold = [_DEFAULT_THRESHOLD]

# -- hostsync tripwire --------------------------------------------------------

_prev_hook = [None]
_hook_installed = [False]

# -- numerics sentinel --------------------------------------------------------

_numsan_lock = threading.Lock()
_numsan_counts = {}  # site -> device-side checks issued


def enabled(kind=None):
    """Whether any sanitizer (or one specific kind) is enabled."""
    if kind is None:
        return _state.on
    if kind not in _KINDS:
        raise ValueError(f"unknown sanitizer {kind!r} (known: {_KINDS})")
    return getattr(_state, kind)


def enable(*kinds):
    """Enable sanitizers (all five when called bare). Module-level monitor
    locks are wrapped now; locks constructed AFTER this call pick up
    wrapping via :func:`new_lock` at their construction sites."""
    kinds = kinds or _KINDS
    for k in kinds:
        if k not in _KINDS:
            raise ValueError(f"unknown sanitizer {k!r} (known: {_KINDS})")
        setattr(_state, k, True)
    _state.on = True
    _state.locktrack = _state.lock or _state.race
    if _state.locktrack:
        _wrap_known_locks()
    if _state.hostsync:
        _install_hook()


def disable(*kinds):
    """Disable sanitizers (all when called bare). Wrapped locks stay
    wrapped (they become pass-throughs: the guard slot is off)."""
    for k in (kinds or _KINDS):
        if k not in _KINDS:
            raise ValueError(f"unknown sanitizer {k!r} (known: {_KINDS})")
        setattr(_state, k, False)
    _state.on = (_state.lock or _state.recompile or _state.hostsync
                 or _state.race or _state.numerics)
    _state.locktrack = _state.lock or _state.race
    if not _state.hostsync:
        _uninstall_hook()


def install_from_env(env=None):
    """Enable from ``PADDLE_TPU_SANITIZE`` (comma list, ``all``, or ``1``);
    called once at package import. Returns the enabled kinds."""
    spec = (env if env is not None
            else os.environ.get("PADDLE_TPU_SANITIZE", "")).strip().lower()
    if not spec:
        return ()
    if spec in ("all", "1", "true", "on"):
        kinds = _KINDS
    else:
        kinds = tuple(k.strip() for k in spec.split(",") if k.strip())
        bad = [k for k in kinds if k not in _KINDS]
        if bad:
            import warnings

            warnings.warn(f"PADDLE_TPU_SANITIZE: unknown sanitizer(s) "
                          f"{bad}; known: {list(_KINDS)}", stacklevel=2)
            kinds = tuple(k for k in kinds if k in _KINDS)
    if kinds:
        enable(*kinds)
    thr = os.environ.get("PADDLE_TPU_SANITIZE_RECOMPILE_THRESHOLD")
    if thr:
        try:
            set_recompile_threshold(int(thr))
        except ValueError:
            pass
    return kinds


def reset():
    """Drop witnessed edges, compile counts and trip records (test
    isolation). Enable state is untouched."""
    with _graph_lock:
        _edges.clear()
    with _race_lock:
        _fields.clear()
    with _recompile_lock:
        _compiles.clear()
        _signatures.clear()
    with _numsan_lock:
        _numsan_counts.clear()
    del _trips[:]
    _tls.__dict__.clear()


def trips():
    """[(kind, message)] recorded by every trip so far."""
    return list(_trips)


# -- trip plumbing ------------------------------------------------------------

def _trip(exc_type, kind, message):
    """Record, export (metric + span + flight dump, all best-effort), then
    raise. The raise is the contract; the telemetry documents it."""
    _trips.append((kind, message))
    try:
        from .. import monitor as _m

        if _m._state.on:
            _m.counter("paddle_tpu_monitor_sanitizer_trips_total",
                       labelnames=("sanitizer",)).labels(kind).inc()
        t = _m.trace
        if t._state.on:
            now = _m.now_ns()
            t.record_span("monitor.sanitizer_trip", now, now,
                          attrs={"sanitizer": kind})
        if t._state.on or os.environ.get("PADDLE_TPU_FLIGHT_DIR"):
            t.flight_dump(reason=f"graftsan {kind} trip: {message[:300]}")
    except Exception:  # noqa: BLE001 — telemetry must not mask the trip
        pass
    raise exc_type(message)


# -- lock-order witness -------------------------------------------------------

def _held():
    st = getattr(_tls, "held", None)
    if st is None:
        st = _tls.held = []
    return st


class SanitizedLock:
    """Thin proxy over a real lock that feeds the order witness. The inner
    lock keeps the blocking semantics; the witness only reads/writes the
    per-thread held list and the (tiny) process-wide edge map. Stacks are
    captured ONLY when a new edge is first witnessed, so steady-state
    acquisition cost is a list append."""

    __slots__ = ("name", "_inner")

    def __init__(self, name, inner=None):
        self.name = name
        self._inner = inner if inner is not None else threading.Lock()

    def acquire(self, blocking=True, timeout=-1):
        if _state.lock:
            self._witness()
        ok = self._inner.acquire(blocking, timeout)
        if ok and _state.locktrack:
            # the race witness reads this held-set too, so maintenance
            # stays on whenever either consumer is enabled
            _held().append(self.name)
        return ok

    def _witness(self):
        """Record held→this edges; trip on a known reverse edge BEFORE
        blocking (the reproducer raises instead of deadlocking)."""
        held = _held()
        if not held:
            return
        trip_msg = None
        with _graph_lock:
            for h in held:
                if h == self.name:
                    continue
                rev = _edges.get((self.name, h))
                if rev is not None:
                    here = "".join(traceback.format_stack(limit=12))
                    trip_msg = (
                        f"lock-order inversion: this thread holds '{h}' and "
                        f"is acquiring '{self.name}', but the opposite "
                        f"order '{self.name}' -> '{h}' was already "
                        "witnessed — a deadlock under the right "
                        "interleaving.\n"
                        f"-- first witness of {self.name} -> {h}:\n{rev}\n"
                        f"-- this acquisition of {h} -> {self.name}:\n"
                        f"{here}")
                    break
                if (h, self.name) not in _edges:
                    _edges[(h, self.name)] = "".join(
                        traceback.format_stack(limit=12))
        if trip_msg is not None:
            _trip(LockOrderInversion, "lock", trip_msg)

    def release(self):
        self._inner.release()
        # pop unconditionally: a disable() between another thread's acquire
        # and its release must not leak a phantom held entry that causes
        # false trips after the next enable (no-op when the name is absent)
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == self.name:
                del held[i]
                break

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def __repr__(self):
        return f"SanitizedLock({self.name!r}, {self._inner!r})"


def new_lock(name, factory=threading.Lock):
    """A lock for construction sites on the sanitizer's known-lock list
    (watchdog, registry): sanitized when the lock sanitizer is on at
    construction, a plain lock (zero overhead) otherwise."""
    inner = factory()
    return SanitizedLock(name, inner) if _state.locktrack else inner


def wrap_lock(name, lock):
    """Wrap an existing lock (module-level monitor/trace locks at
    enable time). Idempotent."""
    if isinstance(lock, SanitizedLock):
        return lock
    return SanitizedLock(name, lock)


def lock_order_edges():
    """Snapshot of witnessed ordered edges: {(held, acquired): stack}."""
    with _graph_lock:
        return dict(_edges)


def check_wait(site):
    """Declare an about-to-block wait (queue get, join). Trips when the
    calling thread holds any sanitized lock — the dynamic form of GL004."""
    if not _state.lock:
        return
    held = _held()
    if held:
        _trip(BlockingWaitUnderLock, "lock",
              f"blocking wait at {site} while holding {held} — every "
              "other thread touching the lock(s) convoys behind this "
              "wait; move it outside the critical section")


def _wrap_known_locks():
    """Swap the module-level monitor/trace/obs-server locks for sanitized
    proxies. Instrument sites reference the module globals by name, so the
    swap takes effect everywhere at once. Lazy: pulls in the monitor
    package (already imported in any running process). Instance locks in
    the fleet/checkpoint tier (FleetRouter, SLOTracker, per-metric
    Registry locks, the checkpoint writer's error lock) are constructed
    through :func:`new_lock` and pick up wrapping at construction — enable
    sanitizers before building the objects you want witnessed."""
    try:
        from .. import monitor as _m
        from ..monitor import trace as _t

        _t._open_lock = wrap_lock("monitor.trace._open_lock", _t._open_lock)
        _m._sample_lock = wrap_lock("monitor._sample_lock", _m._sample_lock)
        # the default Registry is constructed at package import, BEFORE an
        # env-driven enable runs — wrap its (held-across-construction-and-
        # snapshot) lock here; per-metric locks created after enable pick
        # up wrapping via new_lock at their construction sites
        _m.registry._lock = wrap_lock("monitor.registry.Registry",
                                      _m.registry._lock)
    except Exception:  # noqa: BLE001 — partial bootstrap must not fail
        pass
    try:
        # the obs-server module lock guards the scrape/statusz section
        # registry from request-handler threads (also import-time state)
        from ..monitor import server as _srv

        _srv._lock = wrap_lock("monitor.server._lock", _srv._lock)
    except Exception:  # noqa: BLE001
        pass


# -- race witness -------------------------------------------------------------

class _FieldAccess:
    """Eraser state for one (owner, field): ``exclusive`` while a single
    thread touches it (initialization), then ``shared``/``shared_mod``
    with a candidate lockset that intersects toward the truth."""

    __slots__ = ("state", "tid", "written", "lockset", "stack",
                 "stack_locks", "tripped")

    def __init__(self, tid, written):
        self.state = "exclusive"
        self.tid = tid
        self.written = written
        self.lockset = None         # TOP until a second thread arrives
        self.stack = None           # first conflicting-access stack
        self.stack_locks = None
        self.tripped = False


def race_access(owner, field, write=False):
    """One access to an instrumented shared field. ``owner`` names the
    instance (the engine's ``_san_tag``, ``fleet.<tag>``), ``field`` the
    attribute. Per (owner, field), the candidate lockset starts at TOP
    during single-threaded initialization and intersects with the
    caller's sanitized-lock held-set on every access once a second
    thread arrives (Eraser). An empty candidate set on a written field
    raises :class:`DataRace` naming BOTH conflicting stacks — the first
    cross-thread access and this one. Disabled cost: one slot load."""
    if not _state.race:
        return
    held = frozenset(_held())
    me = threading.get_ident()
    trip = None
    with _race_lock:
        fa = _fields.get((owner, field))
        if fa is None:
            _fields[(owner, field)] = _FieldAccess(me, write)
            return
        if fa.state == "exclusive" and fa.tid == me:
            fa.written = fa.written or write
            return
        if fa.state == "exclusive":
            # second thread: initialization is over, constraints begin
            fa.state = "shared_mod" if (write or fa.written) else "shared"
            fa.lockset = set(held)
            fa.stack = "".join(traceback.format_stack(limit=12))
            fa.stack_locks = held
        else:
            fa.lockset &= held
            if write and fa.state == "shared":
                fa.state = "shared_mod"
        if fa.state == "shared_mod" and not fa.lockset \
                and not fa.tripped:
            fa.tripped = True     # one report per field, not a cascade
            here = "".join(traceback.format_stack(limit=12))
            trip = (
                f"data race on '{field}' of '{owner}': the candidate "
                "lockset is EMPTY for a written shared field — no "
                "single lock is held at every access, so two threads "
                "can interleave on it.\n"
                f"-- first cross-thread access (held "
                f"{sorted(fa.stack_locks or ())}):\n{fa.stack}\n"
                f"-- this access (held {sorted(held)}):\n{here}")
    if trip is not None:
        _trip(DataRace, "race", trip)


def race_fields():
    """Snapshot: {(owner, field): (state, sorted candidate locks|None)}
    for every instrumented field seen while enabled."""
    with _race_lock:
        return {k: (fa.state,
                    None if fa.lockset is None else sorted(fa.lockset))
                for k, fa in _fields.items()}


# -- recompile sentinel -------------------------------------------------------

def recompile_threshold():
    return _threshold[0]


def set_recompile_threshold(n):
    n = int(n)
    if n < 1:
        raise ValueError("recompile threshold must be >= 1")
    _threshold[0] = n


def note_compile(label, signature=None):
    """One program-cache miss for ``label``. Called by jit/api.py,
    jit/sot.py and the serving engine's jit caches — guarded at the call
    site on ``_state.recompile`` so the disabled cost is one slot load."""
    if not _state.recompile:
        return
    trip_msg = None
    with _recompile_lock:
        c = _compiles.get(label, 0) + 1
        _compiles[label] = c
        sigs = _signatures.get(label)
        if sigs is None:
            sigs = _signatures[label] = collections.deque(maxlen=8)
        if signature is not None:
            sigs.append(str(signature)[:200])
        if c == _threshold[0] + 1:
            recent = "\n  ".join(sigs) or "<signatures not reported>"
            trip_msg = (
                f"recompile storm: '{label}' compiled {c} times "
                f"(threshold {_threshold[0]}). Each miss pays a full "
                "trace+XLA compile. Shape-varying inputs? Pad or bucket "
                "them; unhashable/per-call static args? Hoist them. "
                f"Recent signatures:\n  {recent}")
    if trip_msg is not None:
        _trip(RecompileStorm, "recompile", trip_msg)


def compile_counts():
    """Snapshot: {label: cache-miss count} recorded while enabled."""
    with _recompile_lock:
        return dict(_compiles)


# -- hostsync tripwire --------------------------------------------------------

class _Region:
    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name

    def __enter__(self):
        st = getattr(_tls, "regions", None)
        if st is None:
            st = _tls.regions = []
        st.append(self.name)
        return self

    def __exit__(self, *exc):
        st = getattr(_tls, "regions", None)
        if st:
            st.pop()
        return False


def protected_region(name):
    """Mark a host-code region (serving step, custom training loop) in
    which a Tensor device→host sync is a bug. Nestable, per-thread."""
    return _Region(name)


class _Allow:
    __slots__ = ()

    def __enter__(self):
        _tls.allow = getattr(_tls, "allow", 0) + 1
        return self

    def __exit__(self, *exc):
        _tls.allow = max(0, getattr(_tls, "allow", 1) - 1)
        return False


def allow_host_sync():
    """Sanction an intentional sync inside a protected region (metrics
    readout, debugging)."""
    return _Allow()


_PROTECTED_PREFIXES = ("train", "serving")


def _active_protected_region():
    st = getattr(_tls, "regions", None)
    if st:
        return st[-1]
    try:
        from ..monitor import trace as _t

        if _t._state.on:
            for sp in reversed(_t.thread_span_stack()):
                if sp.name.split(".", 1)[0] in _PROTECTED_PREFIXES:
                    return sp.name
    except Exception:  # noqa: BLE001
        return None
    return None


def _concretize_tripwire(t):
    if _state.hostsync and not getattr(_tls, "allow", 0):
        region = _active_protected_region()
        if region is not None:
            _trip(HostSyncInProtectedRegion, "hostsync",
                  f"device->host sync (Tensor concretization) inside "
                  f"active span '{region}': a hidden round-trip "
                  "serializes the async dispatch pipeline. Hoist the "
                  "read out of the hot region, keep the reduction on "
                  "device, or wrap an intentional read in "
                  "sanitizers.allow_host_sync().")
    prev = _prev_hook[0]
    # never chain to ourselves: a disable() landing inside SOT's temporary
    # hook swap (jit/sot.py capture) leaves the tripwire in the slot after
    # SOT restores it, and the next enable() would otherwise save it as
    # its own prev — infinite recursion on every .numpy()
    if prev is not None and prev is not _concretize_tripwire:
        prev(t)


def _install_hook():
    """Chain the tripwire into the framework's concretization hook slot
    (framework/core.py ``_CONCRETIZE_HOOK``). Install only while enabled:
    the disabled process keeps its bare None slot (zero cost). SOT's
    cold-run recorder swaps the slot for the duration of a capture — host
    reads there are the graph-break mechanism, not a bug — and restores it
    after."""
    if _hook_installed[0]:
        return
    try:
        from ..framework import core as _core
    except Exception:  # noqa: BLE001 — analysis-only venv: no runtime hook
        return
    prev = _core._CONCRETIZE_HOOK[0]
    # the slot may still hold the tripwire (uninstall raced SOT's capture
    # swap, see _concretize_tripwire) — a stale self-reference must not
    # become our prev
    _prev_hook[0] = None if prev is _concretize_tripwire else prev
    _core._CONCRETIZE_HOOK[0] = _concretize_tripwire
    _hook_installed[0] = True


def _uninstall_hook():
    if not _hook_installed[0]:
        return
    try:
        from ..framework import core as _core
    except Exception:  # noqa: BLE001
        return
    if _core._CONCRETIZE_HOOK[0] is _concretize_tripwire:
        _core._CONCRETIZE_HOOK[0] = _prev_hook[0]
    _prev_hook[0] = None
    _hook_installed[0] = False


# -- numerics sentinel (numsan) -----------------------------------------------

def numsan_check(site, regions, step=None):
    """One device-side all-finite check over ``regions`` at a step/burst
    boundary. ``regions`` is ``((tag, pytree), ...)`` — the step's
    committed outputs (serving tokens + KV pools, the mesh step's loss /
    params / optimizer state), in the order the bisection should report
    them. Callers guard on ``_state.numerics`` so the disabled cost is
    one slot load; the enabled cost is one compiled reduction and ONE
    bool to the host per step (a raw jax.Array read, not a Tensor
    concretization — it cannot cross the hostsync tripwire).

    The ``numsan.check`` fault point drills the path: armed with
    ``action="flag"``, the check sees region ``seed % len(regions)``
    with one extra NaN leaf appended host-side — the engine's values are
    never touched, so step outputs stay bit-exact whether or not the
    drill (or numsan itself) is on.
    """
    if not _state.numerics:
        return
    regions = tuple(regions)
    if not regions:
        return
    from . import faultinject as _fi
    from . import numerics as _num

    spec = _fi.fire("numsan.check")
    if spec is not None:
        k = spec.seed % len(regions)
        tag, tree = regions[k]
        regions = (regions[:k] + ((tag, _num.poisoned(tree)),)
                   + regions[k + 1:])
    with _numsan_lock:
        _numsan_counts[site] = _numsan_counts.get(site, 0) + 1
    try:
        from .. import monitor as _m

        if _m._state.on:
            _m.counter("paddle_tpu_monitor_numsan_checks_total",
                       labelnames=("site",)).labels(site).inc()
    except Exception:  # noqa: BLE001 — telemetry must not break the check
        pass
    if _num.all_finite(tuple(t for _, t in regions)):
        return
    bad = _num.first_bad_region(regions)
    at = f"step {step}" if step is not None else "an untracked step"
    msg = (f"non-finite value at {site} ({at}): first non-finite region "
           f"is '{bad or '<combined check only>'}' of "
           f"{[t for t, _ in regions]} — a NaN/inf crossed the step "
           "boundary; replay under the eager checker "
           "(amp.debugging.enable_tensor_checker) to name the op, or "
           "run the GI006 hazard report for the static candidates")
    _numsan_trip(site, step, bad, msg)


def numsan_counts():
    """Snapshot: {site: device-side checks issued} while enabled."""
    with _numsan_lock:
        return dict(_numsan_counts)


def _numsan_trip(site, step, region, message):
    """The numerics flavor of :func:`_trip`: same record/export/raise
    contract, but the span is ``monitor.numsan_trip`` carrying the
    site/step/region the bisection localized."""
    _trips.append(("numerics", message))
    try:
        from .. import monitor as _m

        if _m._state.on:
            _m.counter("paddle_tpu_monitor_sanitizer_trips_total",
                       labelnames=("sanitizer",)).labels("numerics").inc()
        t = _m.trace
        if t._state.on:
            now = _m.now_ns()
            t.record_span("monitor.numsan_trip", now, now,
                          attrs={"site": site,
                                 "step": "?" if step is None
                                 else str(step),
                                 "region": region or "?"})
        if t._state.on or os.environ.get("PADDLE_TPU_FLIGHT_DIR"):
            t.flight_dump(
                reason=f"graftsan numerics trip: {message[:300]}")
    except Exception:  # noqa: BLE001 — telemetry must not mask the trip
        pass
    raise NumericsTrip(message)
