"""Convolution functionals.

Reference analog: python/paddle/nn/functional/conv.py over phi conv kernels (cuDNN). TPU:
lax.conv_general_dilated maps straight onto the MXU; weight layout is kept OIHW
(paddle-compatible) and XLA handles the internal re-layout.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...ops._apply import defop


def _tup(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


def _norm_padding(padding, n, strides=None):
    """paddle padding: int, list[int], list[pair], or 'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    if all(isinstance(p, (list, tuple)) for p in padding):
        # may include batch/channel dims: take the last n entries
        pairs = [tuple(p) for p in padding][-n:]
        return pairs
    raise ValueError(f"bad padding {padding}")


@defop("conv2d", amp_category="white")
def _conv2d(x, weight, bias=None, stride=(1, 1), padding="VALID", dilation=(1, 1), groups=1,
            data_format="NCHW"):
    dn = (data_format, "OIHW", data_format)
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=padding, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups,
    )
    if bias is not None:
        if data_format == "NCHW":
            out = out + bias.reshape(1, -1, 1, 1)
        else:
            out = out + bias
    return out


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv2d(x, weight, bias, stride=_tup(stride, 2),
                   padding=_norm_padding(padding, 2), dilation=_tup(dilation, 2),
                   groups=int(groups), data_format=data_format)


@defop("conv1d", amp_category="white")
def _conv1d(x, weight, bias=None, stride=(1,), padding="VALID", dilation=(1,), groups=1,
            data_format="NCL"):
    dn = ("NCH" if data_format == "NCL" else "NHC", "OIH", "NCH" if data_format == "NCL" else "NHC")
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=padding, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups,
    )
    if bias is not None:
        if data_format == "NCL":
            out = out + bias.reshape(1, -1, 1)
        else:
            out = out + bias
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv1d(x, weight, bias, stride=_tup(stride, 1),
                   padding=_norm_padding(padding, 1), dilation=_tup(dilation, 1),
                   groups=int(groups), data_format=data_format)


@defop("conv3d", amp_category="white")
def _conv3d(x, weight, bias=None, stride=(1, 1, 1), padding="VALID", dilation=(1, 1, 1),
            groups=1, data_format="NCDHW"):
    dn = (data_format, "OIDHW", data_format)
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=padding, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups,
    )
    if bias is not None:
        if data_format == "NCDHW":
            out = out + bias.reshape(1, -1, 1, 1, 1)
        else:
            out = out + bias
    return out


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv3d(x, weight, bias, stride=_tup(stride, 3),
                   padding=_norm_padding(padding, 3), dilation=_tup(dilation, 3),
                   groups=int(groups), data_format=data_format)


@defop("conv2d_transpose", amp_category="white")
def _conv2d_transpose(x, weight, bias=None, stride=(1, 1), padding=((0, 0), (0, 0)),
                      output_padding=(0, 0), dilation=(1, 1), groups=1, data_format="NCHW"):
    # weight layout paddle: (in_channels, out_channels//groups, kH, kW) = IOHW
    kh, kw = weight.shape[2], weight.shape[3]
    if isinstance(padding, str):
        pad_cfg = padding
    else:
        # transpose conv: effective padding = dilation*(k-1) - pad
        pad_cfg = [
            (dilation[i] * (k - 1) - padding[i][0],
             dilation[i] * (k - 1) - padding[i][1] + output_padding[i])
            for i, k in enumerate((kh, kw))
        ]
    dn = (data_format, "IOHW", data_format)
    if groups > 1:
        n_in = x.shape[1] if data_format == "NCHW" else x.shape[-1]
        xs = jnp.split(x, groups, axis=1 if data_format == "NCHW" else -1)
        ws = jnp.split(weight, groups, axis=0)
        outs = [
            jax.lax.conv_general_dilated(
                xg, wg, window_strides=(1, 1), padding=pad_cfg, lhs_dilation=stride,
                rhs_dilation=dilation, dimension_numbers=dn,
            )
            for xg, wg in zip(xs, ws)
        ]
        out = jnp.concatenate(outs, axis=1 if data_format == "NCHW" else -1)
    else:
        out = jax.lax.conv_general_dilated(
            x, weight, window_strides=(1, 1), padding=pad_cfg, lhs_dilation=stride,
            rhs_dilation=dilation, dimension_numbers=dn,
        )
    # flip kernel spatially: conv_transpose = conv with flipped kernel and lhs dilation
    if bias is not None:
        if data_format == "NCHW":
            out = out + bias.reshape(1, -1, 1, 1)
        else:
            out = out + bias
    return out


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, data_format="NCHW", output_size=None, name=None):
    import jax.numpy as jnp_

    stride = _tup(stride, 2)
    dilation = _tup(dilation, 2)
    pad = _norm_padding(padding, 2)
    opad = _tup(output_padding, 2)
    # conv_transpose needs the spatially-flipped kernel for exact gradient semantics
    from ...framework.core import Tensor
    from ...ops.manipulation import flip

    wf = flip(weight, [2, 3])
    return _conv2d_transpose(x, wf, bias, stride=stride, padding=pad,
                             output_padding=opad, dilation=dilation, groups=int(groups),
                             data_format=data_format)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, data_format="NCL", output_size=None, name=None):
    from ...ops.manipulation import squeeze, unsqueeze

    x4 = unsqueeze(x, [3 if data_format == "NCL" else 2])
    w4 = unsqueeze(weight, [3])
    df = "NCHW" if data_format == "NCL" else "NHWC"
    s = _tup(stride, 1)
    d = _tup(dilation, 1)
    p = padding if isinstance(padding, str) else _norm_padding(padding, 1)
    out = conv2d_transpose(
        x4, w4, bias,
        stride=(s[0], 1),
        padding=p if isinstance(p, str) else [tuple(p[0]), (0, 0)],
        output_padding=(_tup(output_padding, 1)[0], 0),
        groups=groups, dilation=(d[0], 1), data_format=df,
    )
    return squeeze(out, [3 if data_format == "NCL" else 2])


@defop("conv3d_transpose_inner", amp_category="white")
def _c3t(x, w, bias=None, stride=None, pad=None, opad=None, dilation=None, groups=1,
         data_format="NCDHW"):
    ks = w.shape[2:]
    if isinstance(pad, str):
        cfg = pad
    else:
        cfg = [
            (dilation[i] * (k - 1) - pad[i][0],
             dilation[i] * (k - 1) - pad[i][1] + opad[i])
            for i, k in enumerate(ks)
        ]
    dn = (data_format, "IODHW", data_format)
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(1, 1, 1), padding=cfg, lhs_dilation=stride,
        rhs_dilation=dilation, dimension_numbers=dn,
    )
    if bias is not None:
        out = out + (bias.reshape(1, -1, 1, 1, 1) if data_format == "NCDHW" else bias)
    return out


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, data_format="NCDHW", output_size=None, name=None):
    from ...ops.manipulation import flip

    stride = _tup(stride, 3)
    dilation = _tup(dilation, 3)
    pad = _norm_padding(padding, 3)
    opad = _tup(output_padding, 3)
    wf = flip(weight, [2, 3, 4])
    kd, kh, kw = weight.value.shape[2:]

    return _c3t(x, wf, bias, stride=stride, pad=pad, opad=opad, dilation=dilation,
                groups=int(groups), data_format=data_format)
