"""paddle.incubate.nn.functional fused transformer FUNCTIONAL forms
(reference incubate/nn/functional/fused_transformer.py) + nn.quant.

The layer classes are covered by test_fused_layers.py; these pin the raw
functional surface: packed-qkv attention (with cache append), feedforward,
the whole-stack call, single-step masked attention, and the nn.quant
re-exports."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate.nn import functional as IF

E, H, D = 16, 4, 4
B, S = 2, 5


def _weights(seed=0):
    r = np.random.RandomState(seed)
    return dict(
        x=paddle.to_tensor(r.randn(B, S, E).astype("float32")),
        qkv_w=paddle.to_tensor((r.randn(3, H, D, E) * 0.1).astype("float32")),
        lin_w=paddle.to_tensor((r.randn(E, E) * 0.1).astype("float32")),
        ln_s=paddle.to_tensor(np.ones(E, "float32")),
        ln_b=paddle.to_tensor(np.zeros(E, "float32")),
        ffn1=paddle.to_tensor((r.randn(E, 32) * 0.1).astype("float32")),
        ffn2=paddle.to_tensor((r.randn(32, E) * 0.1).astype("float32")))


class TestFusedMHA:
    def test_forward_shape_and_grads(self):
        w = _weights()
        x = paddle.to_tensor(np.asarray(w["x"].numpy()),
                             stop_gradient=False)
        out = IF.fused_multi_head_attention(
            x, w["qkv_w"], w["lin_w"], ln_scale=w["ln_s"],
            ln_bias=w["ln_b"], dropout_rate=0.0, attn_dropout_rate=0.0,
            training=False)
        assert out.shape == [B, S, E]
        out.sum().backward()
        assert x.grad is not None and np.isfinite(x.grad.numpy()).all()

    def test_cache_append_contract(self):
        w = _weights()
        T = 3
        cache = paddle.to_tensor(
            np.random.RandomState(1).randn(2, B, H, T, D).astype("float32"))
        out, c2 = IF.fused_multi_head_attention(
            w["x"], w["qkv_w"], w["lin_w"], ln_scale=w["ln_s"],
            ln_bias=w["ln_b"], cache_kv=cache, dropout_rate=0.0,
            attn_dropout_rate=0.0, training=False)
        assert out.shape == [B, S, E]
        assert c2.shape == [2, B, H, T + S, D]  # past + new tokens
        # the past keys survive unchanged at the front of the cache
        np.testing.assert_allclose(np.asarray(c2.numpy())[0, :, :, :T],
                                   np.asarray(cache.numpy())[0], rtol=1e-6)

    def test_pre_ln_variant(self):
        w = _weights()
        out = IF.fused_multi_head_attention(
            w["x"], w["qkv_w"], w["lin_w"], pre_layer_norm=True,
            pre_ln_scale=w["ln_s"], pre_ln_bias=w["ln_b"],
            dropout_rate=0.0, attn_dropout_rate=0.0, training=False)
        assert np.isfinite(out.numpy()).all()


class TestFusedFFNAndStack:
    def test_feedforward(self):
        w = _weights()
        out = IF.fused_feedforward(
            w["x"], w["ffn1"], w["ffn2"], ln2_scale=w["ln_s"],
            ln2_bias=w["ln_b"], dropout1_rate=0.0, dropout2_rate=0.0,
            training=False, activation="gelu")
        assert out.shape == [B, S, E]

    def test_multi_transformer_two_layers(self):
        w = _weights()
        out = IF.fused_multi_transformer(
            w["x"], [w["ln_s"]] * 2, [w["ln_b"]] * 2, [w["qkv_w"]] * 2,
            None, [w["lin_w"]] * 2, None, [w["ln_s"]] * 2, [w["ln_b"]] * 2,
            [w["ffn1"]] * 2, None, [w["ffn2"]] * 2, None, dropout_rate=0.0)
        assert out.shape == [B, S, E]

    def test_linear_activation_and_bias_dropout_residual_ln(self):
        w = _weights()
        h = IF.fused_linear_activation(w["x"], w["ffn1"], activation="relu")
        assert (np.asarray(h.numpy()) >= 0).all()
        out = IF.fused_bias_dropout_residual_layer_norm(
            w["x"], w["x"], ln_scale=w["ln_s"], ln_bias=w["ln_b"],
            dropout_rate=0.0, training=False)
        assert out.shape == [B, S, E]


class TestMaskedMHA:
    def test_single_step_against_manual(self):
        r = np.random.RandomState(2)
        T = 6
        cache = paddle.to_tensor(r.randn(2, B, H, T, D).astype("float32"))
        xstep = paddle.to_tensor(r.randn(B, 3 * E).astype("float32"))
        sl = np.array([2, 4], "int32")  # per-row write positions
        out, c2 = IF.masked_multihead_attention(
            xstep, cache_kv=cache, sequence_lengths=sl)
        assert out.shape == [B, E] and c2.shape == [2, B, H, T, D]
        # the new k landed at each row's write position
        qkv = np.asarray(xstep.numpy()).reshape(B, 3, H, D)
        np.testing.assert_allclose(np.asarray(c2.numpy())[0, 0, :, 2],
                                   qkv[0, 1], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(c2.numpy())[0, 1, :, 4],
                                   qkv[1, 1], rtol=1e-6)

    def test_requires_sequence_lengths(self):
        cache = paddle.to_tensor(np.zeros((2, B, H, 4, D), "float32"))
        x = paddle.to_tensor(np.zeros((B, 3 * E), "float32"))
        with pytest.raises(ValueError, match="sequence_lengths"):
            IF.masked_multihead_attention(x, cache_kv=cache)

    def test_blha_get_max_len(self):
        mx_e, mx_d = IF.blha_get_max_len(np.array([3, 9]), np.array([1, 2]))
        assert int(mx_e.numpy()[0]) == 9 and int(mx_d.numpy()[0]) == 2


class TestNNQuant:
    def test_llm_int8_linear_and_stub(self):
        import paddle_tpu.nn.quant as Q

        r = np.random.RandomState(0)
        w = paddle.to_tensor(r.randn(8, 4).astype("float32"))
        qw, scale = Q.weight_quantize(w, algo="weight_only_int8")
        x = paddle.to_tensor(np.ones((2, 8), "float32"))
        out = Q.llm_int8_linear(x, qw, weight_scale=scale)
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   np.asarray((x @ w).numpy()),
                                   rtol=0.05, atol=0.1)

        class M(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.stub = Q.Stub()
                self.lin = paddle.nn.Linear(2, 2)

            def forward(self, t):
                return self.lin(self.stub(t))

        m = M()
        assert any(isinstance(s, Q.Stub) for s in m.sublayers())
        m(paddle.to_tensor(np.ones((1, 2), "float32")))


class TestFusedGateAttention:
    """reference fused_gate_attention.py:26 (AlphaFold gated MSA
    self-attention) vs a direct numpy oracle of its documented pseudo-code,
    merged + unmerged qkv, gating on/off, with both bias inputs."""

    def _oracle(self, q_data, m_data, qw, kw, vw, gw, gb, ow, ob, nb_bias,
                mask, has_gating):
        c = qw.shape[-1] ** -0.5
        q = np.einsum("nbqa,ahc->nbqhc", q_data, qw) * c
        k = np.einsum("nbka,ahc->nbkhc", m_data, kw)
        v = np.einsum("nbka,ahc->nbkhc", m_data, vw)
        logits = np.einsum("nbqhc,nbkhc->nbhqk", q, k)
        if mask is not None:
            logits = logits + mask
        if nb_bias is not None:
            logits = logits + nb_bias[:, None]
        e = np.exp(logits - logits.max(-1, keepdims=True))
        w = e / e.sum(-1, keepdims=True)
        out = np.einsum("nbhqk,nbkhc->nbqhc", w, v)
        if has_gating:
            gate = 1 / (1 + np.exp(-(np.einsum("nbqa,ahc->nbqhc", q_data, gw)
                                     + gb)))
            out = out * gate
        return np.einsum("nbqhc,hco->nbqo", out, ow) + ob

    def test_merged_and_unmerged_match_oracle(self):
        import paddle_tpu.incubate.nn.functional as IF

        r = np.random.RandomState(0)
        N, B, Q, M, A, H, D = 2, 3, 4, 6, 8, 2, 5
        x = r.randn(N, B, Q, A).astype("float64")
        m = r.randn(N, B, M, A).astype("float64")   # DISTINCT key tensor
        qw = r.randn(A, H, D).astype("float64")
        kw = r.randn(A, H, D).astype("float64")
        vw = r.randn(A, H, D).astype("float64")
        gw = r.randn(A, H, D).astype("float64")
        gb = r.randn(H, D).astype("float64")
        ow = r.randn(H, D, A).astype("float64")
        ob = r.randn(A).astype("float64")
        nb_bias = r.randn(N, H, Q, M).astype("float64")
        mask = np.where(r.rand(N, B, 1, 1, M) < 0.2, -1e9, 0.0)

        for has_gating in (True, False):
            # unmerged = CROSS attention over a distinct key tensor (a
            # same-as-query key would mask q/k source mixups)
            want = self._oracle(x, m, qw, kw, vw, gw, gb, ow, ob, nb_bias,
                                mask, has_gating)
            got_u = IF.fused_gate_attention(
                paddle.to_tensor(x), key=paddle.to_tensor(m),
                query_weight=paddle.to_tensor(qw),
                key_weight=paddle.to_tensor(kw),
                value_weight=paddle.to_tensor(vw),
                gate_linear_weight=paddle.to_tensor(gw),
                gate_linear_bias=paddle.to_tensor(gb),
                out_linear_weight=paddle.to_tensor(ow),
                out_linear_bias=paddle.to_tensor(ob),
                nonbatched_bias=paddle.to_tensor(nb_bias),
                attn_mask=paddle.to_tensor(mask),
                has_gating=has_gating, merge_qkv=False)
            np.testing.assert_allclose(np.asarray(got_u.value), want,
                                       rtol=1e-9, atol=1e-10)

            # merged form: self-attention (qkv from query) — oracle with
            # m_data == q_data; qkv_weight [3, H, D, A] stacks transposes
            want_m = self._oracle(x, x, qw, kw, vw, gw, gb, ow, ob,
                                  nb_bias[..., :Q], mask[..., :Q],
                                  has_gating)
            qkv_w = np.stack([np.transpose(w, (1, 2, 0))
                              for w in (qw, kw, vw)])
            got_m = IF.fused_gate_attention(
                paddle.to_tensor(x), qkv_weight=paddle.to_tensor(qkv_w),
                gate_linear_weight=paddle.to_tensor(gw),
                gate_linear_bias=paddle.to_tensor(gb),
                out_linear_weight=paddle.to_tensor(ow),
                out_linear_bias=paddle.to_tensor(ob),
                nonbatched_bias=paddle.to_tensor(nb_bias[..., :Q]),
                attn_mask=paddle.to_tensor(mask[..., :Q]),
                has_gating=has_gating, merge_qkv=True)
            np.testing.assert_allclose(np.asarray(got_m.value), want_m,
                                       rtol=1e-9, atol=1e-10)

    def test_merged_with_key_rejected(self):
        import paddle_tpu.incubate.nn.functional as IF

        x = paddle.to_tensor(np.zeros((1, 1, 2, 4), "float32"))
        with pytest.raises(ValueError, match="self-attention only"):
            IF.fused_gate_attention(x, key=x,
                                    qkv_weight=paddle.to_tensor(
                                        np.zeros((3, 2, 2, 4), "float32")))


class TestFusedMultiTransformerCached:
    """The cached generation contract (reference fused_multi_transformer
    with cache_kvs/time_step): prefill + per-token decode over preallocated
    [2, B, H, max_len, D] caches must reproduce the uncached causal run."""

    def _weights(self, L, E, H, seed=0):
        r = np.random.RandomState(seed)
        D = E // H

        def t(*s):
            return paddle.to_tensor(r.randn(*s).astype("float32") * 0.3)

        return dict(
            ln_scales=[t(E) for _ in range(L)],
            ln_biases=[t(E) for _ in range(L)],
            qkv_weights=[t(3, H, D, E) for _ in range(L)],
            qkv_biases=[t(3, H, D) for _ in range(L)],
            linear_weights=[t(E, E) for _ in range(L)],
            linear_biases=[t(E) for _ in range(L)],
            ffn_ln_scales=[t(E) for _ in range(L)],
            ffn_ln_biases=[t(E) for _ in range(L)],
            ffn1_weights=[t(E, 2 * E) for _ in range(L)],
            ffn1_biases=[t(2 * E) for _ in range(L)],
            ffn2_weights=[t(2 * E, E) for _ in range(L)],
            ffn2_biases=[t(E) for _ in range(L)])

    def test_prefill_decode_matches_uncached_causal(self):
        import paddle_tpu.incubate.nn.functional as IF

        L, B, E, H = 2, 2, 16, 4
        D = E // H
        S, T = 5, 3
        w = self._weights(L, E, H)
        r = np.random.RandomState(1)
        x_full = r.randn(B, S + T, E).astype("float32")

        # uncached causal run over the full sequence (additive mask)
        causal = np.where(
            np.tril(np.ones((S + T, S + T), bool)), 0.0, -1e9
        ).astype("float32")[None, None]
        want = IF.fused_multi_transformer(
            paddle.to_tensor(x_full), pre_layer_norm=True,
            attn_mask=paddle.to_tensor(causal), dropout_rate=0.0,
            training=False, **w)
        want = np.asarray(want.value)

        # cached: prefill S tokens, then 3 single-token decode steps
        caches = [paddle.to_tensor(np.zeros((2, B, H, S + T, D), "float32"))
                  for _ in range(L)]
        out_p, caches = IF.fused_multi_transformer(
            paddle.to_tensor(x_full[:, :S]), pre_layer_norm=True,
            cache_kvs=caches, dropout_rate=0.0, training=False, **w)
        np.testing.assert_allclose(np.asarray(out_p.value), want[:, :S],
                                   rtol=2e-5, atol=2e-5)
        for step in range(T):
            out_d, caches = IF.fused_multi_transformer(
                paddle.to_tensor(x_full[:, S + step:S + step + 1]),
                pre_layer_norm=True, cache_kvs=caches,
                time_step=paddle.to_tensor(np.array([S + step], "int32")),
                dropout_rate=0.0, training=False, **w)
            np.testing.assert_allclose(
                np.asarray(out_d.value)[:, 0], want[:, S + step],
                rtol=2e-5, atol=2e-5, err_msg=f"decode step {step}")

    def test_time_step_without_cache_raises(self):
        import paddle_tpu.incubate.nn.functional as IF

        w = self._weights(1, 8, 2)
        with pytest.raises(ValueError, match="time_step needs cache_kvs"):
            IF.fused_multi_transformer(
                paddle.to_tensor(np.zeros((1, 1, 8), "float32")),
                time_step=paddle.to_tensor(np.array([3], "int32")), **w)

    def test_cache_overflow_and_mask_rejected(self):
        import paddle_tpu.incubate.nn.functional as IF

        w = self._weights(1, 8, 2)
        cache = [paddle.to_tensor(np.zeros((2, 1, 2, 4, 4), "float32"))]
        x = paddle.to_tensor(np.zeros((1, 5, 8), "float32"))  # 5 > max_len 4
        with pytest.raises(ValueError, match="overflows the preallocated"):
            IF.fused_multi_transformer(x, cache_kvs=cache, **w)
        with pytest.raises(NotImplementedError, match="attn_mask with"):
            IF.fused_multi_transformer(
                paddle.to_tensor(np.zeros((1, 2, 8), "float32")),
                cache_kvs=cache,
                attn_mask=paddle.to_tensor(np.zeros((1, 1, 2, 2),
                                                    "float32")), **w)


class TestMaskedMHARotary:
    """masked_multihead_attention rotary path (reference mmha_util.cu.h:46:
    rotary_emb [2, B, max_seq, 1, D] cos/sin read at the row's position):
    must equal pre-rotating q/k by hand and calling the non-rotary path."""

    def _run(self, neox):
        import paddle_tpu.incubate.nn.functional as IF

        r = np.random.RandomState(0)
        B, H, T, D = 2, 2, 8, 8
        x = r.randn(B, 3 * H * D).astype("float32")
        cache = r.randn(2, B, H, T, D).astype("float32")
        seq_lens = np.array([3, 5], np.int32)

        # rope tables over max_seq positions
        inv = 1.0 / (10000.0 ** (np.arange(0, D, 2) / D))
        tpos = np.arange(T)[:, None] * inv[None, :]
        if neox:
            emb = np.concatenate([tpos, tpos], -1)      # half-split pairing
        else:
            emb = np.repeat(tpos, 2, axis=-1)           # interleaved pairing
        rot = np.stack([np.broadcast_to(np.cos(emb), (B, T, D)),
                        np.broadcast_to(np.sin(emb), (B, T, D))])
        rot = rot[:, :, :, None, :].astype("float32")   # [2, B, T, 1, D]

        got, got_cache = IF.masked_multihead_attention(
            paddle.to_tensor(x), cache_kv=paddle.to_tensor(cache),
            sequence_lengths=paddle.to_tensor(seq_lens),
            rotary_tensor=paddle.to_tensor(rot), rotary_emb_dims=1,
            use_neox_rotary_style=neox)

        # oracle: rotate q/k by hand at each row's position, then the plain
        # non-rotary call
        def rotate(t, cos, sin):
            if neox:
                half = D // 2
                r2 = np.concatenate([-t[..., half:], t[..., :half]], -1)
            else:
                r2 = np.stack([-t[..., 1::2], t[..., ::2]],
                              -1).reshape(t.shape)
            return t * cos + r2 * sin

        xq = x.reshape(B, 3, H, D).copy()
        for b in range(B):
            cos = np.cos(emb)[seq_lens[b]]
            sin = np.sin(emb)[seq_lens[b]]
            xq[b, 0] = rotate(xq[b, 0], cos, sin)
            xq[b, 1] = rotate(xq[b, 1], cos, sin)
        want, want_cache = IF.masked_multihead_attention(
            paddle.to_tensor(xq.reshape(B, 3 * H * D)),
            cache_kv=paddle.to_tensor(cache),
            sequence_lengths=paddle.to_tensor(seq_lens))
        np.testing.assert_allclose(np.asarray(got.value),
                                   np.asarray(want.value), rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(got_cache.value),
                                   np.asarray(want_cache.value), rtol=1e-5,
                                   atol=1e-6)

    def test_interleaved_default_style(self):
        self._run(neox=False)

    def test_neox_half_split_style(self):
        self._run(neox=True)

    def test_rotary_dims_validation(self):
        import paddle_tpu.incubate.nn.functional as IF

        with pytest.raises(NotImplementedError, match="rotary_emb_dims=2"):
            IF.masked_multihead_attention(
                paddle.to_tensor(np.zeros((1, 48), "float32")),
                cache_kv=paddle.to_tensor(np.zeros((2, 1, 2, 4, 8),
                                                   "float32")),
                sequence_lengths=paddle.to_tensor(np.zeros(1, "int32")),
                rotary_emb_dims=2)
        with pytest.raises(ValueError, match="needs\\s+rotary_tensor"):
            IF.masked_multihead_attention(
                paddle.to_tensor(np.zeros((1, 48), "float32")),
                cache_kv=paddle.to_tensor(np.zeros((2, 1, 2, 4, 8),
                                                   "float32")),
                sequence_lengths=paddle.to_tensor(np.zeros(1, "int32")),
                rotary_emb_dims=1)

    def test_src_mask_additive(self):
        """src_mask is ADDITIVE on the scores, broadcast over heads
        (reference masked_multihead_attention_kernel.cu:385 qk += mask):
        a -1e9 at a position must zero its attention weight."""
        import paddle_tpu.incubate.nn.functional as IF

        r = np.random.RandomState(1)
        B, H, T, D = 2, 2, 6, 8
        x = r.randn(B, 3 * H * D).astype("float32")
        cache = r.randn(2, B, H, T, D).astype("float32")
        seq_lens = np.array([4, 4], np.int32)
        sm = np.zeros((B, 1, 1, T), "float32")
        sm[:, :, :, 1] = -1e9                         # forbid position 1

        got, _ = IF.masked_multihead_attention(
            paddle.to_tensor(x), cache_kv=paddle.to_tensor(cache),
            sequence_lengths=paddle.to_tensor(seq_lens),
            src_mask=paddle.to_tensor(sm))
        # oracle: plain call on a cache whose position-1 K is pushed to
        # -inf attention by recomputing probabilities manually
        xq = x.reshape(B, 3, H, D)
        q, k, v = xq[:, 0], xq[:, 1], xq[:, 2]
        ck = cache[0].copy()
        cv = cache[1].copy()
        for b in range(B):
            ck[b, :, seq_lens[b]] = k[b]
            cv[b, :, seq_lens[b]] = v[b]
        want = np.zeros((B, H, D))
        for b in range(B):
            for h in range(H):
                lg = (ck[b, h] @ q[b, h]) / np.sqrt(D)
                lg = lg + sm[b, 0, 0]
                lg[seq_lens[b] + 1:] = -np.inf
                w = np.exp(lg - lg.max()); w /= w.sum()
                want[b, h] = w @ cv[b, h]
        np.testing.assert_allclose(np.asarray(got.value).reshape(B, H, D),
                                   want, rtol=1e-5, atol=1e-6)
