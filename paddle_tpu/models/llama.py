"""LLaMA model family — the flagship LLM used for parallelism validation and benchmarks.

Reference analog: the reference validates its whole hybrid/semi-auto parallel stack on a
LLaMA implementation (test/auto_parallel/hybrid_strategy/semi_auto_parallel_llama_model.py;
run under dp+mp+pp in semi_auto_llama.py). Capability parity here means: RMSNorm + rotary
attention (GQA) + SwiGLU MLP decoder, a causal-LM head with optional weight tying, a
pretraining criterion that masks ignored tokens, and the same four parallel modes —
plain single-device, tensor parallel (mp), Megatron sequence parallel inside mp, and
pipeline parallel via PipelineLayer descs.

TPU-first design: the compute is pure functional jnp under the framework's ops layer, so
a whole training step jits into ONE XLA program; parallelism comes from GSPMD sharding
annotations carried by the fleet TP/SP layers rather than hand-placed collectives. Flash
attention dispatches to the Pallas TPU kernel via F.scaled_dot_product_attention.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import ops
from ..framework.core import Tensor
from ..nn import functional as F
from ..nn.initializer import Normal
from ..nn.layer.common import Embedding, Linear
from ..nn.layer.container import LayerList
from ..nn.layer.layers import Layer
from ..nn.layer.norm import RMSNorm


class LlamaConfig:
    """Plain config object (PaddleNLP LlamaConfig field names)."""

    def __init__(
        self,
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=11008,
        num_hidden_layers=32,
        num_attention_heads=32,
        num_key_value_heads=None,
        max_position_embeddings=4096,
        initializer_range=0.02,
        rms_norm_eps=1e-6,
        rope_theta=10000.0,
        use_flash_attention=True,
        tie_word_embeddings=False,
        num_experts=0,
        moe_topk=2,
        moe_gate="gshard",
        moe_every_k=1,
        tensor_parallel_degree=1,
        sequence_parallel=False,
        pipeline_parallel_degree=1,
        recompute=False,
        recompute_granularity="full",
        recompute_policy=None,
        hbm_budget=None,
        fused_head_ce=False,
        dtype="float32",
        **kwargs,
    ):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.num_key_value_heads = num_key_value_heads or num_attention_heads
        self.max_position_embeddings = max_position_embeddings
        self.initializer_range = initializer_range
        self.rms_norm_eps = rms_norm_eps
        self.rope_theta = rope_theta
        self.use_flash_attention = use_flash_attention
        self.tie_word_embeddings = tie_word_embeddings
        self.num_experts = num_experts
        self.moe_topk = moe_topk
        self.moe_gate = moe_gate
        self.moe_every_k = moe_every_k
        self.tensor_parallel_degree = tensor_parallel_degree
        self.sequence_parallel = sequence_parallel
        self.pipeline_parallel_degree = pipeline_parallel_degree
        self.recompute = recompute
        self.recompute_granularity = recompute_granularity
        # recompute_policy replaces the all-or-nothing `recompute` bool:
        # "none"/"all" are its endpoints; "budget" lets the graftopt
        # planner (analysis/jaxpr/planner.py) pick the MINIMAL per-layer
        # remat set that fits hbm_budget bytes of per-device HBM —
        # consumed by mesh.parallelize() and hapi.Model.plan_remat()
        self.recompute_policy = recompute_policy
        self.hbm_budget = hbm_budget
        self.fused_head_ce = fused_head_ce
        self.dtype = dtype
        for k, v in kwargs.items():
            setattr(self, k, v)

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def _tp(config):
    return config.tensor_parallel_degree > 1


def _mp_linears(config):
    """(ColumnParallel, RowParallel) classes honoring the sequence_parallel switch."""
    if config.sequence_parallel:
        from ..distributed.fleet.utils.sequence_parallel_utils import (
            ColumnSequenceParallelLinear, RowSequenceParallelLinear)

        return ColumnSequenceParallelLinear, RowSequenceParallelLinear
    from ..distributed.fleet.mpu.mp_layers import (
        ColumnParallelLinear, RowParallelLinear)

    return ColumnParallelLinear, RowParallelLinear


@ops.fuse(static_argnums=(0, 1, 2, 3))
def _rope_cos_sin(seq_len, head_dim, theta, dtype):
    # every argument is static, so the eager path pays ONE cached
    # dispatch per (seq, dim) instead of rebuilding the table op by op
    # each attention layer (ops/fused.py — the elementwise-chain twin
    # of the graftopt outline rewrite)
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)            # (S, D/2)
    emb = jnp.concatenate([freqs, freqs], -1)  # (S, D)
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _rotate_half(x):
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def apply_rotary_pos_emb(q, k, cos, sin):
    """q,k: (B, S, H, D); cos/sin: (S, D) broadcast over batch and heads."""
    cos = cos[None, :, None, :]
    sin = sin[None, :, None, :]
    q2 = q * cos + _rotate_half(q) * sin
    k2 = k * cos + _rotate_half(k) * sin
    return q2, k2


class LlamaRotaryEmbedding(Layer):
    def __init__(self, head_dim, max_position_embeddings=4096, base=10000.0,
                 dtype="float32"):
        super().__init__()
        self.head_dim = head_dim
        self.max_position_embeddings = max_position_embeddings
        self.base = base

    def forward(self, x, seq_len):
        cos, sin = _rope_cos_sin(seq_len, self.head_dim, self.base, x.dtype)
        return cos, sin


class LlamaAttention(Layer):
    """Multi-head attention with rotary embeddings and grouped KV heads."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = config.head_dim
        h = config.hidden_size
        kv = self.num_kv_heads * self.head_dim
        init = Normal(std=config.initializer_range)
        if _tp(config):
            Col, Row = _mp_linears(config)
            self.q_proj = Col(h, h, has_bias=False, gather_output=False, weight_attr=init)
            self.k_proj = Col(h, kv, has_bias=False, gather_output=False, weight_attr=init)
            self.v_proj = Col(h, kv, has_bias=False, gather_output=False, weight_attr=init)
            self.o_proj = Row(h, h, has_bias=False, input_is_parallel=True,
                              weight_attr=init)
        else:
            self.q_proj = Linear(h, h, weight_attr=init, bias_attr=False)
            self.k_proj = Linear(h, kv, weight_attr=init, bias_attr=False)
            self.v_proj = Linear(h, kv, weight_attr=init, bias_attr=False)
            self.o_proj = Linear(h, h, weight_attr=init, bias_attr=False)
        self.rotary_emb = LlamaRotaryEmbedding(
            self.head_dim, config.max_position_embeddings, config.rope_theta)

    def forward(self, hidden_states, attn_mask=None):
        # sequence_parallel: Column fwd all-gathers the seq-sharded input, so q/k/v
        # hold the full sequence here regardless of the SP switch.
        q = self.q_proj(hidden_states)
        k = self.k_proj(hidden_states)
        v = self.v_proj(hidden_states)
        B = q.shape[0] if not self.config.sequence_parallel else None
        # under SP the layer input is (S/sp, B, H); Column output is (S, B, H)
        if self.config.sequence_parallel:
            S, B = q.shape[0], q.shape[1]
            q = ops.transpose(q, [1, 0, 2])
            k = ops.transpose(k, [1, 0, 2])
            v = ops.transpose(v, [1, 0, 2])
        else:
            B, S = q.shape[0], q.shape[1]
        q = ops.reshape(q, [B, S, self.num_heads, self.head_dim])
        k = ops.reshape(k, [B, S, self.num_kv_heads, self.head_dim])
        v = ops.reshape(v, [B, S, self.num_kv_heads, self.head_dim])

        from ..incubate.nn.functional import fused_rotary_position_embedding

        # use_neox_rotary_style=False = rotate-half pairing (fused_rope_kernel.cu:188
        # maps True→rotate_every_two) — matches apply_rotary_pos_emb above.
        q, k, _ = fused_rotary_position_embedding(
            q, k, rotary_theta=self.config.rope_theta, use_neox_rotary_style=False)

        if getattr(self.config, "use_ring_attention", False):
            # context parallelism: exact attention with S/P per device, k/v
            # ring-rotating over the sep axis (distributed/ring_attention.py);
            # GQA k/v rotate UN-repeated — the ring's grouped einsum shares
            # each kv head across its query group
            if attn_mask is not None:
                raise NotImplementedError(
                    "ring attention supports causal masking only; a custom "
                    "attn_mask would be silently ignored — use the math "
                    "attention path for masked inputs")
            from ..distributed.ring_attention import ring_attention

            out = ring_attention(
                q, k, v, mesh=getattr(self.config, "ring_mesh", None),
                axis_name=getattr(self.config, "ring_axis", "sep"),
                causal=True)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, is_causal=attn_mask is None,
                training=self.training)
        out = ops.reshape(out, [B, S, self.num_heads * self.head_dim])
        if self.config.sequence_parallel:
            out = ops.transpose(out, [1, 0, 2])  # back to (S, B, H) for Row
        return self.o_proj(out)


class LlamaMLP(Layer):
    """SwiGLU feed-forward: down(silu(gate(x)) * up(x))."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, m = config.hidden_size, config.intermediate_size
        init = Normal(std=config.initializer_range)
        if _tp(config):
            Col, Row = _mp_linears(config)
            self.gate_proj = Col(h, m, has_bias=False, gather_output=False,
                                 weight_attr=init)
            self.up_proj = Col(h, m, has_bias=False, gather_output=False,
                               weight_attr=init)
            self.down_proj = Row(m, h, has_bias=False, input_is_parallel=True,
                                 weight_attr=init)
        else:
            self.gate_proj = Linear(h, m, weight_attr=init, bias_attr=False)
            self.up_proj = Linear(h, m, weight_attr=init, bias_attr=False)
            self.down_proj = Linear(m, h, weight_attr=init, bias_attr=False)

    def forward(self, x):
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaMoEMLP(Layer):
    """Sparse MoE feed-forward: MoELayer over SwiGLU experts.

    Reference analog: the reference wires its MoELayer into transformer MLP slots
    (incubate/distributed/models/moe/moe_layer.py usage); `num_experts`/`moe_topk`
    /`moe_gate` config fields select it here. Experts are identical SwiGLU MLPs,
    so MoELayer's stacked-vmap path runs them as one batched program (and shards
    them over an `ep` mesh axis when one is provided)."""

    def __init__(self, config: LlamaConfig, mesh=None, expert_axis="ep"):
        super().__init__()
        from ..incubate.distributed.models.moe import MoELayer

        experts = LayerList([LlamaMLP(config)
                             for _ in range(config.num_experts)])
        self.moe = MoELayer(
            d_model=config.hidden_size, experts=experts,
            gate={"type": config.moe_gate, "top_k": config.moe_topk},
            mesh=mesh, expert_axis=expert_axis)

    @property
    def aux_loss(self):
        return self.moe.gate.get_loss()

    def forward(self, x):
        return self.moe(x)


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig, layer_idx=0):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        use_moe = (getattr(config, "num_experts", 0) or 0) > 1 and \
            (layer_idx % max(1, getattr(config, "moe_every_k", 1)) == 0)
        self.mlp = LlamaMoEMLP(
            config, mesh=getattr(config, "moe_mesh", None),
            expert_axis=getattr(config, "moe_expert_axis", "ep"),
        ) if use_moe else LlamaMLP(config)
        self.input_layernorm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        self.post_attention_layernorm = RMSNorm(
            config.hidden_size, epsilon=config.rms_norm_eps)
        self._recompute = config.recompute
        # reference recompute_granularity: "full" re-runs everything;
        # "full_attn"/"core_attn" map onto XLA remat policies that keep matmul
        # outputs resident and recompute only the cheap elementwise ops
        gran = getattr(config, "recompute_granularity", "full") or "full"
        self._recompute_policy = (None if gran == "full"
                                  else "dots_with_no_batch_dims_saveable")

    def _block(self, hidden_states, attn_mask=None):
        residual = hidden_states
        h = self.input_layernorm(hidden_states)
        h = self.self_attn(h, attn_mask)
        h = residual + h
        residual = h
        h = self.post_attention_layernorm(h)
        h = self.mlp(h)
        return residual + h

    def _block_with_aux(self, hidden_states, attn_mask=None):
        # bound method: recompute() collects this layer's parameters into the
        # differentiation set (a plain closure would sever their gradients)
        out = self._block(hidden_states, attn_mask)
        aux = self.mlp.aux_loss
        if aux is None:
            aux = ops.to_tensor(0.0, dtype="float32")
        return out, aux

    def consume_moe_aux(self):
        """This layer's gate balance loss from the last forward (cleared on
        read), threaded out of any recompute segment as a real output — reading
        gate.loss after the segment closes would leak an inner-trace tracer."""
        aux = self._moe_aux
        self._moe_aux = None
        if aux is None and isinstance(self.mlp, LlamaMoEMLP):
            aux = self.mlp.aux_loss
        return aux

    def forward(self, hidden_states, attn_mask=None):
        self._moe_aux = None
        if self._recompute and self.training:
            from ..distributed.fleet.recompute import recompute

            if isinstance(self.mlp, LlamaMoEMLP):
                out, self._moe_aux = recompute(
                    self._block_with_aux, hidden_states, attn_mask,
                    checkpoint_policy=self._recompute_policy)
                return out
            return recompute(self._block, hidden_states, attn_mask,
                             checkpoint_policy=self._recompute_policy)
        return self._block(hidden_states, attn_mask)


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        init = Normal(std=config.initializer_range)
        if _tp(config):
            from ..distributed.fleet.mpu.mp_layers import VocabParallelEmbedding

            self.embed_tokens = VocabParallelEmbedding(
                config.vocab_size, config.hidden_size, weight_attr=init)
        else:
            self.embed_tokens = Embedding(
                config.vocab_size, config.hidden_size, weight_attr=init)
        self.layers = LayerList(
            [LlamaDecoderLayer(config, layer_idx=i)
             for i in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def forward(self, input_ids, attn_mask=None):
        h = self.embed_tokens(input_ids)
        if self.config.sequence_parallel:
            from ..distributed.fleet.utils.sequence_parallel_utils import scatter

            h = ops.transpose(h, [1, 0, 2])  # (B,S,H) -> (S,B,H)
            h = scatter(h)                   # shard seq over mp
        for layer in self.layers:
            h = layer(h, attn_mask)
        h = self.norm(h)
        if self.config.sequence_parallel:
            from ..distributed.fleet.utils.sequence_parallel_utils import all_gather

            h = all_gather(h)
            h = ops.transpose(h, [1, 0, 2])  # back to (B,S,H)
        return h


class LlamaLMHead(Layer):
    def __init__(self, config: LlamaConfig, embedding=None):
        super().__init__()
        self.config = config
        self._tied = config.tie_word_embeddings and embedding is not None
        if self._tied:
            self._embedding = [embedding]  # list: not a registered sublayer
        else:
            init = Normal(std=config.initializer_range)
            w = self.create_parameter(
                shape=[config.hidden_size, config.vocab_size],
                attr=None, default_initializer=init)
            if _tp(config):
                from ..distributed.fleet.mpu.mp_layers import _mp_context, _shard_param

                mesh, axis_idx, _ = _mp_context()
                w = _shard_param(w, mesh, axis_idx, 1)
            self.weight = w

    def forward(self, hidden_states):
        if self._tied:
            w = ops.transpose(self._embedding[0].weight, [1, 0])
        else:
            w = self.weight
        logits = ops.matmul(hidden_states, w)
        if _tp(self.config):
            from ..distributed.fleet.mpu import mp_ops

            logits = mp_ops.mark_sharded(logits, dim=-1)
        return logits


class LlamaPretrainingCriterion(Layer):
    """Token-mean causal-LM loss with ignore_index masking (reference criterion shape)."""

    def __init__(self, config: LlamaConfig, ignore_index=-100):
        super().__init__()
        self.config = config
        self.ignore_index = ignore_index

    def forward(self, logits, labels):
        if _tp(self.config):
            from ..distributed.fleet.mpu.mp_layers import ParallelCrossEntropy

            tok_loss = ParallelCrossEntropy(ignore_index=self.ignore_index)(
                logits, labels)
        else:
            tok_loss = F.softmax_with_cross_entropy(
                logits, labels, ignore_index=self.ignore_index)
        tok_loss = ops.squeeze(tok_loss, -1) if tok_loss.ndim > labels.ndim else tok_loss
        return self.masked_mean(tok_loss, labels)

    def masked_mean(self, tok_loss, labels):
        """Token-mean over non-ignored positions (shared by the materialized
        and the fused head+CE paths)."""
        mask = (labels != self.ignore_index).astype(tok_loss.dtype)
        denom = ops.maximum(mask.sum(), ops.to_tensor(1.0, dtype=tok_loss.dtype))
        return (tok_loss * mask).sum() / denom


class LlamaForCausalLM(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        self.lm_head = LlamaLMHead(
            config, embedding=self.llama.embed_tokens
            if config.tie_word_embeddings else None)
        self.criterion = LlamaPretrainingCriterion(config)

    def moe_aux_loss(self):
        """Sum of the decoder MLPs' gate balance losses from the LAST forward
        (cleared on read); zero Tensor when no MoE layer ran."""
        total = None
        for layer in self.llama.layers:
            aux = layer.consume_moe_aux()
            if aux is not None:
                total = aux if total is None else total + aux
        if total is None:
            return ops.to_tensor(0.0, dtype="float32")
        return total

    def forward(self, input_ids, labels=None, attn_mask=None):
        h = self.llama(input_ids, attn_mask)
        if (labels is not None
                and getattr(self.config, "fused_head_ce", False)
                and not _tp(self.config)):
            # fused LM-head + CE: the [B, S, V] logits are never
            # materialized (sequence-chunked matmul + fp32 online softmax
            # under remat — incubate.nn.functional.fused_linear_cross_entropy).
            # Returns logits=None; training callers only consume the loss.
            from ..incubate.nn.functional import fused_linear_cross_entropy

            w = (ops.transpose(self.lm_head._embedding[0].weight, [1, 0])
                 if self.lm_head._tied else self.lm_head.weight)
            if labels.ndim == 3:  # reference [B, S, 1] label convention
                labels = ops.squeeze(labels, -1)
            tok_loss = fused_linear_cross_entropy(
                h, w, labels, ignore_index=self.criterion.ignore_index)
            loss = self.criterion.masked_mean(tok_loss, labels)
            if self.training and (getattr(self.config, "num_experts", 0) or 0) > 1:
                loss = loss + 0.01 * self.moe_aux_loss().astype(loss.dtype)
            return loss, None
        logits = self.lm_head(h)
        if labels is not None:
            loss = self.criterion(logits, labels)
            if self.training and (getattr(self.config, "num_experts", 0) or 0) > 1:
                # gate balance pressure (GShard §3.2), training only — eval
                # loss/perplexity must stay pure cross-entropy
                loss = loss + 0.01 * self.moe_aux_loss().astype(loss.dtype)
            return loss, logits
        return logits

    def generate(self, input_ids, max_new_tokens=32, temperature=0.0):
        """Greedy / temperature sampling, recomputing the prefix each step.

        (A KV-cache decode path is the inference engine's job; this is the
        correctness-oriented generate used by tests.)
        """
        out = input_ids
        for _ in range(max_new_tokens):
            logits = self.forward(out)
            nxt = logits[:, -1, :]
            if temperature and temperature > 0.0:
                nxt = nxt / temperature
                probs = F.softmax(nxt, axis=-1)
                tok = ops.multinomial(probs, 1)
            else:
                tok = ops.argmax(nxt, axis=-1, keepdim=True)
            out = ops.concat([out, tok.astype(out.dtype)], axis=1)
        return out


# ---------------------------------------------------------------------------
# Pipeline-parallel variant (PipelineLayer descs), reference: llama_pp tests
# ---------------------------------------------------------------------------
class _EmbeddingPipe(Layer):
    def __init__(self, config):
        super().__init__()
        self.config = config
        init = Normal(std=config.initializer_range)
        if _tp(config):
            from ..distributed.fleet.mpu.mp_layers import VocabParallelEmbedding

            self.embed_tokens = VocabParallelEmbedding(
                config.vocab_size, config.hidden_size, weight_attr=init)
        else:
            self.embed_tokens = Embedding(
                config.vocab_size, config.hidden_size, weight_attr=init)

    def forward(self, input_ids):
        h = self.embed_tokens(input_ids)
        if self.config.sequence_parallel:
            # same layout contract as LlamaModel.forward: decoder blocks run
            # sequence-major (S, B, H), seq-sharded over mp
            from ..distributed.fleet.utils.sequence_parallel_utils import scatter

            h = ops.transpose(h, [1, 0, 2])
            h = scatter(h)
        return h


class _NormPipe(Layer):
    def __init__(self, config):
        super().__init__()
        self.config = config
        self.norm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def forward(self, h):
        h = self.norm(h)
        if self.config.sequence_parallel:
            from ..distributed.fleet.utils.sequence_parallel_utils import all_gather

            h = all_gather(h)
            h = ops.transpose(h, [1, 0, 2])  # (S,B,H) -> (B,S,H) for the head
        return h


class _LMHeadPipe(LlamaLMHead):
    pass


def LlamaForCausalLMPipe(config: LlamaConfig, **pp_kwargs):
    """Build the PipelineLayer form: embedding | decoder x N | norm | lm_head."""
    from ..distributed.fleet.meta_parallel.pp_layers import LayerDesc, PipelineLayer

    descs = [LayerDesc(_EmbeddingPipe, config)]
    descs += [LayerDesc(LlamaDecoderLayer, config, layer_idx=i)
              for i in range(config.num_hidden_layers)]
    descs += [LayerDesc(_NormPipe, config), LayerDesc(_LMHeadPipe, config)]
    crit = LlamaPretrainingCriterion(config)
    pipe = PipelineLayer(
        descs,
        num_stages=config.pipeline_parallel_degree or None,
        loss_fn=lambda out, label: crit(out, label),
        seg_method="layer:LlamaDecoderLayer",
        **pp_kwargs,
    )
    # under sequence parallel the inter-block activation is sequence-major
    # (S, B, H): the compiled pipeline must micro-batch along axis 1
    pipe._microbatch_axis = 1 if config.sequence_parallel else 0

    if (getattr(config, "num_experts", 0) or 0) > 1:
        moe_decs = [l for l in pipe.run_function
                    if isinstance(l, LlamaDecoderLayer)
                    and isinstance(l.mlp, LlamaMoEMLP)]

        def loss_with_aux(out, label):
            loss = crit(out, label)
            aux = None
            for dec in moe_decs:
                a = dec.consume_moe_aux()
                # training only: eval loss/perplexity stays pure cross-entropy
                # (matches LlamaForCausalLM.forward)
                if a is not None and dec.training:
                    aux = a if aux is None else aux + a
            if aux is not None:
                loss = loss + 0.01 * aux.astype(loss.dtype)
            return loss

        pipe._loss_fn = loss_with_aux
    return pipe
