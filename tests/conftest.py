"""Test config: force an 8-device virtual CPU mesh BEFORE jax backends initialize.

Mirrors the reference's test strategy (SURVEY.md §4): distributed features are tested
single-host on a fake multi-device backend (their fake_cpu_device / gloo path; here XLA-CPU
with --xla_force_host_platform_device_count=8).

Note: this environment's sitecustomize registers a TPU PJRT plugin and forces
jax_platforms='axon,cpu' in every process; jax.config.update('jax_platforms', 'cpu') after
import (but before backend init) restores a pure-CPU test environment without touching the
TPU tunnel.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
