"""Tensor-parallel layers: VocabParallelEmbedding, Column/RowParallelLinear,
ParallelCrossEntropy.

Reference analog: python/paddle/distributed/fleet/layers/mpu/mp_layers.py
(VocabParallelEmbedding :49, ColumnParallelLinear :336, RowParallelLinear :543,
ParallelCrossEntropy :744). There each rank allocates 1/mp of the weight, and forward code
hand-places collectives (identity/allreduce/allgather) around the local matmul.

TPU-first redesign: each layer owns the FULL logical weight annotated with a GSPMD sharding
over the topology's `mp` mesh axis; forward is the plain math, with one sharding constraint
stating where the output should live. XLA's partitioner then emits exactly the collectives
the reference hand-writes: Column fwd = none (output stays sharded) or all-gather
(gather_output=True); Row fwd = psum of the partial matmul; embedding fwd = the masked
lookup + psum. Backward collectives come out of the same annotations by transposition.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....framework.core import Tensor
from ....nn.layer.layers import Layer
from ....nn import functional as F
from ....nn.initializer import Constant, XavierNormal
from ... import api as dist_api
from ...placement import Replicate, Shard
from ..topology import get_hybrid_parallel_group
from . import mp_ops


def _mp_context():
    """(ProcessMesh, mp axis index, mp degree) from the active topology."""
    hcg = get_hybrid_parallel_group()
    if hcg is not None:
        mesh = hcg.global_mesh
        return mesh, mesh.dim_names.index("mp"), hcg.get_model_parallel_world_size()
    import numpy as np

    from ...process_mesh import ProcessMesh

    mesh = ProcessMesh(np.arange(jax.device_count()), ["mp"])
    return mesh, 0, jax.device_count()


def _shard_param(param, mesh, mesh_axis_idx, tensor_dim):
    placements = [Replicate()] * mesh.ndim
    placements[mesh_axis_idx] = Shard(tensor_dim)
    return dist_api.shard_tensor(param, mesh, placements)


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over mp (mp_layers.py:49)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None,
                 name=None):
        super().__init__()
        mesh, axis_idx, degree = _mp_context()
        if num_embeddings % degree != 0:
            raise ValueError(
                f"vocab size {num_embeddings} must be divisible by mp degree {degree}"
            )
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        w = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=XavierNormal())
        self.weight = _shard_param(w, mesh, axis_idx, 0)

    def forward(self, x):
        out = F.embedding(x, self.weight)
        # reference: masked local lookup + allreduce; GSPMD derives both from the
        # vocab-sharded operand — constrain the result replicated to materialize the psum
        return mp_ops.mark_replicated(out)


class ColumnParallelLinear(Layer):
    """Linear with the output dim sharded over mp (mp_layers.py:336)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=None,
                 gather_output=True, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        mesh, axis_idx, degree = _mp_context()
        if out_features % degree != 0:
            raise ValueError(
                f"out_features {out_features} must be divisible by mp degree {degree}"
            )
        self._in_features = in_features
        self._out_features = out_features
        self.is_mp = degree > 1
        self.gather_output = gather_output
        w = self.create_parameter(shape=[in_features, out_features], attr=weight_attr)
        self.weight = _shard_param(w, mesh, axis_idx, 1)
        if has_bias is None or has_bias:
            b = self.create_parameter(shape=[out_features], attr=None, is_bias=True,
                                      default_initializer=Constant(0.0))
            self.bias = _shard_param(b, mesh, axis_idx, 0)
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return mp_ops._c_concat(out)
        return mp_ops.mark_sharded(out, dim=-1)


class RowParallelLinear(Layer):
    """Linear with the input dim sharded over mp (mp_layers.py:543)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None,
                 name=None):
        super().__init__()
        mesh, axis_idx, degree = _mp_context()
        if in_features % degree != 0:
            raise ValueError(
                f"in_features {in_features} must be divisible by mp degree {degree}"
            )
        self._in_features = in_features
        self._out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.is_mp = degree > 1
        w = self.create_parameter(shape=[in_features, out_features], attr=weight_attr)
        self.weight = _shard_param(w, mesh, axis_idx, 0)
        if has_bias:
            # bias is NOT sharded: applied after the partial-sum reduction
            self.bias = self.create_parameter(
                shape=[out_features], attr=None, is_bias=True,
                default_initializer=Constant(0.0))
        else:
            self.bias = None

    def forward(self, x):
        if not self.input_is_parallel:
            x = mp_ops.mark_sharded(x, dim=-1)
        out = F.linear(x, self.weight)
        # partial over mp -> replicated (the reference's mp_allreduce), bias after
        out = mp_ops.mark_replicated(out)
        if self.bias is not None:
            out = out + self.bias
        return out


class ParallelCrossEntropy(Layer):
    """Softmax cross-entropy over mp-sharded logits (mp_layers.py:744).

    The reference implements c_softmax_with_cross_entropy: local max/sum + allreduce
    pairs. Here the vocab axis of `input` is annotated sharded and the standard
    softmax_with_cross_entropy math compiles to those same two psums over mp.
    """

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):  # noqa: A002
        logits = mp_ops.mark_sharded(input, dim=-1)
        return F.softmax_with_cross_entropy(
            logits, label, ignore_index=self.ignore_index)
