"""OpTest sweep: forward vs numpy + analytic-vs-FD grads + dtype coverage for
the top ~100 ops (reference test/legacy_test/op_test.py methodology)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

from op_test import OpCase

S = (3, 4)          # default test shape
V = (6,)            # vector shape
SQ = (4, 4)         # square


def _sp(x):  # numpy softplus without overflow
    return np.logaddexp(0.0, x)


CASES = [
    # ---- unary math ----
    OpCase("abs", paddle.abs, np.abs, [S]),
    OpCase("exp", paddle.exp, np.exp, [S]),
    OpCase("expm1", paddle.expm1, np.expm1, [S]),
    OpCase("log", paddle.log, np.log, [S], positive=True),
    OpCase("log2", paddle.log2, np.log2, [S], positive=True),
    OpCase("log10", paddle.log10, np.log10, [S], positive=True),
    OpCase("log1p", paddle.log1p, np.log1p, [S], positive=True),
    OpCase("sqrt", paddle.sqrt, np.sqrt, [S], positive=True),
    OpCase("rsqrt", paddle.rsqrt, lambda x: 1 / np.sqrt(x), [S], positive=True),
    OpCase("sin", paddle.sin, np.sin, [S]),
    OpCase("cos", paddle.cos, np.cos, [S]),
    OpCase("tan", paddle.tan, np.tan, [S]),
    OpCase("asin", paddle.asin, np.arcsin, [S]),
    OpCase("acos", paddle.acos, np.arccos, [S]),
    OpCase("atan", paddle.atan, np.arctan, [S]),
    OpCase("sinh", paddle.sinh, np.sinh, [S]),
    OpCase("cosh", paddle.cosh, np.cosh, [S]),
    OpCase("tanh", paddle.tanh, np.tanh, [S]),
    OpCase("asinh", paddle.asinh, np.arcsinh, [S]),
    OpCase("acosh", lambda x: paddle.acosh(x + 1.5),
           lambda x: np.arccosh(x + 1.5), [S], positive=True),
    OpCase("atanh", paddle.atanh, np.arctanh, [S]),
    OpCase("floor", paddle.floor, np.floor, [S], grad=False,
           dtypes=("float32",)),  # bf16 quantization crosses integer steps
    OpCase("ceil", paddle.ceil, np.ceil, [S], grad=False,
           dtypes=("float32",)),  # bf16 quantization crosses integer steps
    OpCase("round", paddle.round, np.round, [S], grad=False,
           dtypes=("float32",)),  # bf16 quantization crosses integer steps
    OpCase("sign", paddle.sign, np.sign, [S], grad=False),
    OpCase("square", paddle.square, np.square, [S]),
    OpCase("reciprocal", paddle.reciprocal, np.reciprocal, [S], positive=True),
    OpCase("neg", paddle.neg, np.negative, [S]),
    OpCase("erf", paddle.erf, None, [S]),
    OpCase("lgamma", paddle.lgamma, None, [S], positive=True, grad=False),
    OpCase("digamma", paddle.digamma, None, [S], positive=True, grad=False),
    OpCase("frac", paddle.frac, lambda x: x - np.trunc(x), [S], grad=False,
           dtypes=("float32",)),
    OpCase("trunc", paddle.trunc, np.trunc, [S], grad=False,
           dtypes=("float32",)),  # bf16 quantization crosses integer steps
    OpCase("deg2rad", paddle.deg2rad, np.deg2rad, [S]),
    OpCase("rad2deg", paddle.rad2deg, np.rad2deg, [S]),
    OpCase("logit", lambda x: paddle.logit(x * 0.3 + 0.5),
           lambda x: (lambda p: np.log(p / (1 - p)))(x * 0.3 + 0.5), [S]),
    # ---- binary math ----
    OpCase("add", paddle.add, np.add, [S, S], int_dtypes=("int32", "int64")),
    OpCase("subtract", paddle.subtract, np.subtract, [S, S],
           int_dtypes=("int32",)),
    OpCase("multiply", paddle.multiply, np.multiply, [S, S],
           int_dtypes=("int32",)),
    OpCase("divide", paddle.divide, np.divide, [S, S], positive=True),
    OpCase("pow", paddle.pow, np.power, [S, S], positive=True),
    OpCase("maximum", paddle.maximum, np.maximum, [S, S]),
    OpCase("minimum", paddle.minimum, np.minimum, [S, S]),
    OpCase("fmax", paddle.fmax, np.fmax, [S, S]),
    OpCase("fmin", paddle.fmin, np.fmin, [S, S]),
    OpCase("mod", paddle.mod, np.mod, [S, S], positive=True, grad=False),
    OpCase("floor_divide", paddle.floor_divide, np.floor_divide, [S, S],
           positive=True, grad=False),
    OpCase("atan2", paddle.atan2, np.arctan2, [S, S]),
    OpCase("hypot", paddle.hypot, np.hypot, [S, S]),
    OpCase("logaddexp", paddle.logaddexp, np.logaddexp, [S, S]),
    OpCase("copysign", paddle.copysign, np.copysign, [S, S], grad=False),
    OpCase("heaviside", paddle.heaviside, np.heaviside, [S, S], grad=False),
    OpCase("lerp",
           lambda x, y, w: paddle.lerp(x, y, w),
           lambda x, y, w: x + w * (y - x), [S, S, S]),
    OpCase("nextafter", paddle.nextafter, np.nextafter, [S, S], grad=False,
           dtypes=("float32",)),
    # ---- broadcasting ----
    OpCase("add_broadcast", paddle.add, np.add, [(3, 1), (1, 4)]),
    OpCase("mul_broadcast", paddle.multiply, np.multiply, [(2, 3, 1), (3, 4)]),
    # ---- reductions ----
    OpCase("sum", paddle.sum, lambda x: np.sum(x), [S]),
    OpCase("sum_axis", lambda x: paddle.sum(x, axis=1),
           lambda x: np.sum(x, axis=1), [S]),
    OpCase("sum_keepdim", lambda x: paddle.sum(x, axis=0, keepdim=True),
           lambda x: np.sum(x, axis=0, keepdims=True), [S]),
    OpCase("mean", paddle.mean, lambda x: np.mean(x), [S]),
    OpCase("mean_axis", lambda x: paddle.mean(x, axis=-1),
           lambda x: np.mean(x, axis=-1), [S]),
    OpCase("prod", paddle.prod, lambda x: np.prod(x), [V], positive=True),
    OpCase("max_red", lambda x: paddle.max(x, axis=1),
           lambda x: np.max(x, axis=1), [S], grad=False),
    OpCase("min_red", lambda x: paddle.min(x, axis=1),
           lambda x: np.min(x, axis=1), [S], grad=False),
    OpCase("amax", lambda x: paddle.amax(x, axis=0),
           lambda x: np.max(x, axis=0), [S], grad=False),
    OpCase("amin", lambda x: paddle.amin(x, axis=0),
           lambda x: np.min(x, axis=0), [S], grad=False),
    OpCase("std", lambda x: paddle.std(x),
           lambda x: np.std(x, ddof=1), [S]),
    OpCase("var", lambda x: paddle.var(x),
           lambda x: np.var(x, ddof=1), [S]),
    OpCase("logsumexp", lambda x: paddle.logsumexp(x, axis=1),
           lambda x: np.log(np.sum(np.exp(x), axis=1)), [S]),
    OpCase("nansum", paddle.nansum, lambda x: np.nansum(x), [S]),
    OpCase("nanmean", paddle.nanmean, lambda x: np.nanmean(x), [S]),
    OpCase("count_nonzero", paddle.count_nonzero,
           lambda x: np.count_nonzero(x), [S], grad=False),
    # ---- cumulative ----
    OpCase("cumsum", lambda x: paddle.cumsum(x, axis=1),
           lambda x: np.cumsum(x, axis=1), [S]),
    OpCase("cumprod", lambda x: paddle.cumprod(x, dim=1),
           lambda x: np.cumprod(x, axis=1), [S], positive=True),
    OpCase("logcumsumexp", lambda x: paddle.logcumsumexp(x, axis=1),
           lambda x: np.log(np.cumsum(np.exp(x), axis=1)), [S]),
    # ---- linalg ----
    OpCase("matmul", paddle.matmul, np.matmul, [(3, 4), (4, 5)]),
    OpCase("matmul_batched", paddle.matmul, np.matmul,
           [(2, 3, 4), (2, 4, 5)]),
    OpCase("bmm", paddle.bmm, np.matmul, [(2, 3, 4), (2, 4, 5)]),
    OpCase("mm", paddle.mm, np.matmul, [(3, 4), (4, 2)]),
    OpCase("mv", paddle.mv, lambda a, b: a @ b, [(3, 4), (4,)]),
    OpCase("dot", paddle.dot, np.dot, [V, V]),
    OpCase("inner", paddle.inner, np.inner, [(3, 4), (5, 4)]),
    OpCase("outer", paddle.outer, np.outer, [V, V]),
    OpCase("cross", lambda a, b: paddle.cross(a, b, axis=-1),
           lambda a, b: np.cross(a, b, axis=-1), [(4, 3), (4, 3)]),
    OpCase("norm_fro", lambda x: paddle.norm(x),
           lambda x: np.linalg.norm(x), [S]),
    OpCase("trace", paddle.trace, np.trace, [SQ]),
    OpCase("diagonal", paddle.diagonal, lambda x: np.diagonal(x), [SQ]),
    OpCase("triu", paddle.triu, np.triu, [SQ]),
    OpCase("tril", paddle.tril, np.tril, [SQ]),
    OpCase("kron", paddle.kron, np.kron, [(2, 2), (2, 3)]),
    OpCase("addmm",
           lambda c, a, b: paddle.addmm(c, a, b, alpha=0.5, beta=2.0),
           lambda c, a, b: 2.0 * c + 0.5 * (a @ b),
           [(3, 5), (3, 4), (4, 5)]),
    OpCase("einsum_ij",
           lambda a, b: paddle.einsum("ij,jk->ik", a, b),
           lambda a, b: a @ b, [(3, 4), (4, 5)]),
    OpCase("matrix_power", lambda x: paddle.matrix_power(x, 3),
           lambda x: np.linalg.matrix_power(x, 3), [SQ], grad=False),
    # ---- manipulation ----
    OpCase("reshape", lambda x: paddle.reshape(x, [4, 3]),
           lambda x: np.reshape(x, (4, 3)), [S]),
    OpCase("transpose", lambda x: paddle.transpose(x, [1, 0]),
           lambda x: np.transpose(x), [S]),
    OpCase("concat", lambda a, b: paddle.concat([a, b], axis=0),
           lambda a, b: np.concatenate([a, b], 0), [S, S]),
    OpCase("stack", lambda a, b: paddle.stack([a, b], axis=0),
           lambda a, b: np.stack([a, b], 0), [S, S]),
    OpCase("split",
           lambda x: paddle.split(x, 2, axis=1),
           lambda x: np.split(x, 2, axis=1), [S]),
    OpCase("chunk",
           lambda x: paddle.chunk(x, 2, axis=0),
           lambda x: np.split(x, 2, axis=0), [(4, 3)]),
    OpCase("squeeze", lambda x: paddle.squeeze(x, axis=1),
           lambda x: np.squeeze(x, 1), [(3, 1, 4)]),
    OpCase("unsqueeze", lambda x: paddle.unsqueeze(x, axis=0),
           lambda x: np.expand_dims(x, 0), [S]),
    OpCase("flatten", paddle.flatten, np.ravel, [S]),
    OpCase("flip", lambda x: paddle.flip(x, axis=[0]),
           lambda x: np.flip(x, 0).copy(), [S]),
    OpCase("roll", lambda x: paddle.roll(x, 1, axis=0),
           lambda x: np.roll(x, 1, 0), [S]),
    OpCase("tile", lambda x: paddle.tile(x, [2, 1]),
           lambda x: np.tile(x, (2, 1)), [S]),
    OpCase("broadcast_to", lambda x: paddle.broadcast_to(x, [3, 4]),
           lambda x: np.broadcast_to(x, (3, 4)).copy(), [(1, 4)]),
    OpCase("expand", lambda x: paddle.expand(x, [3, 4]),
           lambda x: np.broadcast_to(x, (3, 4)).copy(), [(1, 4)]),
    OpCase("clip", lambda x: paddle.clip(x, -0.5, 0.5),
           lambda x: np.clip(x, -0.5, 0.5), [S]),
    OpCase("pad",
           lambda x: paddle.nn.functional.pad(x, [1, 1, 0, 2]),
           # 2*ndim flat pads apply first dim -> last dim (reference contract)
           lambda x: np.pad(x, ((1, 1), (0, 2))), [S]),
    OpCase("moveaxis", lambda x: paddle.moveaxis(x, 0, 1),
           lambda x: np.moveaxis(x, 0, 1), [S]),
    OpCase("diff", lambda x: paddle.diff(x, axis=0),
           lambda x: np.diff(x, axis=0), [S]),
    OpCase("masked_fill",
           lambda x: paddle.masked_fill(
               x, paddle.to_tensor(np.eye(3, 4) > 0), 9.0),
           lambda x: np.where(np.eye(3, 4) > 0, 9.0, x), [S]),
    # ---- indexing ----
    OpCase("gather",
           lambda x: paddle.gather(x, paddle.to_tensor(
               np.array([2, 0], "int64")), axis=0),
           lambda x: x[[2, 0]], [S]),
    OpCase("index_select",
           lambda x: paddle.index_select(x, paddle.to_tensor(
               np.array([1, 3], "int64")), axis=1),
           lambda x: x[:, [1, 3]], [S]),
    OpCase("take_along_axis",
           lambda x: paddle.take_along_axis(
               x, paddle.to_tensor(np.zeros((3, 1), "int64")), axis=1,
               broadcast=False),
           lambda x: np.take_along_axis(x, np.zeros((3, 1), np.int64), 1),
           [S]),
    OpCase("index_sample",
           lambda x: paddle.index_sample(x, paddle.to_tensor(
               np.array([[0, 1], [2, 3], [1, 0]], "int64"))),
           lambda x: np.take_along_axis(
               x, np.array([[0, 1], [2, 3], [1, 0]]), 1), [S]),
    # ---- search / sort ----
    OpCase("argmax", lambda x: paddle.argmax(x, axis=1),
           lambda x: np.argmax(x, 1), [S], grad=False),
    OpCase("argmin", lambda x: paddle.argmin(x, axis=1),
           lambda x: np.argmin(x, 1), [S], grad=False),
    OpCase("argsort", lambda x: paddle.argsort(x, axis=1),
           lambda x: np.argsort(x, 1, kind="stable"), [S], grad=False),
    OpCase("sort", lambda x: paddle.sort(x, axis=1),
           lambda x: np.sort(x, 1), [S]),
    OpCase("topk",
           lambda x: paddle.topk(x, 2, axis=1)[0],
           lambda x: np.sort(x, 1)[:, ::-1][:, :2].copy(), [S], grad=False),
    OpCase("kthvalue",
           lambda x: paddle.kthvalue(x, 2, axis=1)[0],
           lambda x: np.sort(x, 1)[:, 1], [S], grad=False),
    OpCase("where",
           lambda a, b: paddle.where(paddle.to_tensor(
               np.eye(3, 4) > 0), a, b),
           lambda a, b: np.where(np.eye(3, 4) > 0, a, b), [S, S]),
    OpCase("median", lambda x: paddle.median(x, axis=1),
           lambda x: np.median(x, axis=1), [(3, 5)], grad=False),
    OpCase("bucketize",
           lambda x: paddle.bucketize(x, paddle.to_tensor(
               np.array([-0.5, 0.0, 0.5]))),
           lambda x: np.searchsorted(np.array([-0.5, 0.0, 0.5]), x,
                                     side="left"), [S], grad=False),
    # ---- comparison / logical (forward only) ----
    OpCase("equal", paddle.equal, np.equal, [S, S], grad=False),
    OpCase("greater_than", paddle.greater_than, np.greater, [S, S],
           grad=False),
    OpCase("less_equal", paddle.less_equal, np.less_equal, [S, S],
           grad=False),
    OpCase("isnan", paddle.isnan, np.isnan, [S], grad=False),
    OpCase("isinf", paddle.isinf, np.isinf, [S], grad=False),
    OpCase("isfinite", paddle.isfinite, np.isfinite, [S], grad=False),
    OpCase("sgn_allclose", lambda a, b: paddle.allclose(a, a),
           lambda a, b: np.array(True), [S, S], grad=False),
    # ---- activations (nn.functional) ----
    OpCase("relu", F.relu, lambda x: np.maximum(x, 0), [S]),
    OpCase("sigmoid", F.sigmoid, lambda x: 1 / (1 + np.exp(-x)), [S]),
    OpCase("silu", F.silu, lambda x: x / (1 + np.exp(-x)), [S]),
    OpCase("gelu_tanh",
           lambda x: F.gelu(x, approximate=True),
           lambda x: 0.5 * x * (1 + np.tanh(
               np.sqrt(2 / np.pi) * (x + 0.044715 * x ** 3))), [S]),
    OpCase("leaky_relu", lambda x: F.leaky_relu(x, 0.1),
           lambda x: np.where(x >= 0, x, 0.1 * x), [S]),
    OpCase("elu", lambda x: F.elu(x, 1.0),
           lambda x: np.where(x > 0, x, np.exp(x) - 1), [S]),
    OpCase("softplus", F.softplus, _sp, [S]),
    OpCase("softsign", F.softsign, lambda x: x / (1 + np.abs(x)), [S]),
    OpCase("hardtanh", F.hardtanh, lambda x: np.clip(x, -1, 1), [S]),
    OpCase("mish", F.mish, lambda x: x * np.tanh(_sp(x)), [S]),
    OpCase("tanhshrink", F.tanhshrink, lambda x: x - np.tanh(x), [S]),
    OpCase("softmax",
           lambda x: F.softmax(x, axis=-1),
           lambda x: np.exp(x - x.max(-1, keepdims=True))
           / np.exp(x - x.max(-1, keepdims=True)).sum(-1, keepdims=True),
           [S]),
    OpCase("log_softmax",
           lambda x: F.log_softmax(x, axis=-1),
           lambda x: x - x.max(-1, keepdims=True) - np.log(
               np.exp(x - x.max(-1, keepdims=True)).sum(-1, keepdims=True)),
           [S]),
]

# special-cased references that need scipy-free implementations
import math

_ERF = np.vectorize(math.erf)
_LGAMMA = np.vectorize(math.lgamma)
for case in list(CASES):
    if case.name == "erf":
        case.ref = lambda x: _ERF(x)
    if case.name == "lgamma":
        case.ref = lambda x: _LGAMMA(x)
    if case.name == "digamma":
        try:
            from scipy.special import psi

            case.ref = lambda x: psi(x)
        except ImportError:
            CASES.remove(case)


_BY_NAME = {c.name: c for c in CASES}


@pytest.mark.parametrize("name", sorted(_BY_NAME), ids=str)
def test_forward(name):
    _BY_NAME[name].run_forward()


_GRAD_CASES = sorted(n for n, c in _BY_NAME.items() if c.grad)


@pytest.mark.parametrize("name", _GRAD_CASES, ids=str)
def test_grad_finite_difference(name):
    _BY_NAME[name].run_grad()


_INT_CASES = sorted(n for n, c in _BY_NAME.items() if c.int_dtypes)


@pytest.mark.parametrize("name", _INT_CASES, ids=str)
def test_int_forward(name):
    _BY_NAME[name].run_int_forward()
