"""AutoTuner: search the parallelism configuration space.

Reference analog: python/paddle/distributed/auto_tuner/{tuner,search,prune,
recorder,utils}.py — enumerate (dp, mp, pp, micro_batch, sharding) candidates,
prune invalid ones, launch trial jobs, record metrics, pick the best.

TPU-first mapping: candidates describe mesh factorizations; pruning knows the
TPU constraints (mp should ride the fastest ICI axis and divide heads; pp
divides layers; memory estimate = params*(2+4+4+4)/dp_shard + activations).
Trials run through a user callable (compile+time one step — in-process on the
single-controller runtime instead of launching subprocess jobs).
"""
from __future__ import annotations

import itertools

__all__ = ["SearchSpace", "prune_candidates", "AutoTuner", "Recorder"]


class SearchSpace:
    def __init__(self, num_devices, max_mp=8, max_pp=8,
                 micro_batch_sizes=(1, 2, 4, 8), shardings=(0, 1, 2, 3)):
        self.num_devices = num_devices
        self.max_mp = max_mp
        self.max_pp = max_pp
        self.micro_batch_sizes = tuple(micro_batch_sizes)
        self.shardings = tuple(shardings)

    def candidates(self):
        n = self.num_devices
        for mp, pp in itertools.product(range(1, self.max_mp + 1),
                                        range(1, self.max_pp + 1)):
            if n % (mp * pp) != 0:
                continue
            dp = n // (mp * pp)
            for mbs, stage in itertools.product(self.micro_batch_sizes,
                                                self.shardings):
                yield {"dp_degree": dp, "mp_degree": mp, "pp_degree": pp,
                       "micro_batch_size": mbs, "sharding_stage": stage}


def _estimate_bytes(cand, model_params, hidden, layers, seq, dtype_bytes=2):
    """Per-device memory estimate (reference prune.py memory heuristics)."""
    dp, mp, pp = cand["dp_degree"], cand["mp_degree"], cand["pp_degree"]
    stage = cand["sharding_stage"]
    shard = mp * pp
    param_b = model_params * dtype_bytes / shard
    master_opt = model_params * 12 / shard          # fp32 master + 2 moments
    if stage >= 1:
        master_opt /= dp
    if stage >= 3:
        param_b /= dp
    act = (cand["micro_batch_size"] * seq * hidden * layers
           * 4 * dtype_bytes) / (mp * pp)
    return param_b + master_opt + act


def prune_candidates(space, model_params=0, hidden=0, layers=0, seq=0,
                     num_heads=None, global_batch=None, hbm_bytes=None):
    """Drop invalid/overflowing candidates (reference prune.py rules)."""
    out = []
    for cand in space.candidates():
        mp, pp = cand["mp_degree"], cand["pp_degree"]
        dp, mbs = cand["dp_degree"], cand["micro_batch_size"]
        if num_heads is not None and num_heads % mp != 0:
            continue
        if layers and pp > layers:
            continue
        if global_batch is not None:
            if global_batch % (dp * mbs) != 0:
                continue
        if hbm_bytes is not None and model_params:
            if _estimate_bytes(cand, model_params, hidden, layers, seq) \
                    > hbm_bytes:
                continue
        out.append(cand)
    return out


class Recorder:
    """Trial metric store, best-first (reference recorder.py)."""

    def __init__(self, metric="tokens_per_sec", maximize=True):
        self.metric = metric
        self.maximize = maximize
        self.history = []

    def add(self, candidate, metrics, error=None):
        self.history.append(
            {"candidate": dict(candidate), "metrics": dict(metrics or {}),
             "error": error})

    def best(self):
        scored = [h for h in self.history
                  if h["error"] is None and self.metric in h["metrics"]]
        if not scored:
            return None
        key = lambda h: h["metrics"][self.metric]
        return (max if self.maximize else min)(scored, key=key)


class AutoTuner:
    """Drive trials over the pruned space (reference tuner.py)."""

    def __init__(self, space, trial_fn, metric="tokens_per_sec",
                 maximize=True, max_trials=None, **prune_kwargs):
        self.space = space
        self.trial_fn = trial_fn
        self.recorder = Recorder(metric, maximize)
        self.max_trials = max_trials
        self.prune_kwargs = prune_kwargs

    def tune(self):
        cands = prune_candidates(self.space, **self.prune_kwargs)
        if self.max_trials is not None:
            cands = cands[: self.max_trials]
        for cand in cands:
            try:
                metrics = self.trial_fn(cand)
                self.recorder.add(cand, metrics)
            except Exception as e:  # noqa: BLE001 — a failed trial is data
                self.recorder.add(cand, None, error=str(e))
        return self.recorder.best()
