"""GL003 clean sample: every registration matches its docs/ops.md row."""
import jax.numpy as jnp

from paddle_tpu.ops._apply import defop


@defop("fx_add")
def fx_add(x, y):
    return x + y


@defop("fx_matmul", amp_category="white")
def fx_matmul(x, y):
    return jnp.matmul(x, y)
