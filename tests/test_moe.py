"""MoE / expert parallelism.

Mirrors the reference's MoE semantics (incubate/distributed/models/moe/
moe_layer.py, gate/gshard_gate.py, gate/switch_gate.py) on the 8-device virtual
CPU mesh."""
import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import ProcessMesh
from paddle_tpu.incubate.distributed.models.moe import (
    GShardGate,
    MoELayer,
    NaiveGate,
    SwitchGate,
)


class Expert(paddle.nn.Layer):
    def __init__(self, d_model, d_hidden):
        super().__init__()
        self.htoh4 = paddle.nn.Linear(d_model, d_hidden)
        self.h4toh = paddle.nn.Linear(d_hidden, d_model)

    def forward(self, x):
        return self.h4toh(paddle.nn.functional.relu(self.htoh4(x)))


def _make_moe(d_model=8, d_hidden=16, num_experts=4, gate=None, mesh=None):
    paddle.seed(0)
    experts = paddle.nn.LayerList(
        [Expert(d_model, d_hidden) for _ in range(num_experts)])
    return MoELayer(d_model=d_model, experts=experts, gate=gate, mesh=mesh)


def _dense_reference(layer, x):
    """Dense mixture: y_t = sum_k gate_w[t,k] * expert_{topk[t,k]}(x_t), with the
    same top-k selection, no capacity drops."""
    import jax
    import jax.numpy as jnp

    logits = layer.gate.gate(paddle.to_tensor(x)).numpy()
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits, jnp.float32), axis=-1))
    k = layer.top_k
    topi = np.argsort(-probs, axis=-1)[:, :k]
    topw = np.take_along_axis(probs, topi, axis=-1)
    topw = topw / topw.sum(-1, keepdims=True)
    outs = np.stack([layer.experts[e](paddle.to_tensor(x)).numpy()
                     for e in range(layer.num_expert)])           # (E, T, d)
    y = np.zeros_like(x)
    for t in range(x.shape[0]):
        for j in range(k):
            y[t] += topw[t, j] * outs[topi[t, j], t]
    return y


class TestMoEForward:
    def test_matches_dense_reference_no_drops(self):
        layer = _make_moe(gate={"type": "naive", "top_k": 2})
        layer.eval()  # capacity = T for naive gate; nothing dropped
        x = np.random.RandomState(0).randn(24, 8).astype("float32")
        y = layer(paddle.to_tensor(x)).numpy()
        ref = _dense_reference(layer, x)
        np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)

    def test_batch_shape_preserved(self):
        layer = _make_moe(gate={"type": "gshard", "top_k": 2})
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 6, 8).astype("float32"))
        y = layer(x)
        assert y.shape == [2, 6, 8]

    def test_capacity_respected(self):
        from paddle_tpu.incubate.distributed.models.moe.gate import _topk_dispatch

        r = np.random.RandomState(0)
        logits = paddle.to_tensor(r.randn(32, 4).astype("float32"))
        dispatch, _, _, kept = _topk_dispatch(logits, None, top_k=2, capacity=5)
        d = dispatch.numpy()
        # each expert's token queue never exceeds capacity, one token per slot
        per_slot = d.sum(axis=0)               # (E, C)
        assert (per_slot <= 1.0 + 1e-6).all()
        tokens_per_expert = d.sum(axis=(0, 2))  # (E,)
        assert (tokens_per_expert <= 5 + 1e-6).all()

    def test_gshard_aux_loss_and_grads(self):
        layer = _make_moe(gate={"type": "gshard", "top_k": 2})
        x = paddle.to_tensor(
            np.random.RandomState(2).randn(32, 8).astype("float32"))
        y = layer(x)
        aux = layer.gate.get_loss()
        assert aux is not None and float(aux.numpy()) > 0
        (y.mean() + 0.01 * aux).backward()
        # gate and at least one expert receive gradients
        assert layer.gate.gate.weight.grad is not None
        grads = [e.htoh4.weight.grad for e in layer.experts]
        assert any(g is not None and float(np.abs(g.numpy()).sum()) > 0
                   for g in grads)

    def test_switch_gate_top1(self):
        layer = _make_moe(gate={"type": "switch", "top_k": 1})
        layer.eval()
        x = paddle.to_tensor(
            np.random.RandomState(3).randn(16, 8).astype("float32"))
        y = layer(x)
        assert y.shape == [16, 8]
        assert isinstance(layer.gate, SwitchGate)

    def test_gate_class_instances_accepted(self):
        g = NaiveGate(8, 4, topk=2)
        experts = paddle.nn.LayerList([Expert(8, 16) for _ in range(4)])
        layer = MoELayer(d_model=8, experts=experts, gate=g)
        assert layer.gate is g
        g2 = GShardGate(8, 4, topk=2)
        assert g2.top_k == 2


class TestExpertParallel:
    def test_expert_axis_sharding_8dev(self):
        """Experts shard over an 8-device ep axis; outputs match unsharded run."""
        mesh = ProcessMesh(np.arange(8), ["ep"]).jax_mesh()
        layer = _make_moe(num_experts=8, gate={"type": "naive", "top_k": 2},
                          mesh=mesh)
        layer.eval()
        x = np.random.RandomState(4).randn(16, 8).astype("float32")
        y_sharded = layer(paddle.to_tensor(x)).numpy()

        layer2 = _make_moe(num_experts=8, gate={"type": "naive", "top_k": 2})
        layer2.eval()
        y_plain = layer2(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(y_sharded, y_plain, rtol=1e-4, atol=1e-5)

    def test_tokens_balanced_under_aux_loss_training(self):
        """Training with the aux loss drives routing toward balance."""
        paddle.seed(0)
        layer = _make_moe(d_model=8, num_experts=4,
                          gate={"type": "gshard", "top_k": 2})
        opt = paddle.optimizer.Adam(learning_rate=5e-3,
                                    parameters=layer.parameters())
        r = np.random.RandomState(5)
        x = paddle.to_tensor(r.randn(64, 8).astype("float32"))
        for _ in range(30):
            y = layer(x)
            aux = layer.gate.get_loss()
            loss = ((y - 1.0) ** 2).mean() + 0.1 * aux
            loss.backward()
            opt.step()
            opt.clear_grad()
        # after training, top-1 assignment is not fully collapsed on one expert
        import jax

        logits = layer.gate.gate(x).numpy()
        top1 = logits.argmax(-1)
        counts = np.bincount(top1, minlength=4)
        assert counts.max() < 64  # not all tokens on a single expert
        assert (counts > 0).sum() >= 2


class TestMoEGradClip:
    def test_clip_scales_grads(self):
        from paddle_tpu.incubate.distributed.models.moe import (
            ClipGradForMOEByGlobalNorm)

        p = paddle.to_tensor(np.ones(4, "float32"), stop_gradient=False)
        g = paddle.to_tensor(np.full(4, 10.0, "float32"))
        clip = ClipGradForMOEByGlobalNorm(clip_norm=1.0)
        out = clip([(p, g)])
        norm = float(np.sqrt((out[0][1].numpy() ** 2).sum()))
        assert abs(norm - 1.0) < 1e-5


class TestGlobalScatterGather:
    def test_roundtrip(self):
        from paddle_tpu.distributed.utils import global_gather, global_scatter

        x = paddle.to_tensor(np.arange(12, dtype="float32").reshape(6, 2))
        counts = paddle.to_tensor(np.array([2, 4], "int64"))
        y = global_scatter(x, counts, counts)
        np.testing.assert_array_equal(y.numpy(), x.numpy())
        z = global_gather(y, counts, counts)
        np.testing.assert_array_equal(z.numpy(), x.numpy())


class TestLlamaMoE:
    def test_llama_with_moe_mlp_trains(self):
        from paddle_tpu.models.llama import (
            LlamaConfig, LlamaForCausalLM, LlamaMoEMLP)

        paddle.seed(0)
        cfg = LlamaConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_experts=4, moe_topk=2, moe_gate="naive",
            use_flash_attention=False)
        model = LlamaForCausalLM(cfg)
        mlps = [l.mlp for l in model.llama.layers]
        assert all(isinstance(m, LlamaMoEMLP) for m in mlps)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 128, (2, 8)).astype("int64"))
        logits = model(ids)
        assert logits.shape == [2, 8, 128]
        loss = paddle.nn.functional.cross_entropy(
            paddle.reshape(logits, [-1, 128]),
            paddle.reshape(ids, [-1])).mean()
        loss.backward()
        g = mlps[0].moe.experts[0].gate_proj.weight.grad
        assert g is not None

    def test_moe_aux_loss_enters_training_loss(self):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        paddle.seed(0)
        cfg = LlamaConfig(
            vocab_size=64, hidden_size=16, intermediate_size=32,
            num_hidden_layers=1, num_attention_heads=2,
            num_experts=4, moe_topk=2, moe_gate="gshard",
            use_flash_attention=False)
        model = LlamaForCausalLM(cfg)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 64, (2, 8)).astype("int64"))
        loss, _ = model(ids, labels=ids)
        # with the gate cleared by the loss path, a second read returns zero
        assert float(model.moe_aux_loss().numpy()) == 0.0
        loss.backward()
        assert model.llama.layers[0].mlp.moe.gate.gate.weight.grad is not None

    def test_pipe_descs_respect_moe_every_k(self):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLMPipe, LlamaMoEMLP

        cfg = LlamaConfig(
            vocab_size=64, hidden_size=16, intermediate_size=32,
            num_hidden_layers=4, num_attention_heads=2,
            num_experts=2, moe_topk=2, moe_gate="naive", moe_every_k=2,
            use_flash_attention=False, pipeline_parallel_degree=1)
        pipe = LlamaForCausalLMPipe(cfg)
        decs = [l for l in pipe.run_function
                if l.__class__.__name__ == "LlamaDecoderLayer"]
        kinds = [isinstance(l.mlp, LlamaMoEMLP) for l in decs]
        assert kinds == [True, False, True, False]

    def test_eval_loss_excludes_aux(self):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        paddle.seed(0)
        cfg = LlamaConfig(
            vocab_size=64, hidden_size=16, intermediate_size=32,
            num_hidden_layers=1, num_attention_heads=2,
            num_experts=4, moe_topk=2, moe_gate="gshard",
            use_flash_attention=False)
        model = LlamaForCausalLM(cfg)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 64, (2, 8)).astype("int64"))
        model.eval()
        loss_eval, logits = model(ids, labels=ids)
        pure_ce = model.criterion(logits, ids)
        np.testing.assert_allclose(loss_eval.numpy(), pure_ce.numpy(),
                                   rtol=1e-6)

    def test_global_scatter_rejects_asymmetric_counts(self):
        from paddle_tpu.distributed.utils import global_scatter

        x = paddle.to_tensor(np.arange(12, dtype="float32").reshape(6, 2))
        lc = paddle.to_tensor(np.array([2, 4], "int64"))
        gc = paddle.to_tensor(np.array([4, 2], "int64"))
        with pytest.raises(ValueError, match="symmetric"):
            global_scatter(x, lc, gc)

    def test_recompute_moe_aux_no_tracer_leak(self):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        paddle.seed(0)
        cfg = LlamaConfig(
            vocab_size=64, hidden_size=16, intermediate_size=32,
            num_hidden_layers=1, num_attention_heads=2,
            num_experts=2, moe_topk=2, moe_gate="naive",
            recompute=True, use_flash_attention=False)
        model = LlamaForCausalLM(cfg)
        model.train()
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 64, (2, 8)).astype("int64"))
        loss, _ = model(ids, labels=ids)  # must not raise UnexpectedTracerError
        loss.backward()
        experts = model.llama.layers[0].mlp.moe.experts
        assert any(e.gate_proj.weight.grad is not None for e in experts)
