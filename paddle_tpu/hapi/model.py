"""paddle.Model: the Keras-like high-level trainer.

Reference analog: python/paddle/hapi/model.py (Model.prepare/fit/evaluate/predict/
save/load/summary; DynamicGraphAdapter.train_batch :759). TPU-first: one adapter —
eager steps whose ops are cached XLA executables; `paddle.Model(net).prepare(...)` then
`fit()` drives DataLoaders, callbacks and metrics exactly like the reference.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .. import optimizer as _opt_mod
from ..autograd import no_grad
from ..framework.core import Tensor
from ..framework_io import load as _load, save as _save
from ..io.dataloader import DataLoader
from ..metric import Metric
from ..monitor import trace as _trace
from ..nn.layer.layers import Layer
from .callbacks import config_callbacks

_END = object()  # loader-exhausted sentinel for the traced fit loop


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _to_tensor(x):
    if isinstance(x, Tensor):
        return x
    return Tensor(np.asarray(x))


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._remat_plan = None
        self.stop_training = False

    # -- setup ---------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        for m in _to_list(metrics):
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m} must be a paddle.metric.Metric")
        self._metrics = _to_list(metrics)
        return self

    def plan_remat(self, inputs, labels=None, budget=None):
        """Budget-driven remat for the eager fit path: trace a
        functional train step over this batch, run the graftopt planner
        (``analysis/jaxpr/planner.plan_for_model``) against ``budget``
        bytes of per-device HBM (default: the network config's
        ``hbm_budget``), and APPLY the minimal per-layer remat set —
        the ``recompute_policy="budget"`` replacement for the
        all-or-nothing ``recompute=True``. Returns the plan dict; a
        network whose config declares ``recompute_policy="budget"``
        plans automatically on its first ``train_batch``/``fit``
        batch."""
        from ..analysis.jaxpr import planner as _planner

        if self._optimizer is None:
            raise RuntimeError(
                "plan_remat needs an optimizer: call prepare() first")
        cfg = getattr(self.network, "config", None)
        if budget is None:
            budget = getattr(cfg, "hbm_budget", None)
        if budget is None:
            raise ValueError(
                "plan_remat needs a budget: pass budget= or set "
                "hbm_budget on the network config")
        self.network.train()  # remat wraps only in training mode
        ins = [_to_tensor(x) for x in _to_list(inputs)]
        lbs = [_to_tensor(x) for x in _to_list(labels)]
        n_in = len(ins)
        loss_obj = self._loss

        def loss_fn(net, *tensors):
            outs = _to_list(net(*tensors[:n_in]))
            losses = (_to_list(loss_obj(*(outs + list(tensors[n_in:]))))
                      if loss_obj else outs)
            total = losses[0]
            for l in losses[1:]:  # noqa: E741
                total = total + l
            return total

        self._remat_plan = _planner.plan_for_model(
            self.network, self._optimizer, loss_fn, tuple(ins + lbs),
            budget)
        return self._remat_plan

    # -- single-batch APIs ----------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        # stage spans (monitor.trace, no-ops when tracing is off) nest under
        # the fit() loop's train.step root via implicit thread parenting —
        # the training-step decomposition of docs/tracing.md
        if (self._remat_plan is None and self._optimizer is not None
                and getattr(getattr(self.network, "config", None),
                            "recompute_policy", None) == "budget"):
            self.plan_remat(inputs, labels)
        self.network.train()
        inputs = [_to_tensor(x) for x in _to_list(inputs)]
        labels = [_to_tensor(x) for x in _to_list(labels)]
        with _trace.span("train.forward"):
            outputs = self.network(*inputs)
            outs = _to_list(outputs)
            losses = (_to_list(self._loss(*(outs + labels)))
                      if self._loss else outs)
            total = losses[0]
            for l in losses[1:]:  # noqa: E741
                total = total + l
        with _trace.span("train.backward"):
            total.backward()
        if update:
            with _trace.span("train.optimizer"):
                self._optimizer.step()
                self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            metrics.append(m.update(*_to_list(m.compute(*(outs + labels)))))
        out_losses = [float(np.asarray(l.numpy()).reshape(-1)[0]) for l in losses]
        return (out_losses, metrics) if metrics else out_losses

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        with no_grad():
            inputs = [_to_tensor(x) for x in _to_list(inputs)]
            labels = [_to_tensor(x) for x in _to_list(labels)]
            outs = _to_list(self.network(*inputs))
            losses = (_to_list(self._loss(*(outs + labels)))
                      if self._loss else outs)
            metrics = []
            for m in self._metrics:
                metrics.append(m.update(*_to_list(m.compute(*(outs + labels)))))
            out_losses = [float(np.asarray(l.numpy()).reshape(-1)[0])
                          for l in losses]
        return (out_losses, metrics) if metrics else out_losses

    def predict_batch(self, inputs):
        self.network.eval()
        with no_grad():
            inputs = [_to_tensor(x) for x in _to_list(inputs)]
            outs = _to_list(self.network(*inputs))
            return [o.numpy() for o in outs]

    # -- loops ----------------------------------------------------------------
    @staticmethod
    def _loader(data, batch_size, shuffle, num_workers, drop_last=False):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers, drop_last=drop_last)

    @staticmethod
    def _split_batch(batch):
        batch = list(batch) if isinstance(batch, (list, tuple)) else [batch]
        if len(batch) == 1:
            return batch, []
        return batch[:-1], batch[-1:]

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, checkpoint=None,
            checkpoint_freq=1, resume=True):
        """``checkpoint`` (a ``paddle_tpu.checkpoint.CheckpointManager``
        or a directory path) turns on resumable training: every
        ``checkpoint_freq`` trained batches the network + optimizer state,
        the RNG key and the exact (epoch, batch) cursor are snapshotted
        asynchronously (digest-verified, atomically committed); with
        ``resume`` (default) a restarted ``fit()`` reloads the newest
        valid checkpoint and fast-forwards the loader to the saved
        cursor. Resume-determinism requires a deterministically ordered
        loader (``shuffle=False`` or a seeded sampler) — see
        docs/checkpoint.md."""
        loader = self._loader(train_data, batch_size, shuffle, num_workers,
                              drop_last=drop_last)
        eval_loader = self._loader(eval_data, batch_size, False, num_workers)
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks = config_callbacks(callbacks, model=self, epochs=epochs, steps=steps,
                                log_freq=log_freq, verbose=verbose,
                                save_freq=save_freq, save_dir=save_dir,
                                metrics=self._metrics_name())
        ckpt_mgr = self._ckpt_manager(checkpoint)
        own_mgr = ckpt_mgr is not None and ckpt_mgr is not checkpoint
        if ckpt_mgr is not None and shuffle \
                and not isinstance(train_data, DataLoader):
            import warnings

            warnings.warn(
                "Model.fit(checkpoint=...) with shuffle=True: the resume "
                "cursor fast-forwards a RESHUFFLED loader, so a resumed "
                "run trains different batches than the interrupted one. "
                "Pass shuffle=False (or a deterministically seeded "
                "loader) for the resume-determinism contract "
                "(docs/checkpoint.md).", stacklevel=2)
        start_epoch = 0
        skip_steps = 0
        it_count = 0
        if ckpt_mgr is not None and not resume \
                and ckpt_mgr.latest_step() is not None:
            # fresh run over a directory holding prior commits: purge
            # them — saves are skip-if-committed (atomicity), so stale
            # steps would otherwise shadow this run's snapshots
            ckpt_mgr.clear()
        if ckpt_mgr is not None and resume \
                and ckpt_mgr.latest_step() is not None:
            cursor = self._apply_checkpoint(ckpt_mgr.restore_latest_valid())
            start_epoch = cursor["epoch"]
            skip_steps = cursor["step_in_epoch"]
            it_count = cursor["iteration"]
            if steps is not None and skip_steps >= steps:
                # the checkpoint landed on the epoch's FINAL batch:
                # resume at the next epoch instead of draining an empty
                # fast-forward that would re-fire epoch-end callbacks
                # (and re-run eval) for the already-completed epoch
                start_epoch += 1
                skip_steps = 0
        self.stop_training = False
        cbks.on_train_begin()
        try:
            self._fit_loop(loader, eval_loader, cbks, epochs, start_epoch,
                           skip_steps, it_count, eval_freq, verbose,
                           accumulate_grad_batches, num_iters, ckpt_mgr,
                           checkpoint_freq)
        finally:
            # the fit-owned writer thread must stop (and a failed async
            # write surface) even when training itself raised
            if ckpt_mgr is not None:
                try:
                    ckpt_mgr.wait()
                finally:
                    if own_mgr:
                        ckpt_mgr.close()

    def _fit_loop(self, loader, eval_loader, cbks, epochs, start_epoch,
                  skip_steps, it_count, eval_freq, verbose,
                  accumulate_grad_batches, num_iters, ckpt_mgr,
                  checkpoint_freq):
        for epoch in range(start_epoch, epochs):
            for m in self._metrics:
                m.reset()
            cbks.on_epoch_begin(epoch)
            logs = {}
            it = iter(loader)
            step = 0
            if epoch == start_epoch and skip_steps:
                # resume cursor: fast-forward the already-trained batches
                # of the interrupted epoch (deterministic order contract)
                for _ in range(skip_steps):
                    next(it, _END)
                step = skip_steps
                skip_steps = 0
            while True:
                # train.step root + dataload stage; train_batch adds the
                # forward/backward/optimizer stages under the same root.
                # (With tracing on, the epoch's final loader drain records
                # one dataload-only step span — an honest measurement of
                # the end-of-epoch fetch.)
                with _trace.training_step(step=step) as ts:
                    with ts.stage("dataload"):
                        batch = next(it, _END)
                    if batch is _END:
                        break
                    cbks.on_train_batch_begin(step)
                    ins, labels = self._split_batch(batch)
                    update = (step + 1) % accumulate_grad_batches == 0
                    res = self.train_batch(ins, labels, update=update)
                    logs = self._make_logs(res)
                    cbks.on_train_batch_end(step, logs)
                it_count += 1
                # checkpoints align to accumulation boundaries: a
                # snapshot between them would drop the accumulated-but-
                # unapplied grads and break resume-determinism
                if ckpt_mgr is not None and checkpoint_freq and update \
                        and it_count % checkpoint_freq == 0:
                    self._save_checkpoint(ckpt_mgr, it_count, epoch,
                                          step + 1)
                if num_iters is not None and it_count >= num_iters:
                    self.stop_training = True
                    break
                step += 1
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, verbose=verbose, callbacks=cbks,
                              _inner=True)
            if self.stop_training:
                break
        cbks.on_train_end()

    # -- resumable-fit checkpoint plumbing ------------------------------------
    @staticmethod
    def _ckpt_manager(checkpoint):
        if checkpoint is None:
            return None
        from ..checkpoint import CheckpointManager

        if isinstance(checkpoint, CheckpointManager):
            return checkpoint
        return CheckpointManager(checkpoint)

    @staticmethod
    def _flatten_tree(prefix, tree, arrays, scalars):
        """dict tree -> flat {prefix/path: leaf}; tensor-like leaves go to
        ``arrays``, JSON-able leaves to ``scalars``."""
        for key, val in tree.items():
            path = f"{prefix}/{key}"
            if isinstance(val, dict):
                Model._flatten_tree(path, val, arrays, scalars)
            elif isinstance(val, Tensor):
                arrays[path] = val.value
            elif hasattr(val, "shape") and hasattr(val, "dtype"):
                arrays[path] = val
            else:
                scalars[path] = val

    @staticmethod
    def _unflatten_tree(prefix, arrays, scalars):
        nested = {}
        for src in (arrays, scalars):
            for path, val in src.items():
                if not path.startswith(prefix + "/"):
                    continue
                parts = path[len(prefix) + 1:].split("/")
                cur = nested
                for part in parts[:-1]:
                    cur = cur.setdefault(part, {})
                cur[parts[-1]] = val
        return nested

    def _save_checkpoint(self, mgr, iteration, epoch, step_in_epoch):
        import jax

        from ..framework import random as _rng
        from ..optimizer.lr import LRScheduler

        arrays, scalars = {}, {}
        self._flatten_tree("net", self.network.state_dict(), arrays,
                           scalars)
        opt = self._optimizer
        if opt is not None:
            # optimizer state is keyed STRUCTURALLY (parameter position),
            # not by p.name — auto-names ride a process-global counter,
            # so a fresh model instance could never match them back
            for i, p in enumerate(opt._parameter_list_flat()):
                for k, v in (opt._accumulators.get(id(p)) or {}).items():
                    arrays[f"opt/acc/{i}/{k}"] = v
                mw = opt._master_weights.get(id(p))
                if mw is not None:
                    arrays[f"opt/master/{i}"] = mw
            scalars["opt/@step"] = opt._step_count
            if isinstance(opt._learning_rate, LRScheduler):
                scalars["opt/@lr"] = opt._learning_rate.state_dict()
        arrays["rng/key"] = np.asarray(
            jax.random.key_data(_rng.get_rng_state()))
        mgr.save(iteration, arrays,
                 meta={"epoch": epoch, "step_in_epoch": step_in_epoch,
                       "iteration": iteration, "scalars": scalars})

    def _apply_checkpoint(self, rc):
        import jax
        import jax.numpy as jnp

        from ..framework import random as _rng
        from ..optimizer.lr import LRScheduler

        scalars = rc.meta.get("scalars", {})
        self.network.set_state_dict(
            self._unflatten_tree("net", rc.arrays, scalars))
        opt = self._optimizer
        if opt is not None:
            for i, p in enumerate(opt._parameter_list_flat()):
                acc = opt._init_state(p)
                found = False
                for k in list(acc):
                    v = rc.arrays.get(f"opt/acc/{i}/{k}")
                    if v is not None:
                        acc[k] = jnp.asarray(np.asarray(v))
                        found = True
                if found:
                    opt._accumulators[id(p)] = opt._apply_shard_fn(p, acc)
                mw = rc.arrays.get(f"opt/master/{i}")
                if mw is not None:
                    opt._master_weights[id(p)] = jnp.asarray(
                        np.asarray(mw))
            opt._step_count = int(scalars.get("opt/@step", 0))
            if isinstance(opt._learning_rate, LRScheduler) \
                    and scalars.get("opt/@lr"):
                opt._learning_rate.set_state_dict(scalars["opt/@lr"])
        key = rc.arrays.get("rng/key")
        if key is not None:
            _rng.set_rng_state(jax.random.wrap_key_data(jnp.asarray(key)))
        return {"epoch": int(rc.meta.get("epoch", 0)),
                "step_in_epoch": int(rc.meta.get("step_in_epoch", 0)),
                "iteration": int(rc.meta.get("iteration", rc.step))}

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None, _inner=False):
        loader = self._loader(eval_data, batch_size, False, num_workers)
        cbks = callbacks if _inner else config_callbacks(
            callbacks, model=self, steps=None, log_freq=log_freq, verbose=verbose,
            metrics=self._metrics_name(), mode="eval")
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        logs = {}
        seen = 0
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            ins, labels = self._split_batch(batch)
            res = self.eval_batch(ins, labels)
            logs = self._make_logs(res, prefix="eval_" if not _inner else "")
            cbks.on_eval_batch_end(step, logs)
            seen += ins[0].shape[0] if hasattr(ins[0], "shape") else 1
            if num_samples is not None and seen >= num_samples:
                break
        final = {}
        for m in self._metrics:
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = m.accumulate()
            vals = vals if isinstance(vals, list) else [vals]
            final.update(dict(zip(names, vals)))
        if "loss" in logs:
            final["loss"] = logs["loss"]
        cbks.on_eval_end(final)
        return final

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._loader(test_data, batch_size, False, num_workers)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch)
            outputs.append(self.predict_batch(ins))
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs]) for i in range(n_out)]
        return outputs

    def _make_logs(self, res, prefix=""):
        logs = {}
        if isinstance(res, tuple):
            losses, metrics = res
        else:
            losses, metrics = res, []
        logs[prefix + "loss"] = losses[0] if len(losses) == 1 else losses
        for m, v in zip(self._metrics, metrics):
            name = m.name() if isinstance(m.name(), str) else m.name()[0]
            logs[prefix + name] = np.asarray(m.accumulate()).reshape(-1)[0]
        return logs

    def _metrics_name(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names += n if isinstance(n, list) else [n]
        return names

    # -- persistence -----------------------------------------------------------
    def save(self, path, training=True):
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = _load(path + ".pdparams")
        self.network.set_state_dict(state)
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(_load(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size, dtypes=dtype)


def summary(net, input_size=None, dtypes=None, input=None):  # noqa: A002
    """paddle.summary (hapi/model_summary.py): parameter table + totals."""
    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if len(p.shape) else 1
        total += n
        if not p.stop_gradient:
            trainable += n
        rows.append((name, list(p.shape), n))
    width = max([len(r[0]) for r in rows], default=20) + 2
    lines = [f"{'Layer (param)':<{width}}{'Shape':<20}{'Param #':<12}"]
    lines += [f"{r[0]:<{width}}{str(r[1]):<20}{r[2]:<12,}" for r in rows]
    lines.append(f"Total params: {total:,}")
    lines.append(f"Trainable params: {trainable:,}")
    lines.append(f"Non-trainable params: {total - trainable:,}")
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
