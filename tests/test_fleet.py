"""Fleet hybrid-parallel tests on the 8-device virtual CPU mesh.

Mirrors the reference's test/collective/fleet suites (SURVEY.md §4): hybrid topology
carving, TP layers vs single-device reference numerics, pipeline micro-batch accumulation
vs plain large-batch training, sharding state placement, recompute grad equivalence.
"""
import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_parallel import (
    ColumnParallelLinear, LayerDesc, ParallelCrossEntropy, PipelineLayer,
    RowParallelLinear, VocabParallelEmbedding,
)


def _init_fleet(dp=1, mp=1, pp=1, sharding=1, **pp_cfg):
    s = fleet.DistributedStrategy()
    s.hybrid_configs = {
        "dp_degree": dp, "mp_degree": mp, "pp_degree": pp, "sharding_degree": sharding,
    }
    if pp_cfg:
        s.pipeline_configs = pp_cfg
    fleet.init(is_collective=True, strategy=s)
    return fleet.get_hybrid_communicate_group()


class TestTopology:
    def test_axis_carving(self):
        hcg = _init_fleet(dp=2, mp=2, pp=2)
        assert hcg.nranks == 8
        assert hcg.get_model_parallel_world_size() == 2
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.get_pipe_parallel_world_size() == 2
        # mp is the innermost axis: rank 0's mp peers are adjacent device ids
        assert hcg.get_model_parallel_group().ranks == [0, 1]
        topo = hcg.topology()
        assert topo.get_comm_list("mp") == [[0, 1], [2, 3], [4, 5], [6, 7]]
        assert len(topo.get_comm_list("pp")) == 4

    def test_coord_roundtrip(self):
        hcg = _init_fleet(dp=2, mp=2, pp=2)
        topo = hcg.topology()
        for r in range(8):
            c = topo.get_coord(r)
            assert topo.get_rank(**c._asdict()) == r

    def test_dp_fill(self):
        # unspecified dp fills the remaining world (reference behavior)
        hcg = _init_fleet(mp=2)
        assert hcg.get_data_parallel_world_size() == 4


class TestTensorParallel:
    def test_column_row_matches_dense(self):
        paddle.seed(7)
        _init_fleet(mp=2)
        col = ColumnParallelLinear(16, 32, gather_output=False, has_bias=True)
        row = RowParallelLinear(32, 16, input_is_parallel=True, has_bias=True)
        x = paddle.to_tensor(np.random.RandomState(0).randn(4, 16).astype("float32"),
                             stop_gradient=False)
        out = row(col(x))
        # dense reference with the same (global) weights
        ref = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) @ row.weight.numpy() \
            + row.bias.numpy()
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-5)
        out.sum().backward()
        assert col.weight.grad is not None
        assert row.weight.grad.shape == [32, 16]

    def test_vocab_parallel_embedding(self):
        _init_fleet(mp=2)
        emb = VocabParallelEmbedding(64, 8)
        ids = paddle.to_tensor(np.array([[1, 63], [7, 0]]))
        out = emb(ids)
        np.testing.assert_allclose(
            out.numpy(), emb.weight.numpy()[ids.numpy()], rtol=1e-6)

    def test_parallel_cross_entropy(self):
        _init_fleet(mp=2)
        ce = ParallelCrossEntropy()
        logits = paddle.to_tensor(
            np.random.RandomState(1).randn(6, 32).astype("float32"), stop_gradient=False)
        labels = paddle.to_tensor(np.arange(6) % 32)
        loss = ce(logits, labels)
        ref = F.softmax_with_cross_entropy(logits.detach(), labels)
        np.testing.assert_allclose(loss.numpy(), ref.numpy(), rtol=1e-5, atol=1e-5)
        loss.sum().backward()
        assert logits.grad is not None

    def test_mp_rng_tracker(self):
        _init_fleet(mp=2)
        from paddle_tpu.distributed.fleet.meta_parallel import (
            get_rng_state_tracker, model_parallel_random_seed)

        model_parallel_random_seed(1234)
        tracker = get_rng_state_tracker()
        with tracker.rng_state():
            a = paddle.rand([4])
        with tracker.rng_state():
            b = paddle.rand([4])
        # the tracker stream advances between uses
        assert not np.allclose(a.numpy(), b.numpy())


class TestSequenceParallel:
    def test_sp_linear_pair(self):
        _init_fleet(mp=2)
        from paddle_tpu.distributed.fleet.utils.sequence_parallel_utils import (
            ColumnSequenceParallelLinear, RowSequenceParallelLinear, scatter)

        col = ColumnSequenceParallelLinear(16, 32, has_bias=True)
        row = RowSequenceParallelLinear(32, 16, has_bias=True, input_is_parallel=True)
        x = paddle.to_tensor(np.random.RandomState(2).randn(8, 2, 16).astype("float32"),
                             stop_gradient=False)
        xs = scatter(x)  # seq-shard over mp
        out = row(col(xs))
        ref = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) @ row.weight.numpy() \
            + row.bias.numpy()
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-5)
        out.mean().backward()
        assert col.weight.grad is not None


class TestPipeline:
    def _model(self):
        paddle.seed(0)
        return PipelineLayer(
            layers=[LayerDesc(nn.Linear, 8, 16), LayerDesc(nn.ReLU),
                    LayerDesc(nn.Linear, 16, 4)],
            loss_fn=nn.CrossEntropyLoss())

    def test_microbatch_equals_full_batch(self):
        _init_fleet(pp=2, accumulate_steps=2, micro_batch_size=2)
        pipe = self._model()
        model = fleet.distributed_model(pipe)
        x = np.random.RandomState(3).randn(4, 8).astype("float32")
        y = np.array([0, 1, 2, 3])
        data = (paddle.to_tensor(x), paddle.to_tensor(y))

        model.forward_backward_pipeline(data)
        accum_grad = pipe._sub_layers["0"].weight.grad.numpy().copy()

        # reference: single full-batch backward
        pipe2 = self._model()
        out = pipe2.forward(paddle.to_tensor(x))
        loss = nn.CrossEntropyLoss()(out, paddle.to_tensor(y))
        loss.backward()
        np.testing.assert_allclose(
            accum_grad, pipe2._sub_layers["0"].weight.grad.numpy(), rtol=1e-5, atol=1e-6)

    def test_shared_layer_desc(self):
        from paddle_tpu.distributed.fleet.meta_parallel import SharedLayerDesc

        _init_fleet(pp=2)
        pipe = PipelineLayer(layers=[
            SharedLayerDesc("emb", nn.Linear, None, "weight", 8, 8),
            LayerDesc(nn.ReLU),
            SharedLayerDesc("emb", nn.Linear, None, "weight", 8, 8),
        ])
        first = pipe._sub_layers["0"]
        last = pipe._sub_layers["2"]
        assert first is last  # one layer instance shared across stages

    def test_eval_batch(self):
        _init_fleet(pp=2, accumulate_steps=2, micro_batch_size=2)
        pipe = self._model()
        model = fleet.distributed_model(pipe)
        data = (paddle.to_tensor(np.random.randn(4, 8).astype("float32")),
                paddle.to_tensor(np.array([0, 1, 2, 3])))
        loss = model.eval_batch(data)
        assert np.isfinite(loss.numpy()).all()


class TestSharding:
    def test_optimizer_state_sharded(self):
        hcg = _init_fleet(sharding=2)
        lin = nn.Linear(16, 16)
        from paddle_tpu.distributed import api as dist_api
        from paddle_tpu.distributed.placement import Replicate

        opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=lin.parameters())
        opt = fleet.distributed_optimizer(opt)
        x = paddle.to_tensor(np.random.randn(4, 16).astype("float32"))
        lin(x).mean().backward()
        opt.step()
        # moment state exists and step ran; sharded placement checked via sharding spec
        st = opt.inner_opt._accumulators[id(lin.weight)]
        m = st.get("m", st.get("moment1", None))
        assert m is not None

    def test_group_sharded_stage3(self):
        _init_fleet(sharding=2)
        model = nn.Sequential(nn.Linear(16, 16), nn.ReLU(), nn.Linear(16, 4))
        opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
        from paddle_tpu.distributed.fleet import group_sharded_parallel

        model, opt, _ = group_sharded_parallel(model, opt, level="p_g_os")
        x = paddle.to_tensor(np.random.randn(4, 16).astype("float32"))
        out = model(x)
        out.mean().backward()
        opt.step()
        assert np.isfinite(out.numpy()).all()
        # stage-3: optimizer state must be sharded like the param, not
        # replicated (regression: shard_fn returned state untouched when the
        # PARAM already carried the ZeRO axis)
        w = model[0].weight
        st = opt._accumulators[id(w)]
        mv = next(iter(st.values()))
        shard = mv.addressable_shards[0].data
        assert int(np.prod(shard.shape)) == int(np.prod(mv.shape)) // 2, (
            f"stage-3 state replicated: {shard.shape} of {mv.shape}")


class TestParallelComposition:
    """Products of the hybrid axes (round-2 verdict #4): ZeRO state sharding
    under pp>1, sequence-parallel under pp>1, and the full dp x mp x pp x ZeRO
    stack — loss parity with the unsharded run + per-device byte shrink.
    Reference analog: dygraph_sharding_optimizer.py:592 V2 + PP as a
    first-class config."""

    def _square_pipe(self, n_layers=4):
        paddle.seed(0)
        return PipelineLayer(
            layers=[LayerDesc(nn.Linear, 8, 8) for _ in range(n_layers)])

    def test_pp_x_zero_state_sharding(self):
        hcg = _init_fleet(dp=2, pp=2, sharding=2,
                          accumulate_steps=2, micro_batch_size=2,
                          compiled=True)
        pipe = self._square_pipe()
        model = fleet.distributed_model(pipe)
        assert model._compiled is not None
        opt = fleet.distributed_optimizer(
            paddle.optimizer.AdamW(learning_rate=1e-3,
                                   parameters=model.parameters()))

        # unsharded oracle: same seed -> identical weights, plain sequential
        paddle.seed(0)
        ref = nn.Sequential(*[nn.Linear(8, 8) for _ in range(4)])
        x = np.random.RandomState(0).randn(4, 8).astype("float32")
        out = model(paddle.to_tensor(x))
        out_ref = ref(paddle.to_tensor(x))
        np.testing.assert_allclose(out.numpy(), out_ref.numpy(),
                                   rtol=2e-5, atol=2e-5)

        loss = (out ** 2).mean()
        before = float(loss)
        loss.backward()
        opt.step()
        opt.clear_grad()

        # the stacked pipeline param: pp shards the stage axis (after the step
        # the update's output sharding may ALSO carry the ZeRO axis — stricter
        # than ZeRO-2 residency, re-gathered at the rotation boundary)
        p = model._compiled._stacked_params[0]
        full = int(np.prod(p.value.shape))
        pshard = p.value.addressable_shards[0].data
        assert pshard.shape[1] == p.value.shape[1] // 2, "pp axis not sharded"
        assert int(np.prod(pshard.shape)) <= full // 2
        # ...and ZeRO-1/2 additionally shards its optimizer state on a free dim
        st = opt.inner_opt._accumulators[id(p)]
        m = next(iter(st.values()))
        mshard = m.addressable_shards[0] if hasattr(m, "addressable_shards") \
            else m.value.addressable_shards[0]
        mv = m if hasattr(m, "shape") else m.value
        assert int(np.prod(mshard.data.shape)) == int(np.prod(mv.shape)) // 4, (
            f"state not pp x sharding sharded: {mshard.data.shape} of {mv.shape}")

        after = float((model(paddle.to_tensor(x)) ** 2).mean())
        assert after < before  # the composed step actually optimizes

    @pytest.mark.parametrize("schedule_mode", [None, "ZBH1"])
    def test_pp_x_sep_sequence_parallel(self, schedule_mode):
        """Sequence parallel (sep rides the mp axis) inside pp>1 stages must
        reproduce the replicated sequential forward — under BOTH the default
        1F1B rotation and the zb schedule's custom-VJP rotation (sequence-
        major micro-batching on axis 1 composes with each)."""
        from paddle_tpu.models import LlamaConfig
        from paddle_tpu.models.llama import LlamaForCausalLMPipe

        cfg_kw = dict(accumulate_steps=2, micro_batch_size=2, compiled=True)
        if schedule_mode:
            cfg_kw["schedule_mode"] = schedule_mode
        _init_fleet(dp=2, mp=2, pp=2, **cfg_kw)
        paddle.seed(0)
        cfg = LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64,
            num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=4,
            max_position_embeddings=16,
            tensor_parallel_degree=2, sequence_parallel=True,
            pipeline_parallel_degree=2)
        pipe = LlamaForCausalLMPipe(cfg)
        model = fleet.distributed_model(pipe)
        assert model._compiled is not None
        if schedule_mode == "ZBH1":
            assert model._compiled._schedule == "zb"

        r = np.random.RandomState(0)
        ids = paddle.to_tensor(r.randint(0, 64, (4, 16)).astype("int64"))
        out_mod = model(ids)            # sep x mp x pp compiled rotation
        out_pipe = pipe(ids)            # replicated sequential forward
        np.testing.assert_allclose(
            np.asarray(out_mod.value), np.asarray(out_pipe.value),
            rtol=2e-5, atol=2e-5)
        if schedule_mode == "ZBH1":
            # the zb backward flows grads into the stacked params
            (out_mod ** 2).mean().backward()
            assert all(p.grad is not None
                       for p in model._compiled._stacked_params)

    def test_zero_shard_fn_preserves_existing_axes(self):
        """The state-shard hook must ADD the sharding axis without wiping a
        pre-existing pp placement (regression for the composition fix)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        hcg = _init_fleet(pp=2, sharding=2, dp=2)
        from paddle_tpu.distributed.fleet.hybrid_optimizer import (
            _make_state_shard_fn,
        )

        mesh = hcg.global_mesh
        jmesh = mesh.jax_mesh()
        shard_fn = _make_state_shard_fn(
            mesh, mesh.dim_names.index("sharding"), 2)
        # a pp-stacked accumulator: (v=1, S=2, 8, 8), pp on dim 1
        acc = jax.device_put(
            jnp.zeros((1, 2, 8, 8)),
            NamedSharding(jmesh, P(None, "pp")))
        out = shard_fn("m", None, paddle.Tensor(acc))
        spec = out.value.sharding.spec
        flat = [n for names in spec if names is not None
                for n in (names if isinstance(names, tuple) else (names,))]
        assert "pp" in flat and "sharding" in flat, spec
        shard = out.value.addressable_shards[0].data
        assert int(np.prod(shard.shape)) == (1 * 2 * 8 * 8) // 4


class TestRecompute:
    def test_grad_equivalence(self):
        class Block(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(8, 8)
                self.fc2 = nn.Linear(8, 8)

            def forward(self, x):
                return self.fc2(F.relu(self.fc1(x)))

        paddle.seed(11)
        blk = Block()
        x = paddle.to_tensor(np.random.RandomState(5).randn(4, 8).astype("float32"),
                             stop_gradient=False)
        y_ref = blk(x)
        y_ref.sum().backward()
        g_ref = blk.fc1.weight.grad.numpy().copy()
        xg_ref = x.grad.numpy().copy()
        blk.clear_gradients()
        x.clear_grad()

        y = fleet.recompute(blk, x)
        np.testing.assert_allclose(y.numpy(), y_ref.numpy(), rtol=1e-6)
        y.sum().backward()
        np.testing.assert_allclose(blk.fc1.weight.grad.numpy(), g_ref, rtol=1e-5)
        np.testing.assert_allclose(x.grad.numpy(), xg_ref, rtol=1e-5)

    def test_recompute_with_dropout_replay(self):
        class Block(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(32, 32)

            def forward(self, x):
                return F.dropout(self.fc(x), p=0.5, training=True)

        paddle.seed(21)
        blk = Block()
        x = paddle.to_tensor(np.random.randn(16, 32).astype("float32"),
                             stop_gradient=False)
        y = fleet.recompute(blk, x)
        y.sum().backward()  # would mismatch shapes/NaN if the mask weren't replayed
        assert blk.fc.weight.grad is not None


class TestHybridClip:
    def test_global_norm_clip(self):
        _init_fleet(mp=2)
        col = ColumnParallelLinear(8, 16, gather_output=False)
        opt = paddle.optimizer.SGD(
            learning_rate=0.1, parameters=col.parameters(),
            grad_clip=nn.ClipGradByGlobalNorm(1e-8))
        opt = fleet.distributed_optimizer(opt)
        x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
        before = col.weight.numpy().copy()
        (col(x) ** 2).mean().backward()
        opt.step()
        # grads clipped to ~0 -> params unchanged
        np.testing.assert_allclose(col.weight.numpy(), before, atol=1e-6)


class TestMetaOptimizers:
    """fleet meta-optimizers (reference fleet/meta_optimizers/): strategy
    flags that wrap or swap the inner optimizer."""

    def test_gradient_merge_applies_every_k_steps(self):
        from paddle_tpu.distributed.fleet.meta_optimizers import (
            GradientMergeOptimizer)

        paddle.seed(0)
        lin = paddle.nn.Linear(2, 1)
        inner = paddle.optimizer.SGD(learning_rate=0.5,
                                     parameters=lin.parameters())
        opt = GradientMergeOptimizer(inner, k_steps=2, avg=True)
        x = paddle.to_tensor(np.ones((4, 2), "float32"))
        y = paddle.to_tensor(np.zeros((4, 1), "float32"))
        w0 = np.asarray(lin.weight.numpy()).copy()

        loss = ((lin(x) - y) ** 2).mean()
        loss.backward()
        g1 = np.asarray(lin.weight.grad.numpy()).copy()
        opt.step()           # micro-step 1: banked, no update
        opt.clear_grad()
        np.testing.assert_array_equal(lin.weight.numpy(), w0)

        loss = ((lin(x) - y) ** 2).mean()
        loss.backward()
        opt.step()           # micro-step 2: applies the averaged grad
        opt.clear_grad()
        w2 = np.asarray(lin.weight.numpy())
        assert not np.array_equal(w2, w0)
        # both micro-grads were identical, so avg == g1: one SGD step
        np.testing.assert_allclose(w2, w0 - 0.5 * g1, rtol=1e-6)

    def test_strategy_wires_gradient_merge_and_lamb(self):
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.fleet.meta_optimizers import (
            GradientMergeOptimizer, apply_inner_meta_optimizers,
            apply_outer_meta_optimizers)
        from paddle_tpu.optimizer.optimizer import Lamb

        strategy = fleet.DistributedStrategy()
        strategy.gradient_merge = True
        strategy.gradient_merge_configs = {"k_steps": 4, "avg": True}
        strategy.lamb = True
        strategy.lamb_configs = {"lamb_weight_decay": 0.02}
        lin = paddle.nn.Linear(2, 2)
        sgd = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        inner = apply_inner_meta_optimizers(sgd, strategy)
        assert isinstance(inner, Lamb) and inner._lamb_wd == 0.02
        # the training contract survives the swap
        assert inner._grad_clip is sgd._grad_clip
        assert inner._learning_rate == sgd._learning_rate
        assert inner._parameter_list_flat() == sgd._parameter_list_flat()
        opt = apply_outer_meta_optimizers(inner, strategy)
        assert isinstance(opt, GradientMergeOptimizer)
        assert opt.k_steps == 4
        # gradient merge wraps OUTSIDE hybrid so the hybrid's setattr hooks
        # (clip replacement, ZeRO shard fn) reach the true inner optimizer
        assert opt._inner is inner

    def test_gradient_merge_composes_with_static_amp_bf16(self):
        """GM must own the executor train hook even when the inner is a
        static.amp wrapper — delegation would apply k unmerged updates."""
        from paddle_tpu.distributed.fleet.meta_optimizers import (
            GradientMergeOptimizer)

        paddle.seed(0)
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data("x", [None, 4], "float32")
            y = paddle.static.data("y", [None, 1], "float32")
            net = paddle.nn.Linear(4, 1)
            loss = ((net(x) - y) ** 2).mean()
            loss.name = "loss"
            amp_opt = paddle.static.amp.decorate(
                paddle.optimizer.SGD(learning_rate=0.05,
                                     parameters=net.parameters()),
                use_bf16=True, use_dynamic_loss_scaling=False)
            gm = GradientMergeOptimizer(amp_opt, k_steps=2)
            gm.minimize(loss)
        exe = paddle.static.Executor()
        r = np.random.RandomState(0)
        xs = r.randn(16, 4).astype("float32")
        ys = (xs @ r.randn(4, 1)).astype("float32")
        w0 = np.asarray(net.weight.numpy()).copy()
        exe.run(main, feed={"x": xs, "y": ys}, fetch_list=["loss"])
        # micro-step 1 banked: no parameter update yet
        np.testing.assert_array_equal(net.weight.numpy(), w0)
        exe.run(main, feed={"x": xs, "y": ys}, fetch_list=["loss"])
        assert not np.array_equal(np.asarray(net.weight.numpy()), w0)

    def test_gradient_merge_rejects_fp16_scaler_in_static(self):
        import pytest as _pytest

        from paddle_tpu.distributed.fleet.meta_optimizers import (
            GradientMergeOptimizer)

        paddle.seed(0)
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data("x", [None, 2], "float32")
            net = paddle.nn.Linear(2, 1)
            loss = net(x).mean()
            loss.name = "loss"
            amp_opt = paddle.static.amp.decorate(
                paddle.optimizer.SGD(learning_rate=0.05,
                                     parameters=net.parameters()))
            gm = GradientMergeOptimizer(amp_opt, k_steps=2)
            gm.minimize(loss)
        exe = paddle.static.Executor()
        with _pytest.raises(NotImplementedError, match="loss scaling"):
            exe.run(main, feed={"x": np.ones((2, 2), "float32")},
                    fetch_list=["loss"])
