"""Inference serving path (round-2 verdict #10): KV-cache decode engine parity
+ Predictor AOT warmup cache. Reference: fluid/inference/api/
analysis_predictor.cc's role, TPU-natively (one compiled decode executable).
"""
import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.llama_decode import LlamaDecodeEngine


def _model(layers=2, heads=4, kv=2, hidden=32, maxlen=32):
    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=64, hidden_size=hidden,
                      intermediate_size=hidden * 2, num_hidden_layers=layers,
                      num_attention_heads=heads, num_key_value_heads=kv,
                      max_position_embeddings=maxlen)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m, cfg


class TestDecodeEngine:
    def test_greedy_matches_full_recompute_generate(self):
        model, _ = _model()
        r = np.random.RandomState(0)
        ids = paddle.to_tensor(r.randint(0, 64, (2, 5)).astype("int64"))
        slow = model.generate(ids, max_new_tokens=8).numpy()[:, 5:]
        eng = LlamaDecodeEngine(model, max_len=32)
        fast = np.asarray(eng.generate(ids, max_new_tokens=8))
        np.testing.assert_array_equal(slow, fast)

    def test_gqa_and_mha_variants(self):
        for kv in (1, 2, 4):
            model, _ = _model(kv=kv)
            r = np.random.RandomState(kv)
            ids = paddle.to_tensor(r.randint(0, 64, (1, 4)).astype("int64"))
            slow = model.generate(ids, max_new_tokens=5).numpy()[:, 4:]
            fast = np.asarray(LlamaDecodeEngine(model, max_len=16)
                              .generate(ids, max_new_tokens=5))
            np.testing.assert_array_equal(slow, fast)

    def test_prefill_logits_match_forward(self):
        model, _ = _model()
        r = np.random.RandomState(1)
        ids_np = r.randint(0, 64, (3, 7)).astype("int64")
        full = model(paddle.to_tensor(ids_np)).numpy()[:, -1]
        eng = LlamaDecodeEngine(model, max_len=16)
        logits, cache, pos = eng.prefill(ids_np)
        assert pos == 7
        np.testing.assert_allclose(np.asarray(logits), full,
                                   rtol=1e-4, atol=1e-4)

    def test_step_is_one_compiled_program(self):
        model, _ = _model()
        eng = LlamaDecodeEngine(model, max_len=16)
        ids = np.random.RandomState(0).randint(0, 64, (1, 3)).astype("int32")
        logits, cache, pos = eng.prefill(ids)
        tok = np.asarray(logits.argmax(-1)).astype("int32")[:, None]
        logits, cache = eng.decode_step(tok, cache, pos)
        # the SAME jitted callable serves every later step (AOT executable);
        # the cache is donated each step, so it chains forward
        before = eng._step_jit._cache_size()
        logits, cache = eng.decode_step(tok, cache, pos + 1)
        logits, cache = eng.decode_step(tok, cache, pos + 2)
        assert eng._step_jit._cache_size() == before == 1


class TestPredictorWarmup:
    def test_warmup_shapes_precompiled(self, tmp_path):
        import paddle_tpu.nn as nn
        from paddle_tpu import inference, jit
        from paddle_tpu.jit.api import InputSpec

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        prefix = str(tmp_path / "model")
        jit.save(net, prefix, input_spec=[InputSpec([None, 8], "float32")])

        cfg = inference.Config(prefix)
        cfg.exp_set_warmup_shapes([(1, 8), (4, 8)])
        pred = inference.create_predictor(cfg)
        assert pred._warmed_shapes == [(1, 8), (4, 8)]
        out = pred.run([np.ones((4, 8), "float32")])
        assert out[0].shape == (4, 4)
