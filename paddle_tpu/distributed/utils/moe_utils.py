"""global_scatter / global_gather: count-addressed token exchange for MoE.

Reference analog: python/paddle/distributed/utils/moe_utils.py (global_scatter
:25, global_gather :140 — NCCL all-to-all with per-(rank, expert) counts; device
kernels phi/kernels/{cpu,gpu,custom}/global_scatter_kernel.*).

TPU-first note: compiled MoE should NOT use these — MoELayer's dense one-hot
dispatch lets GSPMD emit the all-to-all. These functions exist for API parity and
for eager experimentation: they operate on the stacked-axis representation the
eager collective layer uses (rank-local rows stacked on axis 0).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ...framework.core import Tensor


def _np(x):
    return np.asarray(x.value if isinstance(x, Tensor) else x)


def global_scatter(x, local_count, global_count, group=None, use_calc_stream=True):
    """Send local_count[i*E+e] rows to expert e of rank i; receive what
    global_count says others send here. Single-controller: the stacked exchange
    reduces to a stable reorder of rows grouped by destination expert."""
    xv = _np(x)
    lc = _np(local_count).astype(np.int64)
    gc = _np(global_count).astype(np.int64)
    # rows are laid out grouped by (expert-major) destination already — the
    # reference contract. Output = rows this "rank" keeps, ordered by source.
    n_out = int(gc.sum())
    starts = np.zeros_like(lc)
    np.cumsum(lc[:-1], out=starts[1:])
    pieces = []
    for j in range(len(gc)):
        # in the single-process view, global==local exchange: take the j-th
        # destination block from x
        s, n = int(starts[j]), int(lc[j]) if j < len(lc) else 0
        if gc[j] > 0:
            pieces.append(xv[s:s + int(gc[j])])
    out = np.concatenate(pieces, axis=0) if pieces else xv[:0]
    assert out.shape[0] == n_out
    return Tensor(jnp.asarray(out))


def global_gather(x, local_count, global_count, group=None, use_calc_stream=True):
    """Inverse of global_scatter (reference moe_utils.py:140)."""
    return global_scatter(x, global_count, local_count, group=group,
                          use_calc_stream=use_calc_stream)
