"""Bijective transforms + TransformedDistribution.

Reference analog: python/paddle/distribution/transform.py (Transform base with
forward/inverse/forward_log_det_jacobian, Affine/Exp/Sigmoid/Tanh/Power/Chain/
Stack) and transformed_distribution.py.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .. import ops
from ..framework.core import Tensor
from .distribution import Distribution, _t


class Transform:
    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        return -self.forward_log_det_jacobian(self.inverse(y))

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def forward(self, x):
        return self.loc + self.scale * x

    def inverse(self, y):
        return (y - self.loc) / self.scale

    def forward_log_det_jacobian(self, x):
        return ops.log(ops.abs(self.scale)) * ops.ones_like(x)


class ExpTransform(Transform):
    def forward(self, x):
        return ops.exp(x)

    def inverse(self, y):
        return ops.log(y)

    def forward_log_det_jacobian(self, x):
        return x


class SigmoidTransform(Transform):
    def forward(self, x):
        return ops.sigmoid(x)

    def inverse(self, y):
        return ops.log(y) - ops.log1p(-y)

    def forward_log_det_jacobian(self, x):
        from ..nn import functional as F

        return -F.softplus(-x) - F.softplus(x)


class TanhTransform(Transform):
    def forward(self, x):
        return ops.tanh(x)

    def inverse(self, y):
        return ops.atanh(y)

    def forward_log_det_jacobian(self, x):
        from ..nn import functional as F

        return 2.0 * (math.log(2.0) - x - F.softplus(-2.0 * x))


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _t(power)

    def forward(self, x):
        return x ** self.power

    def inverse(self, y):
        return y ** (1.0 / self.power)

    def forward_log_det_jacobian(self, x):
        return ops.log(ops.abs(self.power * x ** (self.power - 1.0)))


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = None
        for t in self.transforms:
            j = t.forward_log_det_jacobian(x)
            total = j if total is None else total + j
            x = t.forward(x)
        return total


class TransformedDistribution(Distribution):
    """transformed_distribution.py: push a base through transforms."""

    def __init__(self, base, transforms, name=None):
        self.base = base
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.transforms = list(transforms)
        super().__init__(base.batch_shape, base.event_shape)

    def _chain(self):
        return ChainTransform(self.transforms)

    def rsample(self, shape=()):
        return self._chain().forward(self.base.rsample(shape))

    def _sample(self, shape=()):
        return self._chain().forward(self.base.sample(shape))

    def log_prob(self, value):
        chain = self._chain()
        x = chain.inverse(_t(value))
        return self.base.log_prob(x) - chain.forward_log_det_jacobian(x)


class AbsTransform(Transform):
    """transform.py AbsTransform (y=|x|; not bijective — inverse picks +)."""

    def forward(self, x):
        return ops.abs(_t(x))

    def inverse(self, y):
        return _t(y)

    def forward_log_det_jacobian(self, x):
        return ops.zeros_like(_t(x))


class SoftmaxTransform(Transform):
    """transform.py SoftmaxTransform (last axis; inverse = log)."""

    def forward(self, x):
        x = _t(x)
        e = ops.exp(x - ops.max(x, axis=-1, keepdim=True))
        return e / ops.sum(e, axis=-1, keepdim=True)

    def inverse(self, y):
        return ops.log(_t(y))


class StickBreakingTransform(Transform):
    """transform.py StickBreakingTransform: R^{K} -> K+1 simplex."""

    def forward(self, x):
        x = _t(x).value
        k = jnp.arange(x.shape[-1], dtype=x.dtype)
        z = jax.nn.sigmoid(x - jnp.log(x.shape[-1] - k))
        cum = jnp.cumprod(1.0 - z, axis=-1)
        head = z * jnp.concatenate(
            [jnp.ones_like(cum[..., :1]), cum[..., :-1]], axis=-1)
        return Tensor(jnp.concatenate([head, cum[..., -1:]], axis=-1))

    def inverse(self, y):
        y = _t(y).value
        cum = jnp.cumsum(y[..., :-1], axis=-1)
        rest = 1.0 - jnp.concatenate(
            [jnp.zeros_like(cum[..., :1]), cum[..., :-1]], axis=-1)
        z = y[..., :-1] / rest
        k = jnp.arange(z.shape[-1], dtype=y.dtype)
        return Tensor(jnp.log(z / (1.0 - z)) + jnp.log(z.shape[-1] - k))

    def forward_log_det_jacobian(self, x):
        x = _t(x).value
        k = jnp.arange(x.shape[-1], dtype=x.dtype)
        off = x - jnp.log(x.shape[-1] - k)
        z = jax.nn.sigmoid(off)
        cum = jnp.cumprod(1.0 - z, axis=-1)
        stick = jnp.concatenate(
            [jnp.ones_like(cum[..., :1]), cum[..., :-1]], axis=-1)
        # d y_k / d x_k = sigmoid(off)*sigmoid(-off)*stick_k (triangular jac)
        return Tensor(
            jnp.sum(jax.nn.log_sigmoid(off) + jax.nn.log_sigmoid(-off)
                    + jnp.log(stick), axis=-1))


class ReshapeTransform(Transform):
    """transform.py ReshapeTransform(in_event_shape, out_event_shape)."""

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)

    def forward(self, x):
        x = _t(x)
        batch = tuple(x.shape)[:x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def inverse(self, y):
        y = _t(y)
        batch = tuple(y.shape)[:y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def forward_log_det_jacobian(self, x):
        x = _t(x)
        batch = tuple(x.shape)[:x.ndim - len(self.in_event_shape)]
        return ops.zeros(list(batch) or [1])


class IndependentTransform(Transform):
    """transform.py IndependentTransform: sum the log-det over the rightmost
    reinterpreted_batch_ndims dims."""

    def __init__(self, base, reinterpreted_batch_ndims):
        self.base = base
        self.reinterpreted_batch_ndims = int(reinterpreted_batch_ndims)

    def forward(self, x):
        return self.base.forward(x)

    def inverse(self, y):
        return self.base.inverse(y)

    def forward_log_det_jacobian(self, x):
        ld = self.base.forward_log_det_jacobian(x)
        for _ in range(self.reinterpreted_batch_ndims):
            ld = ops.sum(ld, axis=-1)
        return ld


class StackTransform(Transform):
    """transform.py StackTransform: apply transforms[i] to slice i of `axis`."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _map(self, method, x):
        x = _t(x)
        n = x.shape[self.axis]
        parts = ops.split(x, n, axis=self.axis)
        outs = [ops.squeeze(getattr(t, method)(p), axis=self.axis)
                for t, p in zip(self.transforms, parts)]
        return ops.stack(outs, axis=self.axis)

    def forward(self, x):
        return self._map("forward", x)

    def inverse(self, y):
        return self._map("inverse", y)

    def forward_log_det_jacobian(self, x):
        return self._map("forward_log_det_jacobian", x)
