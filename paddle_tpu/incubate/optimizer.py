"""incubate optimizers: LookAhead, ModelAverage.

Reference analog: python/paddle/incubate/optimizer/{lookahead,modelaverage}.py.
Both wrap an inner optimizer with parameter-trajectory bookkeeping on the
host side of the step (the inner update itself stays the fused jax path).
"""
from __future__ import annotations

import jax.numpy as jnp


class LookAhead:
    """lookahead.py LookAhead(inner_optimizer, alpha, k): every k steps the
    slow weights move alpha of the way toward the fast weights and the fast
    weights reset to them."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_count = 0
        self._slow = {}

    def _params(self):
        return self.inner_optimizer._parameter_list_flat()

    def step(self):
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k:
            return
        for p in self._params():
            pid = id(p)
            slow = self._slow.get(pid)
            if slow is None:
                slow = p.value  # first sync: slow starts at the fast weights
            slow = slow + self.alpha * (p.value - slow)
            self._slow[pid] = slow
            p._replace_value(slow)

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad(set_to_zero)

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        return self.inner_optimizer.state_dict()

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()


class ModelAverage:
    """modelaverage.py ModelAverage: maintain a running average of parameters;
    apply()/restore() swap the averaged weights in and out for evaluation."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self.rate = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        self._parameters = list(parameters or [])
        self._sum = {id(p): jnp.zeros_like(p.value) for p in self._parameters}
        self._count = 0
        self._backup = None

    def step(self):
        self._count += 1
        for p in self._parameters:
            self._sum[id(p)] = self._sum[id(p)] + p.value
        # bound the accumulation window (modelaverage.py window restart)
        window = max(self.min_average_window,
                     min(self.max_average_window,
                         int(self._count * self.rate) or 1))
        if self._count > window:
            for p in self._parameters:
                self._sum[id(p)] = self._sum[id(p)] * (window / self._count)
            self._count = window

    def apply(self, executor=None, need_restore=True):
        if self._count == 0:
            return
        self._backup = {id(p): p.value for p in self._parameters}
        for p in self._parameters:
            p._replace_value((self._sum[id(p)] / self._count)
                             .astype(p.value.dtype))

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._parameters:
            p._replace_value(self._backup[id(p)])
        self._backup = None


# reference incubate/optimizer exports LBFGS (later promoted to
# paddle.optimizer.LBFGS — same class here)
from ..optimizer.optimizer import LBFGS  # noqa: E402,F401
