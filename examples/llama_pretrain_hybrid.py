"""LLaMA pretraining on a hybrid dp x mp x pp mesh via fleet.

Run with 8 (virtual) devices:
    PADDLE_TPU_PLATFORM=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/llama_pretrain_hybrid.py
On a real pod the same code runs under the launcher, one process per host.
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.models import LlamaConfig
from paddle_tpu.models.llama import LlamaForCausalLMPipe


def main():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2}
    strategy.pipeline_configs = {
        "accumulate_steps": 2, "micro_batch_size": 2,
        "compiled": True,              # the lax.ppermute rotation pipeline
        "schedule_mode": "ZBH1",       # zero-bubble B/W-split backward
    }
    fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(0)
    cfg = LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=64, tensor_parallel_degree=2,
        sequence_parallel=True, pipeline_parallel_degree=2)
    model = fleet.distributed_model(LlamaForCausalLMPipe(cfg))
    opt = fleet.distributed_optimizer(paddle.optimizer.AdamW(
        learning_rate=3e-4, parameters=model.parameters()))

    r = np.random.RandomState(0)
    ids = paddle.to_tensor(r.randint(0, 256, (4, 32)).astype("int64"))
    labels = paddle.to_tensor(r.randint(0, 256, (4, 32)).astype("int64"))
    for step in range(3):
        loss = model.train_batch([ids, labels], opt)
        print(f"step {step}  loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
