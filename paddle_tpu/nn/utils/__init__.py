"""paddle.nn.utils — parameter reparameterization + transform helpers.

Reference analog: python/paddle/nn/utils/ (weight_norm_hook.py,
spectral_norm_hook.py, clip_grad_{norm_,value_}.py,
transform_parameters.py). TPU-first form: the reparameterizations are
forward pre-hooks that rebind the live Parameter value — pure functional
math underneath, so they trace cleanly under jit."""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ...framework.core import Tensor
from ..clip import clip_grad_norm_, clip_grad_value_  # noqa: F401

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters",
           "clip_grad_norm_", "clip_grad_value_"]


def _norm_except(v, dim):
    if dim is None:
        return jnp.sqrt(jnp.sum(v * v))
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(v * v, axis=axes, keepdims=True))


class _WeightNormHook:
    def __init__(self, layer, name, dim):
        self.name = name
        self.dim = dim
        w = getattr(layer, name)
        from ...framework.core import Parameter

        v = w.value
        g = _norm_except(v, dim)
        layer.add_parameter(name + "_v", Parameter(v))
        layer.add_parameter(name + "_g", Parameter(g))
        # the original weight becomes a DERIVED tensor recomputed per call
        del layer._parameters[name]
        object.__setattr__(layer, name, Tensor(v))
        self._recompute(layer)

    def _recompute(self, layer):
        # composed through TRACED tensor ops so gradients flow to g and v
        v = getattr(layer, self.name + "_v")
        g = getattr(layer, self.name + "_g")
        axes = (None if self.dim is None
                else [i for i in range(v.ndim) if i != self.dim])
        norm = (v * v).sum(axis=axes, keepdim=self.dim is not None)
        w = g * v * (norm.clip(min=1e-24) ** -0.5)
        object.__setattr__(layer, self.name, w)

    def __call__(self, layer, inputs):
        self._recompute(layer)
        return inputs


def weight_norm(layer, name="weight", dim=0):
    """reference weight_norm_hook.py: w = g * v / ||v|| with g, v trained
    in w's place; the recomputation runs as a forward pre-hook."""
    hook = _WeightNormHook(layer, name, dim)
    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_hooks = getattr(layer, "_weight_norm_hooks", {})
    layer._weight_norm_hooks[name] = (hook, handle)
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g*v/||v|| back into a plain trained weight parameter."""
    from ...framework.core import Parameter

    hooks = getattr(layer, "_weight_norm_hooks", {})
    if name not in hooks:
        raise ValueError(f"weight_norm was not applied to {name!r}")
    hook, handle = hooks.pop(name)
    hook._recompute(layer)
    w = getattr(layer, name).value
    handle.remove()
    del layer._parameters[name + "_v"]
    del layer._parameters[name + "_g"]
    if hasattr(layer, name):
        object.__delattr__(layer, name)
    layer.add_parameter(name, Parameter(w))
    return layer


class _SpectralNormHook:
    def __init__(self, layer, name, n_power_iterations, eps, dim):
        self.name = name
        self.n = max(1, int(n_power_iterations))
        self.eps = float(eps)
        self.dim = dim
        w = getattr(layer, name).value
        mat = self._as_matrix(w)
        r = np.random.RandomState(0)
        self.u = jnp.asarray(r.randn(mat.shape[0]), w.dtype)

    def _as_matrix(self, w):
        if self.dim != 0:
            w = jnp.moveaxis(w, self.dim, 0)
        return w.reshape(w.shape[0], -1)

    def __call__(self, layer, inputs):
        orig = layer._parameters.get(self.name + "_orig")
        w = orig.value
        mat = self._as_matrix(w)
        u = self.u
        for _ in range(self.n):
            v = mat.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), self.eps)
            u = mat @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), self.eps)
        self.u = u
        # sigma = u^T W v = sum(W * (u (x) v)), with u/v detached (the
        # standard power-iteration treatment) and W the TRACED parameter so
        # gradients flow through both the numerator and sigma
        outer = jnp.einsum("i,j->ij", u, v).reshape(
            jnp.moveaxis(w, self.dim, 0).shape if self.dim != 0 else w.shape)
        if self.dim != 0:
            outer = jnp.moveaxis(outer, 0, self.dim)
        sigma = (orig * Tensor(outer.astype(w.dtype))).sum()
        object.__setattr__(layer, self.name, orig * (sigma ** -1.0))
        return inputs


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=0):
    """reference spectral_norm_hook.py: divide the weight by its largest
    singular value (power iteration) before every forward."""
    from ...framework.core import Parameter

    w = getattr(layer, name)
    layer.add_parameter(name + "_orig", Parameter(w.value))
    del layer._parameters[name]
    object.__setattr__(layer, name, Tensor(w.value))
    hook = _SpectralNormHook(layer, name, n_power_iterations, eps, dim)
    layer.register_forward_pre_hook(hook)
    hook(layer, ())  # initialize the normalized weight
    return layer


def parameters_to_vector(parameters, name=None):
    """Flatten parameters into one 1-D tensor (reference
    transform_parameters.py)."""
    vals = [jnp.ravel(p.value) for p in parameters]
    return Tensor(jnp.concatenate(vals) if vals else jnp.zeros((0,)))


def vector_to_parameters(vec, parameters, name=None):
    """Write slices of ``vec`` back into the parameters (in-place)."""
    v = vec.value if isinstance(vec, Tensor) else jnp.asarray(vec)
    parameters = list(parameters)
    sizes = [int(np.prod(p.shape)) if p.ndim else 1 for p in parameters]
    if sum(sizes) != v.shape[0]:
        raise ValueError(
            f"vector length {v.shape[0]} != total parameter size "
            f"{sum(sizes)}")
    off = 0
    for p, n in zip(parameters, sizes):
        p._replace_value(v[off:off + n].reshape(p.value.shape)
                         .astype(p.value.dtype))
        off += n
