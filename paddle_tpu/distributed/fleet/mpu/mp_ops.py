"""Tensor-parallel communication primitives.

Reference analog: python/paddle/distributed/fleet/layers/mpu/mp_ops.py (_c_identity,
_c_concat, _c_split, _mp_allreduce, _parallel_linear, split) — hand-written collective
ops with custom forward/backward pairs (identity-fwd/allreduce-bwd etc.).

TPU-first redesign: in a GSPMD world these become SHARDING TRANSITIONS on global tensors,
and the backward collective is the transpose XLA derives automatically:
  _c_identity   = constrain replicated   (bwd: psum over mp — GSPMD inserts it)
  _c_split      = constrain Shard(last)  (bwd: all-gather)
  _c_concat     = constrain replicated from Shard(last) (fwd all-gather, bwd slice)
  _mp_allreduce = materialize a partial as replicated (fwd psum, bwd identity)
The helpers work identically in eager (device_put) and inside a jit trace
(lax.with_sharding_constraint), so the same layer code serves both modes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ....framework.core import Tensor
from ....ops._apply import apply_raw
from ...process_mesh import ProcessMesh
from ..topology import get_hybrid_parallel_group


def _mp_mesh_axis(group=None):
    """(jax Mesh, axis name) for the model-parallel axis of the active topology."""
    hcg = get_hybrid_parallel_group()
    if hcg is not None:
        return hcg.global_mesh.jax_mesh(), "mp"
    # no fleet topology: treat the whole device space as one mp axis
    import numpy as np

    mesh = ProcessMesh(np.arange(jax.device_count()), ["mp"])
    return mesh.jax_mesh(), "mp"


def _abstract_mesh():
    """The trace context's abstract mesh, or None when this jax has no usable
    abstract-mesh API. jax 0.4.37 ships ``jax._src.mesh.get_abstract_mesh`` as
    a stub that returns None/() and does not re-export it from ``jax.sharding``
    — calling the re-export raised AttributeError at every traced TP
    constraint, which broke the whole tensor-parallel training path (the
    pre-existing gpt_hybrid failure). On such versions the concrete-mesh
    constraint below is the supported spelling, including inside shard_map
    bodies whose specs name only non-manual axes."""
    get_am = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_am is None:
        return None
    try:
        return get_am()
    except Exception:  # noqa: BLE001 - version skew: fall back to concrete
        return None


def _constrain(v, mesh, spec):
    """Apply a sharding constraint: device_put in eager, with_sharding_constraint traced.

    Inside a shard_map body (e.g. TP layers running within the compiled pipeline's
    manual pp axis) the constraint must be expressed on the context's abstract mesh —
    whose axis types mark the manual axes — with manual axes dropped from the spec;
    a constraint over the concrete mesh would type pp as Auto and fail vma checks.
    On jax builds without the abstract-mesh API the concrete-mesh constraint is
    used directly (valid there: manual axes are simply absent from mp specs)."""
    if isinstance(v, jax.core.Tracer):
        am = _abstract_mesh()
        manual = set(getattr(am, "manual_axes", ()) or ())
        if am is not None and not getattr(am, "empty", True) and manual:
            cleaned = []
            for entry in tuple(spec):
                if isinstance(entry, (tuple, list)):
                    kept = tuple(a for a in entry if a not in manual)
                    cleaned.append(kept if kept else None)
                else:
                    cleaned.append(None if entry in manual else entry)
            return jax.lax.with_sharding_constraint(
                v, NamedSharding(am, P(*cleaned)))
        return jax.lax.with_sharding_constraint(v, NamedSharding(mesh, spec))
    return jax.device_put(v, NamedSharding(mesh, spec))


def _spec_last_dim(axis, ndim):
    return P(*([None] * (ndim - 1) + [axis]))


def _spec_dim(axis, dim, ndim):
    entries = [None] * ndim
    entries[dim] = axis
    return P(*entries)


def _c_identity(tensor, group=None, skip_c_identity_dynamic=False):
    """Forward identity, backward all-reduce over mp (mp_ops.py _c_identity)."""
    mesh, axis = _mp_mesh_axis(group)

    def fn(v):
        return _constrain(v, mesh, P())

    return apply_raw("c_identity", fn, [tensor])[0]


def _mp_allreduce(tensor, op=None, group=None, use_calc_stream=True,
                  use_model_parallel=True):
    """Forward all-reduce (materialize partial as replicated), backward identity."""
    mesh, axis = _mp_mesh_axis(group)

    def fn(v):
        return _constrain(v, mesh, P())

    return apply_raw("mp_allreduce_sum", fn, [tensor])[0]


def _c_split(tensor, group=None):
    """Keep only this mp-rank's slice of the last dim = constrain Shard(last)."""
    mesh, axis = _mp_mesh_axis(group)

    def fn(v):
        return _constrain(v, mesh, _spec_last_dim(axis, v.ndim))

    return apply_raw("c_split", fn, [tensor])[0]


def _c_concat(tensor, group=None):
    """All-gather the mp-sharded last dim back to a replicated tensor."""
    mesh, axis = _mp_mesh_axis(group)

    def fn(v):
        return _constrain(v, mesh, P())

    return apply_raw("c_concat", fn, [tensor])[0]


def mark_sharded(tensor, dim=-1, group=None, mesh_axis="mp"):
    """Constrain `tensor` to be sharded on `dim` over the given mesh axis."""
    hcg = get_hybrid_parallel_group()
    if hcg is not None:
        mesh = hcg.global_mesh.jax_mesh()
    else:
        mesh, mesh_axis = _mp_mesh_axis(group)

    def fn(v):
        d = dim if dim >= 0 else v.ndim + dim
        return _constrain(v, mesh, _spec_dim(mesh_axis, d, v.ndim))

    return apply_raw("shard_constraint", fn, [tensor])[0]


def mark_replicated(tensor, group=None):
    mesh, _ = _mp_mesh_axis(group)

    def fn(v):
        return _constrain(v, mesh, P())

    return apply_raw("replicate_constraint", fn, [tensor])[0]


def _parallel_linear(x, num_rows, num_cols, axis, param_attr, bias_attr, gather_out,
                     inner_rank, nranks, split_tensor, name, group=None):
    """paddle.distributed.split's linear branch: build a Row/ColumnParallelLinear."""
    from .mp_layers import ColumnParallelLinear, RowParallelLinear

    if axis == 0:
        layer = RowParallelLinear(
            num_rows, num_cols, weight_attr=param_attr, has_bias=bias_attr is not False,
            input_is_parallel=split_tensor, name=name)
    else:
        layer = ColumnParallelLinear(
            num_rows, num_cols, weight_attr=param_attr, has_bias=bias_attr is not False,
            gather_output=gather_out, name=name)
    return layer(x)


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """paddle.distributed.split (mp_ops.py split): parallel embedding or linear."""
    from .mp_layers import VocabParallelEmbedding

    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1], weight_attr=weight_attr,
                                       name=name)
        return layer(x)
    if operation == "linear":
        return _parallel_linear(x, size[0], size[1], axis, weight_attr, bias_attr,
                                gather_out, 0, num_partitions, False, name)
    raise ValueError(f"unsupported split operation {operation!r}")
