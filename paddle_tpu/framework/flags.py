"""Global FLAGS registry.

The reference defines ~188 exported FLAGS_* in paddle/common/flags.cc with env-var pickup and
runtime get/set surfaced through paddle.set_flags/get_flags
(python/paddle/base/framework.py:144). Here the registry is a plain dict with typed defaults,
env ingestion at import, and the same public get/set API.
"""
from __future__ import annotations

import os
from typing import Any, Dict

_REGISTRY: Dict[str, dict] = {}
# bumped on every set_flags: caches of traced/compiled programs that baked a
# flag value at trace time (ops/_apply.py's jit-cached backwards) key on this
# so a flag change forces a retrace instead of silently using stale values
_EPOCH = [0]


def epoch() -> int:
    return _EPOCH[0]


def define_flag(name: str, default: Any, doc: str = ""):
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    value = default
    env = os.environ.get(name)
    if env is not None:
        value = _parse(env, type(default))
    _REGISTRY[name] = {"value": value, "default": default, "doc": doc, "type": type(default)}
    return value


def _parse(text: str, ty):
    if ty is bool:
        return text.lower() in ("1", "true", "yes", "on")
    if ty in (int, float):
        return ty(text)
    return text


def set_flags(flags: Dict[str, Any]):
    _EPOCH[0] += 1
    for k, v in flags.items():
        if not k.startswith("FLAGS_"):
            k = "FLAGS_" + k
        if k not in _REGISTRY:
            define_flag(k, v)
        else:
            _REGISTRY[k]["value"] = _parse(v, _REGISTRY[k]["type"]) if isinstance(v, str) else v


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        key = k if k.startswith("FLAGS_") else "FLAGS_" + k
        if key not in _REGISTRY:
            raise KeyError(f"Unknown flag {k}")
        out[k] = _REGISTRY[key]["value"]
    return out


def flag(name: str):
    key = name if name.startswith("FLAGS_") else "FLAGS_" + name
    return _REGISTRY[key]["value"]


def exported_flags() -> Dict[str, dict]:
    return dict(_REGISTRY)


# Core flags (subset of the reference's set that is meaningful on TPU).
define_flag("check_nan_inf", False, "scan op outputs for NaN/Inf after each eager op")
define_flag("check_nan_inf_level", 0, "0: error on nan/inf; >0: print statistics only")
define_flag("use_stride_kernel", True, "allow zero-copy view ops (reshape/slice return views)")
define_flag("eager_delete_tensor_gb", 0.0, "kept for API parity; XLA/PJRT manages memory")
define_flag("allocator_strategy", "auto_growth", "kept for API parity; PJRT allocates HBM")
define_flag("tpu_matmul_precision", "default", "jax matmul precision: default|high|highest")
define_flag("embedding_deterministic", 0, "kept for API parity (determinism is XLA default)")
define_flag("cudnn_deterministic", False, "API parity alias; TPU execution is deterministic")
define_flag("max_inplace_grad_add", 0, "API parity; tape always accumulates functionally")
define_flag("log_level", 0, "verbosity of paddle_tpu host-side logging")
define_flag("eager_cached_vjp", True,
            "eager backward via per-signature jit cache (remat-style: primal "
            "re-runs in backward); False = residual capture at forward time")

# Reference flags accepted for parity (paddle/common/flags.cc): ported code
# sets these freely; most govern CUDA/allocator behavior that PJRT/XLA owns
# here, so they are accepted no-ops with the reference defaults. set_flags on
# any OTHER unknown flag also succeeds (define-on-set above) — the reference's
# own behavior is to accept every registered flag, and defining-on-set keeps
# ported set_flags/get_flags pairs working.
for _name, _default in [
    ("benchmark", False), ("check_kernel_launch", False),
    ("conv2d_disable_cudnn", False), ("conv_workspace_size_limit", 512),
    ("cublaslt_exhaustive_search_times", 0), ("cudnn_batchnorm_spatial_persistent", False),
    ("cudnn_exhaustive_search", False), ("cudnn_exhaustive_search_times", -1),
    ("enable_cublas_tensor_op_math", False), ("embedding_deterministic_level", 0),
    ("gemm_use_half_precision_compute_type", False),
    ("gpu_allocator_retry_time", 2000), ("gpu_memory_limit_mb", 0),
    ("fraction_of_gpu_memory_to_use", 0.92), ("initial_gpu_memory_in_mb", 0),
    ("reallocate_gpu_memory_in_mb", 0), ("fraction_of_cpu_memory_to_use", 1.0),
    ("init_allocated_mem", False), ("memory_fraction_of_eager_deletion", 1.0),
    ("fast_eager_deletion_mode", True), ("use_pinned_memory", True),
    ("use_cuda_managed_memory", False), ("use_virtual_memory_auto_growth", False),
    ("free_idle_chunk", False), ("free_when_no_cache_hit", False),
    ("enable_cudnn_frontend", False), ("cudnn_cache_saturation_count", 1),
    ("low_precision_op_list", 0), ("enable_api_kernel_fallback", True),
    ("use_mkldnn", False), ("use_autotune", False),
    ("inner_op_parallelism", 0), ("enable_parallel_graph", False),
    ("sync_nccl_allreduce", True), ("nccl_blocking_wait", False),
    ("fuse_parameter_groups_size", 3), ("fuse_parameter_memory_size", -1.0),
    ("apply_pass_to_program", False), ("convert_all_blocks", True),
    ("new_executor_serial_run", False), ("new_executor_static_build", False),
    ("new_executor_use_inplace", False), ("new_executor_use_local_scope", True),
    ("enable_pir_api", False), ("enable_pir_in_executor", False),
    ("print_ir", False), ("call_stack_level", 1),
    ("check_nan_inf_op_list", ""), ("skip_nan_inf_op_list", ""),
    ("tracer_mkldnn_ops_on", ""), ("tracer_mkldnn_ops_off", ""),
    ("prim_all", False), ("prim_backward", False), ("prim_forward", False),
    ("set_to_1d", True), ("jit_engine_type", "PE"),
    ("multiple_of_cupti_buffer_size", 1), ("enable_gpu_memory_usage_log", False),
    ("allreduce_record_one_event", False), ("rpc_retry_times", 3),
    ("rpc_deadline", 180000), ("eager_communication_connection", False),
    ("dynamic_static_unified_comm", True), ("enable_async_trace", False),
    ("flash_attn_version", 2), ("cudnn_deterministic_level", 0),
]:
    define_flag(_name, _default, "accepted for reference parity (flags.cc)")
del _name, _default
