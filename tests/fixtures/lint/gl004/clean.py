"""GL004 clean sample: short host-only critical sections."""
import threading


class GoodRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counts = {}

    def record(self, key, host_value):
        # device work happens BEFORE the lock; the critical section is
        # one dict mutation
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + host_value

    def snapshot(self):
        with self._lock:
            return dict(self._counts)
