"""GL005 clean fixture catalog (dependency-free, loadable by file path)."""

SUBSYSTEMS = ("serving", "dispatch")

NAME_PATTERN = r"^paddle_tpu_(" + "|".join(SUBSYSTEMS) + r")_[a-z][a-z0-9_]*$"

METRICS = {
    "paddle_tpu_serving_requests_total": (
        "counter", (), "Requests admitted."),
    "paddle_tpu_dispatch_depth": (
        "gauge", (), "Current dispatch queue depth."),
}
