"""Fused op surface (paddle.incubate.nn.functional).

Reference analog: python/paddle/incubate/nn/functional/{fused_rotary_position_embedding,
fused_rms_norm, fused_layer_norm, swiglu, fused_dropout_add, fused_linear}.py — hand-fused
CUDA kernels. TPU-first: each is ONE defop (a single jax-traceable function), so XLA fuses
it into neighbouring HLO; the per-op eager path still runs it as one cached executable.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ....nn.functional.activation import swiglu  # noqa: F401  (already fused)
from ....ops._apply import defop


def _rotate_half(x):
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def _rotate_every_two(x):
    # interleaved layout: rotation pairs are (2i, 2i+1)
    x1 = x[..., ::2]
    x2 = x[..., 1::2]
    return jnp.stack([-x2, x1], axis=-1).reshape(x.shape)


def _rope_tables(seq_len, head_dim, theta, dtype, position_ids=None, every_two=True):
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    if position_ids is None:
        t = jnp.arange(seq_len, dtype=jnp.float32)
    else:
        t = position_ids.astype(jnp.float32)
    freqs = jnp.einsum("...s,d->...sd", t, inv_freq)
    if every_two:
        emb = jnp.repeat(freqs, 2, axis=-1)                  # [f0, f0, f1, f1, ...]
    else:
        emb = jnp.concatenate([freqs, freqs], axis=-1)       # [f0..f_{D/2-1}, f0..]
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _normalize_rope_table(tbl):
    """Accept (S,D), (B,S,D), (1,S,1,D)/(B,S,1,D) layouts → (S,D) or (B,S,D)."""
    if tbl.ndim == 4:                                        # (B,S,1,D) head axis
        tbl = tbl.reshape(tbl.shape[0], tbl.shape[1], tbl.shape[3])
    if tbl.ndim == 3 and tbl.shape[0] == 1:
        tbl = tbl[0]
    return tbl


@defop("fused_rotary_position_embedding", amp_category="white")
def _fused_rope(q, k=None, v=None, sin=None, cos=None, position_ids=None,
                use_neox_rotary_style=True, rotary_theta=10000.0):
    """q/k/v: (B, S, H, D). RoPE applies to EVERY provided input (the reference
    kernel loops all of q/k/v: fused_rope_utils.h rotate_every_two iterates
    num_inputs). use_neox_rotary_style=True selects the interleaved rotate-every-two
    pairing, False the half-split rotate-half pairing — per the kernel dispatch at
    fused_rope_kernel.cu:188-190 (NOT the usual HF naming). Auto-generated tables use
    the pairing-consistent frequency layout for each style."""
    S, D = q.shape[1], q.shape[-1]
    if cos is None or sin is None:
        cos, sin = _rope_tables(S, D, rotary_theta, q.dtype, position_ids,
                                every_two=use_neox_rotary_style)
    else:
        cos = _normalize_rope_table(cos)
        sin = _normalize_rope_table(sin)
    # broadcast (…S,D) over batch/head axes of (B,S,H,D)
    if cos.ndim == 2:
        cos_b = cos[None, :, None, :]
        sin_b = sin[None, :, None, :]
    else:  # (B,S,D) from position_ids
        cos_b = cos[:, :, None, :]
        sin_b = sin[:, :, None, :]

    if use_neox_rotary_style:
        def rot(x):
            return x * cos_b + _rotate_every_two(x) * sin_b
    else:
        def rot(x):
            return x * cos_b + _rotate_half(x) * sin_b

    outs = tuple(rot(t) for t in (q, k, v) if t is not None)
    return outs[0] if len(outs) == 1 else outs


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    rotary_theta=10000.0, name=None):
    out = _fused_rope(q, k, v, sin=sin, cos=cos, position_ids=position_ids,
                      use_neox_rotary_style=use_neox_rotary_style,
                      rotary_theta=rotary_theta)
    if not isinstance(out, tuple):
        out = (out,)
    # fixed positional slots: None inputs yield None outputs in their own slot
    res, it = [], iter(out)
    for t in (q, k, v):
        res.append(next(it) if t is not None else None)
    return tuple(res)


@defop("fused_rms_norm", amp_category="fp32")
def _fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6, begin_norm_axis=-1):
    axes = tuple(range(begin_norm_axis % x.ndim, x.ndim))
    # promote, don't demote: bf16 -> f32 for stability, f64 stays f64
    xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
    var = jnp.mean(xf * xf, axis=axes, keepdims=True)
    y = (xf * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)
    if norm_weight is not None:
        y = y * norm_weight
    if norm_bias is not None:
        y = y + norm_bias
    return y


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6, begin_norm_axis=-1,
                   name=None):
    return _fused_rms_norm(x, norm_weight, norm_bias, epsilon=epsilon,
                           begin_norm_axis=begin_norm_axis)


@defop("fused_layer_norm", amp_category="fp32")
def _fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                      begin_norm_axis=-1, residual=None):
    if residual is not None:
        x = x + residual
    axes = tuple(range(begin_norm_axis % x.ndim, x.ndim))
    # promote, don't demote: bf16 -> f32 for stability, f64 stays f64
    xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    y = ((xf - mean) * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)
    if norm_weight is not None:
        y = y * norm_weight
    if norm_bias is not None:
        y = y + norm_bias
    return y


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=-1, residual=None, name=None):
    return _fused_layer_norm(x, norm_weight, norm_bias, epsilon=epsilon,
                             begin_norm_axis=begin_norm_axis, residual=residual)


@defop("fused_dropout_add")
def _fused_dropout_add(x, y, key=None, p=0.5, training=True,
                       mode="upscale_in_train"):
    if not training or p == 0.0 or key is None:
        return x + y
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    if mode == "upscale_in_train":
        dropped = jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    else:
        dropped = jnp.where(keep, x, 0.0).astype(x.dtype)
    return dropped + y


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train", name=None):
    from ....framework import random as rng

    key = rng.next_key() if (training and p > 0.0) else None
    return _fused_dropout_add(x, y, key=key, p=p, training=training, mode=mode)


@defop("fused_linear")
def _fused_linear(x, weight, bias=None, transpose_weight=False):
    w = weight.T if transpose_weight else weight
    y = jnp.matmul(x, w)
    if bias is not None:
        y = y + bias
    return y


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return _fused_linear(x, weight, bias, transpose_weight=transpose_weight)


@defop("fused_bias_act")
def _fused_bias_act(x, bias=None, act_method="gelu"):
    if bias is not None:
        x = x + bias
    if act_method in ("gelu", "geglu"):
        return jax.nn.gelu(x, approximate=False)
    if act_method == "relu":
        return jax.nn.relu(x)
    if act_method in ("swiglu",):
        a, b = jnp.split(x, 2, axis=-1)
        return jax.nn.silu(a) * b
    if act_method in ("silu", "swish"):
        return jax.nn.silu(x)
    raise ValueError(f"unsupported act_method {act_method}")


def fused_bias_act(x, bias=None, act_method="gelu", name=None, **kwargs):
    return _fused_bias_act(x, bias, act_method=act_method)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """fused_matmul_bias.py: matmul+bias in one op (XLA fuses the epilogue)."""
    from ....ops.linalg import matmul

    out = matmul(x, y, transpose_x=transpose_x, transpose_y=transpose_y)
    return out + bias if bias is not None else out


@defop("fused_gate_attention", amp_category="white")
def _fused_gate_attention(query, key=None, query_weight=None, key_weight=None,
                          value_weight=None, qkv_weight=None,
                          gate_linear_weight=None, gate_linear_bias=None,
                          out_linear_weight=None, out_linear_bias=None,
                          nonbatched_bias=None, attn_mask=None,
                          has_gating=True, merge_qkv=True):
    """reference fused_gate_attention.py:26 — AlphaFold-style gated MSA
    self-attention as ONE traced op (the reference fuses it as a CUDA
    kernel; here XLA fuses the einsum chain). Shapes per the reference:
    query [N, B, Q, A]; merged qkv_weight [3, H, D, A]; separate
    query/key/value weights [A, H, D]; gating [A, H, D] + [H, D]; output
    [H, D, A_out]; nonbatched_bias [N, H, Q, M] (unsqueezed over the msa
    axis); attn_mask [N, B, 1, 1, M] added as a bias."""
    if merge_qkv:
        qkv = jnp.einsum("nbqa,thda->tnbqhd", query, qkv_weight)
        q, k, v = qkv[0], qkv[1], qkv[2]
    else:
        kin = query if key is None else key
        q = jnp.einsum("nbqa,ahd->nbqhd", query, query_weight)
        k = jnp.einsum("nbka,ahd->nbkhd", kin, key_weight)
        v = jnp.einsum("nbka,ahd->nbkhd", kin, value_weight)
    head_dim = q.shape[-1]
    q = q * (head_dim ** -0.5)
    logits = jnp.einsum("nbqhd,nbkhd->nbhqk", q, k)
    if attn_mask is not None:
        logits = logits + attn_mask
    if nonbatched_bias is not None:
        logits = logits + nonbatched_bias[:, None]
    ct = jnp.promote_types(logits.dtype, jnp.float32)
    probs = jax.nn.softmax(logits.astype(ct), -1).astype(logits.dtype)
    out = jnp.einsum("nbhqk,nbkhd->nbqhd", probs, v)
    if has_gating:
        gate = jnp.einsum("nbqa,ahd->nbqhd", query, gate_linear_weight)
        if gate_linear_bias is not None:
            gate = gate + gate_linear_bias
        out = out * jax.nn.sigmoid(gate)
    out = jnp.einsum("nbqhd,hdo->nbqo", out, out_linear_weight)
    if out_linear_bias is not None:
        out = out + out_linear_bias
    return out


def fused_gate_attention(query, key=None, query_weight=None, key_weight=None,
                         value_weight=None, qkv_weight=None,
                         gate_linear_weight=None, gate_linear_bias=None,
                         out_linear_weight=None, out_linear_bias=None,
                         nonbatched_bias=None, attn_mask=None,
                         has_gating=True, merge_qkv=True,
                         use_flash_attn=False):
    """reference incubate/nn/functional/fused_gate_attention.py:26 public
    surface. ``use_flash_attn`` is accepted (the XLA fusion plays that
    role; the gate-attention shapes are small-res AlphaFold blocks, not
    long-sequence flash territory)."""
    if merge_qkv and key is not None:
        # the merged path is self-attention only (reference contract):
        # silently dropping `key` would return plausible-but-wrong numbers
        raise ValueError(
            "fused_gate_attention: merge_qkv=True is self-attention only "
            "(qkv projected from `query`); pass merge_qkv=False with "
            "query/key/value weights for cross-attention over `key`")
    if merge_qkv and qkv_weight is None:
        raise ValueError(
            "fused_gate_attention: merge_qkv=True needs qkv_weight "
            "([3, num_heads, head_dim, q_dim])")
    if not merge_qkv and (query_weight is None or key_weight is None
                          or value_weight is None):
        raise ValueError(
            "fused_gate_attention: merge_qkv=False needs query_weight, "
            "key_weight and value_weight ([dim, num_heads, head_dim])")
    if has_gating and gate_linear_weight is None:
        raise ValueError(
            "fused_gate_attention: has_gating=True needs "
            "gate_linear_weight (pass has_gating=False to skip gating)")
    if out_linear_weight is None:
        raise ValueError("fused_gate_attention: out_linear_weight is "
                         "required ([num_heads, head_dim, out_dim])")
    return _fused_gate_attention(
        query, key, query_weight, key_weight, value_weight, qkv_weight,
        gate_linear_weight, gate_linear_bias, out_linear_weight,
        out_linear_bias, nonbatched_bias, attn_mask,
        has_gating=bool(has_gating), merge_qkv=bool(merge_qkv))


def fused_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                is_causal=False, training=True,
                                scaling_factor=None, name=None):
    """fused_dot_product_attention.py: served by the sdp dispatcher (Pallas
    flash attention when shapes allow)."""
    from ....nn.functional.flash_attention import scaled_dot_product_attention

    return scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                        dropout_p=dropout_p,
                                        is_causal=is_causal,
                                        training=training)


def variable_length_memory_efficient_attention(query, key, value, seq_lens,
                                               kv_seq_lens, mask=None,
                                               scale=None, causal=False,
                                               name=None):
    """variable_length_memory_efficient_attention.py: padding positions beyond
    kv_seq_lens are masked out (the reference kernel's varlen semantics)."""
    import jax.numpy as jnp

    from ....framework.core import Tensor
    from ....nn.functional.flash_attention import _sdpa, _use_pallas

    # (B, H, S, D) reference layout -> sdp's (B, S, H, D)
    from ....ops.manipulation import transpose

    q = transpose(query, [0, 2, 1, 3])
    k = transpose(key, [0, 2, 1, 3])
    v = transpose(value, [0, 2, 1, 3])

    sk = int(k.shape[1])
    kv_lens = kv_seq_lens if kv_seq_lens is not None else seq_lens
    if kv_lens is not None:
        lens = (kv_lens.value if isinstance(kv_lens, Tensor)
                else jnp.asarray(kv_lens)).reshape(-1)
        # keep key column j for batch b iff j < kv_len[b]; (B, 1, 1, Sk)
        keep = (jnp.arange(sk)[None, :] < lens[:, None])[:, None, None, :]
        if mask is None:
            mask = keep
        else:
            mv = mask.value if isinstance(mask, Tensor) else jnp.asarray(mask)
            if mv.dtype == jnp.bool_:
                mask = mv & keep
            else:
                mask = mv + jnp.where(keep, 0.0, -1e30).astype(mv.dtype)
    out = _sdpa(q, k, v, mask, None, dropout_p=0.0, causal=bool(causal),
                scale=scale, use_pallas=_use_pallas(q))
    return transpose(out, [0, 2, 1, 3])


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn2_bias=None, quant_method="None", moe_topk=2,
              norm_topk_prob=True, name=None):
    """fused_moe.py: token top-k routing + expert FFNs, einsum-dispatched so
    GSPMD can shard the expert axis.

    x: (B, S, D); gate_weight: (D, E); ffn1_weight: (E, D, I) (swiglu packs
    2*I); ffn2_weight: (E, I_or_I, D).
    """
    import jax
    import jax.numpy as jnp

    from ....framework.core import Tensor

    xv = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    gw = gate_weight.value if isinstance(gate_weight, Tensor) \
        else jnp.asarray(gate_weight)
    w1 = ffn1_weight.value if isinstance(ffn1_weight, Tensor) \
        else jnp.asarray(ffn1_weight)
    w2 = ffn2_weight.value if isinstance(ffn2_weight, Tensor) \
        else jnp.asarray(ffn2_weight)
    B, S, D = xv.shape
    E = gw.shape[1]
    tokens = xv.reshape(B * S, D)
    logits = tokens @ gw
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_e = jax.lax.top_k(probs, moe_topk)
    if norm_topk_prob:
        top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
    # dense dispatch: weight each expert by its routed probability (0 when
    # not in the top-k) — einsums keep the E axis shardable
    weights = jnp.zeros((B * S, E), xv.dtype)
    weights = weights.at[jnp.arange(B * S)[:, None], top_e].set(
        top_p.astype(xv.dtype))
    h = jnp.einsum("td,edi->tei", tokens, w1)
    if ffn1_bias is not None:
        b1 = ffn1_bias.value if isinstance(ffn1_bias, Tensor) \
            else jnp.asarray(ffn1_bias)
        h = h + b1[None]
    inter = w2.shape[1]
    if h.shape[-1] == 2 * inter:  # swiglu-packed ffn1
        gate_h, up = jnp.split(h, 2, axis=-1)
        h = jax.nn.silu(gate_h) * up
    else:
        h = jax.nn.gelu(h, approximate=False)
    y = jnp.einsum("tei,eid->ted", h, w2)
    if ffn2_bias is not None:
        b2 = ffn2_bias.value if isinstance(ffn2_bias, Tensor) \
            else jnp.asarray(ffn2_bias)
        y = y + b2[None]
    out = jnp.einsum("ted,te->td", y, weights)
    return Tensor(out.reshape(B, S, D))


@defop("fused_linear_cross_entropy", amp_category="black")
def _fused_linear_cross_entropy(hidden, weight, labels, ignore_index=-100,
                                chunk_size=512):
    """Chunked LM-head matmul + softmax cross-entropy that never materializes
    the full [B, S, V] logits (at V=32k, B8 x S2048 that is >1 GB bf16 /
    >4 GB fp32 of HBM traffic). Sequence chunks run under jax.checkpoint
    inside lax.map: forward keeps only [B, C, V] live; backward recomputes
    each chunk's logits. The matmul stays in the input dtype (bf16 on the
    MXU); the softmax runs in fp32.

    Reference capability analog: fused_softmax_mask + c_softmax_with_
    cross_entropy family (fused_ops.yaml) — the TPU-first formulation is
    remat-chunking rather than a custom kernel, since the inner matmul and
    the online logsumexp are exactly what XLA already schedules well.
    Returns per-token loss [B, S] (0.0 at ignore_index positions).
    """
    B, S, H = hidden.shape
    C = min(int(chunk_size), S)
    pad = (-S) % C
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)),
                         constant_values=ignore_index)
    sp = S + pad
    n = sp // C
    hs = jnp.moveaxis(hidden.reshape(B, n, C, H), 1, 0)   # [n, B, C, H]
    ls = jnp.moveaxis(labels.reshape(B, n, C), 1, 0)      # [n, B, C]

    @jax.checkpoint
    def chunk_fn(hc, lc):
        logits = jnp.einsum("bch,hv->bcv", hc, weight).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        safe = jnp.where(lc == ignore_index, 0, lc).astype(jnp.int32)
        picked = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        return jnp.where(lc == ignore_index, 0.0, lse - picked)

    tok = jax.lax.map(lambda args: chunk_fn(*args), (hs, ls))  # [n, B, C]
    return jnp.moveaxis(tok, 0, 1).reshape(B, sp)[:, :S]


def fused_linear_cross_entropy(hidden, weight, labels, ignore_index=-100,
                               chunk_size=512, name=None):
    """Per-token causal-LM loss fused with the LM-head projection — see
    `_fused_linear_cross_entropy`. `weight` is [hidden, vocab]."""
    return _fused_linear_cross_entropy(hidden, weight, labels,
                                       ignore_index=int(ignore_index),
                                       chunk_size=int(chunk_size))


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation=None, name=None):
    """reference fused_ops fused_linear_activation: matmul + bias + act in
    one op (XLA fuses the epilogue into the matmul on TPU)."""
    out = fused_matmul_bias(x, y, bias, transpose_x=trans_x,
                            transpose_y=trans_y)
    if activation in (None, "", "none"):
        return out
    from ....nn import functional as F

    act = {"relu": F.relu, "gelu": F.gelu, "swish": F.silu,
           "silu": F.silu}.get(activation)
    if act is None:
        raise ValueError(f"unsupported activation {activation!r}")
    return act(out)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", name=None):
    """reference fused_transformer.py fused_bias_dropout_residual_layer_norm:
    out = LN(residual + dropout(x + bias))."""
    from ....nn import functional as F

    y = x if bias is None else x + bias
    if dropout_rate:
        y = F.dropout(y, p=dropout_rate, training=training, mode=mode)
    y = residual + y
    norm_shape = [int(y.shape[-1])]
    return F.layer_norm(y, norm_shape, ln_scale, ln_bias, ln_epsilon)


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm=False,
        pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None,
        pre_ln_epsilon=1e-5, qkv_bias=None, linear_bias=None, cache_kv=None,
        attn_mask=None, dropout_rate=0.5, attn_dropout_rate=0.5,
        ln_epsilon=1e-5, training=True, mode="upscale_in_train", ring_id=-1,
        add_residual=True, num_heads=-1, transpose_qkv_wb=False, name=None):
    """reference fused_transformer.py fused_multi_head_attention — the
    functional form of FusedMultiHeadAttention (packed [3, H, D, E] qkv
    weight; XLA fuses what the reference hand-fuses in CUDA)."""
    from ....nn import functional as F
    from ....ops import manipulation as m

    if transpose_qkv_wb:
        raise NotImplementedError(
            "transpose_qkv_wb=True is not implemented (packed [3, H, D, E] "
            "layout is — matches incubate.nn.FusedMultiHeadAttention)")
    three, heads, head_dim, embed = (int(s) for s in qkv_weight.shape)
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, [embed], pre_ln_scale, pre_ln_bias,
                         pre_ln_epsilon)
    w = m.reshape(qkv_weight, [3 * embed, embed])
    qkv = fused_matmul_bias(
        x, w, None if qkv_bias is None else m.reshape(qkv_bias, [3 * embed]),
        transpose_y=True)
    qkv = m.reshape(qkv, [0, 0, 3, heads, head_dim])
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    cache_out = None
    if cache_kv is not None:
        # reference contract: cache_kv [2, B, H, T, D] holds past K/V; the
        # new tokens append and the call returns (out, updated_cache)
        from ....framework.core import Tensor as _T
        from ....ops import manipulation as _m

        cv = cache_kv.value if isinstance(cache_kv, _T) \
            else jnp.asarray(cache_kv)
        past_k = _T(jnp.swapaxes(cv[0], 1, 2))  # -> (B, T, H, D)
        past_v = _T(jnp.swapaxes(cv[1], 1, 2))
        k = _m.concat([past_k, k], axis=1)
        v = _m.concat([past_v, v], axis=1)
        cache_out = _T(jnp.stack([jnp.swapaxes(k.value, 1, 2),
                                  jnp.swapaxes(v.value, 1, 2)]))
    out = F.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate,
        is_causal=False, training=training)
    out = m.reshape(out, [0, 0, embed])
    out = fused_matmul_bias(out, linear_weight, linear_bias)
    if dropout_rate:
        out = F.dropout(out, p=dropout_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, [embed], ln_scale, ln_bias, ln_epsilon)
    if cache_out is not None:
        return out, cache_out
    return out


def fused_feedforward(
        x, linear1_weight, linear2_weight, linear1_bias=None,
        linear2_bias=None, ln1_scale=None, ln1_bias=None, ln2_scale=None,
        ln2_bias=None, dropout1_rate=0.5, dropout2_rate=0.5,
        activation="relu", ln1_epsilon=1e-5, ln2_epsilon=1e-5,
        pre_layer_norm=False, training=True, mode="upscale_in_train",
        ring_id=-1, add_residual=True, name=None):
    """reference fused_transformer.py fused_feedforward — functional form of
    FusedFeedForward: [LN ->] linear1 -> act -> dropout -> linear2 ->
    dropout -> residual [-> LN]."""
    from ....nn import functional as F

    embed = int(x.shape[-1])
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, [embed], ln1_scale, ln1_bias, ln1_epsilon)
    h = fused_linear_activation(x, linear1_weight, linear1_bias,
                                activation=activation)
    if dropout1_rate:
        h = F.dropout(h, p=dropout1_rate, training=training, mode=mode)
    h = fused_matmul_bias(h, linear2_weight, linear2_bias)
    if dropout2_rate:
        h = F.dropout(h, p=dropout2_rate, training=training, mode=mode)
    out = residual + h if add_residual else h
    if not pre_layer_norm:
        out = F.layer_norm(out, [embed], ln2_scale, ln2_bias, ln2_epsilon)
    return out


def fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, pre_layer_norm=True,
        epsilon=1e-5, cache_kvs=None, pre_caches=None, seq_lens=None,
        rotary_embs=None, time_step=None, attn_mask=None,
        dropout_rate=0.0, activation="gelu", training=False,
        mode="upscale_in_train", trans_qkvw=True, ring_id=-1, name=None,
        **unused):
    """reference fused_transformer.py fused_multi_transformer — the whole
    decoder stack as one call: per layer, fused attention + fused FFN."""
    # Semantically significant rotary/varlen args must not be silently
    # dropped: a GPT-NeoX-style caller passing rotary_embs would get wrong
    # numerics without any signal (advisor r4).
    for arg_name, arg in (("rotary_embs", rotary_embs),
                          ("pre_caches", pre_caches),
                          ("seq_lens", seq_lens)):
        if arg is not None:
            raise NotImplementedError(
                f"fused_multi_transformer: {arg_name} is not supported by "
                "this build; apply rotary embeddings in the model (see "
                "models/llama.py) or use models.llama_decode."
                "LlamaDecodeEngine for cached decoding")
    if unused:
        raise TypeError(
            "fused_multi_transformer: unexpected keyword arguments "
            f"{sorted(unused)}")
    if not trans_qkvw:
        raise NotImplementedError(
            "fused_multi_transformer: trans_qkvw=False ([E, 3, H, D] weight "
            "layout) is not supported; pass the default transposed "
            "[3, H, D, E] layout")
    if cache_kvs is not None:
        if attn_mask is not None:
            raise NotImplementedError(
                "fused_multi_transformer: attn_mask with cache_kvs is not "
                "supported (the cached path masks by position only); for "
                "padded batches use models.serving.ContinuousBatchingEngine "
                "or left-trim the prompts")
        if training or (dropout_rate and mode == "downscale_in_infer"):
            # dropout_rate with training=False under the default
            # upscale_in_train mode is a no-op in the uncached path too, so
            # it is allowed; only combinations that would actually change
            # inference numerics are rejected
            raise ValueError(
                "fused_multi_transformer: the cached path is inference-only "
                "(training=False; downscale_in_infer dropout would change "
                "eval numerics and is not supported with cache_kvs)")
        return _fused_multi_transformer_cached(
            x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
            linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
            ffn1_biases, ffn2_weights, ffn2_biases,
            pre_layer_norm=pre_layer_norm, epsilon=epsilon,
            cache_kvs=cache_kvs, time_step=time_step,
            activation=activation)
    if time_step is not None:
        raise ValueError(
            "fused_multi_transformer: time_step needs cache_kvs (the "
            "preallocated [2, B, H, max_len, D] per-layer caches)")
    out = x
    for i in range(len(qkv_weights)):
        out = fused_multi_head_attention(
            out, qkv_weights[i], linear_weights[i],
            pre_layer_norm=pre_layer_norm,
            pre_ln_scale=ln_scales[i] if ln_scales else None,
            pre_ln_bias=ln_biases[i] if ln_biases else None,
            ln_scale=ln_scales[i] if ln_scales else None,
            ln_bias=ln_biases[i] if ln_biases else None,
            pre_ln_epsilon=epsilon, ln_epsilon=epsilon,
            qkv_bias=qkv_biases[i] if qkv_biases else None,
            linear_bias=linear_biases[i] if linear_biases else None,
            attn_mask=attn_mask, dropout_rate=dropout_rate,
            attn_dropout_rate=dropout_rate, training=training, mode=mode)
        out = fused_feedforward(
            out, ffn1_weights[i], ffn2_weights[i],
            linear1_bias=ffn1_biases[i] if ffn1_biases else None,
            linear2_bias=ffn2_biases[i] if ffn2_biases else None,
            ln1_scale=ffn_ln_scales[i] if ffn_ln_scales else None,
            ln1_bias=ffn_ln_biases[i] if ffn_ln_biases else None,
            ln2_scale=ffn_ln_scales[i] if ffn_ln_scales else None,
            ln2_bias=ffn_ln_biases[i] if ffn_ln_biases else None,
            dropout1_rate=dropout_rate, dropout2_rate=dropout_rate,
            activation=activation, ln1_epsilon=epsilon, ln2_epsilon=epsilon,
            pre_layer_norm=pre_layer_norm, training=training, mode=mode)
    return out


def _fused_multi_transformer_cached(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, pre_layer_norm, epsilon,
        cache_kvs, time_step, activation):
    """The reference's cached generation contract
    (fused_multi_transformer_op.cu): per-layer PREALLOCATED caches
    [2, B, H, max_len, D]; with ``time_step=None`` the call is the context/
    prefill phase (writes positions 0..S-1, causal attention within the
    prompt); with ``time_step=t`` it is one decode step (x is [B, 1, E],
    K/V written at position t, attention over positions <= t). Returns
    (out, updated_cache_kvs). Inference semantics: dropout off."""
    from ....framework.core import Tensor as _T
    from ....nn import functional as F
    from ....ops import manipulation as m

    def _v(t):
        return t.value if isinstance(t, _T) else jnp.asarray(t)

    xv = _v(x)
    B, S, E = xv.shape
    t0 = None if time_step is None else int(
        np.asarray(_v(time_step)).reshape(-1)[0])
    if t0 is not None and S != 1:
        raise ValueError(
            "fused_multi_transformer decode (time_step given) expects one "
            f"token per call, got S={S}")
    max_len = int(_v(cache_kvs[0]).shape[3])
    start0 = 0 if t0 is None else t0
    if start0 + S > max_len:
        # dynamic_update_slice would silently CLAMP an out-of-range write,
        # corrupting the last cache slot instead of failing
        raise ValueError(
            f"fused_multi_transformer: writing positions "
            f"{start0}..{start0 + S - 1} overflows the preallocated cache "
            f"(max_len={max_len}); allocate larger cache_kvs")

    out = x
    new_caches = []
    for i in range(len(qkv_weights)):
        residual = out
        h = out
        if pre_layer_norm:
            h = F.layer_norm(h, [E], ln_scales[i] if ln_scales else None,
                             ln_biases[i] if ln_biases else None, epsilon)
        three, heads, head_dim, _ = (int(s) for s in qkv_weights[i].shape)
        w = m.reshape(qkv_weights[i], [3 * E, E])
        qkv = fused_matmul_bias(
            h, w, None if not qkv_biases
            else m.reshape(qkv_biases[i], [3 * E]), transpose_y=True)
        qkv_v = _v(qkv).reshape(B, S, 3, heads, head_dim)
        q, k, v = qkv_v[:, :, 0], qkv_v[:, :, 1], qkv_v[:, :, 2]

        cv = _v(cache_kvs[i])                  # [2, B, H, max_len, D]
        k_btxd = jnp.swapaxes(k, 1, 2)         # [B, H, S, D]
        v_btxd = jnp.swapaxes(v, 1, 2)
        start = 0 if t0 is None else t0
        ck = jax.lax.dynamic_update_slice(cv[0], k_btxd.astype(cv.dtype),
                                          (0, 0, start, 0))
        cvv = jax.lax.dynamic_update_slice(cv[1], v_btxd.astype(cv.dtype),
                                           (0, 0, start, 0))
        new_caches.append(_T(jnp.stack([ck, cvv])))

        # attention over the cache with a position mask (dense decode-engine
        # semantics: static shapes, one compiled program per phase)
        positions = start + jnp.arange(S)                       # query pos
        tpos = jnp.arange(max_len)[None, None, :]
        pos_mask = tpos <= positions[None, :, None]             # [1, S, T]
        ct = jnp.promote_types(q.dtype, jnp.float32)
        logits = jnp.einsum("bshd,bhtd->bhst", q.astype(ct),
                            ck.astype(ct)) / np.sqrt(head_dim)
        logits = jnp.where(pos_mask[:, None], logits, -1e30)
        probs = jax.nn.softmax(logits, -1)
        attn = jnp.einsum("bhst,bhtd->bshd", probs, cvv.astype(ct))
        attn = attn.reshape(B, S, heads * head_dim).astype(xv.dtype)

        o = fused_matmul_bias(_T(attn), linear_weights[i],
                              linear_biases[i] if linear_biases else None)
        o = residual + o
        if not pre_layer_norm:
            o = F.layer_norm(o, [E], ln_scales[i] if ln_scales else None,
                             ln_biases[i] if ln_biases else None, epsilon)
        out = fused_feedforward(
            o, ffn1_weights[i], ffn2_weights[i],
            linear1_bias=ffn1_biases[i] if ffn1_biases else None,
            linear2_bias=ffn2_biases[i] if ffn2_biases else None,
            ln1_scale=ffn_ln_scales[i] if ffn_ln_scales else None,
            ln1_bias=ffn_ln_biases[i] if ffn_ln_biases else None,
            ln2_scale=ffn_ln_scales[i] if ffn_ln_scales else None,
            ln2_bias=ffn_ln_biases[i] if ffn_ln_biases else None,
            dropout1_rate=0.0, dropout2_rate=0.0, activation=activation,
            ln1_epsilon=epsilon, ln2_epsilon=epsilon,
            pre_layer_norm=pre_layer_norm, training=False)
    return out, new_caches


def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size=None,
                     name=None):
    """reference blha_get_max_len: the (max encoder len, max decoder len)
    pair the block-attention kernels size their launch by."""
    from ....framework.core import Tensor

    enc = seq_lens_encoder.value if isinstance(seq_lens_encoder, Tensor) \
        else jnp.asarray(seq_lens_encoder)
    dec = seq_lens_decoder.value if isinstance(seq_lens_decoder, Tensor) \
        else jnp.asarray(seq_lens_decoder)
    return (Tensor(jnp.max(enc).reshape(1)),
            Tensor(jnp.max(dec).reshape(1)))


def masked_multihead_attention(
        x, cache_kv=None, bias=None, src_mask=None, sequence_lengths=None,
        rotary_tensor=None, beam_cache_offset=None, qkv_out_scale=None,
        out_shift=None, out_smooth=None, seq_len=1, rotary_emb_dims=0,
        use_neox_rotary_style=False, compute_dtype="default",
        out_scale=-1.0, quant_round_type=1, quant_max_bound=127.0,
        quant_min_bound=-127.0, name=None):
    """reference masked_multihead_attention: ONE decode step of multi-head
    attention against a growing [2, B, H, T, D] cache — the generation-loop
    kernel. x is the packed qkv for the new token: (B, 3*H*D)."""
    from ....framework.core import Tensor

    # Reject (rather than silently ignore) args that change the attention
    # result: masking and the int8 quantization contract (advisor r4 —
    # mirrors the existing explicit rejections below).
    if rotary_emb_dims not in (0, 1):
        raise NotImplementedError(
            "masked_multihead_attention: rotary_emb_dims=2 (extra position "
            "ids) is not supported; the standard rotary_emb_dims=1 form is")
    if rotary_tensor is None and rotary_emb_dims:
        raise ValueError(
            "masked_multihead_attention: rotary_emb_dims=1 needs "
            "rotary_tensor ([2, B, max_seq, 1, head_dim] cos/sin tables)")
    if rotary_tensor is not None and not rotary_emb_dims:
        raise ValueError(
            "masked_multihead_attention: rotary_tensor given but "
            "rotary_emb_dims=0 (the reference kernel gates rotation on "
            "rotary_emb_dims; pass rotary_emb_dims=1)")
    if beam_cache_offset is not None:
        raise NotImplementedError(
            "masked_multihead_attention: beam_cache_offset (beam-search KV "
            "reordering) is not supported; use LlamaDecodeEngine's beam "
            "search for reordered-cache generation")
    if qkv_out_scale is not None or out_shift is not None \
            or out_smooth is not None or out_scale != -1.0:
        raise NotImplementedError(
            "masked_multihead_attention: int8 quantization params "
            "(qkv_out_scale/out_shift/out_smooth/out_scale) are not "
            "supported; use LlamaDecodeEngine(kv_cache_dtype='int8') for "
            "quantized-KV decoding")
    if cache_kv is None:
        raise ValueError("cache_kv is required (shape [2, B, H, T, D])")
    xv = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    cv = cache_kv.value if isinstance(cache_kv, Tensor) \
        else jnp.asarray(cache_kv)
    if bias is not None:
        xv = xv + (bias.value if isinstance(bias, Tensor)
                   else jnp.asarray(bias)).reshape(-1)
    two, B, H, T, D = cv.shape
    qkv = xv.reshape(B, 3, H, D)
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]        # (B, H, D)
    if sequence_lengths is None:
        raise ValueError(
            "sequence_lengths is required: it is the per-row cache write "
            "position — without it every step would overwrite slot 0 and "
            "decode with no history")
    sl = (sequence_lengths.value if isinstance(sequence_lengths, Tensor)
          else jnp.asarray(sequence_lengths)).reshape(-1)
    pos = sl.astype(jnp.int32)                        # write position per row
    if int(np.asarray(sl).max()) >= T:
        # the scatter would silently drop/clamp the write while the causal
        # mask opens the whole cache — plausible-but-wrong logits
        raise ValueError(
            f"masked_multihead_attention: write position "
            f"{int(np.asarray(sl).max())} exceeds the cache "
            f"(T={T}); allocate a longer cache_kv")
    bidx = jnp.arange(B)
    if rotary_tensor is not None and rotary_emb_dims:
        # reference mmha_util.cu.h:46: rotary_emb [2, B, max_seq, 1, D]
        # (cos at [0], sin at [1]); the kernel reads the row's CURRENT
        # position and rotates q and k with the same tables. The default
        # (use_neox_rotary_style=False) is the interleaved pairs-of-two
        # pairing; neox is the half-split pairing.
        rv = rotary_tensor.value if isinstance(rotary_tensor, Tensor) \
            else jnp.asarray(rotary_tensor)
        max_rot = int(rv.shape[2])
        if int(np.asarray(sl).max()) >= max_rot:
            # the gather would silently CLAMP to the last table row and
            # reuse its cos/sin for every later step
            raise ValueError(
                f"masked_multihead_attention: position "
                f"{int(np.asarray(sl).max())} exceeds the rotary table "
                f"(max_seq={max_rot}); build larger rotary_tensor tables")
        cos = rv[0][bidx, pos, 0].astype(q.dtype)[:, None, :]  # (B, 1, D)
        sin = rv[1][bidx, pos, 0].astype(q.dtype)[:, None, :]

        def _rot(t):
            rot = (_rotate_half(t) if use_neox_rotary_style
                   else _rotate_every_two(t))
            return t * cos + rot * sin

        q = _rot(q)
        k = _rot(k)
    ck = cv[0].at[bidx, :, pos].set(k)
    cvv = cv[1].at[bidx, :, pos].set(v)
    t = jnp.arange(T)[None, None, :]
    mask = t <= pos[:, None, None]                    # (B, 1, T)
    logits = jnp.einsum("bhd,bhtd->bht", q, ck) / jnp.sqrt(jnp.asarray(D, jnp.float32)).astype(q.dtype)
    logits = logits.astype(jnp.float32)
    if src_mask is not None:
        # reference kernel: qk += mask (additive, [B, 1, 1, T] broadcast
        # over heads — masked_multihead_attention_kernel.cu:385)
        sm = src_mask.value if isinstance(src_mask, Tensor) \
            else jnp.asarray(src_mask)
        if sm.shape[-1] != T or sm.shape[0] not in (1, B):
            raise ValueError(
                "masked_multihead_attention: src_mask must be "
                f"[B|1, 1, 1, T] with T={T} (the cache length); got "
                f"{tuple(sm.shape)}")
        logits = logits + sm.reshape(sm.shape[0], 1, T).astype(jnp.float32)
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, -1).astype(q.dtype)
    out = jnp.einsum("bht,bhtd->bhd", probs, cvv).reshape(B, H * D)
    return Tensor(out), Tensor(jnp.stack([ck, cvv]))


def block_multihead_attention(
        qkv, key_cache, value_cache, seq_lens_encoder, seq_lens_decoder,
        seq_lens_this_time, padding_offsets=None, cum_offsets=None,
        cu_seqlens_q=None, cu_seqlens_k=None, block_tables=None,
        pre_key_cache=None, pre_value_cache=None, cache_k_quant_scales=None,
        cache_v_quant_scales=None, cache_k_dequant_scales=None,
        cache_v_dequant_scales=None, qkv_out_scale=None, qkv_bias=None,
        out_shift=None, out_smooth=None, max_enc_len_this_time=None,
        max_dec_len_this_time=None, rope_emb=None, mask=None, tgt_mask=None,
        max_seq_len=-1, block_size=64, use_neox_style=False,
        use_dynamic_cachekv_quant=False, quant_round_type=1,
        quant_max_bound=127.0, quant_min_bound=-127.0, out_scale=-1,
        compute_dtype="default", rope_theta=10000.0, name=None):
    """reference block_multihead_attention.py:33 — paged-KV (block-table)
    serving attention. The KV cache is a POOL of fixed-size blocks; each
    sequence's block_tables row lists the blocks it owns. TPU-first: the
    block indirection is jnp gathers/scatters the compiler fuses into the
    attention chain (models/paged_kv.py), not a page-table CUDA kernel.

    Layouts follow the reference contract: ``qkv`` is varlen-packed rows
    [token_num, (q_heads + 2*kv_heads) * head_dim]; ``key_cache``/
    ``value_cache`` are [max_block_num, kv_heads, block_size, head_dim].
    Two phases, per the reference semantics: prefill rows
    (seq_lens_encoder > 0) run causal self-attention over the prompt and
    write it into the blocks; decode rows (seq_lens_this_time == 1 with
    seq_lens_decoder > 0) append one token and attend over the paged
    history. Returns (out, qkv, key_cache, value_cache).

    Quantized-cache / rotary / smooth-quant extras raise (the
    masked_multihead_attention policy: reject, never silently ignore)."""
    from ....framework.core import Tensor
    from ....models import paged_kv as _pk

    for bad_name, bad in (
            ("cache_k_quant_scales", cache_k_quant_scales),
            ("cache_v_quant_scales", cache_v_quant_scales),
            ("cache_k_dequant_scales", cache_k_dequant_scales),
            ("cache_v_dequant_scales", cache_v_dequant_scales),
            ("qkv_out_scale", qkv_out_scale), ("out_shift", out_shift),
            ("out_smooth", out_smooth), ("rope_emb", rope_emb),
            ("pre_key_cache", pre_key_cache),
            ("pre_value_cache", pre_value_cache)):
        if bad is not None:
            raise NotImplementedError(
                f"block_multihead_attention: {bad_name} is not supported by "
                "this build (apply rotary in the model; use "
                "LlamaDecodeEngine(kv_cache_dtype='int8') for quantized KV)")
    if use_dynamic_cachekv_quant or out_scale != -1:
        raise NotImplementedError(
            "block_multihead_attention: cache-KV quantization paths are not "
            "supported here")
    if mask is not None or tgt_mask is not None:
        raise NotImplementedError(
            "block_multihead_attention: custom mask/tgt_mask are not "
            "supported; the paged path computes causal prefill and "
            "full-history decode masking only")
    if block_tables is None:
        raise ValueError("block_tables is required")

    def _v(x):
        return x.value if isinstance(x, Tensor) else jnp.asarray(x)

    qkv_v = _v(qkv)
    kc = _v(key_cache)
    vc = _v(value_cache)
    tables = _v(block_tables).astype(jnp.int32)
    enc = np.asarray(_v(seq_lens_encoder)).reshape(-1)
    dec = np.asarray(_v(seq_lens_decoder)).reshape(-1)
    this = np.asarray(_v(seq_lens_this_time)).reshape(-1)

    n_kv, bs, hd = kc.shape[1], kc.shape[2], kc.shape[3]
    n_q = qkv_v.shape[-1] // hd - 2 * n_kv
    if qkv_bias is not None:
        qkv_v = qkv_v + _v(qkv_bias).reshape(-1)

    # reference layout [nb, kv, bs, d] <-> pool layout [nb, bs, kv, d]
    kc_p = jnp.swapaxes(kc, 1, 2)
    vc_p = jnp.swapaxes(vc, 1, 2)

    is_prefill = enc.sum() > 0
    B = tables.shape[0]
    if is_prefill:
        if dec.sum() != 0:
            raise NotImplementedError(
                "block_multihead_attention: mixed prefill+decode batches "
                "are not supported; split the batch by phase")
        if not (this == enc).all():
            raise NotImplementedError(
                "block_multihead_attention: chunked prefill "
                "(seq_lens_this_time != seq_lens_encoder) is not supported "
                f"(this={this.tolist()}, encoder={enc.tolist()})")
        S = int(enc.max())
        # unpack varlen rows -> padded [B, S, ...] in ONE scatter (a
        # per-sequence .at[b, :L].set loop would copy the whole padded
        # array B times)
        row_b = np.repeat(np.arange(B), this)               # [token_num]
        row_t = np.concatenate([np.arange(int(L)) for L in this])
        rows_all = qkv_v.reshape(-1, n_q + 2 * n_kv, hd)
        q_pad = jnp.zeros((B, S, n_q, hd), qkv_v.dtype).at[
            row_b, row_t].set(rows_all[:, :n_q])
        k_pad = jnp.zeros((B, S, n_kv, hd), qkv_v.dtype).at[
            row_b, row_t].set(rows_all[:, n_q:n_q + n_kv])
        v_pad = jnp.zeros((B, S, n_kv, hd), qkv_v.dtype).at[
            row_b, row_t].set(rows_all[:, n_q + n_kv:])
        lens = jnp.asarray(enc, jnp.int32)
        kc_p, vc_p = _pk.paged_write_prefill(kc_p, vc_p, tables, lens,
                                             k_pad, v_pad)
        # causal self-attention over the prompt (fp32 softmax)
        groups = n_q // n_kv
        qg = q_pad.reshape(B, S, n_kv, groups, hd)
        logits = jnp.einsum("bshgd,bthd->bhgst", qg.astype(jnp.float32),
                            k_pad.astype(jnp.float32)) / np.sqrt(hd)
        t_idx = jnp.arange(S)
        causal = t_idx[None, :] <= t_idx[:, None]           # [S, S]
        valid = t_idx[None, :] < lens[:, None]              # [B, S]
        m = causal[None, None, None] & valid[:, None, None, None, :]
        logits = jnp.where(m, logits, -1e30)
        probs = jax.nn.softmax(logits, -1)
        o = jnp.einsum("bhgst,bthd->bshgd", probs,
                       v_pad.astype(jnp.float32)).astype(qkv_v.dtype)
        # re-pack the padded output to varlen rows with one gather
        out = o.reshape(B, S, n_q * hd)[row_b, row_t]
    else:
        if not (this == 1).all():
            raise NotImplementedError(
                "block_multihead_attention decode phase expects one token "
                "per sequence (seq_lens_this_time == 1)")
        rows = qkv_v.reshape(B, n_q + 2 * n_kv, hd)
        q_new = rows[:, :n_q]
        k_new = rows[:, n_q:n_q + n_kv]
        v_new = rows[:, n_q + n_kv:]
        lens = jnp.asarray(dec, jnp.int32)
        kc_p, vc_p = _pk.paged_write_decode(kc_p, vc_p, tables, lens,
                                            k_new, v_new)
        o = _pk.paged_attention_decode(q_new, kc_p, vc_p, tables, lens)
        out = o.reshape(B, n_q * hd)

    kc_out = jnp.swapaxes(kc_p, 1, 2)
    vc_out = jnp.swapaxes(vc_p, 1, 2)
    # the returned qkv reflects the bias actually used for attention (the
    # reference kernel applies qkv_bias in place)
    return (Tensor(out), Tensor(qkv_v), Tensor(kc_out), Tensor(vc_out))
