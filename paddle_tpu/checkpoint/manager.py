"""Async sharded training checkpoints: digest-verified, atomically
committed, resumable across a CHANGED data-parallel degree.

Reference analog: the reference's ``python/paddle/distributed/checkpoint``
save/load pair (global-offset flat shards + async save queue). This module
is the production rebuild the mesh trainer actually rides
(``distributed/checkpoint`` keeps the API-compatible flat-shard format for
``save_state_dict``/``load_state_dict``):

- **asynchronous** — ``save()`` performs only the device->host copy on the
  calling (step) thread; serialization, fsync and the atomic commit run on
  ONE writer thread with double-buffering (one write in flight + one
  staged), so step N+1 never blocks on step N's write;
- **integrity-checked** — every shard file carries a blake2b digest in the
  manifest; ``restore()`` re-hashes the bytes it reads and raises
  :class:`CheckpointCorrupt` on any mismatch (``restore_latest_valid``
  falls back to the previous committed step);
- **atomic** — shards + manifest are written into a hidden temp directory,
  fsynced, then renamed into place in one ``os.replace`` — a reader never
  sees a torn checkpoint, and a writer killed mid-save leaves only an
  ignored temp directory;
- **elastic** — ZeRO-1 per-replica optimizer-state slices (arXiv
  2004.13336) are saved one shard PER REPLICA ROW; restore gathers the
  rows into the flat logical vector and re-slices onto the CURRENT dp
  degree (``RestoredCheckpoint.zero_sharded``), so a dp=8 save resumes on
  a dp=4 mesh;
- **bounded** — retention keeps the newest ``keep`` committed steps.

Deliberately numpy+stdlib only (no jax, no package-relative hard deps) so
``tools/ckpt_inspect.py`` can path-load it without initializing the
framework; fault-injection and telemetry bindings resolve lazily and
degrade to no-ops outside the package.

See docs/checkpoint.md for the manifest format and the commit protocol.
"""
from __future__ import annotations

import hashlib
import io
import json
import os
import queue
import re
import shutil
import threading
import time

import numpy as np

try:  # the drillable path (package context); inert when path-loaded
    from ..analysis import faultinject as _fi
except ImportError:  # pragma: no cover - tools/ckpt_inspect.py path-load

    class _fi:  # noqa: N801 - module-shaped stub
        @staticmethod
        def fire(point):
            return None

try:  # graftsan witnesses (lock order + data race); inert when path-loaded
    from ..analysis import sanitizers as _san
except ImportError:  # pragma: no cover - tools/ckpt_inspect.py path-load

    class _san:  # noqa: N801 - module-shaped stub
        @staticmethod
        def new_lock(name, factory=threading.Lock):
            return factory()

        @staticmethod
        def race_access(owner, field, write=False):
            return None

import itertools as _itertools

# per-manager tag for the graftsan race witness (owner identity)
_CKPT_SEQ = _itertools.count(1)


__all__ = [
    "CheckpointError", "CheckpointCorrupt", "NoCheckpoint",
    "CheckpointManager", "RestoredCheckpoint",
    "FORMAT", "MANIFEST", "read_manifest", "verify_checkpoint",
    "step_dirs",
]

FORMAT = "paddle_tpu-ckpt-v1"
MANIFEST = "manifest.json"
_STEP_RE = re.compile(r"^step_(\d{8})$")
_TMP_PREFIX = ".tmp-"
_STOP = object()


class CheckpointError(RuntimeError):
    """Base class of every checkpoint failure."""


class CheckpointCorrupt(CheckpointError):
    """A shard's bytes do not match the manifest digest (or the manifest
    itself is unreadable): the checkpoint must not be restored."""

    def __init__(self, message, step=None, shard=""):
        super().__init__(message)
        self.step = step
        self.shard = shard


class NoCheckpoint(CheckpointError):
    """No committed (and digest-valid, when verifying) checkpoint exists."""


def _step_dirname(step):
    return f"step_{int(step):08d}"


def step_dirs(directory):
    """Committed steps under ``directory``: sorted ``[(step, path), ...]``.
    Only ``step_NNNNNNNN`` directories containing a manifest count — temp
    dirs and torn writes are invisible by construction."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        m = _STEP_RE.match(name)
        if not m:
            continue
        path = os.path.join(directory, name)
        if os.path.isfile(os.path.join(path, MANIFEST)):
            out.append((int(m.group(1)), path))
    out.sort()
    return out


def read_manifest(path):
    """Parse one checkpoint directory's manifest; raises
    :class:`CheckpointCorrupt` when it is missing or unparseable."""
    mf = os.path.join(path, MANIFEST)
    try:
        with open(mf) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorrupt(
            f"unreadable manifest {mf!r}: {e}") from e
    if doc.get("format") != FORMAT:
        raise CheckpointCorrupt(
            f"{mf!r}: unknown format {doc.get('format')!r} "
            f"(expected {FORMAT!r})")
    return doc


def _digest(data):
    return hashlib.blake2b(data, digest_size=16).hexdigest()


def _resolve_dtype(name):
    """Logical dtype from its string, including ml_dtypes (bfloat16,
    float8_*) when available."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _storable(arr):
    """npz/npy round-trips only native dtypes; ml_dtypes come back as
    opaque void — store the bit pattern as a same-width uint (the logical
    dtype is recorded in the manifest entry)."""
    if arr.dtype.kind == "V":
        return arr.view(f"u{arr.dtype.itemsize}")
    return arr


def _encode(arr):
    """One shard's on-disk bytes (npy container) + its digest."""
    buf = io.BytesIO()
    np.save(buf, _storable(np.ascontiguousarray(arr)), allow_pickle=False)
    data = buf.getvalue()
    return data, _digest(data)


def _decode(data, dtype_name):
    arr = np.load(io.BytesIO(data), allow_pickle=False)
    logical = _resolve_dtype(dtype_name)
    if arr.dtype != logical:
        arr = arr.view(logical)
    return arr


def _read_shard_verified(path, name, sh, step=None):
    """ONE read of one shard, digest-gated: the returned bytes are
    exactly the bytes that were hashed (no verify-then-reread TOCTOU).
    Shared by ``verify_checkpoint`` (the ``tools/ckpt_inspect.py``
    contract) and ``restore()`` — a checkpoint the tool calls clean is a
    checkpoint the trainer will accept, by construction."""
    fp = os.path.join(path, sh["file"])
    try:
        with open(fp, "rb") as f:
            data = f.read()
    except OSError as e:
        raise CheckpointCorrupt(
            f"missing shard {sh['file']!r} of {name!r} under "
            f"{path!r}: {e}", step=step, shard=sh["file"]) from e
    if _digest(data) != sh["digest"]:
        raise CheckpointCorrupt(
            f"digest mismatch for shard {sh['file']!r} of {name!r} "
            f"under {path!r} (torn or corrupted write)",
            step=step, shard=sh["file"])
    return data


def verify_checkpoint(path):
    """Re-hash every shard of the checkpoint at ``path`` against its
    manifest. Returns the manifest doc; raises :class:`CheckpointCorrupt`
    on the first mismatch or missing shard."""
    doc = read_manifest(path)
    for name, ent in doc["entries"].items():
        for sh in ent["shards"]:
            _read_shard_verified(path, name, sh, step=doc.get("step"))
    return doc


class RestoredCheckpoint:
    """One restored checkpoint: host arrays + the re-shardable ZeRO flats.

    ``arrays``: {name: np.ndarray} for kind="full" entries.
    ``zero``:   {name: flat (numel,) np.ndarray} for kind="zero" entries —
    the logical UNSHARDED optimizer-state vector, gathered from however
    many replica rows the SAVING mesh had.
    """

    def __init__(self, step, path, arrays, zero, meta, manifest):
        self.step = step
        self.path = path
        self.arrays = arrays
        self.zero = zero
        self.meta = meta
        self.manifest = manifest

    def zero_sharded(self, name, dp_degree):
        """Re-slice one ZeRO flat onto ``dp_degree`` replicas: the
        ``(dp_degree, k)`` zero-padded row layout
        ``mesh/zero.init_sharded_state`` produces — restoring onto a
        DIFFERENT dp degree than the save is exactly this re-slice."""
        return reshard_rows(self.zero[name], dp_degree)


def reshard_rows(flat, dp_degree):
    """A logical flat state vector -> the zero-padded ``(dp, k)`` row
    layout of ``mesh/zero.init_sharded_state``. THE one implementation of
    the ZeRO row layout on the host side — ``zero_sharded`` and the
    trainer's full->rows conversion both ride it."""
    flat = np.asarray(flat).reshape(-1)
    dp = int(dp_degree)
    k = -(-flat.shape[0] // dp)
    pad = dp * k - flat.shape[0]
    if pad:
        flat = np.concatenate([flat, np.zeros((pad,), flat.dtype)])
    return flat.reshape(dp, k)


def _telemetry(step, n_shards, total_bytes, seconds, kind):
    """Best-effort counter/gauge/histogram + span per commit/restore;
    inert outside the package or with the monitor off."""
    try:
        from .. import monitor as _m
    except ImportError:  # pragma: no cover - path-loaded
        return
    try:
        if _m._state.on:
            if kind == "save":
                _m.counter("paddle_tpu_ckpt_saves_total").inc()
                _m.gauge("paddle_tpu_ckpt_bytes").set(total_bytes)
                _m.histogram("paddle_tpu_ckpt_save_seconds",
                             buckets=_m.DEFAULT_SECONDS_BUCKETS
                             ).observe(seconds)
        if _m.trace._state.on:
            now = _m.now_ns()
            _m.trace.record_span(
                f"ckpt.{kind}", now - int(seconds * 1e9), now,
                attrs={"step": step, "shards": n_shards,
                       "bytes": total_bytes})
    except Exception:  # noqa: BLE001 - telemetry never fails a checkpoint
        pass


class CheckpointManager:
    """Own one checkpoint directory: async digest-verified saves with an
    atomic-rename commit, bounded retention, and dp-elastic restore.

    ``save(step, arrays, zero=, meta=)`` snapshot contract:

    - ``arrays``: {name: array-like} — full (replicated) tensors: params,
      non-elementwise optimizer state, RNG key data;
    - ``zero``: {name: (value, numel)} — per-replica sharded state in the
      ``(dp, k)`` row layout; ``numel`` is the TRUE element count of the
      logical vector (the rows carry zero padding);
    - ``meta``: any JSON-able payload (loss scale, dataloader cursor,
      dp degree, step provenance).

    The device->host copy happens synchronously inside ``save()`` (so the
    caller may immediately donate its device buffers to the next step);
    everything after — npy encode, digests, fsync, commit, retention —
    runs on the writer thread. ``wait()`` joins outstanding writes and
    re-raises the first failure.
    """

    def __init__(self, directory, keep=3):
        self.directory = str(directory)
        self.keep = int(keep)
        os.makedirs(self.directory, exist_ok=True)
        self._pending = queue.Queue(maxsize=1)  # + 1 in flight = 2 buffers
        self._writer = None
        self._errors = []
        # graftsan-witnessed when sanitizers are enabled at construction
        self._err_lock = _san.new_lock(
            "checkpoint.CheckpointManager._err_lock")
        self._san_tag = f"ckpt{next(_CKPT_SEQ)}"
        self._clean_stale_tmp()

    # -- save ----------------------------------------------------------------
    def save(self, step, arrays, zero=None, meta=None, block=False):
        """Snapshot one step. Host copies happen here (the step thread);
        the write + commit happen on the writer thread unless ``block``.
        Returns ``step``."""
        job = self._prepare(int(step), arrays or {}, zero or {}, meta or {})
        if block:
            self._write(job)
        else:
            self._ensure_writer()
            self._pending.put(job)  # bounded: the double-buffer backstop
        return int(step)

    def _prepare(self, step, arrays, zero, meta):
        """The synchronous half: device->host copies only. The copy must
        be a REAL copy (np.array(copy=True)) — np.asarray of a jax CPU
        array can alias the device buffer zero-copy, and the caller's
        next donated step would overwrite it while the writer thread is
        still encoding, committing corrupted bytes under a valid
        digest."""
        t0 = time.perf_counter()
        host_full = {}
        for name, v in arrays.items():
            a = np.array(v, copy=True)
            host_full[name] = (a, str(a.dtype))
        host_zero = {}
        for name, (v, numel) in zero.items():
            a = np.array(v, copy=True)
            if a.ndim != 2:
                raise ValueError(
                    f"zero entry {name!r} must be (dp, k)-shaped, "
                    f"got {a.shape}")
            host_zero[name] = (a, str(a.dtype), int(numel))
        return {"step": step, "full": host_full, "zero": host_zero,
                "meta": meta, "t0": t0}

    def _ensure_writer(self):
        if self._writer is None or not self._writer.is_alive():
            self._writer = threading.Thread(
                target=self._writer_loop, daemon=True,
                name="ckpt-writer")
            self._writer.start()

    def _writer_loop(self):
        while True:
            job = self._pending.get()
            if job is _STOP:
                self._pending.task_done()
                return
            try:
                self._write(job)
            except BaseException as e:  # surfaced by wait()
                with self._err_lock:
                    _san.race_access(self._san_tag, "_errors",
                                     write=True)
                    self._errors.append(e)
            finally:
                self._pending.task_done()

    def _write(self, job):
        """The asynchronous half: encode + digest + fsync + atomic commit
        + retention. ``ckpt.write`` fires HERE — action=raise leaves only
        the ignored temp directory (the torn-write drill), action=flag
        corrupts one shard's bytes AFTER its digest was recorded (the
        restore-must-reject drill)."""
        step = job["step"]
        final = os.path.join(self.directory, _step_dirname(step))
        if os.path.isfile(os.path.join(final, MANIFEST)):
            # already committed: a deterministic replay re-saves the
            # same step with the same bytes — keep the existing commit.
            # Deleting a good commit to rewrite it would open a crash
            # window that can DESTROY it (and a corrupted existing
            # commit is already handled by restore's fallback).
            return
        tmp = os.path.join(
            self.directory,
            f"{_TMP_PREFIX}{_step_dirname(step)}-{os.getpid()}")
        if os.path.isdir(tmp):
            shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        spec = _fi.fire("ckpt.write")
        corrupt = spec is not None and spec.action == "flag"
        entries = {}
        n, total = 0, 0
        for name, (arr, dtype_name) in job["full"].items():
            data, dig = _encode(arr)
            if corrupt:
                # flip one payload byte after digesting: the bytes on
                # disk no longer match the manifest — exactly what a torn
                # device write / bit rot looks like to restore()
                data = data[:-1] + bytes([data[-1] ^ 0xFF])
                corrupt = False
            fname = f"s{n:05d}.npy"
            n += 1
            total += len(data)
            self._fsync_write(os.path.join(tmp, fname), data)
            entries[name] = {
                "kind": "full", "dtype": dtype_name,
                "shape": list(arr.shape),
                "shards": [{"file": fname, "digest": dig,
                            "bytes": len(data)}],
            }
        for name, (arr, dtype_name, numel) in job["zero"].items():
            dp, k = arr.shape
            shards = []
            for row in range(dp):
                data, dig = _encode(arr[row])
                if corrupt:
                    data = data[:-1] + bytes([data[-1] ^ 0xFF])
                    corrupt = False
                fname = f"s{n:05d}.npy"
                n += 1
                total += len(data)
                self._fsync_write(os.path.join(tmp, fname), data)
                shards.append({"file": fname, "digest": dig,
                               "bytes": len(data), "row": row})
            entries[name] = {
                "kind": "zero", "dtype": dtype_name, "numel": numel,
                "dp": dp, "slice_len": k, "shards": shards,
            }
        manifest = {
            "format": FORMAT, "step": step,
            "saved_unix": time.time(),
            "meta": job["meta"], "entries": entries,
            "total_bytes": total, "n_shards": n,
        }
        self._fsync_write(
            os.path.join(tmp, MANIFEST),
            json.dumps(manifest, indent=1, sort_keys=True).encode())
        if os.path.isdir(final):
            # a manifest-less leftover (torn write) is not a commit:
            # clearing it loses nothing
            shutil.rmtree(final, ignore_errors=True)
        os.replace(tmp, final)  # THE commit: readers see all-or-nothing
        self._fsync_dir(self.directory)
        self._prune()
        _telemetry(step, n, total, time.perf_counter() - job["t0"], "save")

    @staticmethod
    def _fsync_write(path, data):
        with open(path, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())

    @staticmethod
    def _fsync_dir(path):
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:  # pragma: no cover - platform without dir fds
            return
        try:
            os.fsync(fd)
        except OSError:  # pragma: no cover
            pass
        finally:
            os.close(fd)

    def _prune(self):
        committed = step_dirs(self.directory)
        for _, path in committed[:max(0, len(committed) - self.keep)]:
            shutil.rmtree(path, ignore_errors=True)

    def _clean_stale_tmp(self):
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            if name.startswith(_TMP_PREFIX):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    def clear(self):
        """Delete EVERY committed step (and stale temp dirs) — the fresh-
        run reset: a trainer starting with ``resume=False`` must not let
        a later recovery restore a PRIOR run's state from the same
        directory. Flushes in-flight writes first."""
        self.wait()
        for _, path in step_dirs(self.directory):
            shutil.rmtree(path, ignore_errors=True)
        self._clean_stale_tmp()

    def wait(self):
        """Join outstanding async writes; re-raise the first failure (a
        silently lost checkpoint would otherwise only surface at restore
        time)."""
        self._pending.join()
        with self._err_lock:
            _san.race_access(self._san_tag, "_errors", write=True)
            errors, self._errors = self._errors, []
        if errors:
            raise errors[0]

    def close(self):
        """Flush and stop the writer thread."""
        if self._writer is not None and self._writer.is_alive():
            self._pending.put(_STOP)
            self._writer.join(timeout=30)
        self._writer = None

    def status(self):
        """The manager's graftscope /statusz section (embedded in the
        trainer's): commit state read from the directory listing —
        numpy+stdlib only, like everything in this module."""
        steps = self.steps()
        return {
            "directory": str(self.directory),
            "committed": len(steps),
            "steps": steps[-5:],
            "latest_step": steps[-1] if steps else None,
            "keep": self.keep,
            "writer_alive": bool(self._writer is not None
                                 and self._writer.is_alive()),
        }

    # -- restore -------------------------------------------------------------
    def steps(self):
        """Committed step numbers, ascending."""
        return [s for s, _ in step_dirs(self.directory)]

    def latest_step(self):
        steps = self.steps()
        return steps[-1] if steps else None

    def restore(self, step=None):
        """Load ONE committed checkpoint (default: the newest), verifying
        every shard digest. Raises :class:`CheckpointCorrupt` on any
        mismatch and :class:`NoCheckpoint` when nothing is committed."""
        _fi.fire("ckpt.restore")
        committed = dict(step_dirs(self.directory))
        if step is None:
            if not committed:
                raise NoCheckpoint(
                    f"no committed checkpoint under {self.directory!r}")
            step = max(committed)
        elif int(step) not in committed:
            raise NoCheckpoint(
                f"step {step} is not committed under {self.directory!r} "
                f"(have: {sorted(committed)})")
        t0 = time.perf_counter()
        path = committed[int(step)]
        doc = read_manifest(path)
        arrays, zero = {}, {}
        for name, ent in doc["entries"].items():
            if ent["kind"] == "full":
                arr = _decode(
                    _read_shard_verified(path, name, ent["shards"][0],
                                         step=doc.get("step")),
                    ent["dtype"])
                arrays[name] = arr.reshape(tuple(ent["shape"]))
            else:
                rows = [
                    _decode(_read_shard_verified(path, name, sh,
                                                 step=doc.get("step")),
                            ent["dtype"])
                    for sh in sorted(ent["shards"],
                                     key=lambda s: s["row"])]
                flat = np.concatenate([r.reshape(-1) for r in rows])
                zero[name] = flat[:int(ent["numel"])]
        rc = RestoredCheckpoint(int(step), path, arrays, zero,
                                doc.get("meta", {}), doc)
        _telemetry(int(step), doc.get("n_shards", 0),
                   doc.get("total_bytes", 0),
                   time.perf_counter() - t0, "restore")
        return rc

    def restore_latest_valid(self):
        """Newest committed checkpoint that passes digest verification —
        a torn or corrupted newest step FALLS BACK to the previous commit
        instead of failing the recovery. Raises :class:`NoCheckpoint`
        when none survives (the per-step failures are attached as
        ``.failures``)."""
        failures = []
        for step in sorted(self.steps(), reverse=True):
            try:
                return self.restore(step)
            except CheckpointCorrupt as e:
                failures.append((step, str(e)))
        err = NoCheckpoint(
            f"no digest-valid committed checkpoint under "
            f"{self.directory!r}"
            + (f"; rejected: {failures}" if failures else ""))
        err.failures = failures
        raise err
