from . import mp_ops  # noqa: F401
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from .random import (  # noqa: F401
    RNGStatesTracker,
    dropout,
    get_rng_state_tracker,
    model_parallel_random_seed,
)
