"""__getitem__ / __setitem__ with paddle indexing semantics.

Reference analog: the eager slice path in paddle/fluid/pybind/slice_utils.h +
python/paddle/base/variable_index.py. Tensor indices become jnp advanced indexing; boolean
mask indexing is dynamic-shape and therefore eager-only (same constraint XLA imposes).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..framework.core import Tensor
from ._apply import defop


def _norm_index(idx):
    """Convert a user index into (static_parts, tensor_parts) for the op call."""
    if not isinstance(idx, tuple):
        idx = (idx,)
    out = []
    for it in idx:
        if isinstance(it, Tensor):
            if np.dtype(it.dtype) == np.bool_:
                # boolean mask: dynamic shape — materialize indices eagerly
                out.append(Tensor(jnp.asarray(np.nonzero(it.numpy()))[0])
                           if it.ndim == 1 else it)
            else:
                out.append(it)
        elif isinstance(it, (list, np.ndarray)):
            arr = np.asarray(it)
            if arr.dtype == np.bool_:
                out.append(Tensor(jnp.asarray(np.nonzero(arr)[0])))
            else:
                out.append(Tensor(jnp.asarray(arr)))
        else:
            out.append(it)
    return tuple(out)


@defop("getitem")
def _getitem(x, idx):
    return x[idx]


def getitem(x, idx):
    idx = _norm_index(idx)
    # bool Tensor mask: dynamic-shape selection, eager-only (numpy semantics: a k-dim mask
    # selects cells over the first k dims, result [n_true, *trailing_dims])
    has_bool = any(isinstance(i, Tensor) and np.dtype(i.dtype) == np.bool_ for i in idx)
    if has_bool:
        if len(idx) == 1:
            mask = idx[0]
            from .manipulation import gather, masked_select, reshape

            m = np.asarray(mask.numpy())  # graftlint: disable=GL002 — bool-mask indexing is eager-only by contract (dynamic shape)
            if m.ndim == x.ndim:
                return masked_select(x, mask)
            k = m.ndim
            lead = int(np.prod(x.value.shape[:k]))
            flat = reshape(x, [lead] + list(x.value.shape[k:]))
            sel = Tensor(jnp.asarray(np.nonzero(m.reshape(-1))[0]))
            return gather(flat, sel, axis=0)
        raise NotImplementedError("mixed boolean advanced indexing")
    return _getitem(x, idx=idx)


@defop("setitem")
def _setitem(x, idx, value):
    return x.at[idx].set(jnp.asarray(value, x.dtype) if not hasattr(value, "dtype") else
                         value.astype(x.dtype))


def setitem_(x, idx, value):
    """In-place x[idx] = value with autograd support (functional under the hood)."""
    idx = _norm_index(idx)
    has_bool = any(isinstance(i, Tensor) and np.dtype(i.dtype) == np.bool_ for i in idx)
    if has_bool and len(idx) == 1:
        from .manipulation import _masked_fill, _where

        mask = idx[0]
        if isinstance(value, Tensor):
            # route through the op layer so autograd flows into both x and value
            v = value.astype(x.dtype) if np.dtype(value.dtype) != x.dtype else value
            out = _where(mask, v, x)
        else:
            out = _masked_fill(x, mask, value)
    else:
        if not isinstance(value, Tensor):
            value = Tensor(jnp.asarray(value))
        out = _setitem(x, idx, value)
    x._replace_value(out.value)
    x._grad_node, x._out_index = out._grad_node, out._out_index
    x.stop_gradient = x.stop_gradient and out.stop_gradient
    return x
