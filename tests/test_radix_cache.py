"""Radix prefix cache (models/radix_cache.py): the content index over the
paged KV pool — chain-digest matching, LRU eviction under pool pressure,
and the digest-collision fallback (verified tokens, never another
prompt's KV)."""
import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu.models import radix_cache
from paddle_tpu.models.paged_kv import PagedKVCache
from paddle_tpu.models.radix_cache import PrefixCache


def _pager(batch=4, blocks=32, bs=4):
    return PagedKVCache(num_layers=1, num_blocks=blocks, block_size=bs,
                        kv_heads=1, head_dim=2, batch=batch,
                        max_blocks_per_seq=8, dtype=jnp.float32)


def _written(pager, row, n_tokens):
    """Simulate a prefilled row: grant blocks for n_tokens."""
    need = np.zeros(pager.batch, np.int64)
    need[row] = n_tokens
    pager.ensure_capacity(need)
    return pager._tables_np[row]


class TestMatchRegister:
    def test_chain_match_is_longest_prefix(self):
        pager = _pager()
        pc = PrefixCache(pager)
        prompt = np.arange(10, dtype=np.int32)          # 2 full blocks @ 4
        row = _written(pager, 0, 10)
        assert pc.register(prompt, 10, row) == 2
        # identical prompt: both full blocks match
        blocks, n = pc.match(prompt)
        assert n == 8 and len(blocks) == 2
        assert blocks == [int(row[0]), int(row[1])]
        # diverges inside block 2: only block 1 matches
        other = prompt.copy()
        other[6] = 99
        blocks, n = pc.match(other)
        assert n == 4 and blocks == [int(row[0])]
        # diverges in block 1: no match
        other = prompt.copy()
        other[0] = 99
        assert pc.match(other) == ([], 0)

    def test_register_only_full_written_blocks(self):
        pager = _pager()
        pc = PrefixCache(pager)
        prompt = np.arange(10, dtype=np.int32)
        row = _written(pager, 0, 10)
        # only 5 tokens written so far -> one full block indexable
        assert pc.register(prompt, 5, row) == 1
        assert pc.register(prompt, 10, row) == 1        # the second one
        assert pc.register(prompt, 10, row) == 0        # idempotent

    def test_registration_pins_blocks(self):
        pager = _pager()
        pc = PrefixCache(pager)
        prompt = np.arange(8, dtype=np.int32)
        row = _written(pager, 0, 8)
        pc.register(prompt, 8, row)
        blocks = [int(row[0]), int(row[1])]
        pager.free_sequence(0)                          # owner gone
        assert all(pager._refs[b] == 1 for b in blocks)
        assert pc.match(prompt)[0] == blocks            # still servable


class TestCollisions:
    def test_digest_collision_degrades_to_miss(self, monkeypatch):
        """With the digest function maliciously constant, every lookup
        collides — the token comparison must turn that into a miss rather
        than serve another prompt's KV."""
        monkeypatch.setattr(radix_cache, "_digest",
                            lambda parent, tokens: b"same")
        pager = _pager()
        pc = PrefixCache(pager)
        p1 = np.arange(4, dtype=np.int32)
        p2 = np.arange(4, dtype=np.int32) + 50
        row = _written(pager, 0, 4)
        pc.register(p1, 4, row)
        assert pc.match(p2) == ([], 0)
        assert pc.collisions == 1
        assert pc.match(p1)[1] == 4                     # the real owner hits

    def test_collision_on_register_never_double_indexes(self, monkeypatch):
        monkeypatch.setattr(radix_cache, "_digest",
                            lambda parent, tokens: b"same")
        pager = _pager()
        pc = PrefixCache(pager)
        row0 = _written(pager, 0, 4)
        row1 = _written(pager, 1, 4)
        pc.register(np.arange(4, dtype=np.int32), 4, row0)
        pc.register(np.arange(4, dtype=np.int32) + 9, 4, row1)
        assert len(pc) == 1                             # second one skipped
        assert pager._refs[int(row1[0])] == 1           # and NOT pinned


class TestEviction:
    def test_lru_evicts_cache_only_blocks(self):
        pager = _pager(batch=2, blocks=16)
        pc = PrefixCache(pager)
        old = np.arange(4, dtype=np.int32)
        new = np.arange(4, dtype=np.int32) + 10
        row0 = _written(pager, 0, 4)
        pc.register(old, 4, row0)
        old_blk = int(row0[0])
        pager.free_sequence(0)
        row1 = _written(pager, 0, 4)
        pc.register(new, 4, row1)
        pc.match(new)                                   # touches: new is MRU
        freed = pc.evict(1)
        assert freed == 1 and pc.evicted == 1
        assert old_blk in pager._free                   # LRU entry went
        assert pc.match(old) == ([], 0)
        assert pc.match(new)[1] == 4

    def test_evict_takes_leaves_before_roots(self):
        """Chains shed from the tail: evicting one block of a 2-block
        chain must take the LEAF, keeping the 1-block prefix matchable —
        a beheaded root would strand its pinned descendant forever."""
        pager = _pager(batch=2, blocks=32)
        pc = PrefixCache(pager)
        prompt = np.arange(8, dtype=np.int32)           # 2-block chain
        row = _written(pager, 0, 8)
        pc.register(prompt, 8, row)
        root_blk, leaf_blk = int(row[0]), int(row[1])
        pager.free_sequence(0)
        assert pc.evict(1) == 1
        assert leaf_blk in pager._free and root_blk not in pager._free
        blocks, n = pc.match(prompt)                    # shorter prefix lives
        assert n == 4 and blocks == [root_blk]
        assert pc.evict(1) == 1 and root_blk in pager._free

    def test_evict_frees_whole_chain_tail_to_root(self):
        pager = _pager(batch=2, blocks=32)
        pc = PrefixCache(pager)
        prompt = np.arange(12, dtype=np.int32)          # 3-block chain
        row = _written(pager, 0, 12)
        pc.register(prompt, 12, row)
        pager.free_sequence(0)
        assert pc.evict(8) == 3                         # multi-sweep
        assert len(pc) == 0

    def test_evict_skips_live_blocks(self):
        pager = _pager(batch=2)
        pc = PrefixCache(pager)
        prompt = np.arange(4, dtype=np.int32)
        row = _written(pager, 0, 4)
        pc.register(prompt, 4, row)                     # refs: row + pin = 2
        assert pc.evict(4) == 0                         # mapped: untouchable
        pager.free_sequence(0)
        assert pc.evict(4) == 1                         # now reclaimable

    def test_capacity_bound_evicts_on_register(self):
        pager = _pager(batch=4, blocks=64, bs=4)
        pc = PrefixCache(pager, capacity_blocks=2)
        for i in range(4):
            prompt = (np.arange(4, dtype=np.int32) + 17 * i)
            row = _written(pager, i % 4, 4)
            pc.register(prompt, 4, row)
            pager.free_sequence(i % 4)
        assert len(pc) <= 2

    def test_clear_releases_every_pin(self):
        pager = _pager()
        pc = PrefixCache(pager)
        row = _written(pager, 0, 8)
        pc.register(np.arange(8, dtype=np.int32), 8, row)
        pager.free_sequence(0)
        free_before = len(pager._free)
        pc.clear()
        assert len(pc) == 0
        assert len(pager._free) == free_before + 2
        assert (pager._refs == 0).sum() == pager.num_blocks - 1 + 1


def test_reregistered_parent_reconnects_orphaned_children():
    """Evicting a parent strands its child entry; re-registering the same
    prefix (same content digest) makes the child reachable again — the
    content-addressed chain heals itself."""
    pager = _pager(batch=2, blocks=32)
    pc = PrefixCache(pager)
    prompt = np.arange(8, dtype=np.int32)               # blocks P0, P1
    row = _written(pager, 0, 8)
    pc.register(prompt, 8, row)
    child_blk = int(row[1])
    pager.free_sequence(0)
    pc.match(np.concatenate([prompt[4:], prompt[:4]]))  # parent-less probe
    # evict ONLY the parent (it is LRU: match() above touched neither)
    parent_digest = next(iter(pc._entries))
    parent_blk = pc._entries[parent_digest].block
    pager.release_blocks([parent_blk])
    del pc._by_block[parent_blk]
    del pc._entries[parent_digest]
    assert pc.match(prompt) == ([], 0)                  # chain broken
    row1 = _written(pager, 1, 4)
    pc.register(prompt[:4], 4, row1)                    # parent reborn
    blocks, n = pc.match(prompt)
    assert n == 8 and blocks[1] == child_blk            # child reattached


class TestContinueTokens:
    """The speculative drafter's radix source (ISSUE 7): a cached chain
    proposes the tokens it stores PAST the current context — verified by
    token comparison, walked block to block, None on any mismatch."""

    def test_walks_down_the_chain(self):
        pager = _pager(blocks=32, bs=4)
        pc = PrefixCache(pager)
        prompt = np.arange(12, dtype=np.int32)          # 3 full blocks @ 4
        row = _written(pager, 0, 12)
        assert pc.register(prompt, 12, row) == 3
        # context = first 6 tokens: 1 full block + partial [4, 5]
        parent = next(d for d, e in pc._entries.items()
                      if e.tokens[0] == 0)
        got = pc.continue_tokens(parent, [4, 5], 10)
        np.testing.assert_array_equal(got, [6, 7, 8, 9, 10, 11])
        # k caps the proposal
        np.testing.assert_array_equal(
            pc.continue_tokens(parent, [4, 5], 3), [6, 7, 8])

    def test_block_aligned_context_continues_from_child(self):
        pager = _pager(blocks=32, bs=4)
        pc = PrefixCache(pager)
        prompt = np.arange(8, dtype=np.int32)
        row = _written(pager, 0, 8)
        pc.register(prompt, 8, row)
        parent = next(d for d, e in pc._entries.items()
                      if e.tokens[0] == 0)
        got = pc.continue_tokens(parent, [], 8)
        np.testing.assert_array_equal(got, [4, 5, 6, 7])

    def test_mismatched_partial_returns_none(self):
        pager = _pager(blocks=32, bs=4)
        pc = PrefixCache(pager)
        prompt = np.arange(8, dtype=np.int32)
        row = _written(pager, 0, 8)
        pc.register(prompt, 8, row)
        parent = next(d for d, e in pc._entries.items()
                      if e.tokens[0] == 0)
        assert pc.continue_tokens(parent, [99], 8) is None      # diverges
        assert pc.continue_tokens(b"nope", [4, 5], 8) is None   # no chain
        # context already past everything the chain stores
        assert pc.continue_tokens(parent, [4, 5, 6, 7], 8) is None

    def test_newest_matching_child_wins(self):
        """Two children extend the same parent with different partials
        (the same prompt re-decoded after divergence): the proposal must
        come from a child whose stored tokens MATCH the context, not
        whichever registered first."""
        pager = _pager(batch=2, blocks=32, bs=4)
        pc = PrefixCache(pager)
        a = np.array([0, 1, 2, 3, 4, 5, 6, 7], np.int32)
        b = np.array([0, 1, 2, 3, 9, 8, 7, 6], np.int32)
        row0 = _written(pager, 0, 8)
        row1 = _written(pager, 1, 8)
        pc.register(a, 8, row0)
        pc.register(b, 8, row1)
        parent = next(d for d, e in pc._entries.items()
                      if list(e.tokens) == [0, 1, 2, 3])
        np.testing.assert_array_equal(
            pc.continue_tokens(parent, [4, 5], 4), [6, 7])
        np.testing.assert_array_equal(
            pc.continue_tokens(parent, [9, 8], 4), [7, 6])

    def test_eviction_unlinks_child_edges(self):
        pager = _pager(blocks=32, bs=4)
        pc = PrefixCache(pager)
        prompt = np.arange(8, dtype=np.int32)
        row = _written(pager, 0, 8)
        pc.register(prompt, 8, row)
        pager.free_sequence(0)
        assert pc.evict(2, pools=[(pager.k[0], pager.v[0])]) == 2
        assert pc._children == {}
        parent = b""
        assert pc.continue_tokens(parent, [0, 1], 8) is None

    def test_reborn_parent_reconnects_continue_tokens(self):
        """Downward edges survive their entry's eviction (digests are
        content-addressed): evicting a chain's root and re-registering
        the same prefix must bring continue_tokens back for the still-
        cached child — the drafter's radix source heals exactly like
        match() does."""
        pager = _pager(batch=2, blocks=32, bs=4)
        pc = PrefixCache(pager)
        prompt = np.arange(8, dtype=np.int32)           # blocks P0, P1
        row = _written(pager, 0, 8)
        pc.register(prompt, 8, row)
        parent_digest = next(d for d, e in pc._entries.items()
                             if e.tokens[0] == 0)
        pager.free_sequence(0)
        # evict ONLY the root (P1 stays cached, now orphaned)
        pc._drop(pc._entries[parent_digest])
        assert pc.continue_tokens(b"", [0, 1], 8) is None
        # the orphan's edge is still reachable under the DEAD digest —
        # content addressing makes that correct, not stale
        np.testing.assert_array_equal(
            pc.continue_tokens(parent_digest, [4, 5], 8), [6, 7])
        row1 = _written(pager, 1, 4)
        pc.register(prompt[:4], 4, row1)                # root reborn
        np.testing.assert_array_equal(
            pc.continue_tokens(b"", [0, 1], 8), [2, 3, 4, 5, 6, 7])
