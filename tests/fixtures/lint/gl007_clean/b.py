"""GL007 clean sample, file 2: B_LOCK is only ever acquired after A_LOCK
(via a.step) or alone — no reverse edge exists."""
import threading

B_LOCK = threading.Lock()


def flush(sink):
    with B_LOCK:
        sink.push(4)


def drain(sink):
    with B_LOCK:
        sink.push(5)
