"""graftlint: framework-aware static analysis for paddle_tpu.

An AST-based rule engine that walks the source tree WITHOUT importing it
and reports framework-specific hazards the test suite cannot see:

- GL001 trace-impurity — impure host calls inside to_static/defop/jit
  bodies bake one traced value into the compiled program;
- GL002 host-sync-in-hot-path — hidden device→host round-trips in the
  dispatch and serving/decode hot paths;
- GL003 registry-consistency — defop registrations, AMP categories, and
  docs/ops.md stay in agreement;
- GL004 lock-discipline — no device dispatch or blocking wait inside a
  lock body;
- GL005 metric-name-contract — every registered metric is declared in
  monitor/catalog.py and follows the naming convention (the engine form
  of tools/check_metric_names.py).

Run it as ``python -m paddle_tpu.analysis`` (or, without importing the
framework at all, ``python tools/lint_framework.py``). Inline
suppressions (``# graftlint: disable=GL002``), a checked-in baseline for
grandfathered findings, and a tier-1 test keep the tree clean going
forward; see docs/static_analysis.md.

This package intentionally uses only the standard library — no jax, no
framework imports — so ``tools/lint_framework.py`` can load it by file
path in any venv.
"""
from __future__ import annotations

import os

from .core import (Finding, Project, load_baseline, partition, render_json,
                   render_text, run, write_baseline)
from .rules import ALL_RULES, RULES_BY_ID, Rule

__all__ = ["Finding", "Project", "Rule", "ALL_RULES", "RULES_BY_ID",
           "run", "partition", "load_baseline", "write_baseline",
           "render_text", "render_json", "analyze", "main",
           "DEFAULT_BASELINE", "repo_root"]

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


def repo_root():
    """The tree this installation would lint by default (two levels above
    this package: <root>/paddle_tpu/analysis)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def analyze(root=None, rules=None, baseline_path=None, include=("paddle_tpu",)):
    """One-call API: (new, baselined, suppressed, rules) over a tree."""
    project = Project(root or repo_root(), include=include)
    rules = list(rules if rules is not None else ALL_RULES)
    findings = run(project, rules)
    baseline = load_baseline(
        DEFAULT_BASELINE if baseline_path is None else baseline_path)
    new, base, supp = partition(project, findings, baseline)
    return new, base, supp, rules


def main(argv=None):
    """CLI: exit 0 when clean (baseline applied), 1 on new findings."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="graftlint: framework-aware static analysis "
                    "(GL001–GL005)")
    ap.add_argument("--root", default=None,
                    help="tree to analyze (default: this repo)")
    ap.add_argument("--include", default="paddle_tpu",
                    help="comma-separated subdirs of root to scan "
                         "(default: paddle_tpu; pass '' for the whole "
                         "root — fixture trees)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: the checked-in "
                         "paddle_tpu/analysis/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report grandfathered findings too")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "and exit 0")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.id}\t{r.name}\t{r.rationale}")
        return 0

    if args.rules:
        try:
            rules = [RULES_BY_ID[rid.strip()]
                     for rid in args.rules.split(",") if rid.strip()]
        except KeyError as e:
            print(f"graftlint: unknown rule {e.args[0]!r} "
                  f"(known: {', '.join(sorted(RULES_BY_ID))})",
                  file=sys.stderr)
            return 2
    else:
        rules = list(ALL_RULES)

    include = tuple(i for i in args.include.split(",") if i) or None
    baseline_path = args.baseline or DEFAULT_BASELINE
    if args.no_baseline:
        baseline_path = ""
    new, base, supp, rules = analyze(
        root=args.root, rules=rules, baseline_path=baseline_path,
        include=include)

    if args.update_baseline:
        path = args.baseline or DEFAULT_BASELINE
        write_baseline(path, new + base)
        print(f"graftlint: baseline updated "
              f"({len(new + base)} fingerprints) -> {path}")
        return 0

    if args.json:
        print(render_json(new, base, supp, rules))
    else:
        print(render_text(new, base, supp, rules))
    return 1 if new else 0
