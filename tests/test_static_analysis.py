"""graftlint (paddle_tpu/analysis): the framework-aware static-analysis
gate, tier-1.

Four contracts under test:

1. the shipped tree is CLEAN — zero findings over paddle_tpu/ with an
   EMPTY baseline (the same invariant ``python -m paddle_tpu.analysis``
   enforces with its exit code) — including the interprocedural engine;
2. every rule GL001–GL011 fires on its dirty fixture and stays silent on
   its clean one (tests/fixtures/lint/ mini-trees), the
   interprocedural upgrades of GL001/GL002/GL004 flag helper-hidden
   hazards at the call site with the propagation chain, and the GL010/
   GL011 lockset analysis (thread-root inference, entry-lockset
   fixpoint, guarded_by annotations, thread-entry chains) behaves;
3. the silencing machinery works: inline + file-level suppressions
   (which also STOP propagation through the call graph), and the
   baseline round-trip (grandfather findings, rerun clean);
4. the CLI surfaces (tools/lint_framework.py without importing the
   framework, the tools/check_metric_names.py exit-code contract,
   ``--explain GLxxx`` chain rendering, and the
   tools/run_static_checks.py aggregator incl. the check_lock_order /
   check_recompile_hazards rows) behave as subprocesses.
"""
import json
import os
import subprocess
import sys

import pytest

from paddle_tpu import analysis

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(ROOT, "tests", "fixtures", "lint")


def _analyze(subdir, rules=None):
    """(new, baselined, suppressed) over one fixture mini-tree, no
    baseline."""
    rule_objs = None
    if rules is not None:
        rule_objs = [analysis.RULES_BY_ID[r] for r in rules]
    new, base, supp, _ = analysis.analyze(
        root=os.path.join(FIX, subdir), rules=rule_objs,
        baseline_path="", include=None)
    return new, base, supp


class TestShippedTree:
    def test_tree_is_clean_with_shipped_baseline(self):
        """The acceptance invariant: `python -m paddle_tpu.analysis`
        exits 0 on this tree. Any new finding must be fixed, suppressed
        with a rationale, or (exceptionally) baselined."""
        new, _base, _supp, rules = analysis.analyze()
        assert len(rules) == 11
        assert not new, "new graftlint findings:\n" + "\n".join(
            repr(f) for f in new)

    def test_baseline_is_empty(self):
        """PR 4 burned the grandfathered debt to zero; the baseline must
        STAY empty — fix or suppress-with-rationale, never grandfather."""
        fps = analysis.load_baseline(analysis.DEFAULT_BASELINE)
        assert len(fps) == 0


class TestRuleFixtures:
    """One dirty + one clean sample per rule; dirty must fire exactly the
    rule under test, clean must be silent."""

    @pytest.mark.parametrize("subdir,rule,expect", [
        # gl001 includes a call-form jax.jit(run) case; gl002 includes a
        # sync in the unselected branch of an isinstance guard; gl007 has
        # one intra-file pairwise inversion + one cross-file cycle only
        # the call graph sees; gl008 covers all three hazard shapes
        ("gl001", "GL001", 4),
        ("gl002", "GL002", 5),
        ("gl003_dirty", "GL003", 7),
        ("gl004", "GL004", 3),
        ("gl005_dirty", "GL005", 4),
        ("gl006_dirty", "GL006", 4),
        ("gl007_dirty", "GL007", 2),
        ("gl008_dirty", "GL008", 6),
        # gl009 covers decorator, to_static and call-form captures;
        # its clean.py shadows the global via a parameter
        ("gl009_dirty", "GL009", 3),
        # gl010 pins the two PR 15 fleet races as fixture shapes: the
        # ledger insert landing AFTER the spawned worker can abort
        # (submit→rid2att gap), and a finished request re-entering the
        # ledger from a lock-free resubmit loop
        ("gl010_dirty", "GL010", 2),
        # gl011 covers both halves: split-lock guarding (no common
        # lock across write sites) and a deque escaping its lock
        # region via a bare return
        ("gl011_dirty", "GL011", 2),
    ])
    def test_dirty_fixture_fires(self, subdir, rule, expect):
        new, _, _ = _analyze(subdir)
        assert {f.rule for f in new} == {rule}
        assert len(new) == expect
        # flat fixtures keep violations in dirty.py; clean.py is silent
        for f in new:
            assert "clean" not in f.path

    @pytest.mark.parametrize("subdir", ["gl003_clean", "gl005_clean",
                                        "gl006_clean", "gl007_clean",
                                        "gl008_clean", "gl009_clean",
                                        "gl010_clean", "gl011_clean",
                                        "interproc_clean"])
    def test_clean_trees_are_silent(self, subdir):
        new, _, _ = _analyze(subdir)
        assert new == []

    def test_findings_carry_location_and_scope(self):
        new, _, _ = _analyze("gl001")
        f = next(x for x in new if "time.time" in x.message)
        assert f.path == "dirty.py" and f.line > 0
        assert f.scope == "stamped_forward"
        assert f.rule == "GL001"
        d = f.as_dict()
        assert d["message"] and d["line"] == f.line

    def test_rule_selection(self):
        new, _, _ = _analyze("gl001", rules=["GL002"])
        assert new == []


class TestInterprocedural:
    """The call-graph upgrade: helper-hidden hazards flagged at the call
    site, with the propagation chain, across module boundaries."""

    def test_dirty_tree_fires_all_three_rules(self):
        new, _, _ = _analyze("interproc_dirty")
        by_rule = {}
        for f in new:
            by_rule.setdefault(f.rule, []).append(f)
        assert {r: len(v) for r, v in by_rule.items()} == {
            "GL001": 1, "GL002": 1, "GL004": 1}
        # the finding sits at the CALL SITE, not in the helper
        assert by_rule["GL001"][0].path == "traced.py"
        assert by_rule["GL002"][0].path == "paddle_tpu/ops/hot.py"
        assert by_rule["GL004"][0].path == "locks.py"

    def test_chain_names_in_message_and_hops_in_chain(self):
        """The message carries the qualname chain (line-number-free, so
        fingerprints survive drift); the chain field carries file:line
        hops for --explain."""
        new, _, _ = _analyze("interproc_dirty")
        gl001 = next(f for f in new if f.rule == "GL001")
        assert "deep_stamp -> stamp -> time.time()" in gl001.message
        assert "helpers.py:" not in gl001.message  # line-free fingerprint
        assert gl001.chain  # hops present, with file:line detail
        assert any("helpers.py:" in hop for hop in gl001.chain)
        d = gl001.as_dict()
        assert d["chain"] == list(gl001.chain)

    def test_suppressing_the_helper_stops_propagation(self, tmp_path):
        """An inline suppression on the helper's sync line is an ACCEPTED
        sync: callers must not be flagged for reaching it."""
        root = tmp_path / "tree"
        (root / "paddle_tpu" / "ops").mkdir(parents=True)
        (root / "helpers.py").write_text(
            "def read_scalar(t):\n"
            "    return t.numpy()  "
            "# graftlint: disable=GL002 — sanctioned\n")
        (root / "paddle_tpu" / "ops" / "hot.py").write_text(
            "import helpers\n\n\n"
            "def hot_read(x):\n"
            "    return helpers.read_scalar(x)\n")
        new, _, _, _ = analysis.analyze(root=str(root), baseline_path="",
                                        include=None)
        assert new == []

    def test_guarded_call_site_is_exempt(self, tmp_path):
        """The isinstance-guard normalization idiom applies to the CALL
        SITE of a syncing helper exactly as it does to a direct sync."""
        root = tmp_path / "tree"
        (root / "paddle_tpu" / "ops").mkdir(parents=True)
        (root / "helpers.py").write_text(
            "def read_scalar(t):\n    return t.numpy()\n")
        (root / "paddle_tpu" / "ops" / "hot.py").write_text(
            "import helpers\n"
            "from paddle_tpu.framework.core import Tensor\n\n\n"
            "def hot_read(x):\n"
            "    if isinstance(x, Tensor):\n"
            "        return helpers.read_scalar(x)\n"
            "    return x\n")
        new, _, _, _ = analysis.analyze(root=str(root), baseline_path="",
                                        include=None)
        assert new == []

    def test_lock_key_distinguishes_classes(self, tmp_path):
        """Two different classes' ``self._lock`` must not alias into one
        graph node: A holds its lock then the global lock, B holds the
        global lock then ITS OWN lock — a naive 'self._lock' key would
        report a false inversion; class-qualified keys must not."""
        root = tmp_path / "tree"
        root.mkdir()
        (root / "mod.py").write_text(
            "import threading\n\n"
            "g_lock = threading.Lock()\n\n\n"
            "class A:\n"
            "    def go(self):\n"
            "        with self._lock:\n"
            "            with g_lock:\n"
            "                pass\n\n\n"
            "class B:\n"
            "    def go(self):\n"
            "        with g_lock:\n"
            "            self.grab()\n\n"
            "    def grab(self):\n"
            "        with self._lock:\n"
            "            pass\n")
        new, _, _, _ = analysis.analyze(root=str(root), baseline_path="",
                                        include=None, rules=[
                                            analysis.RULES_BY_ID["GL007"]])
        assert new == []


class TestLocksets:
    """The GL010/GL011 guarded-by analysis: thread-entry chains, the
    entry-lockset fixpoint, and the guarded_by annotation's two-way
    contract (silences GL010, feeds GL011)."""

    def test_gl010_chain_carries_spawn_site(self):
        """The finding sits at the unguarded access; Finding.chain leads
        with the Thread(target=...) spawn site, file:line per hop; the
        MESSAGE stays line-free so fingerprints survive drift."""
        new, _, _ = _analyze("gl010_dirty")
        gap = next(f for f in new if "'_rid2att'" in f.message
                   or "_rid2att" in f.message)
        assert gap.scope == "GapRouter._submit_loop"
        assert "spawned via 'GapRouter._submit_loop'" in gap.message
        assert "dirty.py:" not in gap.message      # line-free fingerprint
        assert gap.chain
        assert "spawned: threading.Thread(self._submit_loop) " \
               "in GapRouter.start at dirty.py:" in gap.chain[0]
        assert gap.as_dict()["chain"] == list(gap.chain)

    def test_entry_lockset_needs_every_call_site_locked(self, tmp_path):
        """A *_locked helper is only exempt while EVERY resolved call
        site holds the lock: adding one unlocked caller must resurrect
        the finding (the fixpoint intersects, it does not union)."""
        root = tmp_path / "tree"
        root.mkdir()
        (root / "mod.py").write_text(
            "import threading\n\n\n"
            "class W:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._jobs = {}\n\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._loop).start()\n\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            self._take_locked()\n"
            "        self._take_locked()\n\n"   # the unlocked call site
            "    def _take_locked(self):\n"
            "        self._jobs.pop(1, None)\n\n"
            "    def put(self, k, v):\n"
            "        with self._lock:\n"
            "            self._jobs[k] = v\n")
        new, _, _, _ = analysis.analyze(
            root=str(root), baseline_path="", include=None,
            rules=[analysis.RULES_BY_ID["GL010"]])
        assert [f.scope for f in new] == ["W._take_locked"]

    def test_guarded_by_wrong_lock_feeds_gl011(self, tmp_path):
        """`# guarded_by: <lock>` is an assertion, not an off switch: it
        silences GL010 at the site, but naming a DIFFERENT lock than the
        real write sites hold trips the GL011 consistency check."""
        root = tmp_path / "tree"
        root.mkdir()
        src = ("import threading\n\n\n"
               "class W:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self._other_lock = threading.Lock()\n"
               "        self._view = {}\n\n"
               "    def start(self):\n"
               "        threading.Thread(target=self._loop).start()\n\n"
               "    def _loop(self):\n"
               "        self._view = {}   # guarded_by: ANN\n\n"
               "    def put(self, k, v):\n"
               "        with self._lock:\n"
               "            self._view[k] = v\n")
        (root / "mod.py").write_text(src.replace("ANN", "self._other_lock"))
        new, _, _, _ = analysis.analyze(root=str(root), baseline_path="",
                                        include=None)
        assert [f.rule for f in new] == ["GL011"]
        assert "no common lock" in new[0].message
        (root / "mod.py").write_text(src.replace("ANN", "self._lock"))
        new, _, _, _ = analysis.analyze(root=str(root), baseline_path="",
                                        include=None)
        assert new == []

    def test_gl011_escape_says_return_a_copy(self):
        new, _, _ = _analyze("gl011_dirty")
        esc = next(f for f in new if "escapes" in f.message)
        assert "return a copy instead" in esc.message
        split = next(f for f in new if "no common lock" in f.message)
        assert split.chain     # the write sites, file:line per hop
        assert all("dirty.py:" in hop for hop in split.chain)

    def test_explain_gl010_renders_chain(self):
        p = subprocess.run(
            [sys.executable, "tools/lint_framework.py", "--root",
             os.path.join(FIX, "gl010_dirty"), "--include", "",
             "--no-baseline", "--explain", "GL010"],
            cwd=ROOT, capture_output=True, text=True, timeout=120)
        assert p.returncode == 1, p.stdout + p.stderr
        assert "| spawned: threading.Thread(" in p.stdout


class TestSuppression:
    def test_inline_and_file_level(self):
        new, _base, supp = _analyze("suppress")
        assert new == []
        assert len(supp) == 3  # two inline + one file-level GL001

    def test_suppression_is_rule_specific(self):
        src = os.path.join(FIX, "suppress", "dirty_suppressed.py")
        f = analysis.Project(FIX, paths=[
            os.path.relpath(src, FIX)]).files[0]
        line = next(i for i, l in enumerate(f.lines, 1)
                    if "disable=GL001" in l)
        assert f.suppressed("GL001", line)
        assert not f.suppressed("GL002", line)

    def test_bare_disable_file_is_absorbing(self, tmp_path):
        """A later rule-specific disable-file must not narrow an earlier
        bare (all-rules) one — comment order must not matter."""
        root = tmp_path / "tree"
        root.mkdir()
        (root / "mod.py").write_text(
            "# graftlint: disable-file\n"
            "# graftlint: disable-file=GL003\n"
            "import time\n"
            "from paddle_tpu.jit import to_static\n\n\n"
            "@to_static\n"
            "def f(x):\n"
            "    return x * time.time()\n")
        new, _, supp, _ = analysis.analyze(root=str(root), baseline_path="",
                                           include=None)
        assert new == []
        assert [f.rule for f in supp] == ["GL001"]

    def test_directive_inside_string_is_not_a_suppression(self, tmp_path):
        """Only COMMENT tokens carry directives: documentation that QUOTES
        the suppression syntax in a docstring must not silence the file."""
        root = tmp_path / "tree"
        root.mkdir()
        (root / "mod.py").write_text(
            '"""Docs: write `# graftlint: disable-file=GL001` to opt '
            'out."""\n'
            "import time\n"
            "from paddle_tpu.jit import to_static\n\n\n"
            "@to_static\n"
            "def f(x):\n"
            "    return x * time.time()\n")
        new, _, supp, _ = analysis.analyze(root=str(root), baseline_path="",
                                           include=None)
        assert [f.rule for f in new] == ["GL001"]
        assert supp == []


class TestBaseline:
    def test_roundtrip(self, tmp_path):
        new, _, _ = _analyze("gl001")
        assert new
        bl = tmp_path / "baseline.json"
        analysis.write_baseline(str(bl), new)
        new2, base2, _, _ = analysis.analyze(
            root=os.path.join(FIX, "gl001"), baseline_path=str(bl),
            include=None)
        assert new2 == []
        assert len(base2) == len(new)

    def test_fingerprint_survives_line_drift(self, tmp_path):
        """Baseline keys carry no line number: prepending code above a
        grandfathered finding must not resurrect it."""
        root = tmp_path / "tree"
        root.mkdir()
        dirty = open(os.path.join(FIX, "gl001", "dirty.py")).read()
        (root / "mod.py").write_text(dirty)
        new, _, _, _ = analysis.analyze(root=str(root), baseline_path="",
                                        include=None)
        bl = tmp_path / "bl.json"
        analysis.write_baseline(str(bl), new)
        (root / "mod.py").write_text("# shifted\n# shifted\n" + dirty)
        new2, base2, _, _ = analysis.analyze(
            root=str(root), baseline_path=str(bl), include=None)
        assert new2 == []
        assert len(base2) == len(new)

    def test_duplicate_violation_is_not_absorbed(self, tmp_path):
        """The baseline is a multiset: grandfathering ONE .numpy() sync in
        a scope must not silence a SECOND identical one added later."""
        root = tmp_path / "tree"
        (root / "paddle_tpu" / "ops").mkdir(parents=True)
        mod = root / "paddle_tpu" / "ops" / "m.py"
        mod.write_text("def f(x, y):\n    a = x.numpy()\n    return a\n")
        new, _, _, _ = analysis.analyze(root=str(root), baseline_path="",
                                        include=None)
        assert len(new) == 1
        bl = tmp_path / "bl.json"
        analysis.write_baseline(str(bl), new)
        mod.write_text("def f(x, y):\n    a = x.numpy()\n"
                       "    b = y.numpy()\n    return a + b\n")
        new2, base2, _, _ = analysis.analyze(
            root=str(root), baseline_path=str(bl), include=None)
        assert len(base2) == 1 and len(new2) == 1


class TestCLISurfaces:
    def _run(self, *cmd):
        return subprocess.run([sys.executable, *cmd], cwd=ROOT,
                              capture_output=True, text=True, timeout=120)

    def _run_slow(self, *cmd):
        """For surfaces that legitimately pay a jax import + the
        flagship program builds (the graftir aggregator rows)."""
        env = dict(os.environ)
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8")
        env.setdefault("JAX_PLATFORMS", "cpu")
        return subprocess.run([sys.executable, *cmd], cwd=ROOT, env=env,
                              capture_output=True, text=True, timeout=420)

    def test_lint_framework_runs_without_importing_the_framework(self):
        """tools/lint_framework.py path-loads the analysis package: dirty
        fixture -> exit 1 with parseable JSON; clean fixture -> exit 0."""
        p = self._run("tools/lint_framework.py", "--root",
                      os.path.join(FIX, "gl002"), "--include", "",
                      "--no-baseline", "--json")
        assert p.returncode == 1, p.stderr
        report = json.loads(p.stdout)
        assert report["counts"] == {"GL002": 5}
        assert not report["ok"]
        p = self._run("tools/lint_framework.py", "--root",
                      os.path.join(FIX, "gl003_clean"), "--include", "")
        assert p.returncode == 0, p.stdout + p.stderr

    def test_check_metric_names_exit_contract(self):
        """The PR 1 CLI (now a GL005 shim) still exits 0 on the clean
        repo and still supports --list."""
        p = self._run("tools/check_metric_names.py")
        assert p.returncode == 0, p.stdout + p.stderr
        assert "OK" in p.stdout
        p = self._run("tools/check_metric_names.py", "--list")
        assert p.returncode == 0
        assert "paddle_tpu_dispatch_op_calls_total\tcounter" in p.stdout

    def test_run_static_checks_aggregator(self):
        """15/15: the nine source-level rows (incl. the ISSUE 15
        check_doc_rows telemetry-doc contract, the ISSUE 17
        check_shared_state lockset row and the ISSUE 18
        check_control_bounds actuation-bounds row) plus the six
        graftir rows (one jax subprocess analyzing — and
        graftopt-transforming — the flagship live programs, now incl.
        the ISSUE 19 check_precision_flow and check_numeric_hazards
        rows). The summary stamps per-row wall time as one flat map."""
        p = self._run_slow("tools/run_static_checks.py", "--json")
        assert p.returncode == 0, p.stdout + p.stderr
        summary = json.loads(p.stdout)
        assert summary["ok"] is True
        assert [c["check"] for c in summary["checks"]] == [
            "graftlint", "check_metric_names", "check_span_names",
            "check_lock_order", "check_recompile_hazards",
            "check_shared_state",
            "check_fault_points", "check_doc_rows",
            "check_control_bounds",
            "check_collective_consistency",
            "check_donation", "check_hbm_budgets",
            "check_precision_flow", "check_numeric_hazards",
            "check_opt_parity"]
        assert all(c["ok"] for c in summary["checks"])
        assert set(summary["seconds"]) == {c["check"]
                                           for c in summary["checks"]}
        assert summary["total_seconds"] >= summary["seconds"]["graftlint"]

    def test_sarif_emitter_shapes_rules_and_locations(self):
        """sarif_report: one reporting rule per check row; a failing
        detail with a leading path:line becomes a physical location, a
        graftir-style ``program[where]`` finding a logical one. (The
        emitter runs in-process on fabricated rows — the live aggregator
        already pays its subprocess once in the 15/15 test, and the
        --sarif flag shares main()'s exit-code contract.)"""
        sys.path.insert(0, os.path.join(ROOT, "tools"))
        try:
            import run_static_checks as agg

            rows = [
                {"check": "graftlint", "ok": True, "findings": 0,
                 "seconds": 0.1, "detail": []},
                {"check": "check_doc_rows", "ok": False, "findings": 1,
                 "seconds": 0.1,
                 "detail": ["docs/observability.md:12 missing row"]},
                {"check": "check_numeric_hazards", "ok": False,
                 "findings": 1, "seconds": 0.1,
                 "detail": ["serving.mixed_step[exp[4]]: exp overflow"]},
            ]
            doc = agg.sarif_report(rows)
            assert doc["version"] == "2.1.0"
            (run,) = doc["runs"]
            rules = run["tool"]["driver"]["rules"]
            assert [r["id"] for r in rules] == [
                "graftlint", "check_doc_rows", "check_numeric_hazards"]
            results = run["results"]
            assert len(results) == 2      # only the failing rows
            phys = results[0]["locations"][0]["physicalLocation"]
            assert phys["artifactLocation"]["uri"] == \
                "docs/observability.md"
            assert phys["region"]["startLine"] == 12
            logical = results[1]["locations"][0]["logicalLocations"][0]
            assert logical["name"] == "serving.mixed_step"
        finally:
            sys.path.remove(os.path.join(ROOT, "tools"))

    def test_explain_prints_propagation_chain(self):
        """--explain GLxxx: one rule, every finding followed by its
        indented chain hops with file:line detail."""
        p = self._run("tools/lint_framework.py", "--root",
                      os.path.join(FIX, "interproc_dirty"), "--include",
                      "", "--no-baseline", "--explain", "GL001")
        assert p.returncode == 1, p.stdout + p.stderr
        assert "deep_stamp -> stamp -> time.time()" in p.stdout
        assert "| stamp [time.time() at helpers.py:" in p.stdout
        p = self._run("tools/lint_framework.py", "--explain", "GL999")
        assert p.returncode == 2

    def test_aggregator_and_shim_agree_on_suppressed_metric(self, tmp_path):
        """A suppressed GL005 registration must pass BOTH strict surfaces
        (they share strict_problems) — CI must never fail a row that no
        documented CLI reproduces."""
        import shutil

        root = tmp_path / "tree"
        (root / "paddle_tpu" / "monitor").mkdir(parents=True)
        shutil.copy(os.path.join(ROOT, "paddle_tpu", "monitor",
                                 "catalog.py"),
                    root / "paddle_tpu" / "monitor" / "catalog.py")
        (root / "paddle_tpu" / "rogue.py").write_text(
            'def bind(m):\n'
            '    return m.counter("paddle_tpu_dispatch_rogue_total")'
            '  # graftlint: disable=GL005\n')
        sys.path.insert(0, os.path.join(ROOT, "tools"))
        try:
            import check_metric_names as shim
            import run_static_checks as agg

            assert shim.check(root=str(root)) == []
            rows = agg.run_checks(root=str(root))
            assert [r["check"] for r in rows] == ["graftlint",
                                                 "check_metric_names",
                                                 "check_span_names",
                                                 "check_lock_order",
                                                 "check_recompile_hazards",
                                                 "check_shared_state",
                                                 "check_fault_points"]
            for row in rows[1:]:
                assert row["ok"], row
        finally:
            sys.path.remove(os.path.join(ROOT, "tools"))
