"""Detection ops (python/paddle/vision/ops.py: nms, roi_align, roi_pool,
deform_conv2d, box utilities). TPU-first: static-shape jnp implementations (nms uses a
fixed-iteration suppression loop so it jits; reference kernels are CUDA)."""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..nn.layer.layers import Layer
from ..ops._apply import defop


@defop("vision.nms", differentiable=False)
def _nms(boxes, scores=None, iou_threshold=0.3):
    n = boxes.shape[0]
    if scores is None:
        order = jnp.arange(n)
    else:
        order = jnp.argsort(-scores)
    b = boxes[order]
    x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    areas = (x2 - x1) * (y2 - y1)
    xx1 = jnp.maximum(x1[:, None], x1[None, :])
    yy1 = jnp.maximum(y1[:, None], y1[None, :])
    xx2 = jnp.minimum(x2[:, None], x2[None, :])
    yy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.clip(xx2 - xx1, 0) * jnp.clip(yy2 - yy1, 0)
    iou = inter / (areas[:, None] + areas[None, :] - inter + 1e-10)

    suppressed = jnp.zeros(n, bool)

    def body(i, sup):
        # suppress j>i overlapping an unsuppressed i
        kill = (~sup[i]) & (iou[i] > iou_threshold) & (jnp.arange(n) > i)
        return sup | kill

    suppressed = jax.lax.fori_loop(0, n, body, suppressed)
    keep = order[~suppressed]
    return keep


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None, name=None):
    """paddle.vision.ops.nms (host-returning index list; data-dependent size)."""
    bv = boxes.value if isinstance(boxes, Tensor) else jnp.asarray(boxes)
    sv = scores.value if isinstance(scores, Tensor) else (
        None if scores is None else jnp.asarray(scores))
    if category_idxs is not None:
        cat = (category_idxs.value if isinstance(category_idxs, Tensor)
               else jnp.asarray(category_idxs))
        # per-category suppression via coordinate offset trick
        offset = cat.astype(bv.dtype)[:, None] * (bv.max() + 1.0)
        bv = bv + offset
    keep = np.asarray(_nms(Tensor(bv), None if sv is None else Tensor(sv),
                           iou_threshold=float(iou_threshold)).value)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


@defop("vision.roi_align")
def _roi_align(x, boxes, boxes_num=None, output_size=(1, 1), spatial_scale=1.0,
               sampling_ratio=-1, aligned=True, reduce="mean"):
    # x: (N, C, H, W); boxes: (R, 4) in image coords; boxes assigned per batch by
    # boxes_num prefix counts
    N, C, H, W = x.shape
    R = boxes.shape[0]
    oh, ow = output_size
    if boxes_num is None:
        batch_idx = jnp.zeros(R, jnp.int32)
    else:
        batch_idx = jnp.repeat(jnp.arange(N), boxes_num, total_repeat_length=R)

    offset = 0.5 if aligned else 0.0
    bx = boxes * spatial_scale
    x1, y1, x2, y2 = bx[:, 0] - offset, bx[:, 1] - offset, bx[:, 2] - offset, \
        bx[:, 3] - offset
    rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-5)
    rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-5)
    sr = sampling_ratio if sampling_ratio > 0 else 2

    # sample points: (R, oh*sr, ow*sr)
    gy = (jnp.arange(oh * sr) + 0.5) / sr
    gx = (jnp.arange(ow * sr) + 0.5) / sr
    ys = y1[:, None] + rh[:, None] * gy[None, :] / oh          # (R, oh*sr)
    xs = x1[:, None] + rw[:, None] * gx[None, :] / ow          # (R, ow*sr)

    def bilinear(feat, yy, xx):
        y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
        y1_ = jnp.clip(y0 + 1, 0, H - 1)
        x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
        x1_ = jnp.clip(x0 + 1, 0, W - 1)
        wy = jnp.clip(yy - y0, 0, 1)
        wx = jnp.clip(xx - x0, 0, 1)
        # feat: (C, H, W); result (C, len(yy), len(xx))
        f00 = feat[:, y0][:, :, x0]
        f01 = feat[:, y0][:, :, x1_]
        f10 = feat[:, y1_][:, :, x0]
        f11 = feat[:, y1_][:, :, x1_]
        return (f00 * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
                + f01 * (1 - wy)[None, :, None] * wx[None, None, :]
                + f10 * wy[None, :, None] * (1 - wx)[None, None, :]
                + f11 * wy[None, :, None] * wx[None, None, :])

    def per_roi(r):
        feat = x[batch_idx[r]]
        samples = bilinear(feat, ys[r], xs[r])                # (C, oh*sr, ow*sr)
        binned = samples.reshape(C, oh, sr, ow, sr)
        if reduce == "max":
            return binned.max(axis=(2, 4))
        return binned.mean(axis=(2, 4))

    return jax.vmap(per_roi)(jnp.arange(R))


def roi_align(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    return _roi_align(x, boxes, boxes_num, output_size=tuple(output_size),
                      spatial_scale=float(spatial_scale),
                      sampling_ratio=int(sampling_ratio), aligned=bool(aligned))


def roi_pool(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0, name=None):
    # max-pool variant: dense bilinear sampling reduced with max (reference roi_pool
    # takes the max over integer bins; dense sampling + max converges to the same)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    return _roi_align(x, boxes, boxes_num, output_size=tuple(output_size),
                      spatial_scale=float(spatial_scale), sampling_ratio=2,
                      aligned=False, reduce="max")


@defop("vision.deform_conv2d")
def _deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1,
                   deformable_groups=1, groups=1, mask=None):
    # Reference: deformable conv v1/v2 (vision/ops.py deform_conv2d). Implemented by
    # gathering deformed sampling locations per kernel tap then a 1x1 contraction.
    N, Cin, H, W = x.shape
    Cout, _, kh, kw = weight.shape
    sh = sw = stride if isinstance(stride, int) else stride[0]
    ph = pw = padding if isinstance(padding, int) else padding[0]
    dh = dw = dilation if isinstance(dilation, int) else dilation[0]
    Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1

    xp = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    Hp, Wp = H + 2 * ph, W + 2 * pw

    base_y = jnp.arange(Ho) * sh
    base_x = jnp.arange(Wo) * sw
    out = jnp.zeros((N, Cout, Ho, Wo), jnp.float32)

    cols = []
    for iy in range(kh):
        for ix in range(kw):
            tap = iy * kw + ix
            oy = offset[:, 2 * tap, :, :]
            ox = offset[:, 2 * tap + 1, :, :]
            yy = base_y[None, :, None] + iy * dh + oy
            xx = base_x[None, None, :] + ix * dw + ox
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, Hp - 1)
            y1 = jnp.clip(y0 + 1, 0, Hp - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, Wp - 1)
            x1 = jnp.clip(x0 + 1, 0, Wp - 1)
            wy = jnp.clip(yy - y0, 0, 1)[:, None]
            wx = jnp.clip(xx - x0, 0, 1)[:, None]

            def gather(yi, xi):
                flat = xp.reshape(N, Cin, Hp * Wp)
                idx = yi[:, None] * Wp + xi[:, None]          # (N,1,Ho,Wo)
                idx = jnp.broadcast_to(idx, (N, Cin, Ho, Wo)).reshape(N, Cin, -1)
                return jnp.take_along_axis(flat, idx, axis=2).reshape(
                    N, Cin, Ho, Wo)

            val = (gather(y0, x0) * (1 - wy) * (1 - wx)
                   + gather(y0, x1) * (1 - wy) * wx
                   + gather(y1, x0) * wy * (1 - wx)
                   + gather(y1, x1) * wy * wx)
            if mask is not None:
                val = val * mask[:, tap, None, :, :]
            cols.append(val)

    col = jnp.stack(cols, axis=2)                             # (N, Cin, kh*kw, Ho, Wo)
    w = weight.reshape(Cout, Cin * kh * kw)
    col = col.reshape(N, Cin * kh * kw, Ho * Wo)
    out = jnp.einsum("oc,ncp->nop", w, col).reshape(N, Cout, Ho, Wo)
    if bias is not None:
        out = out + bias[None, :, None, None]
    return out.astype(x.dtype)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1,
                  deformable_groups=1, groups=1, mask=None, name=None):
    if groups != 1 or deformable_groups != 1:
        raise NotImplementedError(
            "deform_conv2d currently supports groups=1 and deformable_groups=1")
    return _deform_conv2d(x, offset, weight, bias, stride=stride, padding=padding,
                          dilation=dilation, deformable_groups=deformable_groups,
                          groups=groups, mask=mask)


def box_iou(boxes1, boxes2):
    b1 = boxes1.value if isinstance(boxes1, Tensor) else jnp.asarray(boxes1)
    b2 = boxes2.value if isinstance(boxes2, Tensor) else jnp.asarray(boxes2)
    a1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
    a2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
    xx1 = jnp.maximum(b1[:, None, 0], b2[None, :, 0])
    yy1 = jnp.maximum(b1[:, None, 1], b2[None, :, 1])
    xx2 = jnp.minimum(b1[:, None, 2], b2[None, :, 2])
    yy2 = jnp.minimum(b1[:, None, 3], b2[None, :, 3])
    inter = jnp.clip(xx2 - xx1, 0) * jnp.clip(yy2 - yy1, 0)
    return Tensor(inter / (a1[:, None] + a2[None, :] - inter + 1e-10))


# -- round-2 detection batch --------------------------------------------------
@defop("vision.box_coder")
def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0):
    """ops.py box_coder: encode/decode boxes against priors (SSD-family).
    prior_box_var accepts a 4-list of floats like the reference."""
    if isinstance(prior_box_var, (list, tuple)):
        prior_box_var = jnp.asarray(prior_box_var, jnp.float32)
    pw = prior_box[:, 2] - prior_box[:, 0] + (0 if box_normalized else 1)
    ph = prior_box[:, 3] - prior_box[:, 1] + (0 if box_normalized else 1)
    pcx = prior_box[:, 0] + pw * 0.5
    pcy = prior_box[:, 1] + ph * 0.5
    if prior_box_var is None:
        var = jnp.ones((1, 4), target_box.dtype)
    elif jnp.ndim(prior_box_var) == 1:
        var = jnp.reshape(prior_box_var, (1, 4))
    else:
        var = prior_box_var
    if code_type == "encode_center_size":
        tw = target_box[:, 2] - target_box[:, 0] + (0 if box_normalized else 1)
        th = target_box[:, 3] - target_box[:, 1] + (0 if box_normalized else 1)
        tcx = target_box[:, 0] + tw * 0.5
        tcy = target_box[:, 1] + th * 0.5
        out = jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :],
            (tcy[:, None] - pcy[None, :]) / ph[None, :],
            jnp.log(tw[:, None] / pw[None, :]),
            jnp.log(th[:, None] / ph[None, :]),
        ], axis=-1) / var[None, :, :] if var.shape[0] != 1 else jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :] / var[0, 0],
            (tcy[:, None] - pcy[None, :]) / ph[None, :] / var[0, 1],
            jnp.log(tw[:, None] / pw[None, :]) / var[0, 2],
            jnp.log(th[:, None] / ph[None, :]) / var[0, 3],
        ], axis=-1)
        return out
    # decode_center_size: target_box (N, M, 4) deltas against priors on `axis`
    t = target_box
    if axis == 0:
        pcx_, pcy_, pw_, ph_ = (v[None, :] for v in (pcx, pcy, pw, ph))
        v0, v1, v2, v3 = var[None, :, 0], var[None, :, 1], var[None, :, 2], \
            var[None, :, 3]
    else:
        pcx_, pcy_, pw_, ph_ = (v[:, None] for v in (pcx, pcy, pw, ph))
        v0, v1, v2, v3 = var[:, None, 0], var[:, None, 1], var[:, None, 2], \
            var[:, None, 3]
    cx = v0 * t[..., 0] * pw_ + pcx_
    cy = v1 * t[..., 1] * ph_ + pcy_
    w = jnp.exp(v2 * t[..., 2]) * pw_
    h = jnp.exp(v3 * t[..., 3]) * ph_
    norm = 0.0 if box_normalized else 1.0
    return jnp.stack([cx - w / 2, cy - h / 2,
                      cx + w / 2 - norm, cy + h / 2 - norm], axis=-1)


@defop("vision.prior_box", differentiable=False)
def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False):
    """ops.py prior_box: SSD anchor generation over the feature map grid."""
    fh, fw = input.shape[2], input.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_w = steps[0] or float(iw) / fw
    step_h = steps[1] or float(ih) / fh
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    boxes = []
    for ms in min_sizes:
        ms = float(ms)
        for ar in ars:
            boxes.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if max_sizes:
            pass
    if max_sizes:
        for ms, mx in zip(min_sizes, max_sizes):
            boxes.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    wh = jnp.asarray(np.array(boxes, "float32"))  # (A, 2)
    cx = (jnp.arange(fw) + offset) * step_w
    cy = (jnp.arange(fh) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy, indexing="xy")
    centers = jnp.stack([cxg, cyg], -1)[..., None, :]       # (fh, fw, 1, 2)
    half = wh[None, None, :, :] / 2.0
    out = jnp.concatenate([
        (centers[..., 0:1] - half[..., 0:1]) / iw,
        (centers[..., 1:2] - half[..., 1:2]) / ih,
        (centers[..., 0:1] + half[..., 0:1]) / iw,
        (centers[..., 1:2] + half[..., 1:2]) / ih,
    ], axis=-1)                                             # (fh, fw, A, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, out.dtype), out.shape)
    return out, var


@defop("vision.yolo_box", differentiable=False)
def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """ops.py yolo_box: decode YOLOv3 head output into boxes + scores."""
    n, c, h, w = x.shape
    na = len(anchors) // 2
    an = jnp.asarray(np.array(anchors, "float32").reshape(na, 2))
    xr = x.reshape(n, na, 5 + class_num, h, w)
    gx = (jnp.arange(w) + 0.0)[None, None, None, :]
    gy = (jnp.arange(h) + 0.0)[None, None, :, None]
    sig = jax.nn.sigmoid
    bx = (sig(xr[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2 + gx) / w
    by = (sig(xr[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2 + gy) / h
    input_h = downsample_ratio * h
    input_w = downsample_ratio * w
    bw = jnp.exp(xr[:, :, 2]) * an[None, :, 0, None, None] / input_w
    bh = jnp.exp(xr[:, :, 3]) * an[None, :, 1, None, None] / input_h
    conf = sig(xr[:, :, 4])
    probs = sig(xr[:, :, 5:]) * conf[:, :, None]
    mask = (conf > conf_thresh).astype(x.dtype)
    imh = img_size[:, 0].astype(x.dtype)[:, None, None, None]
    imw = img_size[:, 1].astype(x.dtype)[:, None, None, None]
    x1 = (bx - bw / 2) * imw
    y1 = (by - bh / 2) * imh
    x2 = (bx + bw / 2) * imw
    y2 = (by + bh / 2) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0)
        y1 = jnp.clip(y1, 0)
        x2 = jnp.minimum(x2, imw - 1)
        y2 = jnp.minimum(y2, imh - 1)
    boxes = jnp.stack([x1, y1, x2, y2], -1) * mask[..., None]
    boxes = boxes.reshape(n, -1, 4)
    scores = (probs * mask[:, :, None]).transpose(0, 1, 3, 4, 2) \
        .reshape(n, -1, class_num)
    return boxes, scores


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """ops.py psroi_pool: position-sensitive ROI average pooling."""
    os = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)
    C = int(x.shape[1])
    out_c = C // (os[0] * os[1])
    pooled = _roi_align(x, boxes, boxes_num, output_size=os,
                        spatial_scale=spatial_scale, sampling_ratio=1,
                        aligned=False)
    # position-sensitive: output channel c at bin (i,j) reads input channel
    # c*os0*os1 + i*os1 + j
    idx = jnp.arange(out_c * os[0] * os[1]).reshape(out_c, os[0], os[1])
    n = pooled.shape[0]
    gi = jnp.broadcast_to(idx[None], (n, out_c, os[0], os[1]))
    ii = jnp.broadcast_to(jnp.arange(os[0])[None, None, :, None],
                          (n, out_c, os[0], os[1]))
    jj = jnp.broadcast_to(jnp.arange(os[1])[None, None, None, :],
                          (n, out_c, os[0], os[1]))
    pv = pooled.value if isinstance(pooled, Tensor) else pooled
    out = pv[jnp.arange(n)[:, None, None, None], gi, ii, jj]
    return Tensor(out)


@defop("vision.matrix_nms", differentiable=False)
def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True):
    """ops.py matrix_nms: soft suppression by pairwise IoU decay (SOLOv2)."""
    n, c, m = scores.shape  # (batch, classes, boxes)
    outs = []
    for b in range(n):
        cls_scores = scores[b]
        boxes = bboxes[b]
        flat_scores = cls_scores.reshape(-1)
        labels = jnp.repeat(jnp.arange(c), m)
        box_idx = jnp.tile(jnp.arange(m), c)
        k = min(nms_top_k, int(flat_scores.shape[0]))
        top, ti = jax.lax.top_k(flat_scores, k)
        sel_boxes = boxes[box_idx[ti]]
        sel_labels = labels[ti]
        x1, y1, x2, y2 = (sel_boxes[:, i] for i in range(4))
        off = 0.0 if normalized else 1.0
        areas = (x2 - x1 + off) * (y2 - y1 + off)
        xx1 = jnp.maximum(x1[:, None], x1[None, :])
        yy1 = jnp.maximum(y1[:, None], y1[None, :])
        xx2 = jnp.minimum(x2[:, None], x2[None, :])
        yy2 = jnp.minimum(y2[:, None], y2[None, :])
        inter = jnp.clip(xx2 - xx1 + off, 0) * jnp.clip(yy2 - yy1 + off, 0)
        iou = inter / (areas[:, None] + areas[None, :] - inter + 1e-10)
        same = (sel_labels[:, None] == sel_labels[None, :])
        upper = jnp.triu(jnp.ones((k, k), bool), 1)  # j decayed by better i
        ious = jnp.where(same & upper.T, iou, 0.0)
        comp = jnp.max(ious, axis=1)          # worst overlap with better box
        if use_gaussian:
            decay = jnp.min(jnp.where(
                same & upper.T,
                jnp.exp(-(ious ** 2 - comp[None, :] ** 2) / gaussian_sigma),
                1.0), axis=1)
        else:
            decay = jnp.min(jnp.where(same & upper.T,
                                      (1 - ious) / (1 - comp[None, :] + 1e-10),
                                      1.0), axis=1)
        new_scores = top * decay
        keep = new_scores > jnp.maximum(post_threshold, score_threshold)
        new_scores = jnp.where(keep, new_scores, 0.0)
        kk = min(keep_top_k, k)
        fin, fi = jax.lax.top_k(new_scores, kk)
        out = jnp.concatenate([
            sel_labels[fi][:, None].astype(bboxes.dtype),
            fin[:, None], sel_boxes[fi]], axis=1)
        outs.append(out)
    return jnp.stack(outs)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """ops.py distribute_fpn_proposals: route each ROI to its FPN level by
    sqrt-area scale (eager: host-side grouping like the reference CPU path)."""
    rois = np.asarray(fpn_rois.numpy() if isinstance(fpn_rois, Tensor)
                      else fpn_rois)
    off = 1.0 if pixel_offset else 0.0
    scale = np.sqrt(np.clip((rois[:, 2] - rois[:, 0] + off)
                            * (rois[:, 3] - rois[:, 1] + off), 0, None))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype("int64")
    outs, idx_in_level, counts = [], [], []
    order = []
    for level in range(min_level, max_level + 1):
        sel = np.flatnonzero(lvl == level)
        order.append(sel)
        outs.append(Tensor(jnp.asarray(rois[sel])))
        counts.append(len(sel))
    restore = np.argsort(np.concatenate(order)) if order else np.zeros(0)
    return (outs, Tensor(jnp.asarray(restore.astype("int32")[:, None])),
            [Tensor(jnp.asarray(np.array([c], "int32"))) for c in counts]
            if rois_num is not None else None)


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False, name=None):
    """ops.py generate_proposals (RPN): decode deltas -> clip -> filter ->
    nms -> top-k, composed from box_coder + nms."""
    n = scores.shape[0]
    all_rois, all_scores, nums = [], [], []
    for b in range(n):
        s = scores[b].reshape([-1])
        d = bbox_deltas[b].transpose([1, 2, 0]).reshape([-1, 4])
        a = anchors.reshape([-1, 4])
        v = variances.reshape([-1, 4])
        k = min(pre_nms_top_n, int(s.shape[0]))
        top_s, ti = jax.lax.top_k(s.value, k)
        props = box_coder(a[Tensor(ti)], v[Tensor(ti)],
                          d[Tensor(ti)].unsqueeze(0),
                          code_type="decode_center_size", axis=0)
        props = props.squeeze(0)
        h, w = img_size[b].numpy()[:2]
        pv = jnp.stack([
            jnp.clip(props.value[:, 0], 0, w - (1.0 if pixel_offset else 0.0)),
            jnp.clip(props.value[:, 1], 0, h - (1.0 if pixel_offset else 0.0)),
            jnp.clip(props.value[:, 2], 0, w - (1.0 if pixel_offset else 0.0)),
            jnp.clip(props.value[:, 3], 0, h - (1.0 if pixel_offset else 0.0)),
        ], axis=1)
        wide = (pv[:, 2] - pv[:, 0]) >= min_size
        tall = (pv[:, 3] - pv[:, 1]) >= min_size
        ok = wide & tall
        masked_scores = jnp.where(ok, top_s, -jnp.inf)
        keep = _nms(pv, scores=masked_scores, iou_threshold=nms_thresh)
        keep_v = keep.value if isinstance(keep, Tensor) else keep
        # drop sub-min_size boxes entirely (they were only demoted to -inf for
        # the NMS ranking; the reference removes them before NMS)
        keep_v = keep_v[jnp.isfinite(masked_scores[keep_v])]
        keep_v = keep_v[:post_nms_top_n]
        all_rois.append(Tensor(pv[keep_v]))
        all_scores.append(Tensor(masked_scores[keep_v]))
        nums.append(len(keep_v))
    rois = Tensor(jnp.concatenate([r.value for r in all_rois]))
    rscores = Tensor(jnp.concatenate([s.value for s in all_scores]))
    if return_rois_num:
        return rois, rscores, Tensor(jnp.asarray(np.array(nums, "int32")))
    return rois, rscores


def read_file(filename, name=None):
    """ops.py read_file: raw bytes as a uint8 tensor."""
    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """ops.py decode_jpeg via PIL (the reference uses nvjpeg on GPU)."""
    import io

    from PIL import Image

    data = bytes(np.asarray(x.numpy() if isinstance(x, Tensor) else x,
                            np.uint8))
    img = Image.open(io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None, :, :]
    else:
        arr = arr.transpose(2, 0, 1)  # HWC -> CHW
    return Tensor(jnp.asarray(arr))


# -- layer classes over the functionals (reference vision/ops.py classes) ----
class DeformConv2D(Layer):
    """vision/ops.py DeformConv2D: layer form of deform_conv2d."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from ..nn.initializer import Constant, XavierUniform

        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, ks[0], ks[1]],
            attr=weight_attr, default_initializer=XavierUniform())
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], attr=bias_attr,
            default_initializer=Constant(0.0), is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, bias=self.bias,
                             stride=self._stride, padding=self._padding,
                             dilation=self._dilation,
                             deformable_groups=self._deformable_groups,
                             groups=self._groups, mask=mask)


class RoIAlign(Layer):
    """vision/ops.py RoIAlign: layer form of roi_align."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale)


class RoIPool(Layer):
    """vision/ops.py RoIPool: layer form of roi_pool."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


class PSRoIPool(Layer):
    """vision/ops.py PSRoIPool: layer form of psroi_pool."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size,
                          self._spatial_scale)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """vision/ops.py yolo_loss (YOLOv3): per-cell objectness + box + class
    loss against assigned ground-truth boxes.

    Decodes predictions exactly like yolo_box, assigns each gt to the best
    anchor of this head's mask, and sums MSE box terms + BCE
    objectness/class terms — the reference kernel's loss shape
    (paddle/phi/kernels/impl/yolov3_loss_kernel_impl.h), host-vectorized."""
    import jax

    from ..ops._apply import apply_raw

    gb = gt_box.value if isinstance(gt_box, Tensor) else jnp.asarray(gt_box)
    gl = gt_label.value if isinstance(gt_label, Tensor) else jnp.asarray(gt_label)

    gs = None if gt_score is None else (
        gt_score.value if isinstance(gt_score, Tensor) else jnp.asarray(gt_score))

    def _loss_fn(xv):
        return _yolo_loss_impl(xv, gb, gl, anchors, anchor_mask, class_num,
                               downsample_ratio, use_label_smooth,
                               ignore_thresh, gs, scale_x_y)

    return apply_raw("vision.yolo_loss", _loss_fn,
                     [x if isinstance(x, Tensor) else Tensor(x)])[0]


def _yolo_loss_impl(xv, gb, gl, anchors, anchor_mask, class_num,
                    downsample_ratio, use_label_smooth, ignore_thresh=0.7,
                    gt_score=None, scale_x_y=1.0):
    import jax

    n, c, h, w = xv.shape
    an_num = len(anchor_mask)
    preds = xv.reshape(n, an_num, 5 + class_num, h, w)
    tx, ty = preds[:, :, 0], preds[:, :, 1]
    tw, th = preds[:, :, 2], preds[:, :, 3]
    obj_logit = preds[:, :, 4]
    cls_logit = preds[:, :, 5:]

    input_size = downsample_ratio * h
    masked_anchors = np.asarray([(anchors[2 * i], anchors[2 * i + 1])
                                 for i in anchor_mask], np.float32)

    loss = jnp.zeros((n,), jnp.float32)
    obj_target = jnp.zeros((n, an_num, h, w), jnp.float32)
    # decode every predicted box once (yolo_box semantics, scale_x_y bias)
    gyx, gxx = jnp.meshgrid(jnp.arange(h), jnp.arange(w), indexing="ij")
    px = (jax.nn.sigmoid(tx) * scale_x_y - 0.5 * (scale_x_y - 1.0)
          + gxx[None, None]) / w
    py = (jax.nn.sigmoid(ty) * scale_x_y - 0.5 * (scale_x_y - 1.0)
          + gyx[None, None]) / h
    pw = jnp.exp(tw) * masked_anchors[None, :, 0, None, None] / input_size
    phh = jnp.exp(th) * masked_anchors[None, :, 1, None, None] / input_size
    # best IoU of each predicted box against ANY gt of its sample
    best_iou = jnp.zeros((n, an_num, h, w), jnp.float32)
    b_count = gb.shape[1]
    for bi in range(b_count):
        ggx, ggy = gb[:, bi, 0], gb[:, bi, 1]
        ggw, ggh = gb[:, bi, 2], gb[:, bi, 3]
        valid = ((ggw > 0) & (ggh > 0)).astype(jnp.float32)
        x1 = jnp.maximum(px - pw / 2, (ggx - ggw / 2)[:, None, None, None])
        y1 = jnp.maximum(py - phh / 2, (ggy - ggh / 2)[:, None, None, None])
        x2 = jnp.minimum(px + pw / 2, (ggx + ggw / 2)[:, None, None, None])
        y2 = jnp.minimum(py + phh / 2, (ggy + ggh / 2)[:, None, None, None])
        inter = jnp.clip(x2 - x1, 0) * jnp.clip(y2 - y1, 0)
        union = pw * phh + (ggw * ggh)[:, None, None, None] - inter
        iou = inter / jnp.maximum(union, 1e-9)
        best_iou = jnp.maximum(best_iou, iou * valid[:, None, None, None])
    # the reference's ignore mask: unmatched cells whose best IoU exceeds
    # ignore_thresh take NO objectness penalty
    obj_weight_base = (best_iou < ignore_thresh).astype(jnp.float32)
    for bi in range(b_count):
        # gt boxes are (cx, cy, w, h) normalized to [0,1]
        gx, gy = gb[:, bi, 0], gb[:, bi, 1]
        gw, gh = gb[:, bi, 2], gb[:, bi, 3]
        valid = (gw > 0) & (gh > 0)
        gi = jnp.clip((gx * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gy * h).astype(jnp.int32), 0, h - 1)
        # best anchor by IoU of (w, h) only (the reference's assignment)
        gwa = gw[:, None] * input_size
        gha = gh[:, None] * input_size
        inter = jnp.minimum(gwa, masked_anchors[None, :, 0]) * \
            jnp.minimum(gha, masked_anchors[None, :, 1])
        union = gwa * gha + masked_anchors[None, :, 0] * \
            masked_anchors[None, :, 1] - inter
        best = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=1)
        bidx = jnp.arange(n)
        sel = (bidx, best, gj, gi)
        tgt_x = gx * w - gi
        tgt_y = gy * h - gj
        tgt_w = jnp.log(jnp.maximum(
            gw * input_size / masked_anchors[best, 0], 1e-9))
        tgt_h = jnp.log(jnp.maximum(
            gh * input_size / masked_anchors[best, 1], 1e-9))
        scale = 2.0 - gw * gh
        vf = valid.astype(jnp.float32)
        if gt_score is not None:
            vf = vf * gt_score[:, bi]
        loss = loss + vf * scale * (
            (jax.nn.sigmoid(tx[sel]) - tgt_x) ** 2
            + (jax.nn.sigmoid(ty[sel]) - tgt_y) ** 2
            + (tw[sel] - tgt_w) ** 2 + (th[sel] - tgt_h) ** 2)
        cls_t = jax.nn.one_hot(gl[:, bi], class_num)
        if use_label_smooth:
            delta = 1.0 / max(class_num, 1)
            cls_t = cls_t * (1.0 - delta) + delta / class_num
        clg = cls_logit[bidx, best, :, gj, gi]
        bce = jnp.logaddexp(0.0, clg) - cls_t * clg
        loss = loss + vf * jnp.sum(bce, axis=-1)
        obj_target = obj_target.at[sel].set(
            jnp.maximum(obj_target[sel], vf))
    obj_bce = jnp.logaddexp(0.0, obj_logit) - obj_target * obj_logit
    # matched cells always count; unmatched count unless ignored by IoU
    obj_weight = jnp.maximum(obj_weight_base, obj_target)
    loss = loss + jnp.sum(obj_bce * obj_weight, axis=(1, 2, 3))
    return loss
