"""XLA device-trace (xplane) ingestion: merge device spans into the host
chrome trace and aggregate per-op device time.

Reference analog: the reference merges its host tracer and CUPTI device
tracer into ONE chrome timeline
(paddle/fluid/platform/profiler/chrometracing_logger.cc) and reports per-op
device-time tables (python/paddle/profiler/profiler_statistic.py). On TPU
the device tracer is XLA's own profiler: jax.profiler.start_trace writes an
.xplane.pb whose planes carry the per-kernel device spans. This module reads
it back via jax.profiler.ProfileData (no TensorBoard needed) and translates
event times onto the host clock so both layers land in one timeline.

Clock model: collect_device_events normalizes every event onto a
trace-relative clock (earliest collected event = 0): the raw xplane epoch
differs across builds (trace start on some, PROCESS start on the
jax 0.4.37 CPU tracer), so the only portable anchor is the trace's own
first event. The Profiler records host perf_counter_ns immediately after
jax.profiler.start_trace returns (xla_t0_ns); device-absolute =
xla_t0_ns + event.start_ns — the same translate-to-host-clock correlation
the reference applies to CUPTI timestamps.

Readers, tried in order (first available wins):

1. ``jax.profiler.ProfileData`` — newer jax wheels bundle the xplane
   reader;
2. the raw ``xplane.pb`` proto via tensorflow's bundled
   ``tsl.profiler.protobuf.xplane_pb2`` — jax 0.4.37 ships no reader, but
   the wire format is the same XSpace proto.
"""
from __future__ import annotations

import glob
import os

__all__ = ["collect_device_events", "device_op_stats"]

# lines/events that are scheduler noise rather than op execution
_SKIP_EVENT_PREFIXES = ("ThreadpoolListener::", "TaskDispatcher::", "end: ")
_SKIP_LINE_NAMES = ("python",)


def _iter_xplane_files(trace_dir):
    return sorted(glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                            recursive=True))


def _is_device_plane(name):
    return name.startswith("/device:")


def _iter_events_profile_data(path):
    """(plane, line, name, start_ns, dur_ns, stats) via the bundled reader
    of newer jax wheels. Raises ImportError when unavailable."""
    from jax.profiler import ProfileData

    pd = ProfileData.from_file(path)
    for plane in pd.planes:
        for line in plane.lines:
            for ev in line.events:
                stats = {}
                try:
                    stats = dict(ev.stats)
                except Exception:  # noqa: BLE001 - stats are optional
                    pass
                yield (plane.name, line.name, ev.name,
                       float(ev.start_ns), float(ev.duration_ns), stats)


def _stat_value(stat, stat_metadata):
    """Decode one XStat: strings usually arrive as ref_value indices into
    the plane's stat_metadata (string interning), scalars as oneof fields."""
    which = stat.WhichOneof("value")
    if which is None:
        return None
    if which == "ref_value":
        meta = stat_metadata.get(stat.ref_value)
        return meta.name if meta is not None else None
    return getattr(stat, which)


def _iter_events_proto(path):
    """(plane, line, name, start_ns, dur_ns, stats) straight off the
    XSpace proto — jax 0.4.37 writes the trace but ships no reader, so
    parse with tensorflow's tsl xplane_pb2 (same wire format). Raises
    ImportError when tensorflow's protos are unavailable."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())
    for plane in xs.planes:
        emeta = plane.event_metadata
        smeta = plane.stat_metadata
        for line in plane.lines:
            base_ns = float(line.timestamp_ns)
            for ev in line.events:
                meta = emeta.get(ev.metadata_id)
                name = meta.name if meta is not None else ""
                stats = {}
                for s in ev.stats:
                    sm = smeta.get(s.metadata_id)
                    if sm is not None:
                        stats[sm.name] = _stat_value(s, smeta)
                yield (plane.name, line.name, name,
                       base_ns + ev.offset_ps / 1e3, ev.duration_ps / 1e3,
                       stats)


def _iter_events(path):
    for reader in (_iter_events_profile_data, _iter_events_proto):
        try:
            return list(reader(path))
        except ImportError:
            continue
        except Exception:  # noqa: BLE001 - partial/foreign traces: skip file
            return []
    return []


def collect_device_events(trace_dir, limit=200000):
    """Read every device-side op span from the trace dir.

    Returns a list of dicts: {plane, line, name, start_ns, dur_ns, hlo_module}
    with start_ns NORMALIZED to the trace (earliest collected event = 0 —
    the raw epoch is build-dependent, see module docstring). Device planes
    ("/device:TPU:N") contribute every op event; the "/host:CPU" plane
    (XLA-CPU backend, used by the virtual-mesh tests) contributes only
    events carrying an hlo_op stat so python-tracing noise stays out.
    Never raises — an unreadable trace yields []."""
    out = []
    for path in _iter_xplane_files(trace_dir):
        for plane_name, line_name, name, start_ns, dur_ns, stats \
                in _iter_events(path):
            if line_name in _SKIP_LINE_NAMES:
                continue
            if any(name.startswith(p) for p in _SKIP_EVENT_PREFIXES):
                continue
            on_device = _is_device_plane(plane_name)
            if not on_device and "hlo_op" not in stats \
                    and "hlo_module" not in stats:
                continue
            out.append({
                "plane": plane_name,
                "line": line_name,
                "name": name,
                "start_ns": start_ns,
                "dur_ns": dur_ns,
                "hlo_module": stats.get("hlo_module"),
            })
            if len(out) >= limit:
                break
        if len(out) >= limit:
            break
    return _normalize_clock(out)


_CLUSTER_GAP_NS = 5e9   # a >5s hole in device activity marks a foreign epoch


def _normalize_clock(events):
    """Shift start_ns onto a trace-relative clock (earliest event of the
    DOMINANT cluster = 0). The jax 0.4.37 CPU tracer stamps a handful of
    events without the session base (they land seconds away from the real
    cluster); anchoring on the raw min would shove the whole timeline off
    the host window. Only GLITCH-sized minorities are dropped: at a >5s
    silence, a side holding under max(16, 1%) of the events is discarded;
    a real multi-burst trace (two serving waves seconds apart) keeps every
    burst, separated by its true gap."""
    if not events:
        return events
    events.sort(key=lambda ev: ev["start_ns"])
    lo, hi = 0, len(events)
    glitch = max(16, len(events) // 100)
    for _ in range(8):
        gap_at, gap = None, _CLUSTER_GAP_NS
        for i in range(lo + 1, hi):
            d = events[i]["start_ns"] - events[i - 1]["start_ns"]
            if d > gap:
                gap_at, gap = i, d
        if gap_at is None:
            break
        left, right = gap_at - lo, hi - gap_at
        if right <= glitch and right < left:
            hi = gap_at
        elif left <= glitch and left < right:
            lo = gap_at
        else:
            break   # both sides real: keep the whole trace
    kept = events[lo:hi]
    t0 = kept[0]["start_ns"]
    for ev in kept:
        ev["start_ns"] -= t0
    return kept


def device_op_stats(device_events):
    """Aggregate device spans per op name (the reference's per-op
    device-time table): calls, total/avg/max ns, share of device time.
    Rows sort by total time descending."""
    agg = {}
    for ev in device_events:
        row = agg.setdefault(ev["name"], {
            "name": ev["name"], "calls": 0, "total_ns": 0.0, "max_ns": 0.0,
            "hlo_module": ev.get("hlo_module")})
        row["calls"] += 1
        row["total_ns"] += ev["dur_ns"]
        row["max_ns"] = max(row["max_ns"], ev["dur_ns"])
    total = sum(r["total_ns"] for r in agg.values()) or 1.0
    rows = sorted(agg.values(), key=lambda r: -r["total_ns"])
    for r in rows:
        r["avg_ns"] = r["total_ns"] / r["calls"]
        r["ratio"] = r["total_ns"] / total
    return rows


def chrome_events(device_events, xla_t0_ns, base_pid=900000):
    """Translate device spans into chrome-trace dicts on the host clock.
    One chrome pid per plane, one tid per line, with metadata naming."""
    pids, tids, out = {}, {}, []
    for ev in device_events:
        if ev["plane"] not in pids:
            pid = base_pid + len(pids)
            pids[ev["plane"]] = pid
            out.append({"name": "process_name", "ph": "M", "pid": pid,
                        "tid": 0, "args": {"name": f"XLA {ev['plane']}"}})
        pid = pids[ev["plane"]]
        lkey = (ev["plane"], ev["line"])
        if lkey not in tids:
            tid = len(tids) + 1
            tids[lkey] = tid
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tid, "args": {"name": ev["line"]}})
        out.append({
            "name": ev["name"],
            "cat": "DeviceOp",
            "ph": "X",
            "ts": (xla_t0_ns + ev["start_ns"]) / 1e3,
            "dur": max(ev["dur_ns"], 1.0) / 1e3,
            "pid": pid,
            "tid": tids[lkey],
            "args": {k: v for k, v in (("hlo_module", ev["hlo_module"]),)
                     if v},
        })
    return out
