"""paddle.inference: the deploy-engine API veneer.

Reference analog: paddle/fluid/inference/api/ AnalysisPredictor surfaced as
python paddle.inference (Config -> create_predictor -> get_input_handle /
run / get_output_handle; paddle_infer tutorial flow). TPU-first redesign: the
"analysis + IR fusion + engine subgraphs" pipeline IS XLA — a saved program
(jit.save's StableHLO-backed artifact) reloads as one compiled callable, so
the Predictor is a thin stateful shell holding named input/output handles
around that callable. GPU/TensorRT/MKLDNN toggles are accepted for API
compatibility and recorded; device placement follows the active platform.
"""
from __future__ import annotations

import numpy as np

import jax

from .framework.core import Tensor


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


class PlaceType:
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM = 3


class Config:
    """Predictor configuration (inference_api.cc Config / AnalysisConfig)."""

    def __init__(self, prog_file=None, params_file=None):
        # jit.save artifacts use one path prefix; accept both call shapes
        self.prog_file = prog_file
        self.params_file = params_file
        self._model_dir = prog_file
        self._use_gpu = False
        self._device_id = 0
        self._enable_memory_optim = True
        self._switch_ir_optim = True
        self._cpu_math_threads = 1
        self._precision = PrecisionType.Float32
        self._extra = {}

    # -- device toggles (recorded; XLA owns actual placement) ---------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0,
                       precision=PrecisionType.Float32):
        self._use_gpu = True
        self._device_id = device_id
        self._precision = precision

    def disable_gpu(self):
        self._use_gpu = False

    def use_gpu(self):
        return self._use_gpu

    def enable_xpu(self, *a, **k):
        self._extra["xpu"] = True

    def enable_custom_device(self, device_type, device_id=0):
        self._extra["custom_device"] = (device_type, device_id)

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_math_threads = int(n)

    # -- optimization toggles (XLA always fuses; kept for API parity) -------
    def switch_ir_optim(self, flag=True):
        self._switch_ir_optim = bool(flag)

    def enable_memory_optim(self, flag=True):
        self._enable_memory_optim = bool(flag)

    def enable_tensorrt_engine(self, *a, **k):
        self._extra["tensorrt"] = True  # no-op: XLA is the engine

    def enable_mkldnn(self):
        self._extra["mkldnn"] = True

    def set_model(self, prog_file, params_file=None):
        self.prog_file = prog_file
        self.params_file = params_file
        self._model_dir = prog_file

    def exp_set_warmup_shapes(self, shapes):
        """Input shapes to AOT-compile at predictor creation (the analysis
        pass + engine-build role of analysis_predictor.cc, TPU-natively: each
        shape's executable is compiled ONCE at load and every run() with that
        shape is a cache hit). Each entry is one input's shape tuple, or a
        (shape, dtype) pair for non-float inputs (e.g. ((1, 128), "int32"))."""
        norm = []
        for s in shapes:
            if len(s) == 2 and isinstance(s[1], str):
                norm.append((tuple(s[0]), s[1]))
            else:
                norm.append((tuple(s), "float32"))
        self._extra["warmup_shapes"] = norm

    def model_dir(self):
        return self._model_dir

    def summary(self):
        return (f"Config(model={self._model_dir}, use_gpu={self._use_gpu}, "
                f"ir_optim={self._switch_ir_optim})")


class _IOHandle:
    """Named input/output tensor handle (ZeroCopyTensor analog)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def reshape(self, shape):
        pass  # shapes flow from copy_from_cpu; kept for API parity

    def copy_from_cpu(self, arr):
        self._value = np.asarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def share_external_data(self, arr):
        self.copy_from_cpu(np.asarray(arr))


class Predictor:
    """Compiled-program predictor (AnalysisPredictor analog).

    Wraps a translated function from paddle.jit.load: run() feeds the input
    handles in declaration order, executes the compiled program, and fills
    the output handles.
    """

    def __init__(self, config: Config):
        from . import jit

        self.config = config
        self._fn = jit.load(config.prog_file)
        names = list(getattr(self._fn, "_input_names", None) or ["input_0"])
        self._inputs = {n: _IOHandle(n) for n in names}
        self._input_order = names
        self._outputs = []
        self._warmed_shapes = []
        for shape, dtype in config._extra.get("warmup_shapes", []):
            try:
                self._warm(shape, dtype)
            except Exception as e:  # noqa: BLE001 - warmup is best-effort:
                # a bad shape/dtype must not abort predictor construction
                import warnings

                warnings.warn(f"predictor warmup for {shape} ({dtype}) "
                              f"failed: {e}", stacklevel=2)

    def _warm(self, shape, dtype="float32"):
        """AOT-compile the executable for one input shape (XLA jit cache).
        Single-input programs only — multi-input programs warm on first run."""
        import jax.numpy as jnp

        if len(self._input_order) != 1:
            raise ValueError(
                "warmup shapes support single-input programs; this program "
                f"takes {len(self._input_order)} inputs")
        sample = Tensor(jnp.zeros(shape, jnp.dtype(dtype)))
        out = self._fn(sample)
        jax.block_until_ready(
            out[0].value if isinstance(out, (tuple, list)) else out.value)
        self._warmed_shapes.append(tuple(shape))

    def get_input_names(self):
        return list(self._input_order)

    def get_input_handle(self, name):
        return self._inputs[name]

    def run(self, inputs=None):
        """Execute. `inputs` (list of arrays) may bypass the handle API."""
        if inputs is not None:
            for n, a in zip(self._input_order, inputs):
                self._inputs[n].copy_from_cpu(a)
        args = [Tensor(jax.numpy.asarray(self._inputs[n]._value))
                for n in self._input_order]
        out = self._fn(*args)
        outs = out if isinstance(out, (tuple, list)) else [out]
        self._outputs = []
        for i, o in enumerate(outs):
            h = _IOHandle(f"output_{i}")
            h._value = np.asarray(o.numpy() if hasattr(o, "numpy") else o)
            self._outputs.append(h)
        if inputs is not None:
            return [h.copy_to_cpu() for h in self._outputs]
        return None

    def get_output_names(self):
        return [h.name for h in self._outputs] or ["output_0"]

    def get_output_handle(self, name):
        for h in self._outputs:
            if h.name == name:
                return h
        raise KeyError(name)

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def get_version():
    from . import __version__

    return __version__


__all__ = ["Config", "Predictor", "create_predictor", "PrecisionType",
           "PlaceType", "get_version"]
