"""paddle_tpu.mesh — real SPMD mesh execution.

The execution layer under the ``distributed/`` API surface: where
``process_mesh``/``placement``/``fleet`` describe *how tensors should be
laid out*, this package actually *runs* multi-device programs on a
``jax.sharding.Mesh`` — CPU-simulated 8-device meshes included
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``), so every piece
is tier-1 testable without hardware.

Pieces (docs/distributed.md):

- :mod:`~paddle_tpu.mesh.context` — ``MeshContext``: ProcessMesh ->
  ``jax.sharding.Mesh`` lowering + the placement -> ``PartitionSpec``
  mapping, with the manual/auto axis split the train step uses;
- :mod:`~paddle_tpu.mesh.spmd_rules` — the per-op SPMD rule registry:
  sharding-spec propagation through ``defop`` outputs and EXPLICIT
  resharding (all-gather / reduce-scatter / all-to-all, emitted by XLA
  from a placement change) only where specs disagree;
- :mod:`~paddle_tpu.mesh.zero` — ZeRO-1 flatten/scatter/gather helpers
  (cross-replica weight-update sharding, arXiv 2004.13336);
- :mod:`~paddle_tpu.mesh.comm_opt` — the communication-efficiency
  layer: int8/fp8 quantized grad reduction with error-feedback
  residuals (EQuARX, arXiv 2506.17615), bucketed backward-overlapped
  grad collectives, and the multi-hop reshard router (arXiv
  2112.01075) the SPMD rule engine lowers placement changes through;
- :mod:`~paddle_tpu.mesh.parallelize` — lowers fleet hybrid configs
  (dp_degree/mp_degree) onto mesh axes and runs the real train step
  under ``shard_map`` with donated sharded state;
- :mod:`~paddle_tpu.mesh.trainer` — ``MeshTrainer``: fault-tolerant
  training on top of ``parallelize`` (async sharded checkpoints, step
  watchdog, drilled warm recovery with a bounded fit() retry loop).
"""
from .context import (MeshContext, bootstrap_virtual_devices,  # noqa: F401
                      current_mesh_context, spec_for_placements)
from .comm_opt import (CommOptConfig, classify_placement_change,  # noqa: F401
                       route_spec_change)
from .spmd_rules import (ReshardFault, disable_propagation,  # noqa: F401
                         enable_propagation, propagate, rule_for,
                         sharding_rule)
from .parallelize import MeshParallel, build_mesh_step, parallelize  # noqa: F401
from .trainer import MeshTrainer, TrainStepSuperseded  # noqa: F401

__all__ = [
    "MeshContext", "bootstrap_virtual_devices", "current_mesh_context",
    "spec_for_placements",
    "CommOptConfig", "classify_placement_change", "route_spec_change",
    "sharding_rule", "rule_for", "propagate", "enable_propagation",
    "disable_propagation", "ReshardFault",
    "MeshParallel", "build_mesh_step", "parallelize",
    "MeshTrainer", "TrainStepSuperseded",
]
