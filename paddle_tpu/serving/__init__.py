"""Serving-tier orchestration above the single engine.

``paddle_tpu.models.serving`` is ONE continuous-batching engine;
this package is the layer that makes N of them a fleet:

- :mod:`paddle_tpu.serving.fleet` — :class:`FleetRouter`, the
  health-checked multi-replica router (failover, tail hedging,
  graceful drain) of docs/serving.md's "Fleet" section.

Importing this package is cheap (no jax work beyond what the engine
module itself already did); the router spawns its threads only when
constructed.
"""
from .fleet import (FleetRouter, FleetUnavailable, HEALTHY, SUSPECT,
                    DOWN, DRAINING, PARKED)

__all__ = ["FleetRouter", "FleetUnavailable", "HEALTHY", "SUSPECT",
           "DOWN", "DRAINING", "PARKED"]
