"""graftir passes GI001–GI004: invariants of the traced programs that
actually run on the device, checked statically over their jaxprs.
(The graftnum precision passes GI005–GI007 live in ``precision.py``
and join :data:`ALL_PASSES` below.)

Each pass encodes one hazard class the test suite cannot cheaply see:

- GI001 collective-consistency — divergent collective sequences across
  ``cond`` branches (and collectives over axes no enclosing shard_map
  binds) are SPMD deadlocks: one device enters an all-reduce its peers
  never reach;
- GI002 donation-safety — a donated invar that aliases NO output wastes
  its donation (HBM silently doubled: the runtime keeps input and
  output buffers both); a donated invar read after its aliased output
  materializes forces a defensive copy; a large un-donated invar that
  flows to a same-shaped output is a donation left on the table;
- GI003 hbm-budget — the static per-device peak (hbm.py) must fit the
  declared per-program budget manifest (budgets.json);
- GI004 fusion-opportunity — convert round-trips severing elementwise
  chains, duplicated expensive subexpressions (missed CSE), and operand
  shardings pinned to disagreeing specs (a GSPMD reshard collective the
  ``paddle_tpu_mesh_reshards_total`` counter will pay at run time) —
  the statically visible shapes from "Operator Fusion in XLA"
  (arXiv 2301.13062).

Rationale long-forms live in docs/ir_analysis.md.
"""
from __future__ import annotations

from . import collectives as _coll
from . import hbm as _hbm
from .ir import IRPass, _aval_bytes
from .precision import LossScaleCoverage, NumericHazard, PrecisionFlow

__all__ = ["CollectiveConsistency", "DonationSafety", "HBMBudget",
           "FusionOpportunity", "PrecisionFlow", "NumericHazard",
           "LossScaleCoverage", "ALL_PASSES", "PASSES_BY_ID"]


def _is_var(v):
    return hasattr(v, "aval") and not hasattr(v, "val")


#: primitives expensive enough that a duplicate is worth flagging —
#: ONE set shared by GI004's lint and graftopt's CSE rewrite (opt.py),
#: so what the lint flags is exactly what the rewrite folds
EXPENSIVE_PRIMS = frozenset({
    "dot_general", "conv_general_dilated", "exp", "log", "rsqrt",
    "sqrt", "tanh", "erf", "logistic", "integer_pow", "pow", "div",
    "reduce_sum", "reduce_max", "reduce_min", "cumsum", "cumlogsumexp",
    "sort", "argmax", "argmin",
})


def eqn_structural_key(eqn):
    """Structural identity of one sub-jaxpr-free eqn: primitive, params,
    and operands — vars by identity, LITERALS by value+dtype (the
    per-parameter bias-correction ``pow(beta, step)`` shape). The one
    key both GI004's duplicate lint and graftopt's CSE fold on."""
    ops = []
    for v in eqn.invars:
        if _is_var(v):
            ops.append(id(v))
        else:
            aval = getattr(v, "aval", None)
            ops.append(("lit", repr(getattr(v, "val", None)),
                        str(getattr(aval, "dtype", "?"))))
    params = tuple(sorted((k, repr(v)) for k, v in eqn.params.items()))
    return (eqn.primitive.name, params, tuple(ops))


def _walk_eqns(jaxpr, path=""):
    """(path, jaxpr, eqn_index, eqn) over every level, depth-first."""
    for i, eqn in enumerate(jaxpr.eqns):
        yield path, jaxpr, i, eqn
        for slot, sub in _coll.iter_subjaxprs(eqn):
            sub_path = f"{path}/{eqn.primitive.name}[{i}].{slot}" \
                if path else f"{eqn.primitive.name}[{i}].{slot}"
            yield from _walk_eqns(sub, sub_path)


def _fmt_seq(seq):
    return "[" + ", ".join(
        f"{name}@{'+'.join(axes) if axes else '?'}"
        for name, axes in seq) + "]"


class CollectiveConsistency(IRPass):
    """GI001: every device of the mesh must execute the SAME collective
    sequence. A ``cond`` whose branches disagree (one psums, the other
    doesn't — or they psum over different axes) deadlocks the mesh the
    first time the predicate diverges across devices; a collective over
    an axis no enclosing shard_map binds never lowers to a real ring at
    all. This is the first trap the 1F1B pipeline schedule (ROADMAP
    item 1) will spring: per-stage branches with per-stage collective
    mixes."""

    id = "GI001"
    name = "collective-consistency"
    rationale = ("mismatched collective sequences across branches or "
                 "unbound collective axes deadlock the SPMD mesh")

    def check(self, program):
        out = []
        self._visit(program, program.jaxpr, "", (), out)
        return out

    def _visit(self, program, jaxpr, path, bound_axes, out):
        for i, eqn in enumerate(jaxpr.eqns):
            name = eqn.primitive.name
            here = f"{path}/{name}[{i}]" if path else f"{name}[{i}]"
            canon = _coll.COLLECTIVE_PRIMITIVES.get(name)
            if canon is not None:
                axes = _coll._axis_names(eqn)
                missing = [a for a in axes if a not in bound_axes]
                if missing:
                    out.append(self.finding(
                        program, here,
                        f"collective {canon} over axis "
                        f"{'/'.join(missing)} with no enclosing "
                        "shard_map binding it — the op cannot lower to "
                        "a device ring"))
            if name == "cond":
                seqs = [_coll.collective_sequence(
                            getattr(b, "jaxpr", b))
                        for b in eqn.params.get("branches", ())]
                if len(set(seqs)) > 1 and any(seqs):
                    desc = " vs ".join(_fmt_seq(s) for s in seqs)
                    out.append(self.finding(
                        program, here,
                        f"collective sequence diverges across cond "
                        f"branches ({desc}) — if the predicate differs "
                        "across devices the mesh deadlocks"))
            new_axes = bound_axes
            if name == "shard_map":
                mesh = eqn.params.get("mesh")
                axis_names = tuple(getattr(mesh, "axis_names", ()))
                auto = eqn.params.get("auto", frozenset())
                new_axes = bound_axes + tuple(
                    a for a in axis_names if a not in auto)
            for slot, sub in _coll.iter_subjaxprs(eqn):
                self._visit(program, sub, f"{here}.{slot}", new_axes, out)


class DonationSafety(IRPass):
    """GI002: the donation contract of a donated, jitted step. Donation
    is the mechanism that lets params/pools update in place; broken
    donation doesn't crash — it silently doubles residency or inserts
    copies, and only shows up as an OOM one batch-size later."""

    id = "GI002"
    name = "donation-safety"
    rationale = ("unaliased or re-read donated buffers silently double "
                 "HBM / insert defensive copies")

    # an un-donated invar at least this large, flowing to a same-shaped
    # output, is a donation left on the table
    LARGE_BYTES = 1 << 20

    def check(self, program):
        out = []
        jaxpr = program.jaxpr
        donated = program.donated
        if len(donated) != len(jaxpr.invars):
            return out

        def _key(v):
            aval = v.aval
            return (tuple(getattr(aval, "shape", ())),
                    str(getattr(aval, "dtype", "?")))

        out_keys = {}
        for v in jaxpr.outvars:
            if _is_var(v):
                out_keys[_key(v)] = out_keys.get(_key(v), 0) + 1

        # producer eqn index per var + last use per invar, top level
        producer = {}
        for i, eqn in enumerate(jaxpr.eqns):
            for ov in eqn.outvars:
                producer[id(ov)] = i
        last_use = {}
        for i, eqn in enumerate(jaxpr.eqns):
            for v in eqn.invars:
                if _is_var(v):
                    last_use[id(v)] = i

        avail = dict(out_keys)
        for idx, (v, d) in enumerate(zip(jaxpr.invars, donated)):
            if not d:
                continue
            k = _key(v)
            if avail.get(k, 0) > 0:
                avail[k] -= 1
            else:
                out.append(self.finding(
                    program, f"invar[{idx}]",
                    f"donated invar {k[1]}{list(k[0])} aliases no "
                    "output — the donation is wasted and the buffer is "
                    "silently kept alongside its successor (doubled "
                    "HBM)"))
                continue
            # latest producer of ANY output this invar could alias: a
            # read after that point would read an overwritten buffer, so
            # the runtime must copy defensively
            latest = max((producer.get(id(ov), -1)
                          for ov in jaxpr.outvars
                          if _is_var(ov) and _key(ov) == k), default=-1)
            if latest >= 0 and last_use.get(id(v), -1) > latest:
                out.append(self.finding(
                    program, f"invar[{idx}]",
                    f"donated invar {k[1]}{list(k[0])} is read after "
                    "every output it could alias is already "
                    "materialized — the aliasing forces a defensive "
                    "copy of the whole buffer"))

        if any(donated):
            for idx, (v, d) in enumerate(zip(jaxpr.invars, donated)):
                if d or not _is_var(v):
                    continue
                b = _aval_bytes(v.aval)
                if b >= self.LARGE_BYTES and out_keys.get(_key(v), 0) > 0:
                    out.append(self.finding(
                        program, f"invar[{idx}]",
                        f"large un-donated invar "
                        f"{_key(v)[1]}{list(_key(v)[0])} "
                        f"({b >> 20} MiB) flows to a same-shaped output "
                        "in a step that already donates — donate it or "
                        "pay double residency for the state"))
        return out


class HBMBudget(IRPass):
    """GI003: the static per-device peak (hbm.py liveness walk) must fit
    the program's declared budget from budgets.json. Programs without a
    manifest row only report (the estimate lands in ``program.meta``);
    the three flagship programs MUST have rows."""

    id = "GI003"
    name = "hbm-budget"
    rationale = ("a declared per-program HBM budget catches peak-"
                 "residency regressions before the OOM does")

    def __init__(self, budgets=None):
        self._budgets = budgets

    def check(self, program):
        budgets = self._budgets if self._budgets is not None \
            else _hbm.load_budgets()
        est = _hbm.estimate(program)
        program.meta["hbm_estimate"] = est
        budget = budgets.get(program.name)
        if budget is None:
            return []
        if est["peak_bytes"] > budget:
            return [self.finding(
                program, "",
                f"estimated per-device peak {est['peak_bytes']} bytes "
                f"exceeds the declared budget {budget} bytes "
                f"(args={est['args_bytes']}, consts="
                f"{est['consts_bytes']}, donated="
                f"{est['donated_bytes']})")]
        return []


class FusionOpportunity(IRPass):
    """GI004: statically visible missed-fusion shapes. None of these are
    wrong results — each is a buffer XLA materializes (or a collective
    GSPMD inserts) that a small rewrite avoids, and the decode/train hot
    paths pay it every step."""

    id = "GI004"
    name = "fusion-opportunity"
    rationale = ("convert churn, duplicate subexpressions and "
                 "disagreeing operand shardings each cost an avoidable "
                 "buffer or collective per step")

    EXPENSIVE = EXPENSIVE_PRIMS

    def check(self, program):
        out = []
        for path, jaxpr in self._jaxpr_levels(program.jaxpr):
            self._convert_churn(program, path, jaxpr, out)
            self._duplicates(program, path, jaxpr, out)
            self._sharding_disagreement(program, path, jaxpr, out)
        return out

    # -- helpers -------------------------------------------------------------
    def _jaxpr_levels(self, jaxpr, path=""):
        yield path, jaxpr
        for i, eqn in enumerate(jaxpr.eqns):
            for slot, sub in _coll.iter_subjaxprs(eqn):
                sub_path = f"{path}/{eqn.primitive.name}[{i}].{slot}" \
                    if path else f"{eqn.primitive.name}[{i}].{slot}"
                yield from self._jaxpr_levels(sub, sub_path)

    def _convert_churn(self, program, path, jaxpr, out):
        producer = {}
        for i, eqn in enumerate(jaxpr.eqns):
            for ov in eqn.outvars:
                producer[id(ov)] = eqn
        for i, eqn in enumerate(jaxpr.eqns):
            if eqn.primitive.name != "convert_element_type":
                continue
            src = eqn.invars[0]
            if not _is_var(src):
                continue
            prev = producer.get(id(src))
            if prev is None or prev.primitive.name != "convert_element_type":
                continue
            origin = prev.invars[0]
            o_dt = getattr(getattr(origin, "aval", None), "dtype", None)
            mid_dt = getattr(src.aval, "dtype", None)
            new_dt = getattr(eqn.outvars[0].aval, "dtype", None)
            if o_dt is not None and o_dt == new_dt and mid_dt != new_dt:
                where = f"{path}/convert[{i}]" if path else f"convert[{i}]"
                out.append(self.finding(
                    program, where,
                    f"convert round-trip {o_dt} -> {mid_dt} -> {new_dt} "
                    "severs the elementwise chain — two casts and an "
                    "extra buffer for a no-op"))

    def _duplicates(self, program, path, jaxpr, out):
        seen = {}
        for i, eqn in enumerate(jaxpr.eqns):
            name = eqn.primitive.name
            if name not in self.EXPENSIVE:
                continue
            if next(_coll.iter_subjaxprs(eqn), None) is not None:
                continue
            key = eqn_structural_key(eqn)
            first = seen.get(key)
            if first is None:
                seen[key] = i
                continue
            where = f"{path}/{name}[{i}]" if path else f"{name}[{i}]"
            out.append(self.finding(
                program, where,
                f"duplicated subexpression: {name} over the same "
                f"operands already computed at eqn {first} — XLA does "
                "not CSE across fusion boundaries; hoist it"))

    def _sharding_disagreement(self, program, path, jaxpr, out):
        pinned = {}
        for eqn in jaxpr.eqns:
            if eqn.primitive.name != "sharding_constraint":
                continue
            spec = repr(getattr(eqn.params.get("sharding"), "spec",
                                eqn.params.get("sharding")))
            for ov in eqn.outvars:
                pinned[id(ov)] = spec
        for i, eqn in enumerate(jaxpr.eqns):
            if eqn.primitive.name == "sharding_constraint":
                continue
            specs = sorted({pinned[id(v)] for v in eqn.invars
                            if _is_var(v) and id(v) in pinned})
            if len(specs) > 1:
                where = f"{path}/{eqn.primitive.name}[{i}]" if path \
                    else f"{eqn.primitive.name}[{i}]"
                out.append(self.finding(
                    program, where,
                    f"operands pinned to disagreeing shardings "
                    f"({' vs '.join(specs)}) — GSPMD must insert a "
                    "reshard collective here (counted live in "
                    "paddle_tpu_mesh_reshards_total)"))


ALL_PASSES = (CollectiveConsistency(), DonationSafety(), HBMBudget(),
              FusionOpportunity(), PrecisionFlow(), NumericHazard(),
              LossScaleCoverage())
PASSES_BY_ID = {p.id: p for p in ALL_PASSES}
