"""paddle_tpu.optimizer (reference: python/paddle/optimizer)."""
from . import lr  # noqa: F401
from .optimizer import (  # noqa: F401
    ASGD, LBFGS, Adadelta, Adagrad, Adam, Adamax, AdamW, L1Decay, L2Decay, Lamb, Momentum,
    NAdam, Optimizer, RAdam, RMSProp, Rprop, SGD,
)
