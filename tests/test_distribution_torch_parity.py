"""paddle.distribution math vs torch.distributions goldens.

Reference analog: python/paddle/distribution/ (30+ families with
log_prob/entropy/kl). Distribution math (log-normalizers, entropy
integrals, KL closed forms) is where silent sign/constant errors live;
torch.distributions is the independent oracle. All in fp64.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distribution as D

pytestmark = pytest.mark.slow


def _t(x):
    import torch

    return torch.from_numpy(np.asarray(x, "float64"))


def _chk(got, want, rtol=1e-9, atol=1e-12, msg=""):
    np.testing.assert_allclose(np.asarray(getattr(got, "value", got)),
                               want.numpy(), rtol=rtol, atol=atol,
                               err_msg=msg)


_R = np.random.RandomState(0)


def _cases():
    import torch.distributions as TD

    loc = _R.randn(4)
    scale = np.abs(_R.randn(4)) + 0.3
    conc = np.abs(_R.randn(4)) + 0.5
    rate = np.abs(_R.randn(4)) + 0.2
    probs = np.abs(_R.rand(4)) * 0.8 + 0.1
    x_real = _R.randn(4)
    x_pos = np.abs(_R.randn(4)) + 0.2
    x_unit = _R.rand(4) * 0.8 + 0.1
    return [
        ("Normal", D.Normal(paddle.to_tensor(loc), paddle.to_tensor(scale)),
         TD.Normal(_t(loc), _t(scale)), x_real),
        ("Laplace", D.Laplace(paddle.to_tensor(loc), paddle.to_tensor(scale)),
         TD.Laplace(_t(loc), _t(scale)), x_real),
        ("Gumbel", D.Gumbel(paddle.to_tensor(loc), paddle.to_tensor(scale)),
         TD.Gumbel(_t(loc), _t(scale)), x_real),
        ("Cauchy", D.Cauchy(paddle.to_tensor(loc), paddle.to_tensor(scale)),
         TD.Cauchy(_t(loc), _t(scale)), x_real),
        ("Exponential",
         D.Exponential(paddle.to_tensor(rate)), TD.Exponential(_t(rate)),
         x_pos),
        ("Gamma", D.Gamma(paddle.to_tensor(conc), paddle.to_tensor(rate)),
         TD.Gamma(_t(conc), _t(rate)), x_pos),
        ("Beta", D.Beta(paddle.to_tensor(conc), paddle.to_tensor(rate)),
         TD.Beta(_t(conc), _t(rate)), x_unit),
        ("LogNormal",
         D.LogNormal(paddle.to_tensor(loc), paddle.to_tensor(scale)),
         TD.LogNormal(_t(loc), _t(scale)), x_pos),
        ("Bernoulli", D.Bernoulli(paddle.to_tensor(probs)),
         TD.Bernoulli(probs=_t(probs)),
         (_R.rand(4) > 0.5).astype("float64")),
        ("Poisson", D.Poisson(paddle.to_tensor(rate * 4)),
         TD.Poisson(_t(rate * 4)), np.array([0.0, 1, 3, 7])),
        ("Geometric", D.Geometric(paddle.to_tensor(probs)),
         TD.Geometric(probs=_t(probs)), np.array([0.0, 1, 2, 5])),
    ]


class TestLogProbEntropyParity:
    def test_log_prob_matches_torch(self):
        for name, pd, td, x in _cases():
            _chk(pd.log_prob(paddle.to_tensor(x)), td.log_prob(_t(x)),
                 msg=f"{name}.log_prob")

    def test_entropy_matches_torch(self):
        for name, pd, td, x in _cases():
            if name == "Poisson":
                continue  # torch's Poisson.entropy is NotImplemented
            _chk(pd.entropy(), td.entropy(), msg=f"{name}.entropy")

    def test_poisson_entropy_matches_series(self):
        """torch lacks Poisson.entropy; the oracle is the direct series
        -sum p_k log p_k (reference poisson.py:141 bounded-support sum)."""
        from scipy import stats

        rate = np.array([0.5, 2.0, 7.5])
        pd = D.Poisson(paddle.to_tensor(rate))
        want = np.array([stats.poisson(mu).entropy() for mu in rate])
        np.testing.assert_allclose(np.asarray(pd.entropy().value), want,
                                   rtol=1e-8, atol=1e-10)

    def test_mean_variance_match_torch(self):
        for name, pd, td, x in _cases():
            if name in ("Cauchy",):       # undefined mean/variance
                continue
            _chk(pd.mean, td.mean, msg=f"{name}.mean")
            _chk(pd.variance, td.variance, msg=f"{name}.variance")


class TestMultivariateParity:
    def test_dirichlet(self):
        import torch.distributions as TD

        conc = np.abs(_R.randn(5)) + 0.5
        x = np.abs(_R.rand(5)) + 0.1
        x = x / x.sum()
        pd = D.Dirichlet(paddle.to_tensor(conc))
        td = TD.Dirichlet(_t(conc))
        _chk(pd.log_prob(paddle.to_tensor(x)), td.log_prob(_t(x)))
        _chk(pd.entropy(), td.entropy())

    def test_multivariate_normal(self):
        import torch.distributions as TD

        loc = _R.randn(3)
        a = _R.randn(3, 3)
        cov = a @ a.T + 3 * np.eye(3)
        x = _R.randn(3)
        pd = D.MultivariateNormal(paddle.to_tensor(loc),
                                  covariance_matrix=paddle.to_tensor(cov))
        td = TD.MultivariateNormal(_t(loc), covariance_matrix=_t(cov))
        _chk(pd.log_prob(paddle.to_tensor(x)), td.log_prob(_t(x)),
             rtol=1e-8)
        _chk(pd.entropy(), td.entropy(), rtol=1e-8)

    def test_categorical_and_multinomial(self):
        import torch.distributions as TD

        logits = _R.randn(6)
        p = np.exp(logits) / np.exp(logits).sum()
        x = np.array([0.0, 2, 5])
        pd = D.Categorical(paddle.to_tensor(p))
        td = TD.Categorical(probs=_t(p))
        # log_prob: reference raw normalization == torch given probs input
        _chk(pd.log_prob(paddle.to_tensor(x)), td.log_prob(_t(x)))
        # entropy: the reference computes it in SOFTMAX space over the raw
        # input (categorical.py:292) — compare against that formula, not
        # torch (the reference's own internal inconsistency, mirrored)
        sm = np.exp(p) / np.exp(p).sum()
        want = -(sm * np.log(sm)).sum()
        np.testing.assert_allclose(float(np.asarray(pd.entropy().value)),
                                   want, rtol=1e-9)

        counts = np.array([1.0, 0, 2, 0, 1, 1])
        pm = D.Multinomial(5, paddle.to_tensor(p))
        tm = TD.Multinomial(5, probs=_t(p))
        # rtol 1e-7: the xlogy accumulation order differs across frameworks
        _chk(pm.log_prob(paddle.to_tensor(counts)),
             tm.log_prob(_t(counts)), rtol=1e-7, atol=1e-9)


class TestKLParity:
    def test_kl_divergence_closed_forms(self):
        import torch.distributions as TD

        l1, l2 = _R.randn(4), _R.randn(4)
        s1 = np.abs(_R.randn(4)) + 0.3
        s2 = np.abs(_R.randn(4)) + 0.3
        c1 = np.abs(_R.randn(4)) + 0.5
        c2 = np.abs(_R.randn(4)) + 0.5

        pairs = [
            (D.Normal(paddle.to_tensor(l1), paddle.to_tensor(s1)),
             D.Normal(paddle.to_tensor(l2), paddle.to_tensor(s2)),
             TD.Normal(_t(l1), _t(s1)), TD.Normal(_t(l2), _t(s2))),
            (D.Beta(paddle.to_tensor(c1), paddle.to_tensor(c2)),
             D.Beta(paddle.to_tensor(c2), paddle.to_tensor(c1)),
             TD.Beta(_t(c1), _t(c2)), TD.Beta(_t(c2), _t(c1))),
            (D.Gamma(paddle.to_tensor(c1), paddle.to_tensor(s1)),
             D.Gamma(paddle.to_tensor(c2), paddle.to_tensor(s2)),
             TD.Gamma(_t(c1), _t(s1)), TD.Gamma(_t(c2), _t(s2))),
        ]
        import torch

        for pp, pq, tp, tq in pairs:
            _chk(D.kl_divergence(pp, pq),
                 torch.distributions.kl_divergence(tp, tq), rtol=1e-8,
                 msg=type(pp).__name__)


class TestTransformParity:
    """Transform jacobian math vs torch.distributions.transforms: forward/
    inverse and log|det J| are the error-prone parts (sign conventions,
    chain composition order)."""

    def test_affine_exp_sigmoid_jacobians(self):
        import torch
        import torch.distributions.transforms as TT

        x = _R.randn(5)
        pairs = [
            (D.AffineTransform(paddle.to_tensor(np.array(2.0)),
                               paddle.to_tensor(np.array(3.0))),
             TT.AffineTransform(torch.tensor(2.0, dtype=torch.float64),
                                torch.tensor(3.0, dtype=torch.float64))),
            (D.ExpTransform(), TT.ExpTransform()),
            (D.SigmoidTransform(), TT.SigmoidTransform()),
        ]
        for pt, tt in pairs:
            name = type(pt).__name__
            tx = torch.from_numpy(x)
            want_y = tt(tx)
            got_y = pt.forward(paddle.to_tensor(x))
            np.testing.assert_allclose(np.asarray(got_y.value),
                                       want_y.numpy(), rtol=1e-9,
                                       err_msg=f"{name}.forward")
            want_ldj = tt.log_abs_det_jacobian(tx, want_y)
            got_ldj = pt.forward_log_det_jacobian(paddle.to_tensor(x))
            np.testing.assert_allclose(np.asarray(got_ldj.value),
                                       want_ldj.numpy(), rtol=1e-9,
                                       err_msg=f"{name}.ldj")
            back = pt.inverse(got_y)
            np.testing.assert_allclose(np.asarray(back.value), x,
                                       rtol=1e-8, atol=1e-10,
                                       err_msg=f"{name}.inverse")

    def test_transformed_distribution_log_prob(self):
        import torch
        import torch.distributions as TD

        loc = _R.randn(4)
        scale = np.abs(_R.randn(4)) + 0.3
        base_p = D.Normal(paddle.to_tensor(loc), paddle.to_tensor(scale))
        base_t = TD.Normal(_t(loc), _t(scale))

        # log-normal via ExpTransform
        pd = D.TransformedDistribution(base_p, [D.ExpTransform()])
        td = TD.TransformedDistribution(base_t, [TD.ExpTransform()])
        x = np.abs(_R.randn(4)) + 0.2
        _chk(pd.log_prob(paddle.to_tensor(x)), td.log_prob(_t(x)),
             rtol=1e-9, msg="exp-transformed")

        # affine chain: y = 2*x + 1 after exp
        pd2 = D.TransformedDistribution(
            base_p, [D.ExpTransform(),
                     D.AffineTransform(paddle.to_tensor(np.array(1.0)),
                                       paddle.to_tensor(np.array(2.0)))])
        td2 = TD.TransformedDistribution(
            base_t, [TD.ExpTransform(),
                     TD.AffineTransform(
                         torch.tensor(1.0, dtype=torch.float64),
                         torch.tensor(2.0, dtype=torch.float64))])
        y = np.abs(_R.randn(4)) * 2 + 1.5
        _chk(pd2.log_prob(paddle.to_tensor(y)), td2.log_prob(_t(y)),
             rtol=1e-9, msg="exp+affine chain")
