"""paddle._C_ops compatibility module.

Reference analog: the generated python-C op-function module
(fluid/eager/auto_code_generator/generator/python_c_gen.py -> paddle._C_ops)
— every yaml op as a C-accelerated function; huge amounts of downstream user
code calls `paddle._C_ops.<op>(...)` directly.

TPU-first form: there is no generated C layer — the defop registry IS the op
table — so this module resolves `_C_ops.foo` lazily (PEP 562) onto the same
callables the public namespaces expose: `paddle_tpu.ops` (including the
generated inplace `foo_` variants), `paddle_tpu.tensor` ops, and
`nn.functional`. Legacy `final_state_foo` spellings map to `foo`. Arguments
follow the op signature order (the parity mapping in ops/parity.py keeps
those aligned with the reference yaml), so the common positional call sites
port unchanged.
"""
from __future__ import annotations

_CACHE = {}


def _resolve(name):
    if name in _CACHE:
        return _CACHE[name]
    target = name
    if target.startswith("final_state_"):  # legacy generated spelling
        target = target[len("final_state_"):]

    from . import nn, ops, tensor  # noqa: PLC0415

    sources = [ops, tensor, nn.functional]
    for src in sources:
        fn = getattr(src, target, None)
        if callable(fn):
            _CACHE[name] = fn
            return fn
    # registry fallback: a defop with no public namespace binding still
    # resolves (dispatches through the normal eager apply path)
    from .ops._apply import apply, get_registry  # noqa: PLC0415

    opdef = get_registry().get(target)
    # an unbound inplace spelling must NOT silently fall back to the
    # out-of-place op — callers rely on the mutation; the public-namespace
    # inplace variants (resolved above) are the real in-place surface
    if opdef is not None:
        def fn(*args, _opdef=opdef, **kwargs):
            return apply(_opdef, *args, **kwargs)

        fn.__name__ = name
        _CACHE[name] = fn
        return fn
    return None


def __getattr__(name):
    fn = _resolve(name)
    if fn is None:
        raise AttributeError(
            f"paddle._C_ops has no op {name!r} (not in the defop registry "
            "or any public namespace — see docs/ops_parity.md)")
    return fn


def __dir__():
    from .ops._apply import get_registry  # noqa: PLC0415

    return sorted(set(list(get_registry()) + list(_CACHE)))
