"""paddle.audio: spectral features.

Reference analog: python/paddle/audio/ (functional: window/mel/fbank helpers;
features: Spectrogram/MelSpectrogram/LogMelSpectrogram/MFCC layers).
Built on paddle.signal.stft so feature extraction compiles with the model.
"""
from __future__ import annotations

import math

import numpy as np

import jax.numpy as jnp

from .. import ops, signal
from ..framework.core import Tensor
from ..nn.layer.layers import Layer


def hz_to_mel(freq, htk=False):
    if htk:
        return 2595.0 * np.log10(1.0 + np.asarray(freq) / 700.0)
    f = np.asarray(freq, np.float64)
    mel = 3.0 * f / 200.0
    min_log_hz, logstep = 1000.0, np.log(6.4) / 27.0
    above = f >= min_log_hz
    return np.where(above, 15.0 + np.log(np.maximum(f, 1e-10) / min_log_hz)
                    / logstep, mel)


def mel_to_hz(mel, htk=False):
    if htk:
        return 700.0 * (10.0 ** (np.asarray(mel) / 2595.0) - 1.0)
    m = np.asarray(mel, np.float64)
    hz = 200.0 * m / 3.0
    min_log_hz, logstep = 1000.0, np.log(6.4) / 27.0
    above = m >= 15.0
    return np.where(above, min_log_hz * np.exp(logstep * (m - 15.0)), hz)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """(n_mels, n_fft//2 + 1) triangular mel filterbank
    (reference audio/functional/functional.py compute_fbank_matrix)."""
    f_max = f_max or sr / 2.0
    n_freqs = n_fft // 2 + 1
    freqs = np.linspace(0, sr / 2.0, n_freqs)
    mel_pts = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                          n_mels + 2)
    hz_pts = mel_to_hz(mel_pts, htk)
    fb = np.zeros((n_mels, n_freqs))
    for m in range(n_mels):
        lo, ctr, hi = hz_pts[m], hz_pts[m + 1], hz_pts[m + 2]
        up = (freqs - lo) / max(ctr - lo, 1e-10)
        down = (hi - freqs) / max(hi - ctr, 1e-10)
        fb[m] = np.maximum(0.0, np.minimum(up, down))
    if norm == "slaney":
        enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
        fb *= enorm[:, None]
    return Tensor(jnp.asarray(fb.astype(dtype)))


def get_window(window, win_length, fftbins=True, dtype="float32"):
    n = win_length
    if window in ("hann", "hanning"):
        w = np.hanning(n + 1)[:-1] if fftbins else np.hanning(n)
    elif window == "hamming":
        w = np.hamming(n + 1)[:-1] if fftbins else np.hamming(n)
    elif window == "blackman":
        w = np.blackman(n + 1)[:-1] if fftbins else np.blackman(n)
    elif window in ("rect", "boxcar", "ones"):
        w = np.ones(n)
    else:
        raise ValueError(f"unsupported window {window!r}")
    return Tensor(jnp.asarray(w.astype(dtype)))


class Spectrogram(Layer):
    """features/layers.py Spectrogram: |stft|^power."""

    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window = get_window(window, self.win_length, dtype=dtype)

    def forward(self, x):
        spec = signal.stft(x, self.n_fft, hop_length=self.hop_length,
                           win_length=self.win_length, window=self.window,
                           center=self.center, pad_mode=self.pad_mode)
        mag = spec.abs()
        return mag ** self.power if self.power != 1.0 else mag


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                       power, center, pad_mode, dtype)
        self.fbank = compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                          htk, norm, dtype)

    def forward(self, x):
        spec = self.spectrogram(x)          # (..., n_freqs, frames)
        return ops.matmul(self.fbank, spec)


class LogMelSpectrogram(MelSpectrogram):
    def __init__(self, *args, ref_value=1.0, amin=1e-10, top_db=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = super().forward(x)
        log_spec = 10.0 * ops.log10(ops.maximum(
            mel, ops.full_like(mel, self.amin)))
        log_spec = log_spec - 10.0 * math.log10(max(self.ref_value, self.amin))
        if self.top_db is not None:
            log_spec = ops.maximum(
                log_spec, ops.full_like(log_spec,
                                        float(log_spec.max().numpy())
                                        - self.top_db))
        return log_spec


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=13, n_fft=512, n_mels=64, **kwargs):
        super().__init__()
        self.logmel = LogMelSpectrogram(sr=sr, n_fft=n_fft, n_mels=n_mels,
                                        **kwargs)
        # DCT-II basis
        n = np.arange(n_mels)
        basis = np.cos(np.pi / n_mels * (n[None, :] + 0.5)
                       * np.arange(n_mfcc)[:, None])
        basis *= np.sqrt(2.0 / n_mels)
        basis[0] /= np.sqrt(2.0)
        self.dct = Tensor(jnp.asarray(basis.astype("float32")))

    def forward(self, x):
        return ops.matmul(self.dct, self.logmel(x))






# -- backends: wav io (reference audio/backends/wave_backend.py) -------------
class AudioInfo:
    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding="PCM_S"):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def _wav_info(filepath):
    """backends.info (wave_backend.py:43) — stdlib wave, 16-bit PCM."""
    import wave

    with wave.open(filepath, "rb") as w:
        return AudioInfo(w.getframerate(), w.getnframes(), w.getnchannels(),
                         8 * w.getsampwidth())


def _wav_load(filepath, frame_offset=0, num_frames=-1, normalize=True,
              channels_first=True):
    """backends.load (wave_backend.py:95): (Tensor[C,L] or [L,C], sr)."""
    import wave

    import numpy as np

    from ..framework.core import Tensor

    with wave.open(filepath, "rb") as w:
        sr, nch, width = w.getframerate(), w.getnchannels(), w.getsampwidth()
        if width != 2:
            raise ValueError("wave backend supports 16-bit PCM only")
        w.setpos(frame_offset)
        n = w.getnframes() - frame_offset if num_frames < 0 else num_frames
        data = np.frombuffer(w.readframes(n), dtype="<i2")
    data = data.reshape(-1, nch)
    arr = data.astype("float32") / 32768.0 if normalize \
        else data.astype("int16")
    if channels_first:
        arr = arr.T
    return Tensor(jnp.asarray(arr)), sr


def _wav_save(filepath, src, sample_rate, channels_first=True,
              encoding="PCM_S", bits_per_sample=16):
    """backends.save (wave_backend.py:174)."""
    import wave

    import numpy as np

    arr = np.asarray(src.numpy() if hasattr(src, "numpy") else src)
    if channels_first:
        arr = arr.T  # -> (L, C)
    if arr.dtype != np.int16:
        arr = (np.clip(arr, -1.0, 1.0) * 32767.0).astype("<i2")
    with wave.open(filepath, "wb") as w:
        w.setnchannels(arr.shape[1] if arr.ndim > 1 else 1)
        w.setsampwidth(2)
        w.setframerate(int(sample_rate))
        w.writeframes(arr.tobytes())


class backends:
    """paddle.audio.backends (wave_backend default; soundfile if installed)."""

    info = staticmethod(_wav_info)
    load = staticmethod(_wav_load)
    save = staticmethod(_wav_save)

    @staticmethod
    def list_available_backends():
        avail = ["wave_backend"]
        try:
            import soundfile  # noqa: F401

            avail.append("soundfile")
        except ImportError:
            pass
        return avail

    @staticmethod
    def get_current_backend():
        return "wave_backend"

    @staticmethod
    def set_backend(name):
        if name != "wave_backend":
            raise ValueError("only wave_backend is available in this build")


load = _wav_load
save = _wav_save
info = _wav_info

from . import datasets  # noqa: F401,E402


# real submodules (importable as paddle.audio.features / .functional)
from . import features  # noqa: E402,F401
from . import functional  # noqa: E402,F401
