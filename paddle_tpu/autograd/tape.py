"""Eager autograd engine.

Reference analog: the GradNode graph + backward queue in paddle/fluid/eager/
(grad_node_info.h:197 GradNodeBase, backward.cc:106 RunBackward, backward.cc:473 Backward,
accumulation_node.h:26 leaf accumulation). TPU-first redesign: each recorded op holds a
jax.vjp-produced pullback whose residuals are jax.Arrays in HBM; the backward pass walks the
tape in reverse-topological order exactly like RunBackward's in-degree queue, but every
"kernel" is a cached XLA executable, and higher-order grads (create_graph) re-enter the op
dispatch layer so grad-of-grad is taped too.
"""
from __future__ import annotations

import contextlib
import functools
import weakref

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.core import Tensor

# --------------------------------------------------------------------------
# Global recording state
# --------------------------------------------------------------------------
_GRAD_ENABLED = [True]
# Functional mode: graph capture (jit.to_static) computes grads with jax.grad over the pure
# function; the Python tape is suspended so tracing costs nothing.
_FUNCTIONAL_MODE = [False]
# Master-grad mode: pullbacks of reduced-precision (fp16/bf16) ops re-run in
# fp32 and cotangents stay fp32 end to end, so a scaled loss (2**15) cannot
# overflow the fp16 grads themselves (paddle.amp master_grad; the reference's
# fp32 master gradient accumulation for O2 training).
_MASTER_GRAD = [False]


def is_grad_enabled() -> bool:
    return _GRAD_ENABLED[0] and not _FUNCTIONAL_MODE[0]


def grad_flag() -> bool:
    """The raw no_grad/enable_grad flag, independent of functional (capture) mode."""
    return _GRAD_ENABLED[0]


def set_grad_enabled(mode: bool):
    class _Guard:
        def __init__(self, prev):
            self.prev = prev

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            _GRAD_ENABLED[0] = self.prev

    prev = _GRAD_ENABLED[0]
    _GRAD_ENABLED[0] = bool(mode)
    return _Guard(prev)


class no_grad:
    """Context manager + decorator disabling tape recording (paddle.no_grad)."""

    def __enter__(self):
        self.prev = _GRAD_ENABLED[0]
        _GRAD_ENABLED[0] = False
        return self

    def __exit__(self, *exc):
        _GRAD_ENABLED[0] = self.prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with no_grad():
                return fn(*a, **k)

        return wrapper


class enable_grad:
    def __enter__(self):
        self.prev = _GRAD_ENABLED[0]
        _GRAD_ENABLED[0] = True
        return self

    def __exit__(self, *exc):
        _GRAD_ENABLED[0] = self.prev
        return False


@contextlib.contextmanager
def functional_mode():
    prev = _FUNCTIONAL_MODE[0]
    _FUNCTIONAL_MODE[0] = True
    try:
        yield
    finally:
        _FUNCTIONAL_MODE[0] = prev


@contextlib.contextmanager
def master_grad():
    """Run backward passes inside this context with fp32 master gradients:
    reduced-precision ops re-linearize in fp32 (see _master_vjp)."""
    prev = _MASTER_GRAD[0]
    _MASTER_GRAD[0] = True
    try:
        yield
    finally:
        _MASTER_GRAD[0] = prev


def set_master_grad(mode):
    """Process-wide master-grad switch (paddle.amp.decorate(master_grad=
    True)); the master_grad() context scopes it per-backward."""
    _MASTER_GRAD[0] = bool(mode)


def master_grad_enabled():
    return _MASTER_GRAD[0]


def in_functional_mode() -> bool:
    return _FUNCTIONAL_MODE[0]


# --------------------------------------------------------------------------
# Grad nodes
# --------------------------------------------------------------------------
class _InputRef:
    """Snapshot of an input tensor's autograd identity at record time.

    In-place APIs (add_, setitem_) rebind a Tensor's value and producer node after the op is
    recorded; routing cotangents through the live object would then cycle into the in-place
    op's own node. The snapshot pins (producer, out_index, stop_gradient, value) as they were
    when the op consumed the tensor — the same reason the reference saves inputs through
    TensorWrapper (fluid/eager/tensor_wrapper.h) with inplace-version checks.
    """

    __slots__ = ("tensor", "node", "out_index", "stop_gradient", "value")

    def __init__(self, t: Tensor):
        self.tensor = t
        self.node = t._grad_node
        self.out_index = t._out_index
        self.stop_gradient = t.stop_gradient
        self.value = t._value


class GradNode:
    """One recorded op on the tape.

    inputs: _InputRef per tensor leaf of the op call (order matches the pullback's cotangents).
    vjp_fn: pullback from jax.vjp over the op's pure function.
    pure_fn: the op's pure function itself, kept for create_graph re-linearization.
    out_avals: OutAval (shape, dtype) per output (zero-fill for dead branches).
    """

    __slots__ = ("name", "inputs", "vjp_fn", "pure_fn", "out_avals", "hooks", "__weakref__")

    def __init__(self, name, inputs, vjp_fn, pure_fn, out_avals):
        self.name = name
        self.inputs = inputs
        self.vjp_fn = vjp_fn
        self.pure_fn = pure_fn
        self.out_avals = out_avals
        self.hooks = None  # list of (out_index, hook) applied to incoming cotangents


def record(name, inputs, vjp_fn, pure_fn, out_avals, outputs):
    node = GradNode(name, [_InputRef(t) for t in inputs], vjp_fn, pure_fn, list(out_avals))
    for i, t in enumerate(outputs):
        t._grad_node = node
        t._out_index = i
    return node


def register_tensor_hook(tensor: Tensor, hook):
    """Run `hook(grad)->grad|None` when the cotangent for `tensor` is finalized."""
    node = tensor._grad_node
    if node is None:
        if tensor.stop_gradient:
            raise RuntimeError("cannot register hook on a tensor that stops gradient")
        if tensor._leaf_hooks is None:
            tensor._leaf_hooks = []
        tensor._leaf_hooks.append(hook)
        return _RemovableHandle(tensor._leaf_hooks, hook)
    if node.hooks is None:
        node.hooks = []
    entry = (tensor._out_index, hook)
    node.hooks.append(entry)
    return _RemovableHandle(node.hooks, entry)


class _RemovableHandle:
    def __init__(self, container, entry):
        self._container = container
        self._entry = entry

    def remove(self):
        try:
            self._container.remove(self._entry)
        except ValueError:
            pass


# --------------------------------------------------------------------------
# Backward engine
# --------------------------------------------------------------------------
def _is_inexact(dt):
    return jnp.issubdtype(np.dtype(dt), jnp.inexact)


class OutAval:
    """Lightweight (shape, dtype) pair for GradNode outputs.

    jax.ShapeDtypeStruct costs ~11us to construct (sharding machinery); the
    tape only ever reads .shape/.dtype, so the eager hot path records this
    0.2us object instead (round-4 dispatch work)."""

    __slots__ = ("shape", "dtype")

    def __init__(self, shape, dtype):
        self.shape = shape
        self.dtype = dtype


def _zeros_like(aval):
    # integer/bool outputs carry symbolic-zero float0 cotangents in jax
    if not _is_inexact(aval.dtype):
        return np.zeros(aval.shape, jax.dtypes.float0)
    return jnp.zeros(aval.shape, aval.dtype)


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward: accumulate into leaf .grad."""
    _run_backward(
        tensors,
        grad_tensors,
        retain_graph=retain_graph,
        create_graph=False,
        accumulate_leaves=True,
        wanted=None,
    )


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    only_inputs=True,
    allow_unused=False,
    no_grad_vars=None,
):
    """paddle.grad (eager GeneralGrad, fluid/eager/backward.cc GeneralGrad)."""
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if retain_graph is None:
        retain_graph = create_graph
    skip = set()
    if no_grad_vars:
        skip = {id(t) for t in no_grad_vars}
    got = _run_backward(
        outputs,
        grad_outputs,
        retain_graph=retain_graph,
        create_graph=create_graph,
        accumulate_leaves=False,
        wanted=[t for t in inputs],
        skip_ids=skip,
    )
    results = []
    for t in inputs:
        g = got.get(id(t))
        if g is None and not allow_unused:
            raise RuntimeError(
                f"One of the differentiated tensors ({t.name}) appears unused in the graph; "
                "pass allow_unused=True to return None for it."
            )
        results.append(g)
    return results


def _run_backward(
    tensors,
    grad_tensors,
    retain_graph,
    create_graph,
    accumulate_leaves,
    wanted,
    skip_ids=frozenset(),
):
    tensors = [t for t in tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    grad_tensors = list(grad_tensors)

    # cotangent buffers: (id(node), out_idx) -> value; node kept alive via nodes set
    buf = {}
    nodes = {}

    def seed(t, g):
        node = t._grad_node
        if node is None:
            return None
        nodes[id(node)] = node
        key = (id(node), t._out_index)
        buf[key] = g if key not in buf else _acc(buf[key], g)

    wanted_ids_early = {id(t) for t in (wanted or [])}
    collected_early = {}
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            raise RuntimeError("cannot run backward on a tensor with stop_gradient=True")
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; got shape "
                    f"{t.shape}"
                )
            g = jnp.ones(t.value.shape, t.value.dtype)
        else:
            g = g.value if isinstance(g, Tensor) else jnp.asarray(g)
        if id(t) in wanted_ids_early:
            # an output that is also a requested input receives its seed directly
            prev = collected_early.get(id(t))
            collected_early[id(t)] = g if prev is None else prev + g
        if t._grad_node is None:
            # output IS a leaf
            if accumulate_leaves:
                _leaf_accumulate(t, g, create_graph)
        else:
            seed(t, g)

    # ---- reachability + in-(consumer)-edge count ----
    pending = {}
    visited = set()
    stack = [nodes[k] for k in nodes]
    reachable = dict(nodes)
    while stack:
        node = stack.pop()
        if id(node) in visited:
            continue
        visited.add(id(node))
        for ref in node.inputs:
            if ref.stop_gradient or id(ref.tensor) in skip_ids:
                continue
            p = ref.node
            if p is not None:
                pending[id(p)] = pending.get(id(p), 0) + 1
                if id(p) not in reachable:
                    reachable[id(p)] = p
                    stack.append(p)

    wanted_ids = wanted_ids_early
    collected = dict(collected_early)

    ready = [n for nid, n in nodes.items() if pending.get(nid, 0) == 0]
    # roots with no pending consumers run first; consumers seed producers as they run
    processed = set()

    def deliver(ref, cot):
        """Route a cotangent contribution to the input's producer or leaf storage."""
        if cot is None or ref.stop_gradient or id(ref.tensor) in skip_ids:
            return
        cval = cot.value if isinstance(cot, Tensor) else cot
        if getattr(cval, "dtype", None) == jax.dtypes.float0:
            return
        if id(ref.tensor) in wanted_ids:
            prev = collected.get(id(ref.tensor))
            collected[id(ref.tensor)] = (
                cot if prev is None else _acc_tensorish(prev, cot, create_graph)
            )
        p = ref.node
        if p is None:
            if accumulate_leaves:
                _leaf_accumulate(ref.tensor, cot, create_graph)
            return
        key = (id(p), ref.out_index)
        buf[key] = cot if key not in buf else _acc_tensorish(buf[key], cot, create_graph)
        pending[id(p)] -= 1
        if pending[id(p)] == 0:
            ready.append(p)

    while ready:
        node = ready.pop()
        if id(node) in processed:
            continue
        processed.add(id(node))
        # gather output cotangents, zero-filling unvisited outputs
        cots = []
        for i, aval in enumerate(node.out_avals):
            g = buf.pop((id(node), i), None)
            cots.append(g if g is not None else _zeros_like(aval))
        if node.hooks:
            for idx, hook in node.hooks:
                h = hook(_as_tensor(cots[idx]))
                if h is not None:
                    cots[idx] = h.value if isinstance(h, Tensor) else h
        if node.vjp_fn is None and not (create_graph and node.pure_fn is not None):
            raise RuntimeError(
                f"backward through {node.name} a second time: set retain_graph=True"
            )
        in_cots = _run_vjp(node, cots, create_graph)
        if not retain_graph:
            node.vjp_fn = None
        for ref, c in zip(node.inputs, in_cots):
            deliver(ref, c)

    out = {}
    for t in wanted or []:
        g = collected.get(id(t))
        if g is not None:
            out[id(t)] = g if isinstance(g, Tensor) else Tensor(g, stop_gradient=not create_graph)
    return out


def _acc(a, b):
    return a + b


def _acc_tensorish(a, b, create_graph):
    if isinstance(a, Tensor) or isinstance(b, Tensor):
        from .. import ops

        return ops.add(_as_tensor(a), _as_tensor(b))
    return a + b


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(x)


_REDUCED = (jnp.float16, jnp.bfloat16)


def _is_reduced(dt):
    return np.dtype(dt) in (np.dtype(jnp.float16), np.dtype(jnp.bfloat16))


@functools.lru_cache(maxsize=4096)
def _master_bwd(pure_fn):
    """Jitted fp32 re-linearization of an op's pure function: one trace per
    (pure_fn, avals) signature, shared across backward steps. Cotangents
    are conformed to the recomputed outputs' dtypes INSIDE the program.

    ONLY for pure functions with stable identity (``master_cacheable``,
    stamped by the per-signature caches in ops/_apply.py): caching the
    fresh per-call closures of the fallback dispatch path would never hit
    AND pin up to maxsize closures (each holding that call's input arrays)
    — those take the uncached jax.vjp route in _master_vjp instead."""

    @jax.jit
    def bwd(vals, cots):
        outs, vjp_fn = jax.vjp(pure_fn, *vals)
        cots = _conform_cots(cots, outs)
        return vjp_fn(cots)

    return bwd


def _conform_cots(cots, outs):
    """Cast each inexact cotangent to its recomputed output's dtype."""
    return tuple(
        jnp.asarray(c, o.dtype)
        if _is_inexact(getattr(c, "dtype", np.float32))
        and np.dtype(o.dtype) != np.dtype(c.dtype) else c
        for c, o in zip(cots, outs))


def _master_vjp(node, cots):
    """fp32 pullback for a reduced-precision op, or None to use the stored
    (reduced-precision) pullback.

    The mechanics of master grad here: the op's pure function is dtype-
    polymorphic (one jax function serves fp16 and fp32), so re-linearizing
    it at the fp32-upcast residuals evaluates the SAME pullback in fp32
    arithmetic — grad values like 6 * 2**15 that overflow fp16's 65504 max
    stay finite, and the resulting fp32 cotangents flow on to become fp32
    leaf .grad (the master gradient) for fp16 and fp32 params alike.
    ``cast`` nodes are the one non-polymorphic op (they hard-cast): their
    pullback is mathematically the identity between inexact dtypes, so the
    fp32 cotangent passes straight through instead of round-tripping
    through the fp16 bottleneck that caused the overflow."""
    in_vals = [ref.value for ref in node.inputs]
    if node.name == "cast":
        if (len(in_vals) == 1 and len(cots) == 1
                and _is_inexact(in_vals[0].dtype)
                and _is_inexact(node.out_avals[0].dtype)):
            c = cots[0]
            c = c.value if isinstance(c, Tensor) else c
            if _is_reduced(getattr(c, "dtype", np.float32)):
                c = jnp.asarray(c, jnp.float32)
            tgt = in_vals[0].dtype
            if not _is_reduced(tgt) \
                    and np.dtype(getattr(c, "dtype", np.float32)) \
                    != np.dtype(tgt):
                c = jnp.asarray(c, tgt)   # e.g. an fp64 source stays fp64
            return [c]
        return None
    involved = any(_is_reduced(v.dtype) for v in in_vals
                   if hasattr(v, "dtype")) \
        or any(_is_reduced(a.dtype) for a in node.out_avals
               if _is_inexact(a.dtype))
    if not involved or node.pure_fn is None:
        return None
    if not all(_is_inexact(a.dtype) for a in node.out_avals):
        return None     # mixed int outputs: float0 cots, keep stored path
    try:
        vals32 = tuple(
            jnp.asarray(v, jnp.float32) if hasattr(v, "dtype")
            and _is_reduced(v.dtype) else v for v in in_vals)
        cot_vals = []
        for c in cots:
            c = c.value if isinstance(c, Tensor) else c
            if hasattr(c, "dtype") and _is_reduced(c.dtype):
                c = jnp.asarray(c, jnp.float32)
            cot_vals.append(c)
        if getattr(node.pure_fn, "master_cacheable", False):
            return list(_master_bwd(node.pure_fn)(vals32, tuple(cot_vals)))
        # per-call closure (fallback dispatch path / apply_raw): re-vjp
        # directly — no jit cache could ever hit on a fresh identity
        outs, vjp_fn = jax.vjp(node.pure_fn, *vals32)
        return list(vjp_fn(_conform_cots(tuple(cot_vals), outs)))
    except Exception:  # noqa: BLE001 - non-conforming op: stored pullback
        return None


def _run_vjp(node, cots, create_graph):
    """Execute the node's pullback.

    create_graph: re-linearize through the op dispatcher so the computation is taped and
    residual-paths stay differentiable (the stored pullback treats residuals as constants,
    which would silently drop second-order terms)."""
    if not create_graph and _MASTER_GRAD[0]:
        out = _master_vjp(node, cots)
        if out is not None:
            return out
    if create_graph and node.pure_fn is not None:
        from ..ops._apply import apply_raw

        def grad_fn(*args):
            n_in = len(node.inputs)
            ins, cs = args[:n_in], args[n_in:]
            _, vjp_fn = jax.vjp(node.pure_fn, *ins)
            return vjp_fn(tuple(cs))

        # reuse the live tensors when unmutated (keeps identity for grad(..., inputs=) );
        # fall back to a snapshot copy if an in-place op rebound them since
        in_tensors = []
        for ref in node.inputs:
            live = ref.tensor
            if live._value is ref.value and live._grad_node is ref.node:
                in_tensors.append(live)
            else:
                t = Tensor(ref.value, stop_gradient=ref.stop_gradient)
                t._grad_node, t._out_index = ref.node, ref.out_index
                in_tensors.append(t)
        cot_tensors = [_as_tensor(c) for c in cots]
        outs = apply_raw(
            node.name + "_grad", grad_fn, in_tensors + cot_tensors, n_outs=len(node.inputs)
        )
        return list(outs)
    cot_vals = [c.value if isinstance(c, Tensor) else c for c in cots]
    cot_vals = [
        c
        if not _is_inexact(a.dtype)
        else (jnp.asarray(c, a.dtype) if np.dtype(c.dtype) != a.dtype else c)
        for c, a in zip(cot_vals, node.out_avals)
    ]
    # op pure functions always return a tuple of outputs (see ops/_apply.py)
    return list(node.vjp_fn(tuple(cot_vals)))


def _leaf_accumulate(t: Tensor, g, create_graph=False):
    hooks = t._leaf_hooks
    if hooks:
        for hook in list(hooks):
            h = hook(_as_tensor(g))
            if h is not None:
                g = h.value if isinstance(h, Tensor) else h
    g_val = g.value if isinstance(g, Tensor) else g
    if t._grad is None:
        t._grad = Tensor(g_val, stop_gradient=True)
    else:
        t._grad._replace_value(t._grad.value + g_val)
