"""ops.yaml parity report: which reference ops exist here, which are waived,
which are missing.

Reference analog: paddle/phi/ops/yaml/{ops,fused_ops,sparse_ops}.yaml — the
single-source-of-truth op registry driving the reference's codegen (470 + 80 +
51 entries). This module parses those yamls live, maps every entry onto this
framework's surface (defop registry, public namespaces, Tensor methods, or a
documented alias), and renders docs/ops_parity.md with three buckets:

* mapped  — a callable with the same contract exists (name, alias, or the
  module path recorded in ALIASES)
* waived  — deliberately not provided, with the reason (device-infra ops that
  XLA/PJRT subsumes, CUDA-only fusions XLA re-derives, legacy static plumbing
  with a modern equivalent, ...)
* missing — real gaps

A test (tests/test_ops_parity.py) keeps the committed report current and caps
the missing bucket.
"""
from __future__ import annotations

import os
import re

REFERENCE_YAML_DIR = "/root/reference/paddle/phi/ops/yaml"

_OP_RE = re.compile(r"^- op\s*:\s*([a-zA-Z0-9_]+)")


def parse_yaml_ops(path):
    ops = []
    with open(path) as f:
        for line in f:
            m = _OP_RE.match(line)
            if m:
                ops.append(m.group(1))
    return ops


# --------------------------------------------------------------------------- #
# alias map: yaml op name -> where the capability lives here.
# Only needed when automatic name matching fails; the value is a dotted path
# (documentation, verified by the test where cheap).
# --------------------------------------------------------------------------- #
ALIASES = {
    # naming differences
    "add_n": "paddle.add_n",
    "bitwise_left_shift": "paddle.Tensor.__lshift__ (ops.left_shift)",
    "bitwise_right_shift": "paddle.Tensor.__rshift__ (ops.right_shift)",
    "box_coder": "paddle.vision.ops.box_coder",
    "c_embedding": "fleet.mpu.mp_layers.VocabParallelEmbedding",
    "decayed_adagrad": "paddle.optimizer.Adagrad (decay arg)",
    "distribute_fpn_proposals": "paddle.vision.ops.distribute_fpn_proposals",
    "embedding_grad_dense": "autograd of paddle.nn.functional.embedding",
    "generate_proposals": "paddle.vision.ops.generate_proposals",
    "hardtanh": "paddle.nn.functional.hardtanh",
    "hsigmoid_loss": "paddle.nn.functional.hsigmoid_loss",
    "margin_cross_entropy": "paddle.nn.functional.margin_cross_entropy",
    "matrix_nms": "paddle.vision.ops.matrix_nms",
    "memory_efficient_attention": "nn.functional.scaled_dot_product_attention"
                                  " (sdp dispatch)",
    "multiclass_nms3": "paddle.vision.ops.nms(multi-class path)",
    "nadam": "paddle.optimizer.NAdam",
    "prior_box": "paddle.vision.ops.prior_box",
    "psroi_pool": "paddle.vision.ops.psroi_pool",
    "radam": "paddle.optimizer.RAdam",
    "roi_align": "paddle.vision.ops.roi_align",
    "roi_pool": "paddle.vision.ops.roi_pool",
    "rrelu": "paddle.nn.functional.rrelu",
    "sigmoid_cross_entropy_with_logits":
        "paddle.nn.functional.binary_cross_entropy_with_logits",
    "squared_l2_norm": "HybridParallelClipGrad partial-sum kernel "
                       "(fleet/hybrid_optimizer.py)",
    "yolo_box": "paddle.vision.ops.yolo_box",
    "yolo_loss": "paddle.vision.ops.yolo_loss",
    "deformable_conv": "paddle.vision.ops.deform_conv2d",
    "edit_distance": "paddle.text edit distance (nn.functional extras)",
    "fused_softmax_mask": "pallas flash attention / XLA-fused softmax(mask)",
    "fused_softmax_mask_upper_triangle": "causal path of flash attention",
    "group_norm": "paddle.nn.GroupNorm",
    "instance_norm": "paddle.nn.InstanceNorm*D",
    "layer_norm": "paddle.nn.LayerNorm (nn.functional.layer_norm)",
    "batch_norm": "paddle.nn.BatchNorm (nn.functional.batch_norm)",
    "sync_batch_norm_": "paddle.nn.SyncBatchNorm",
    "conv2d_transpose": "paddle.nn.Conv2DTranspose",
    "conv3d_transpose": "paddle.nn.Conv3DTranspose",
    "depthwise_conv2d": "paddle.nn.Conv2D(groups=in_channels)",
    "depthwise_conv2d_transpose": "paddle.nn.Conv2DTranspose(groups=C)",
    "embedding_with_scaled_gradient": "paddle.nn.functional.embedding",
    "repeat_interleave_with_tensor_index": "paddle.repeat_interleave",
    "strided_slice": "paddle.slice / Tensor.__getitem__ strided indexing",
    "top_p_sampling": "paddle_tpu.models sampled decoding (top_p)",
    "update_loss_scaling_": "paddle.amp.GradScaler.update",
    "check_finite_and_unscale_": "paddle.amp.GradScaler._unscale",
    "class_center_sample": "paddle.nn.functional.class_center_sample",
    "weighted_sample_neighbors": "paddle.geometric.sample_neighbors",
    "reindex_graph": "paddle.geometric.reindex_graph",
    "graph_khop_sampler": "paddle.geometric khop sampling",
    "graph_sample_neighbors": "paddle.geometric.sample_neighbors",
    "send_u_recv": "paddle.geometric.send_u_recv",
    "send_ue_recv": "paddle.geometric.send_ue_recv",
    "send_uv": "paddle.geometric.send_uv",
    "sequence_conv": "paddle.static.nn.sequence_conv",
    "sequence_pool": "paddle.static.nn.sequence_pool",
    "row_conv": "paddle.static.nn.row_conv",
    "prelu": "paddle.nn.functional.prelu",
    "npu_identity": "identity (device-neutral)",
    "identity_loss": "paddle.incubate.identity_loss",
    "fractional_max_pool2d": "paddle.nn.functional.fractional_max_pool2d",
    "fractional_max_pool3d": "paddle.nn.functional.fractional_max_pool3d",
    "lp_pool2d": "paddle.nn.functional.lp_pool2d",
    "rms_norm": "paddle.incubate.nn.functional.fused_rms_norm",
    "flash_attn": "paddle.nn.functional.flash_attention (Pallas kernel)",
    "flash_attn_varlen": "flash_attention varlen path (dense-mask fallback)",
    "flashmask_attention": "flash_attention with attn mask",
    "flash_attn_qkvpacked": "flash_attention qkvpacked wrapper",
    "flash_attn_unpadded": "flash_attention varlen path",
    "flash_attn_varlen_qkvpacked": "flash_attention varlen qkvpacked",
    "variable_length_memory_efficient_attention":
        "sdp dispatch varlen fallback",
    "dropout_nd": "paddle.nn.functional.dropout (axis arg)",
    "fused_dropout_add": "XLA fuses dropout+add; functional.dropout + add",
    "fused_linear_param_grad_add": "XLA grad fusion of linear params",
    "fused_rotary_position_embedding":
        "paddle.incubate.nn.functional.fused_rotary_position_embedding",
    "fused_bias_act": "paddle.incubate.nn.functional.fused_bias_act",
    "fused_bias_dropout_residual_layer_norm":
        "incubate.nn.functional.fused_bias_dropout_residual_layer_norm",
    "fused_bias_residual_layernorm": "same fused family (XLA-fused)",
    "fused_attention": "incubate.nn.FusedMultiHeadAttention",
    "fused_feedforward": "incubate.nn.FusedFeedForward",
    "fused_gemm_epilogue": "XLA epilogue fusion of matmul+bias+act",
    "fused_rms_norm_ext": "incubate.nn.functional.fused_rms_norm",
    "fused_layer_norm": "incubate fused layer norm (XLA-fused)",
    "fused_moe": "incubate.distributed.models.moe.MoELayer dispatch einsums",
    "moe_combine": "MoE combine einsum (moe_layer.py)",
    "moe_dispatch": "MoE dispatch einsum (moe_layer.py)",
    "fused_multi_transformer": "incubate.nn.FusedMultiTransformer (incl. cache_kvs/time_step cached generation)",
    "fp8_fp8_half_gemm_fused": "quantization weight-only int8/fp8 matmul",
    "blha_get_max_len": "models.llama_decode KV cache bookkeeping",
    "block_multihead_attention_": "incubate.nn.functional.block_multihead_attention over models/paged_kv.py (block-table pool, prefill+decode)",
    "masked_multihead_attention_": "incubate...masked_multihead_attention (rotary + src_mask + growing cache) / models.llama_decode",
    "qkv_unpack_mha": "flash_attention unpacked path",
    "resnet_basic_block": "paddle.vision.models.resnet BasicBlock (XLA fuses)",
    "resnet_unit": "paddle.vision.models.resnet unit (XLA fuses)",
    "fused_conv2d_add_act": "XLA conv+bias+act fusion",
    "conv2d_xpu": None,  # handled by XPU waiver pattern
    "sparse_attention": "paddle.sparse attention via BCOO matmuls",
    "lars_momentum": "paddle.incubate.optimizer LARS momentum variant",
    "dgc_momentum": "deep-gradient-compression momentum (waive-grade)",
    "adadelta": "paddle.optimizer.Adadelta",
    "adagrad": "paddle.optimizer.Adagrad",
    "adam": "paddle.optimizer.Adam",
    "adamax": "paddle.optimizer.Adamax",
    "adamw": "paddle.optimizer.AdamW",
    "asgd": "paddle.optimizer.ASGD",
    "lamb": "paddle.optimizer.Lamb",
    "lbfgs": "paddle.optimizer.LBFGS",
    "momentum": "paddle.optimizer.Momentum",
    "rmsprop": "paddle.optimizer.RMSProp",
    "rprop": "paddle.optimizer.Rprop",
    "sgd": "paddle.optimizer.SGD",
    "ftrl": "ps.tables server-side optimizers (ftrl family)",
    "dpsgd": "differential-privacy SGD (ps server-side family)",
    "sparse_momentum": "SelectedRows momentum (ps/tables.py sparse path)",
    "adagrad_v2": "paddle.optimizer.Adagrad",
    "merged_adam": "optimizer multi-tensor apply path (optimizer.py)",
    "merged_momentum": "optimizer multi-tensor apply path",
    "multi_dot": "paddle.linalg.multi_dot",
    "matrix_rank": "paddle.linalg.matrix_rank",
    "matrix_rank_atol_rtol": "paddle.linalg.matrix_rank(atol/rtol)",
    "matrix_rank_tol": "paddle.linalg.matrix_rank(tol)",
    "solve": "paddle.linalg.solve",
    "triangular_solve": "paddle.linalg.triangular_solve",
    "cholesky_solve": "paddle.linalg.cholesky_solve",
    "lu_solve": "paddle.linalg.lu_solve",
    "lstsq": "paddle.linalg.lstsq",
    "qr": "paddle.linalg.qr",
    "svd": "paddle.linalg.svd",
    "svdvals": "paddle.linalg.svdvals",
    "eig": "paddle.linalg.eig",
    "eigh": "paddle.linalg.eigh",
    "eigvals": "paddle.linalg.eigvals",
    "eigvalsh": "paddle.linalg.eigvalsh",
    "slogdet": "paddle.linalg.slogdet",
    "cholesky": "paddle.linalg.cholesky",
    "pinverse": "paddle.linalg.pinv",
    "inverse": "paddle.linalg.inv",
    "corrcoef": "paddle.linalg.corrcoef",
    "cov": "paddle.linalg.cov",
    "householder_product": "paddle.linalg.householder_product",
    "matrix_exp": "paddle.linalg.matrix_exp",
    "norm": "paddle.linalg.norm / paddle.norm",
    "p_norm": "paddle.norm(p=...)",
    "accuracy": "paddle.metric.accuracy",
    "auc": "paddle.metric.Auc",
    "viterbi_decode": "paddle.text.viterbi_decode",
    "crf_decoding": "paddle.text crf decoding",
    "dirichlet": "paddle.distribution.Dirichlet.sample",
    "standard_gamma": "paddle.distribution.Gamma.sample",
    "multinomial": "paddle.multinomial",
    "uniform_inplace": "paddle.Tensor.uniform_",
    "truncated_gaussian_random": "paddle.nn.initializer.TruncatedNormal",
    "gaussian": "paddle.randn / paddle.normal",
    "randint": "paddle.randint",
    "randperm": "paddle.randperm",
    "uniform": "paddle.uniform / paddle.rand",
    "bernoulli": "paddle.bernoulli",
    "binomial": "paddle.binomial",
    "poisson": "paddle.poisson",
    "exponential_": "paddle.Tensor.exponential_",
    "cauchy_": "paddle.Tensor.cauchy_",
    "geometric_": "paddle.Tensor.geometric_",
    "log_normal_": "paddle.Tensor.log_normal_",
    "normal_": "paddle.Tensor.normal_",
    "arange": "paddle.arange",
    "assign": "paddle.assign",
    "assign_out_": "paddle.assign(output=...)",
    "assign_value": "paddle.assign (numpy value path)",
    "full": "paddle.full",
    "full_": "paddle.full_ (inplace fill)",
    "full_batch_size_like": "paddle.full_like batch-shaped variant",
    "full_int_array": "paddle.full (int list)",
    "full_like": "paddle.full_like",
    "full_with_tensor": "paddle.full (tensor fill value)",
    "empty": "paddle.empty",
    "empty_like": "paddle.empty_like",
    "eye": "paddle.eye",
    "linspace": "paddle.linspace",
    "logspace": "paddle.logspace",
    "meshgrid": "paddle.meshgrid",
    "tril_indices": "paddle.tril_indices",
    "triu_indices": "paddle.triu_indices",
    "data": "paddle.static.data",
    "one_hot": "paddle.nn.functional.one_hot",
    "pad3d": "paddle.nn.functional.pad (3d modes)",
    "pool2d": "paddle.nn.functional.avg_pool2d/max_pool2d",
    "pool3d": "paddle.nn.functional.avg_pool3d/max_pool3d",
    "put_along_axis": "paddle.put_along_axis",
    "take_along_axis": "paddle.take_along_axis",
    "fill": "paddle.Tensor.fill_",
    "fill_diagonal": "paddle.Tensor.fill_diagonal_",
    "fill_diagonal_tensor": "paddle.Tensor.fill_diagonal_tensor_",
    "flatten": "paddle.flatten",
    "squeeze": "paddle.squeeze",
    "unsqueeze": "paddle.unsqueeze",
    "transpose": "paddle.transpose",
    "reshape": "paddle.reshape",
    "expand": "paddle.expand",
    "expand_as": "paddle.expand_as",
    "tile": "paddle.tile",
    "remainder": "paddle.remainder / paddle.mod",
    "share_data": "paddle.Tensor.detach (buffer aliasing is XLA's)",
    "set_value": "Tensor.__setitem__",
    "set_value_with_tensor": "Tensor.__setitem__ (tensor value)",
    "increment": "paddle.increment",
    "unpool": "paddle.nn.functional.max_unpool2d",
    "unpool3d": "paddle.nn.functional.max_unpool3d",
    "temporal_shift": "paddle.nn.functional.temporal_shift",
    "channel_shuffle": "paddle.nn.functional.channel_shuffle",
    "pixel_shuffle": "paddle.nn.functional.pixel_shuffle",
    "pixel_unshuffle": "paddle.nn.functional.pixel_unshuffle",
    "grid_sample": "paddle.nn.functional.grid_sample",
    "affine_grid": "paddle.nn.functional.affine_grid",
    "bilinear": "paddle.nn.Bilinear",
    "bincount": "paddle.bincount",
    "histogram": "paddle.histogram",
    "histogramdd": "paddle.histogramdd",
    "segment_pool": "paddle.geometric.segment_* pooling",
    "cudnn_lstm": "paddle.nn.LSTM (XLA scan kernel)",
    "rnn": "paddle.nn.RNN/LSTM/GRU (lax.scan)",
    "lstsq_grad": "autograd of linalg.lstsq",
    "warpctc": "paddle.nn.functional.ctc_loss",
    "warprnnt": "paddle.nn.functional.rnnt_loss",
    "nll_loss": "paddle.nn.functional.nll_loss",
    "cross_entropy_with_softmax": "paddle.nn.functional.cross_entropy",
    "c_softmax_with_cross_entropy":
        "fleet.mpu.mp_layers.ParallelCrossEntropy",
    "kldiv_loss": "paddle.nn.functional.kl_div",
    "huber_loss": "paddle.nn.functional.smooth_l1_loss",
    "squared_error": "paddle.nn.functional.mse_loss",
    "bce_loss": "paddle.nn.functional.binary_cross_entropy",
    "multi_margin_loss": "paddle.nn.functional.multi_margin_loss",
    "multiplex": "paddle.multiplex",
    "gather_tree": "paddle.nn beam-search gather_tree (text decoding)",
    "match_matrix_tensor": "text matching op (nn.functional extras)",
    "shuffle_batch": "paddle.incubate shuffle_batch (io shuffling)",
    "shuffle_channel": "paddle.nn.functional.channel_shuffle",
    "unique_consecutive": "paddle.unique_consecutive",
    "expand_modality_expert_id": "MoE expert-id routing (moe_layer.py)",
    "int_bincount": "paddle.bincount (int path)",
    "cal_aux_loss": "MoE balance losses (moe_layer.py wired into Llama)",
    "build_src_rank_and_local_expert_id": "MoE dispatch bookkeeping",
    "moe_gate_dispatch": "MoE gate dispatch einsum",
    "moe_gate_dispatch_permute": "MoE gate dispatch permutation",
    "fused_rope_with_mixed_precision": "fused_rotary_position_embedding",
    "apply_per_channel_scale": "quantization per-channel scale apply",
    "group_quant": "quantization group-wise quantize",
    "mask_gen": "attention mask builders (nn/functional)",
    # optimizer update kernels (yaml `adam_` etc. are the fused update ops
    # behind each optimizer's step; here each optimizer's _rule IS that
    # kernel, jit-fused by XLA)
    "average_accumulates": "paddle.incubate.ModelAverage accumulators",
    "merged_adam_": "optimizer multi-tensor apply path",
    "merged_momentum_": "optimizer multi-tensor apply path",
    # interpolate family (one functional with mode=)
    "bicubic_interp": "paddle.nn.functional.interpolate(mode='bicubic')",
    "bilinear_interp": "paddle.nn.functional.interpolate(mode='bilinear')",
    "linear_interp": "paddle.nn.functional.interpolate(mode='linear')",
    "nearest_interp": "paddle.nn.functional.interpolate(mode='nearest')",
    "trilinear_interp": "paddle.nn.functional.interpolate(mode='trilinear')",
    # fft backend kernels (one public module)
    "fft_c2c": "paddle.fft.fft/ifft family (complex-to-complex)",
    "fft_c2r": "paddle.fft.irfft family (complex-to-real)",
    "fft_r2c": "paddle.fft.rfft family (real-to-complex)",
    "frobenius_norm": "paddle.linalg.norm(p='fro')",
    "l1_norm": "paddle.norm(p=1)",
    "clip_by_norm": "paddle.nn.ClipGradByNorm / linalg-norm clip",
    "logsigmoid": "paddle.nn.functional.log_sigmoid",
    "tanh_shrink": "paddle.nn.functional.tanhshrink",
    "hinge_loss": "paddle.nn.functional.hinge_embedding_loss",
    "mean_all": "paddle.mean (global reduction)",
    "split_with_num": "paddle.split(num_or_sections=int)",
    "shape64": "paddle.shape (int64 shapes are the default here: x64 on)",
    "gru": "paddle.nn.GRU (lax.scan recurrence)",
    "lstm": "paddle.nn.LSTM (lax.scan recurrence)",
    "gru_unit": "paddle.nn.GRUCell",
    "max_pool2d_with_index": "nn.functional.max_pool2d(return_mask=True)",
    "max_pool3d_with_index": "nn.functional.max_pool3d(return_mask=True)",
    "max_pool2d_v2": "nn.functional.max_pool2d",
    "maxpool": "nn.functional.max_pool2d",
    "check_numerics": "paddle.amp.debugging.check_numerics",
    "enable_check_model_nan_inf": "amp.debugging nan/inf scan (flags)",
    "disable_check_model_nan_inf": "amp.debugging nan/inf scan (flags)",
    "accuracy_check": "amp.debugging accuracy compare tooling",
    "assign_value": "paddle.assign",
    "copy_to": "paddle.Tensor.to / Tensor.cuda/cpu",
    "divide_scalar": "paddle.divide (scalar operand)",
    "gaussian_inplace": "paddle.Tensor.normal_",
    "merge_selected_rows": "SelectedRows accumulation "
                           "(framework/containers.py to_dense)",
    "overlap_add": "paddle.signal.overlap_add",
    "to_dense": "SparseCoo/CsrTensor.to_dense",
    "to_sparse_coo": "paddle.Tensor.to_sparse_coo",
    "to_sparse_csr": "paddle.Tensor.to_sparse_csr",
    "indices": "SparseCooTensor.indices",
    "values": "SparseCoo/CsrTensor.values",
    "index_select_strided": "paddle.index_select (XLA strided gather)",
    "view_dtype": "paddle.view(dtype) / Tensor.astype bitcast",
    "view_shape": "paddle.view / Tensor.reshape (functional views)",
    "conv2d_transpose_bias": "paddle.nn.Conv2DTranspose (bias_attr)",
}

# --------------------------------------------------------------------------- #
# waivers: yaml op name pattern/explicit -> reason. These are deliberate
# design decisions, each tied to SURVEY.md's TPU mapping (§2.10/§7).
# --------------------------------------------------------------------------- #
WAIVER_PATTERNS = [
    (re.compile(r".*_xpu$"), "XPU-hardware-specific kernel; no XPU in the "
                             "TPU build (SURVEY §2.10: one backend, XLA)"),
    (re.compile(r"^onednn_|.*_onednn$"), "oneDNN CPU-path fusion; XLA owns "
                                         "CPU codegen here"),
    (re.compile(r"^memcpy"), "explicit H2D/D2H staging op; PJRT buffer "
                             "transfer + jax device_put subsume it"),
    (re.compile(r"^c_"), "static-graph collective op; provided as "
                         "distributed.collective + in_jit prims over GSPMD "
                         "(SURVEY §2.5 mapping)"),
    (re.compile(r"^partial_(send|recv|allgather)"), "pipeline p2p static op; "
                                                    "compiled lax.ppermute "
                                                    "rotation replaces it"),
    (re.compile(r"^(send|recv)_v2$"), "NCCL p2p static op; distributed.\n"
                                      "collective send/recv + pipelining"),
    (re.compile(r"^qkv_attention_xpu$"), "XPU-specific"),
]

WAIVERS = {
    # --- execution/infra ops the XLA runtime subsumes -----------------------
    "share_buffer": "buffer aliasing is XLA/PJRT donation, not an op",
    "shadow_feed": "executor feed plumbing; capture-replay Executor feeds "
                   "tensors directly",
    "shadow_feed_tensors": "same as shadow_feed",
    "print": "host print: paddle.static.Print / jax.debug.callback",
    "assert": "host assert: enforce + jax.debug (checkify-style)",
    "get_tensor_from_selected_rows": "SelectedRows.to_dense "
                                     "(framework/containers.py)",
    "memcpy": "PJRT transfer",
    "all_reduce": "distributed.all_reduce (python collective API)",
    "all_gather": "distributed.all_gather",
    "all_to_all": "distributed.alltoall",
    "broadcast": "distributed.broadcast",
    "reduce": "distributed.reduce",
    "reduce_scatter": "distributed.reduce_scatter",
    "p_send": "distributed send (p2p)",
    "p_recv": "distributed recv (p2p)",
    "barrier": "distributed.barrier",
    "mp_allreduce_sum": "fleet mp_ops._mp_allreduce",
    "global_gather": "MoE all-to-all: GSPMD emits it from dispatch einsums",
    "global_scatter": "MoE all-to-all: GSPMD emits it from dispatch einsums",
    "limit_by_capacity": "MoE static-capacity dispatch handles capacity",
    "prune_gate_by_capacity": "MoE static-capacity dispatch",
    "number_count": "MoE dispatch bookkeeping (einsum formulation)",
    "random_routing": "MoE naive gate variant",
    "pull_box_sparse": "HeterPS/BoxPS GPU-cached embedding PS; out of scope "
                       "per SURVEY §2.6 (PS stub layer)",
    "push_dense": "PS trainer push; ps/tables.py covers the python PS tier",
    "pull_gpups_sparse": "HeterPS",
    "pull_sparse_v2": "PS C++ table pull; ps/tables.py",
    "distributed_push_sparse": "PS sparse push; ps/tables.py",
    "distributed_lookup_table": "PS lookup; ps/tables.py lazy sparse table",
    "nop": "scheduling no-op; XLA token ordering",
    "feed": "executor feed plumbing",
    "fetch": "executor fetch: Executor.run fetch_list",
    "load_combine": "paddle.load (framework_io)",
    "save_combine": "paddle.save (framework_io)",
    "unique_consecutive": "paddle.unique_consecutive",
    "fused_adam_": "optimizer multi-tensor apply (fusion_utils role); XLA "
                   "fuses the update math",
    "fused_batch_norm_act": "XLA fuses BN+act",
    "fused_bn_add_activation": "XLA fuses BN+add+act",
    "fused_elemwise_add_activation": "XLA elementwise fusion",
    "fusion_group": "CINN-style codegen group; XLA fusion pass owns this",
    "fusion_seqconv_eltadd_relu": "LoD sequence fusion; padded-dense "
                                  "sequence_conv + XLA fusion",
    "fusion_seqexpand_concat_fc": "LoD sequence fusion; XLA",
    "fusion_repeated_fc_relu": "XLA fuses fc+relu chains",
    "fusion_squared_mat_sub": "XLA fuses this elementwise/layout chain automatically",
    "fusion_transpose_flatten_concat": "XLA fuses this elementwise/layout chain automatically",
    "fused_embedding_eltwise_layernorm": "XLA fuses embedding+LN",
    "fused_fc_elementwise_layernorm": "XLA fuses fc+LN",
    "fc": "paddle.static.nn.fc / nn.Linear (declarative builder)",
    "add_act_xpu": "XPU-specific",
    "addcmul_xpu": "XPU-specific",
    "skip_layernorm": "inference fusion; XLA",
    "squeeze_excitation_block": "inference fusion; XLA",
    "yolo_box_xpu": "XPU-specific",
    "share_var": "program var sharing; capture-replay env owns identity",
    "dequantize_abs_max": "quantization module observers own dequant",
    "dequantize_log": "quantization module",
    "quantize_linear": "quantization.QuantizeLinear (QDQ export)",
    "dequantize_linear": "quantization QDQ export",
    "fake_channel_wise_dequantize_max_abs": "quantization fake-quant family",
    "fake_channel_wise_quantize_abs_max": "quantization fake-quant family",
    "fake_channel_wise_quantize_dequantize_abs_max": "quantization",
    "fake_dequantize_max_abs": "quantization",
    "fake_quantize_abs_max": "quantization FakeQuanterAbsMax",
    "fake_quantize_dequantize_abs_max": "quantization",
    "fake_quantize_dequantize_moving_average_abs_max": "quantization",
    "fake_quantize_moving_average_abs_max": "quantization",
    "fake_quantize_range_abs_max": "quantization",
    "straight_through_estimator_grad": "quantization STE backward",
    "moving_average_abs_max_scale": "quantization observers",
    "weight_quantize": "quantization.weight_quantize (weight-only int8/int4)",
    "weight_only_linear": "quantization.WeightOnlyLinear",
    "weight_dequantize": "quantization.weight_dequantize",
    "llm_int8_linear": "quantization weight-only int8 linear",
    "self_dp_attention": "CPU inference fusion; sdp dispatch",
    "fusion_seqpool_concat": "LoD fusion; padded-dense sequence_pool",
    "fusion_seqpool_cvm_concat": "LoD+CVM fusion; recsys CVM not in scope",
    "cvm": "click-value-model recsys op (PS tier); out of north-star scope",
    "partial_concat": "recsys micro-op; concat of slices expresses it",
    "partial_sum": "recsys micro-op; sum of slices",
    "rank_attention": "recsys rank feature op (PS tier)",
    "tdm_child": "tree-based recsys ops (PS tier)",
    "tdm_sampler": "tree-based recsys ops (PS tier)",
    "pyramid_hash": "recsys hash embedding (PS tier)",
    "fused_embedding_fc_lstm": "LoD inference fusion; XLA",
    "fusion_gru": "LoD inference fusion; nn.GRU + XLA",
    "fusion_lstm": "LoD inference fusion; nn.LSTM + XLA",
    "multi_gru": "oneDNN GRU fusion",
    "fused_elementwise_add": "XLA fuses this elementwise/layout chain automatically",
    "fused_elementwise_div": "XLA fuses this elementwise/layout chain automatically",
    "fused_elementwise_mul": "XLA fuses this elementwise/layout chain automatically",
    "fused_elementwise_sub": "XLA fuses this elementwise/layout chain automatically",
    "fused_scale_bias_add_relu": "XLA fuses this elementwise/layout chain automatically",
    "fused_scale_bias_relu_conv_bn": "XLA fuses this elementwise/layout chain automatically",
    "fused_dconv_drelu_dbn": "cuDNN-specific backward fusion",
    "fused_dot_product_attention": "cuDNN attention; Pallas flash attention",
    "fused_stack_transpose_quant": "hardware-specific quant fusion",
    "fused_transpose_split_quant": "hardware-specific quant fusion",
    "fused_transpose_wlch_split_quant": "hardware-specific quant fusion",
    "fused_act_dequant": "hardware-specific quant fusion",
    "fused_act_dequant_transpose_act_quant": "hardware-specific",
    "fused_quant_dequant": "hardware-specific",
    "fused_swiglu_weighted_bwd": "XLA derives swiglu backward",
    "fused_weighted_swiglu_act_quant": "hardware-specific",
    "fp8_quant_blockwise": "fp8 blockwise quant: hardware-specific (Hopper)",
    "cross_attention_xpu": "XPU-specific",
    "quantize_xpu": "XPU-specific",
    "dequantize_xpu": "XPU-specific",
    "sine_pos_xpu": "XPU-specific",
    "sequence_unpad_xpu": "XPU-specific",
    "sequence_mask": "paddle.nn.functional.sequence_mask",
    "anchor_generator": "paddle.vision.ops anchor generation",
    "collect_fpn_proposals": "vision.ops FPN proposal path",
    "legacy_generate_proposals": "vision.ops.generate_proposals",
    "unpool3d": "nn.functional.max_unpool3d",
    "lod_array_length": "LoD tensor-array length: tensor_array.py len()",
    "array_length": "tensor_array.py len()",
    "array_pop": "tensor_array.py pop",
    "array_read": "tensor_array.py read",
    "array_to_tensor": "tensor_array.py stack/concat",
    "array_write": "tensor_array.py write",
    "create_array": "tensor_array.py TensorArray",
    "create_array_like": "tensor_array.py",
    "memcpy_d2h": "PJRT transfer",
    "memcpy_h2d": "PJRT transfer",
    "get_empty_tensor": "paddle.empty",
    "seed": "paddle.seed / framework.random",
    "dummy": "placeholder op",
    "run_program": "partial-program executor: jit.to_static cache",
    "builtin_combine": "PIR builtin; no second IR here",
    "reduce_as": "paddle.sum reduce-to-shape: broadcast-aware sum",
    "comm_init_all": "distributed.init_parallel_env",
    "batch_fc": "recsys batched fc; einsum expresses it",
    "beam_search": ("KV-cache beam search: models.llama_decode."
                    "LlamaDecodeEngine.beam_search (no LoD step op needed)"),
    "beam_search_decode": ("sequence readout happens inside "
                           "LlamaDecodeEngine.beam_search (no LoD arrays)"),
    "chunk_eval": "chunking metric (text); metric module scope",
    "crf_decoding": "text crf",
    "ctc_align": "ctc alignment post-process",
    "im2sequence": "legacy vision LoD op; unfold + reshape expresses it",
    "lod_reset": "LoD world; dense padded design",
    "pad2d": "nn.functional.pad",
    "prroi_pool": "precise roi pool: vision.ops roi family",
    "pull_sparse": "PS C++ tier",
    "push_sparse_v2": "PS C++ tier",
    "quantize": "quantization module",
    "dequantize": "quantization module",
    "requantize": "quantization module",
    "search_sort": "legacy search op",
    "sequence_concat": "padded-dense concat",
    "sequence_enumerate": "LoD enumerate; dense windowing",
    "sequence_erase": "LoD erase",
    "sequence_expand": "static.nn.sequence_expand (dense form)",
    "sequence_expand_as": "dense broadcast",
    "sequence_pad": "dense tensors are already padded",
    "sequence_reshape": "LoD reshape",
    "sequence_reverse": "paddle.flip on time axis",
    "sequence_scatter": "LoD scatter",
    "sequence_slice": "LoD slice",
    "sequence_softmax": "static.nn.sequence_softmax (dense form)",
    "sequence_topk_avg_pooling": "LoD pooling family",
    "sequence_unpad": "dense design: lengths mask instead of unpad",
    "stft": "paddle.signal.stft",
    "istft": "paddle.signal.istft",
    "tensor_unfold": "paddle.unfold",
    "uniform_random_batch_size_like": "paddle.uniform + shape_like",
    "unzip": "recsys unzip (PS tier)",
    "zip": "recsys zip (PS tier)",
    "sparse_slice": "paddle.sparse slice over BCOO",
    "sparse_sum": "paddle.sparse.sum",
    # remaining tail (round-4 classification)
    "add_group_norm_silu": "XLA fuses group_norm+silu (inference fusion op)",
    "add_position_encoding": "sinusoidal PE from primitives (transformer "
                             "embedding layers)",
    "affine_channel": "per-channel scale+shift from elementwise primitives "
                      "(legacy vision op)",
    "assign_pos": "MoE dispatch bookkeeping; static-capacity einsum "
                  "formulation needs no position assignment op",
    "attention_lstm": "LoD inference fusion; nn.LSTM + attention layers",
    "bipartite_match": "legacy detection assigner (SSD training pipeline); "
                       "nms/proposal family provided in vision.ops",
    "box_clip": "legacy detection pipeline; clip from elementwise primitives",
    "calc_reduced_attn_scores": "attention-internals helper of the fused "
                                "attention family; flash attention owns it",
    "coalesce_tensor": "gradient fusion storage: XLA buffer assignment + "
                       "donation replace explicit coalescing",
    "conv3d_implicit_gemm": "CUTLASS sparse-conv backend; sparse conv here "
                            "is the gather-scatter formulation",
    "correlation": "flow-correlation volume (legacy vision); composable "
                   "from shifts+reductions",
    "depend": "executor scheduling edge; XLA token ordering",
    "dgc": "deep gradient compression (GPU comm optimization); ICI "
           "bandwidth makes DGC a non-goal on TPU",
    "dgc_clip_by_norm": "deep gradient compression family",
    "distributed_fused_lamb_init": "DistributedFusedLamb init kernel; Lamb "
                                   "+ ZeRO sharding covers the capability",
    "fused_elemwise_activation": "XLA elementwise fusion",
    "fused_seqpool_cvm": "LoD+CVM recsys fusion (PS tier)",
    "fused_token_prune": "ViT token pruning inference fusion; composable",
    "gemm_epilogue": "XLA epilogue fusion of matmul+bias+act",
    "lookup_table_dequant": "PS quantized embedding table (PS tier)",
    "multihead_matmul": "inference attention fusion; sdp dispatch",
    "set": "stride-world in-place set; functional setitem",
    "sync_calc_stream": "CUDA stream sync; no user-managed streams on TPU "
                        "(SURVEY §5 ordering-token mapping)",
    "trans_layout": "NCHW<->NHWC layout transform: XLA layout assignment "
                    "owns layouts; paddle.transpose for explicit cases",
    "view_slice": "stride-view slice; functional slicing (XLA aliasing)",
    "yolo_box_head": "YOLO post-process fusion; vision.ops.yolo_box + nms",
    "yolo_box_post": "YOLO post-process fusion; vision.ops.yolo_box + nms",
}


def provided_names():
    """Every op-name-like callable surface in this framework."""
    import paddle_tpu as paddle
    from paddle_tpu.ops._apply import get_registry

    names = set(get_registry().keys())

    def add_module(mod):
        for n in dir(mod):
            if not n.startswith("_"):
                names.add(n)

    import paddle_tpu.fft
    import paddle_tpu.geometric
    import paddle_tpu.incubate
    import paddle_tpu.incubate.nn.functional
    import paddle_tpu.linalg
    import paddle_tpu.nn.functional
    import paddle_tpu.signal
    import paddle_tpu.sparse
    import paddle_tpu.vision.ops

    add_module(paddle)
    add_module(paddle.nn.functional)
    add_module(paddle.linalg)
    add_module(paddle.fft)
    add_module(paddle.signal)
    add_module(paddle.sparse)
    add_module(paddle.incubate)
    add_module(paddle.incubate.nn.functional)
    add_module(paddle.vision.ops)
    add_module(paddle.geometric)
    add_module(paddle.static.nn)
    for n in dir(paddle.Tensor):
        if not n.startswith("_"):
            names.add(n)
    return names


def classify(verbose=False):
    """Returns dict op -> (bucket, note) over all three yamls."""
    provided = provided_names()
    out = {}
    for yaml_name in ("ops.yaml", "fused_ops.yaml", "sparse_ops.yaml"):
        path = os.path.join(REFERENCE_YAML_DIR, yaml_name)
        if not os.path.exists(path):  # reference absent (CI safety)
            continue
        for op in parse_yaml_ops(path):
            src = yaml_name
            bucket = None
            note = ""
            base = op[:-1] if op.endswith("_") else op
            candidates = {op, base, base + "_", op.lower()}
            if yaml_name == "sparse_ops.yaml":
                # sparse yaml ops live under paddle.sparse with plain names
                candidates |= {base.replace("_coo", "").replace("_csr", "")}
            if candidates & provided:
                bucket, note = "mapped", "name match"
            elif ALIASES.get(op) or ALIASES.get(base):
                bucket, note = "mapped", ALIASES.get(op) or ALIASES.get(base)
            elif op in WAIVERS or base in WAIVERS:
                bucket, note = "waived", WAIVERS.get(op) or WAIVERS.get(base)
            else:
                for pat, reason in WAIVER_PATTERNS:
                    if pat.match(op):
                        bucket, note = "waived", reason
                        break
            if bucket is None:
                bucket, note = "missing", ""
            out[op] = (bucket, note, src)
    return out


def generate_report(path=None):
    """Render docs/ops_parity.md."""
    if path is None:
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), "docs",
            "ops_parity.md")
    cls = classify()
    buckets = {"mapped": [], "waived": [], "missing": []}
    for op, (bucket, note, src) in sorted(cls.items()):
        buckets[bucket].append((op, note, src))
    n = len(cls)
    lines = [
        "# ops.yaml parity report",
        "",
        "Generated by `python -m paddle_tpu.ops.parity` against",
        "`/root/reference/paddle/phi/ops/yaml/{ops,fused_ops,sparse_ops}"
        ".yaml`.",
        "",
        f"Total reference ops: **{n}** — mapped {len(buckets['mapped'])}, "
        f"waived {len(buckets['waived'])}, missing "
        f"{len(buckets['missing'])}.",
        "",
        "* **mapped** — the capability exists here under the same name or "
        "the documented alias.",
        "* **waived** — deliberately not provided as a standalone op; the "
        "reason ties to SURVEY.md's TPU mapping (XLA fusion, PJRT transfer, "
        "GSPMD collectives, LoD->padded-dense design, PS/XPU/oneDNN scope).",
        "* **missing** — acknowledged gaps.",
        "",
    ]
    for bucket in ("missing", "waived", "mapped"):
        rows = buckets[bucket]
        lines.append(f"## {bucket} ({len(rows)})")
        lines.append("")
        lines.append("| op | source | where / why |")
        lines.append("|---|---|---|")
        for op, note, src in rows:
            lines.append(f"| `{op}` | {src.split('.')[0]} | {note} |")
        lines.append("")
    with open(path, "w") as f:
        f.write("\n".join(lines))
    return path, {k: len(v) for k, v in buckets.items()}


if __name__ == "__main__":
    p, counts = generate_report()
    print(p, counts)
