"""GL008 dirty-tree control: the same shapes done right (must stay
silent)."""
import jax

from paddle_tpu.jit import to_static
from paddle_tpu.ops._apply import defop


def make_op(name, factor):
    # factory: registers inside a function but returns the wrapper UNCALLED
    # — registration runs once, at import, where the factory is invoked
    @defop(name)
    def _op(v):
        return v * factor

    return _op


scale_good = make_op("scale_good", 2)


@jax.jit
def stable(x, training):
    # branching on a PYTHON argument is part of the signature by design
    if training:
        return x * 2
    return x


def _module_key(v):
    return v + 1


compiled = to_static(lambda v, fn: fn(v))


def run_stable(x):
    return compiled(x, _module_key)       # stable identity: one signature
