"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from ..initializer import Constant
from .layers import Layer


def _simple(fn_name, **fixed):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._kwargs = {**fixed}
            # positional args map onto the functional's named params in order
            fn = getattr(F, fn_name)
            import inspect

            params = [p for p in inspect.signature(fn).parameters if p not in ("x", "name")]
            for name_, val in zip(params, args):
                self._kwargs[name_] = val
            for k, v in kwargs.items():
                if k != "name":
                    self._kwargs[k] = v

        def forward(self, x):
            return getattr(F, fn_name)(x, **self._kwargs)

    _Act.__name__ = fn_name
    return _Act


ReLU = _simple("relu")
ReLU6 = _simple("relu6")
Sigmoid = _simple("sigmoid")
Tanh = _simple("tanh")
Silu = _simple("silu")
Swish = _simple("swish")
Mish = _simple("mish")
Hardswish = _simple("hardswish")
Hardsigmoid = _simple("hardsigmoid")
Hardtanh = _simple("hardtanh")
Tanhshrink = _simple("tanhshrink")
Softsign = _simple("softsign")
Softshrink = _simple("softshrink")
Hardshrink = _simple("hardshrink")
Softplus = _simple("softplus")
ELU = _simple("elu")
CELU = _simple("celu")
SELU = _simple("selu")
LeakyReLU = _simple("leaky_relu")
GELU = _simple("gelu")
LogSoftmax = _simple("log_softmax")
Softmax = _simple("softmax")
ThresholdedReLU = _simple("thresholded_relu")
Maxout = _simple("maxout")
GLU = _simple("glu")


class Tanh_(Layer):
    def forward(self, x):
        return F.tanh(x)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter([num_parameters], attr=weight_attr,
                                            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class Softmax2D(Layer):
    def forward(self, x):
        return F.softmax(x, axis=-3)
