"""bench_suite.py: the five BASELINE configs must run end-to-end on CPU
(smoke shapes) and emit well-formed result rows. Reference analog: the
configs named in BASELINE.json (LeNet / ResNet-50 AMP / BERT-base DP /
GPT hybrid / LLaMA — the last is bench.py's flagship)."""
import json
import os
import subprocess
import sys

import pytest

from _retry import retry_smoke, wall_clock_floor

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SUITE = os.path.join(ROOT, "bench_suite.py")


def _run_smoke(config, timeout):
    """One `bench_suite.py --smoke <config>` pass -> its JSON row. The
    worker's own hard bounds are asserted inside the bench (non-zero exit
    fails here immediately)."""
    env = dict(os.environ)
    env["PADDLE_TPU_PLATFORM"] = "cpu"
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, SUITE, "--smoke", config],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=ROOT)
    assert out.returncode == 0, out.stderr[-800:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def _run(configs, timeout=560):
    env = dict(os.environ)
    env["PADDLE_TPU_PLATFORM"] = "cpu"
    out = subprocess.run(
        [sys.executable, SUITE, "--configs", configs],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=ROOT)
    assert out.returncode == 0, out.stderr[-800:]
    rows = json.loads(out.stdout)
    assert [r["config"] for r in rows] == configs.split(",")
    for r in rows:
        assert "error" not in r, r
        assert r["value"] > 0
    return rows


class TestServingSmoke:
    # fast tier on purpose: `bench_suite.py --smoke serving` is the
    # tier-1-safe invocation of the serving benchmark (ISSUE 5)
    def test_smoke_serving_meets_acceptance(self):
        # the >= 2x speedup is a wall-clock ratio on a shared CPU: the
        # single contention-aware gate in tests/_retry.py (retry budget +
        # floor relax together under measured oversubscription)
        floor = wall_clock_floor(2.0, 1.4)
        row = retry_smoke(
            lambda: _run_smoke("serving", 300),
            lambda r: r["detail"]["speedup_vs_static"] >= floor)
        assert row["config"] == "serving"
        assert row["unit"] == "tokens/s"
        d = row["detail"]
        assert row["value"] == d["serving_tokens_per_sec"] > 0
        # ISSUE 5 acceptance: continuous batching + chunked prefill at
        # >= 2x the static-batch engine's tokens/s, equal batch capacity
        # (contention-relaxed floor on oversubscribed runners)
        assert d["speedup_vs_static"] >= floor, d
        # ... with exact shared-block reuse and a fully warm cache pass
        assert d["warm_tokens_match"] is True
        assert d["prefix_hit_rate"] == 1.0
        assert d["static_tokens_per_sec"] > 0
        for k in ("p50", "p99"):
            assert d["ttft_ms"][k] > 0
            assert d["static_ttft_ms"][k] > 0

    def test_smoke_rejects_unknown_config(self):
        out = subprocess.run(
            [sys.executable, SUITE, "--smoke", "lenet"],
            capture_output=True, text=True, timeout=60, cwd=ROOT)
        assert out.returncode != 0
        assert "serving" in out.stderr


class TestChaosSmoke:
    # fast tier on purpose: `bench_suite.py --smoke chaos` is the ISSUE 6
    # resilience drill — kill the driving thread mid-decode, recover
    # warm, and hold gold goodput under a shedding bronze flood
    def test_smoke_chaos_meets_acceptance(self):
        # the goodput ratio is a wall-clock measurement on a shared CPU:
        # the tests/_retry.py gate retries it (a worker whose own
        # wall-clock bound tripped consumes a retry too); every accepted
        # run passed the drill's hard bounds (asserted inside run_chaos)
        floor = wall_clock_floor(0.9, 0.7)
        row = retry_smoke(lambda: _run_smoke("chaos", 560),
                          lambda r: r["value"] >= floor)
        assert row["config"] == "chaos"
        assert row["unit"] == "goodput_ratio"
        d = row["detail"]
        k, o = d["kill_drill"], d["overload"]
        # kill drill: the driving thread died, ONE recovery fired a
        # flight dump, restarted warm, and outputs are bit-identical
        assert k["killed"] is True
        assert k["recoveries"] == 1
        assert k["flight_dump"]
        assert k["recovered_warm"] is True
        assert k["tokens_match_reference"] is True
        assert 0 < k["recovery_ms"] < 5000
        # overload drill: bronze sheds with typed rejections while gold
        # keeps >= 90% of its isolated goodput, outputs untouched
        assert o["bronze_shed"] > 0
        assert 0.05 <= o["bronze_shed_rate"] <= 0.95
        assert o["gold_tokens_match_isolated"] is True
        assert row["value"] == o["gold_goodput_ratio"] >= floor, o


class TestSpecSmoke:
    # fast tier on purpose: `bench_suite.py --smoke spec` is the ISSUE 7
    # speculative-decoding + quantized-KV acceptance — spec-on vs
    # spec-off at equal engine config on a repeat-heavy workload, plus
    # the int8 pool capacity check
    def test_smoke_spec_meets_acceptance(self):
        # the speedup is a wall-clock measurement on a shared CPU: the
        # tests/_retry.py gate retries and contention-relaxes the bar;
        # every run must pass the bench's own hard bounds
        # (bit-exactness, accept rate, capacity — asserted inside
        # run_spec, a non-zero exit consumes a retry)
        floor = wall_clock_floor(1.3, 1.05)
        row = retry_smoke(lambda: _run_smoke("spec", 300),
                          lambda r: r["value"] >= floor)
        assert row["config"] == "spec"
        assert row["unit"] == "speedup_vs_nonspec"
        d = row["detail"]
        # ISSUE 7 acceptance: >= 1.3x serving tokens/s on the
        # repetitive workload, with the accept rate reported and greedy
        # outputs bit-identical to the non-spec pass
        assert row["value"] == d["spec_speedup"] >= floor, d
        assert d["spec_tokens_match"] is True
        assert d["spec_accepted_tokens"] > 0
        assert 0 < d["spec_accept_rate"] <= 1.0
        assert d["spec_on_tokens_per_sec"] > d["spec_off_tokens_per_sec"] > 0
        # ... and the quantized pools admit >= 1.8x the concurrent
        # requests of the full-precision engine at an equal-or-smaller
        # byte budget (read from the pool-bytes gauge)
        cap = d["int8_capacity"]
        assert cap["request_ratio"] >= 1.8, cap
        assert cap["bytes_ratio"] <= 1.0, cap
        assert cap["int8"]["pool_bytes"] <= cap["ref"]["pool_bytes"]


class TestMeshSmoke:
    # fast tier on purpose: `bench_suite.py --smoke mesh` is the ISSUE 8
    # acceptance — DP=8 and DP x TP = 4x2 training of the llama step on
    # the simulated 8-device mesh, losses matching single-device, real
    # collectives in the compiled programs, ZeRO-1 state ~1/dp
    def test_smoke_mesh_meets_acceptance(self):
        # tokens/s is wall-clock on a shared CPU (reported, not gated);
        # retry only guards scheduler-noise zeros — the real bounds are
        # hard-asserted inside run_mesh (non-zero exit fails here)
        row = retry_smoke(lambda: _run_smoke("mesh", 560),
                          lambda r: r.get("value", 0) > 0)
        assert row["config"] == "mesh"
        assert row["unit"] == "tokens/s"
        d = row["detail"]
        assert row["value"] == d["dp8_tokens_per_sec"] > 0
        # ISSUE 8 acceptance: losses match single-device within fp
        # tolerance on every mesh pass
        assert d["dp8_loss_close"] is True
        assert d["zero1_loss_close"] is True
        assert d["hybrid_loss_close"] is True
        # ... with real collectives in the compiled step programs
        assert d["collectives"]["dp8"]["all_reduce"] >= 1
        assert d["collectives"]["dp8_zero1"]["reduce_scatter"] >= 1
        assert d["collectives"]["dp8_zero1"]["all_gather"] >= 1
        # ... and the ZeRO-1 knob shrinking per-replica optimizer state
        # to <= ~(1/dp + eps) of the replicated layout
        b = d["opt_state_bytes"]
        assert b["zero1_per_replica"] < b["replicated"]
        assert b["ratio"] <= 1.0 / d["dp"] + 0.02, b
        # ISSUE 13 acceptance: int8 grad reduction cuts grad
        # bytes-on-wire to <= 30% of the uncompressed ZeRO exchange
        # (census-measured: int8 all_to_all payload + fp32 scales vs the
        # fp32 psum_scatter rows) with final-loss parity inside the
        # declared bound, and the overlap pass really buckets
        c = d["comm_opt"]["int8"]
        assert c["grad_bytes_ratio"] <= 0.30, c
        assert c["loss_parity"] is True
        assert c["loss_gap"] <= c["parity_bound"]
        assert c["buckets"] >= 2
        assert c["grad_bytes_compressed"] < c["grad_bytes_uncompressed"]
        assert d["comm_opt"]["overlap"]["buckets"] >= 2
        # compressed_bytes stamped next to the PR 12 collective_bytes
        assert "dp8_zero1_int8" in d["collective_bytes"]
        assert d["collective_bytes"]["dp8_zero1_int8"][
            "all_to_all"]["bytes"] == c["grad_bytes_compressed"]
        # ISSUE 15 acceptance: the graftscope modeled timeline finally
        # MEASURES the PR 13 overlap claim — the completion-ordered
        # bucketed build strictly above the legacy tape-end exchange
        # (deterministic: the model depends only on the traced programs)
        t = d["timeline"]
        assert t["overlap_strictly_higher"] is True
        assert t["overlapped"]["overlap_fraction"] \
            > t["non_overlapped"]["overlap_fraction"]
        assert 0.0 <= t["non_overlapped"]["overlap_fraction"] <= 1.0
        assert 0.0 <= t["overlapped"]["overlap_fraction"] <= 1.0
        assert t["overlapped"]["collectives"] \
            < t["non_overlapped"]["collectives"]


class TestTrainChaosSmoke:
    # fast tier on purpose: `bench_suite.py --smoke trainchaos` is the
    # ISSUE 10 training-resilience drill — kill a DP=8 mesh train run
    # mid-step, recover WARM from the last committed async checkpoint,
    # and replay to a bit-identical final loss
    def test_smoke_trainchaos_meets_acceptance(self):
        # recovery latency is wall-clock on a shared CPU: the
        # tests/_retry.py gate retries (a worker whose own <5s bound
        # tripped under contention consumes a retry) and relaxes the
        # in-test bar when the runner is oversubscribed; correctness
        # bounds (kill/recovery/bit-identity/zero recompiles) are hard
        # inside run_trainchaos
        floor_ms = wall_clock_floor(5000, 10000)
        row = retry_smoke(lambda: _run_smoke("trainchaos", 560),
                          lambda r: 0 < r["value"] < floor_ms)
        assert row["config"] == "trainchaos"
        assert row["unit"] == "recovery_ms"
        d = row["detail"]
        # ISSUE 10 acceptance: the driving step died mid-run, ONE warm
        # recovery restored from the last committed checkpoint...
        assert d["killed"] is True
        assert d["recoveries"] == 1
        assert d["flight_dump"]
        assert d["restored_step"] >= 0
        assert d["restored_step"] in d["committed_steps"] or \
            d["restored_step"] == 0
        # ... the compiled step program survived (warm = zero
        # post-recovery recompiles) ...
        assert d["compiled_programs_after_recovery"] == 1
        # ... and the resumed run's per-step losses are bit-identical
        # to the uninterrupted reference pass
        assert d["losses_bit_identical"] is True
        assert d["final_loss_chaos"] == d["final_loss_ref"]
        assert row["value"] == d["recovery_ms"] < floor_ms, d


class TestFusionSmoke:
    # fast tier on purpose: `bench_suite.py --smoke fusion` is the
    # ISSUE 12 acceptance — graftopt fusion rewrites over the three
    # live flagship programs (bit-exact, fewer fusible regions) plus
    # the HBM-budget remat drill on the DP=8 ZeRO-1 llama step
    def test_smoke_fusion_meets_acceptance(self):
        # every gate inside run_fusion is DETERMINISTIC (bit-exactness,
        # region counts, estimate/measured band, plan size, recompile
        # silence) — retry only guards scheduler-noise worker deaths;
        # the step-time speedups are reported, never gated
        row = retry_smoke(lambda: _run_smoke("fusion", 560),
                          lambda r: r.get("value", 0) > 0)
        assert row["config"] == "fusion"
        assert row["unit"] == "region_reduction_x"
        d = row["detail"]
        # ISSUE 12 acceptance: optimized mixed_step/decode_burst (and
        # the mesh step) bit-identical to unoptimized...
        for name in ("serving.mixed_step", "serving.decode_burst",
                     "mesh.train_step"):
            prog = d["fusion"][name]
            assert prog["bit_exact"] is True
            # ... with a measurable dispatch-count (fusible-region) win
            assert prog["regions"][1] < prog["regions"][0]
            assert sum(prog["rewrites"].values()) >= 1
        assert row["value"] > 1.0
        # ... and the budget drill: a budget below the unoptimized
        # GI003 peak produces a fitting plan the compiler confirms,
        # with loss parity and a silent recompile sentinel
        rm = d["remat"]
        assert rm["budget_bytes"] < rm["unoptimized_peak_bytes"]
        assert rm["plan_size"] >= 1
        assert rm["fits_budget"] is True
        assert rm["within_band"] is True
        assert rm["loss_parity"] is True
        assert rm["recompiles_post_warmup"] == 0


class TestFleetSmoke:
    # fast tier on purpose: `bench_suite.py --smoke fleet` is the
    # ISSUE 14 resilience drill — kill 1 of 3 router-driven replicas
    # mid-workload, fail over bit-identically, and drain gracefully
    def test_smoke_fleet_meets_acceptance(self):
        # every gate inside run_fleet is deterministic except the
        # recovery-latency wall clock; retry_smoke absorbs a contended
        # runner (a worker whose own bounds tripped consumes a retry)
        row = retry_smoke(lambda: _run_smoke("fleet", 560),
                          lambda r: r.get("value", 0) > 0)
        assert row["config"] == "fleet"
        assert row["unit"] == "tokens/s"
        d = row["detail"]
        assert row["value"] == d["fleet_tokens_per_sec"] > 0
        assert d["all_complete_reference"] is True
        k = d["kill_drill"]
        # ISSUE 14 acceptance: 1-of-3 replicas killed mid-workload →
        # every request completes, outputs bit-identical to the
        # undisturbed fleet, >= 1 failover counted ...
        assert k["killed"] is True
        assert k["recoveries"] >= 1
        assert k["failovers"] >= 1
        assert k["all_complete"] is True
        assert k["tokens_match_reference"] is True
        # ... warm recovery: survivors' compiled programs untouched
        # (zero post-warmup recompiles under the graftsan sentinel),
        # a per-replica flight dump, and a bounded recovery
        assert k["recompiles_post_warmup"] == 0
        assert k["sentinel_trips"] == 0
        assert k["flight_dump"] and k["down_replica"] in k["flight_dump"]
        assert 0 < k["recovery_ms"] < 5000
        # ... and the drain drill loses zero requests
        dd = d["drain_drill"]
        assert dd["lost"] == 0 and dd["all_complete"] is True
        assert dd["parked"] is True
        assert dd["tokens_match_reference"] is True
        assert dd["states"][dd["drained_replica"]] == "parked"


class TestObsSmoke:
    # fast tier on purpose: `bench_suite.py --smoke obs` is the
    # ISSUE 15 graftscope drill — the serving smoke workload under a
    # 10 Hz scraper polling the live debug endpoint
    def test_smoke_obs_meets_acceptance(self):
        # the <=3% overhead bound is a wall-clock ratio on a shared
        # CPU: the single contention-aware gate in tests/_retry.py
        # (retry budget + floor relax together under measured
        # oversubscription); every other gate in run_obs is
        # deterministic and asserted in-worker
        floor = wall_clock_floor(0.97, 0.80)
        row = retry_smoke(
            lambda: _run_smoke("obs", 400),
            lambda r: r["detail"]["overhead_ratio"] >= floor)
        assert row["config"] == "obs"
        assert row["unit"] == "scraped_vs_unscraped_ratio"
        d = row["detail"]
        # ISSUE 15 acceptance: a 10 Hz scraper costs <= 3% tokens/s
        # (contention-relaxed floor on oversubscribed runners) ...
        assert d["overhead_ratio"] >= floor, d
        assert d["scrapes"] >= 5 and d["scrape_errors"] == 0
        # ... while changing NOTHING but wall clock: outputs
        # bit-identical to the unscraped pass
        assert d["tokens_match"] is True
        # ... and the timeline decomposition stays SANE: components
        # non-negative and inside the measured TTFT for every request
        # (the sum identity holds by construction; this is the
        # falsifiable half)
        dec = d["ttft_decomposition"]
        assert dec["components_sane"] is True
        assert dec["requests"] == d["requests"]
        assert dec["p50_ms"]["ttft_ms"] > 0
        assert dec["p50_ms"]["prefill_ms"] > 0


class TestControlSmoke:
    # fast tier on purpose: `bench_suite.py --smoke control` is the
    # graftpilot diurnal sweep — static vs controlled vs controller-off
    # over the same quiet -> peak -> quiet arrivals
    def test_smoke_control_meets_acceptance(self):
        # the comparative bar (controller-on accrues no more
        # SLO-violation minutes than static) compares two measured
        # wall-clock passes on a shared CPU, so it routes through the
        # single contention-aware gate: strict on a quiet runner, one
        # extra violating window of slack on an oversubscribed one.
        # Every other gate (replay identity, bounds/slew, scale-ups,
        # bit-identical outputs) is deterministic and asserted
        # in-worker.
        slack = wall_clock_floor(0.0, 0.009)

        def better(r):
            d = r["detail"]
            return (d["controlled"]["slo_violation_minutes"]
                    <= d["static"]["slo_violation_minutes"] + slack)

        row = retry_smoke(lambda: _run_smoke("control", 400), better)
        assert row["config"] == "control"
        assert row["unit"] == "slo_violation_minutes"
        d = row["detail"]
        c = d["controlled"]
        # the closed loop must help (or at least never hurt) the SLO
        assert c["slo_violation_minutes"] \
            <= d["static"]["slo_violation_minutes"] + slack, d
        # the autoscaler resumed drained replicas under the peak and
        # the record carries the knob trajectories
        assert c["scale_ups"] >= 1 and c["replicas_final"] == 3, c
        assert "fleet.replicas" in c["knob_trajectories"], c
        # flight-recorder contract: the record replays bit-identically
        # and every actuation respected its declared bounds
        assert c["replay_identical"] is True, c
        assert c["bounds_violations"] == [], c
        # controller off (built, registered, never ticked) = zero
        # behavior change; controller on moves latency, never tokens
        assert d["off_tokens_match_static"] is True, d
        assert d["controlled_tokens_match_static"] is True, d


@pytest.mark.slow
class TestBenchSuite:
    def test_lenet_and_bert(self):
        rows = _run("lenet,bert_dp")
        assert rows[0]["unit"] == "images/s"
        assert rows[0]["detail"]["mode"] == "eager"
        assert rows[1]["unit"] == "tokens/s"
        assert rows[1]["detail"]["dp_degree"] == 1

    def test_resnet50_amp(self):
        (row,) = _run("resnet50")
        assert row["detail"]["amp"] in ("O1", "O2")
        assert row["detail"]["step_ms"] > 0

    def test_gpt_hybrid_trains_on_virtual_mesh(self):
        (row,) = _run("gpt_hybrid")
        assert row["detail"]["mesh"].startswith("tp2 x pp2 x sharding2")
        assert row["detail"]["trains"] is True
