"""GL004 dirty sample: device work and blocking waits under a lock."""
import threading
import time

import jax.numpy as jnp


class BadRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0.0

    def record(self, values):
        with self._lock:
            # device dispatch under the lock: every other thread convoys
            # behind XLA execution
            self._total += float(jnp.sum(values))

    def flush(self, worker):
        with self._lock:
            time.sleep(0.1)      # sleeping while holding the lock
            worker.join()        # blocking join under the lock
