"""paddle_tpu.framework — core runtime (tensor handle, dtypes, flags, RNG)."""
from . import dtype, enforce, flags, random  # noqa: F401
from .core import Parameter, Tensor, to_tensor  # noqa: F401

from .containers import SelectedRows, StringTensor  # noqa: F401,E402
