"""Every example script must run end-to-end (the switching user's first
touch of the framework; reference keeps its demos green the same way)."""
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EX = os.path.join(REPO, "examples")


def _run(script, extra_env=None, timeout=300):
    env = dict(os.environ)
    env["PADDLE_TPU_PLATFORM"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra_env or {})
    proc = subprocess.run([sys.executable, os.path.join(EX, script)],
                          env=env, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO)
    assert proc.returncode == 0, f"{script}:\n{proc.stdout}\n{proc.stderr}"
    return proc.stdout


def test_eager_train():
    out = _run("eager_train.py")
    assert "final loss" in out


def test_mnist_hapi():
    out = _run("mnist_hapi.py")
    assert "eval:" in out


def test_static_mnist():
    out = _run("static_mnist.py")
    assert "final loss" in out


def test_jit_to_static():
    out = _run("jit_to_static.py")
    assert "reloaded output shape" in out


def test_llama_pretrain_hybrid():
    out = _run("llama_pretrain_hybrid.py",
               {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
               timeout=420)
    assert "step 2" in out


def test_quantize_and_serve():
    out = _run("quantize_and_serve.py")
    assert "decoded:" in out and "predictor output" in out


def test_launch_dp_under_launcher():
    env = dict(os.environ)
    env["PADDLE_TPU_PLATFORM"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", os.path.join(EX, "launch_dp.py")],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_static_amp_train():
    out = _run("static_amp_train.py")
    assert "final loss" in out


def test_ps_train_under_launcher():
    env = dict(os.environ)
    env["PADDLE_TPU_PLATFORM"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--run_mode", "ps", "--server_num", "1", "--trainer_num", "2",
         os.path.join(EX, "ps_train.py")],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_serve_continuous_batching():
    out = _run("serve_continuous_batching.py")
    assert "[paged]" in out and "[beams]" in out
    assert out.count("[serve] request") == 3
