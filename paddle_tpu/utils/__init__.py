"""paddle.utils parity namespace."""
from . import custom_op  # noqa: F401
from .custom_op import get_custom_op, register_custom_op  # noqa: F401
from ..ops.optable import generate_op_docs, op_table  # noqa: F401
