"""bench.py driver contract: always exit 0, always print exactly one JSON
line, and replay the cached on-device measurement (stale=true) when the
live TPU path fails — the round-2/round-4 wedged-tunnel lesson."""
import json
import os
import shutil
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(ROOT, "bench.py")
CACHE = os.path.join(ROOT, "bench_cache.json")


def _run_bench(env_extra, timeout=560):
    env = dict(os.environ)
    env.update(env_extra)
    p = subprocess.run([sys.executable, BENCH], capture_output=True,
                       text=True, timeout=timeout, env=env, cwd=ROOT)
    assert p.returncode == 0, p.stderr[-500:]
    lines = [ln for ln in p.stdout.splitlines() if ln.strip().startswith("{")]
    assert len(lines) == 1, p.stdout  # exactly one JSON line on stdout
    return json.loads(lines[0])


@pytest.mark.slow
class TestBenchContract:
    def test_cache_replay_when_tpu_unreachable(self, tmp_path):
        """With the probe forced to fail instantly and a cache present, the
        orchestrator must replay the cached TPU number marked stale."""
        backup = None
        if os.path.exists(CACHE):
            backup = tmp_path / "cache.bak"
            shutil.copy(CACHE, backup)
        try:
            doc = {"metric": "llama_train_tokens_per_sec", "value": 111.0,
                   "unit": "tokens/s", "vs_baseline": 0.42,
                   "detail": {"device": "TPU test", "mfu": 0.42,
                              "measured_at": "2030-01-01T00:00:00Z",
                              "measured_git_rev": "deadbee"}}
            with open(CACHE, "w") as f:
                json.dump(doc, f)
            out = _run_bench({"BENCH_PROBE_TIMEOUT": "1",
                              "BENCH_TPU_ATTEMPTS": "1",
                              # the probe child must not reach a live backend
                              "JAX_PLATFORMS": "definitely_not_a_backend"},
                             timeout=300)
            d = out["detail"]
            assert d.get("stale") is True
            assert out["vs_baseline"] == 0.42
            assert d["measured_git_rev"] == "deadbee"
            assert "tpu_error" in d  # failure provenance preserved
        finally:
            if backup is not None:
                shutil.copy(backup, CACHE)
            elif os.path.exists(CACHE):
                os.remove(CACHE)

    def test_expired_cache_is_not_replayed(self, tmp_path):
        """Entries older than BENCH_CACHE_MAX_AGE_H must not replay (a
        long-broken TPU path cannot serve ancient numbers forever)."""
        backup = None
        if os.path.exists(CACHE):
            backup = tmp_path / "cache.bak"
            shutil.copy(CACHE, backup)
        try:
            doc = {"metric": "llama_train_tokens_per_sec", "value": 1.0,
                   "unit": "tokens/s", "vs_baseline": 0.9,
                   "detail": {"device": "TPU test", "mfu": 0.9,
                              "measured_at": "2020-01-01T00:00:00Z"}}
            with open(CACHE, "w") as f:
                json.dump(doc, f)
            # NO BENCH_FORCE_CPU here: the step-1 worker must genuinely
            # fail (bogus backend) so the cache IS consulted; the expired
            # entry must be skipped en route to the step-3 CPU fallback
            out = _run_bench({"BENCH_PROBE_TIMEOUT": "1",
                              "BENCH_TPU_ATTEMPTS": "1",
                              "JAX_PLATFORMS": "definitely_not_a_backend"})
            assert out["detail"].get("stale") is not True
            assert out["detail"]["device"] == "cpu"
            assert "tpu_error" in out["detail"]
        finally:
            if backup is not None:
                shutil.copy(backup, CACHE)
            elif os.path.exists(CACHE):
                os.remove(CACHE)
