"""Fused op surface (paddle.incubate.nn.functional).

Reference analog: python/paddle/incubate/nn/functional/{fused_rotary_position_embedding,
fused_rms_norm, fused_layer_norm, swiglu, fused_dropout_add, fused_linear}.py — hand-fused
CUDA kernels. TPU-first: each is ONE defop (a single jax-traceable function), so XLA fuses
it into neighbouring HLO; the per-op eager path still runs it as one cached executable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....nn.functional.activation import swiglu  # noqa: F401  (already fused)
from ....ops._apply import defop


def _rotate_half(x):
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def _rotate_every_two(x):
    # interleaved layout: rotation pairs are (2i, 2i+1)
    x1 = x[..., ::2]
    x2 = x[..., 1::2]
    return jnp.stack([-x2, x1], axis=-1).reshape(x.shape)


def _rope_tables(seq_len, head_dim, theta, dtype, position_ids=None, every_two=True):
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    if position_ids is None:
        t = jnp.arange(seq_len, dtype=jnp.float32)
    else:
        t = position_ids.astype(jnp.float32)
    freqs = jnp.einsum("...s,d->...sd", t, inv_freq)
    if every_two:
        emb = jnp.repeat(freqs, 2, axis=-1)                  # [f0, f0, f1, f1, ...]
    else:
        emb = jnp.concatenate([freqs, freqs], axis=-1)       # [f0..f_{D/2-1}, f0..]
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _normalize_rope_table(tbl):
    """Accept (S,D), (B,S,D), (1,S,1,D)/(B,S,1,D) layouts → (S,D) or (B,S,D)."""
    if tbl.ndim == 4:                                        # (B,S,1,D) head axis
        tbl = tbl.reshape(tbl.shape[0], tbl.shape[1], tbl.shape[3])
    if tbl.ndim == 3 and tbl.shape[0] == 1:
        tbl = tbl[0]
    return tbl


@defop("fused_rotary_position_embedding", amp_category="white")
def _fused_rope(q, k=None, v=None, sin=None, cos=None, position_ids=None,
                use_neox_rotary_style=True, rotary_theta=10000.0):
    """q/k/v: (B, S, H, D). RoPE applies to EVERY provided input (the reference
    kernel loops all of q/k/v: fused_rope_utils.h rotate_every_two iterates
    num_inputs). use_neox_rotary_style=True selects the interleaved rotate-every-two
    pairing, False the half-split rotate-half pairing — per the kernel dispatch at
    fused_rope_kernel.cu:188-190 (NOT the usual HF naming). Auto-generated tables use
    the pairing-consistent frequency layout for each style."""
    S, D = q.shape[1], q.shape[-1]
    if cos is None or sin is None:
        cos, sin = _rope_tables(S, D, rotary_theta, q.dtype, position_ids,
                                every_two=use_neox_rotary_style)
    else:
        cos = _normalize_rope_table(cos)
        sin = _normalize_rope_table(sin)
    # broadcast (…S,D) over batch/head axes of (B,S,H,D)
    if cos.ndim == 2:
        cos_b = cos[None, :, None, :]
        sin_b = sin[None, :, None, :]
    else:  # (B,S,D) from position_ids
        cos_b = cos[:, :, None, :]
        sin_b = sin[:, :, None, :]

    if use_neox_rotary_style:
        def rot(x):
            return x * cos_b + _rotate_every_two(x) * sin_b
    else:
        def rot(x):
            return x * cos_b + _rotate_half(x) * sin_b

    outs = tuple(rot(t) for t in (q, k, v) if t is not None)
    return outs[0] if len(outs) == 1 else outs


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    rotary_theta=10000.0, name=None):
    out = _fused_rope(q, k, v, sin=sin, cos=cos, position_ids=position_ids,
                      use_neox_rotary_style=use_neox_rotary_style,
                      rotary_theta=rotary_theta)
    if not isinstance(out, tuple):
        out = (out,)
    # fixed positional slots: None inputs yield None outputs in their own slot
    res, it = [], iter(out)
    for t in (q, k, v):
        res.append(next(it) if t is not None else None)
    return tuple(res)


@defop("fused_rms_norm", amp_category="fp32")
def _fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6, begin_norm_axis=-1):
    axes = tuple(range(begin_norm_axis % x.ndim, x.ndim))
    # promote, don't demote: bf16 -> f32 for stability, f64 stays f64
    xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
    var = jnp.mean(xf * xf, axis=axes, keepdims=True)
    y = (xf * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)
    if norm_weight is not None:
        y = y * norm_weight
    if norm_bias is not None:
        y = y + norm_bias
    return y


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6, begin_norm_axis=-1,
                   name=None):
    return _fused_rms_norm(x, norm_weight, norm_bias, epsilon=epsilon,
                           begin_norm_axis=begin_norm_axis)


@defop("fused_layer_norm", amp_category="fp32")
def _fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                      begin_norm_axis=-1, residual=None):
    if residual is not None:
        x = x + residual
    axes = tuple(range(begin_norm_axis % x.ndim, x.ndim))
    # promote, don't demote: bf16 -> f32 for stability, f64 stays f64
    xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    y = ((xf - mean) * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)
    if norm_weight is not None:
        y = y * norm_weight
    if norm_bias is not None:
        y = y + norm_bias
    return y


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=-1, residual=None, name=None):
    return _fused_layer_norm(x, norm_weight, norm_bias, epsilon=epsilon,
                             begin_norm_axis=begin_norm_axis, residual=residual)


@defop("fused_dropout_add")
def _fused_dropout_add(x, y, key=None, p=0.5, training=True,
                       mode="upscale_in_train"):
    if not training or p == 0.0 or key is None:
        return x + y
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    if mode == "upscale_in_train":
        dropped = jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    else:
        dropped = jnp.where(keep, x, 0.0).astype(x.dtype)
    return dropped + y


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train", name=None):
    from ....framework import random as rng

    key = rng.next_key() if (training and p > 0.0) else None
    return _fused_dropout_add(x, y, key=key, p=p, training=training, mode=mode)


@defop("fused_linear")
def _fused_linear(x, weight, bias=None, transpose_weight=False):
    w = weight.T if transpose_weight else weight
    y = jnp.matmul(x, w)
    if bias is not None:
        y = y + bias
    return y


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return _fused_linear(x, weight, bias, transpose_weight=transpose_weight)


@defop("fused_bias_act")
def _fused_bias_act(x, bias=None, act_method="gelu"):
    if bias is not None:
        x = x + bias
    if act_method in ("gelu", "geglu"):
        return jax.nn.gelu(x, approximate=False)
    if act_method == "relu":
        return jax.nn.relu(x)
    if act_method in ("swiglu",):
        a, b = jnp.split(x, 2, axis=-1)
        return jax.nn.silu(a) * b
    if act_method in ("silu", "swish"):
        return jax.nn.silu(x)
    raise ValueError(f"unsupported act_method {act_method}")


def fused_bias_act(x, bias=None, act_method="gelu", name=None, **kwargs):
    return _fused_bias_act(x, bias, act_method=act_method)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """fused_matmul_bias.py: matmul+bias in one op (XLA fuses the epilogue)."""
    from ....ops.linalg import matmul

    out = matmul(x, y, transpose_x=transpose_x, transpose_y=transpose_y)
    return out + bias if bias is not None else out


def fused_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                is_causal=False, training=True,
                                scaling_factor=None, name=None):
    """fused_dot_product_attention.py: served by the sdp dispatcher (Pallas
    flash attention when shapes allow)."""
    from ....nn.functional.flash_attention import scaled_dot_product_attention

    return scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                        dropout_p=dropout_p,
                                        is_causal=is_causal,
                                        training=training)


def variable_length_memory_efficient_attention(query, key, value, seq_lens,
                                               kv_seq_lens, mask=None,
                                               scale=None, causal=False,
                                               name=None):
    """variable_length_memory_efficient_attention.py: padding positions beyond
    kv_seq_lens are masked out (the reference kernel's varlen semantics)."""
    import jax.numpy as jnp

    from ....framework.core import Tensor
    from ....nn.functional.flash_attention import _sdpa, _use_pallas

    # (B, H, S, D) reference layout -> sdp's (B, S, H, D)
    from ....ops.manipulation import transpose

    q = transpose(query, [0, 2, 1, 3])
    k = transpose(key, [0, 2, 1, 3])
    v = transpose(value, [0, 2, 1, 3])

    sk = int(k.shape[1])
    kv_lens = kv_seq_lens if kv_seq_lens is not None else seq_lens
    if kv_lens is not None:
        lens = (kv_lens.value if isinstance(kv_lens, Tensor)
                else jnp.asarray(kv_lens)).reshape(-1)
        # keep key column j for batch b iff j < kv_len[b]; (B, 1, 1, Sk)
        keep = (jnp.arange(sk)[None, :] < lens[:, None])[:, None, None, :]
        if mask is None:
            mask = keep
        else:
            mv = mask.value if isinstance(mask, Tensor) else jnp.asarray(mask)
            if mv.dtype == jnp.bool_:
                mask = mv & keep
            else:
                mask = mv + jnp.where(keep, 0.0, -1e30).astype(mv.dtype)
    out = _sdpa(q, k, v, mask, None, dropout_p=0.0, causal=bool(causal),
                scale=scale, use_pallas=_use_pallas(q))
    return transpose(out, [0, 2, 1, 3])


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn2_bias=None, quant_method="None", moe_topk=2,
              norm_topk_prob=True, name=None):
    """fused_moe.py: token top-k routing + expert FFNs, einsum-dispatched so
    GSPMD can shard the expert axis.

    x: (B, S, D); gate_weight: (D, E); ffn1_weight: (E, D, I) (swiglu packs
    2*I); ffn2_weight: (E, I_or_I, D).
    """
    import jax
    import jax.numpy as jnp

    from ....framework.core import Tensor

    xv = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    gw = gate_weight.value if isinstance(gate_weight, Tensor) \
        else jnp.asarray(gate_weight)
    w1 = ffn1_weight.value if isinstance(ffn1_weight, Tensor) \
        else jnp.asarray(ffn1_weight)
    w2 = ffn2_weight.value if isinstance(ffn2_weight, Tensor) \
        else jnp.asarray(ffn2_weight)
    B, S, D = xv.shape
    E = gw.shape[1]
    tokens = xv.reshape(B * S, D)
    logits = tokens @ gw
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_e = jax.lax.top_k(probs, moe_topk)
    if norm_topk_prob:
        top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
    # dense dispatch: weight each expert by its routed probability (0 when
    # not in the top-k) — einsums keep the E axis shardable
    weights = jnp.zeros((B * S, E), xv.dtype)
    weights = weights.at[jnp.arange(B * S)[:, None], top_e].set(
        top_p.astype(xv.dtype))
    h = jnp.einsum("td,edi->tei", tokens, w1)
    if ffn1_bias is not None:
        b1 = ffn1_bias.value if isinstance(ffn1_bias, Tensor) \
            else jnp.asarray(ffn1_bias)
        h = h + b1[None]
    inter = w2.shape[1]
    if h.shape[-1] == 2 * inter:  # swiglu-packed ffn1
        gate_h, up = jnp.split(h, 2, axis=-1)
        h = jax.nn.silu(gate_h) * up
    else:
        h = jax.nn.gelu(h, approximate=False)
    y = jnp.einsum("tei,eid->ted", h, w2)
    if ffn2_bias is not None:
        b2 = ffn2_bias.value if isinstance(ffn2_bias, Tensor) \
            else jnp.asarray(ffn2_bias)
        y = y + b2[None]
    out = jnp.einsum("ted,te->td", y, weights)
    return Tensor(out.reshape(B, S, D))


@defop("fused_linear_cross_entropy", amp_category="black")
def _fused_linear_cross_entropy(hidden, weight, labels, ignore_index=-100,
                                chunk_size=512):
    """Chunked LM-head matmul + softmax cross-entropy that never materializes
    the full [B, S, V] logits (at V=32k, B8 x S2048 that is >1 GB bf16 /
    >4 GB fp32 of HBM traffic). Sequence chunks run under jax.checkpoint
    inside lax.map: forward keeps only [B, C, V] live; backward recomputes
    each chunk's logits. The matmul stays in the input dtype (bf16 on the
    MXU); the softmax runs in fp32.

    Reference capability analog: fused_softmax_mask + c_softmax_with_
    cross_entropy family (fused_ops.yaml) — the TPU-first formulation is
    remat-chunking rather than a custom kernel, since the inner matmul and
    the online logsumexp are exactly what XLA already schedules well.
    Returns per-token loss [B, S] (0.0 at ignore_index positions).
    """
    B, S, H = hidden.shape
    C = min(int(chunk_size), S)
    pad = (-S) % C
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)),
                         constant_values=ignore_index)
    sp = S + pad
    n = sp // C
    hs = jnp.moveaxis(hidden.reshape(B, n, C, H), 1, 0)   # [n, B, C, H]
    ls = jnp.moveaxis(labels.reshape(B, n, C), 1, 0)      # [n, B, C]

    @jax.checkpoint
    def chunk_fn(hc, lc):
        logits = jnp.einsum("bch,hv->bcv", hc, weight).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        safe = jnp.where(lc == ignore_index, 0, lc).astype(jnp.int32)
        picked = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        return jnp.where(lc == ignore_index, 0.0, lse - picked)

    tok = jax.lax.map(lambda args: chunk_fn(*args), (hs, ls))  # [n, B, C]
    return jnp.moveaxis(tok, 0, 1).reshape(B, sp)[:, :S]


def fused_linear_cross_entropy(hidden, weight, labels, ignore_index=-100,
                               chunk_size=512, name=None):
    """Per-token causal-LM loss fused with the LM-head projection — see
    `_fused_linear_cross_entropy`. `weight` is [hidden, vocab]."""
    return _fused_linear_cross_entropy(hidden, weight, labels,
                                       ignore_index=int(ignore_index),
                                       chunk_size=int(chunk_size))
