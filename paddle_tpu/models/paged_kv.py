"""Paged KV cache: block-table attention for serving decode.

Reference analog: paddle/incubate/nn/functional/block_multihead_attention.py
(paged "Block Multi-head attention": the KV cache is a POOL of fixed-size
blocks, each sequence owns a list of block ids — its block table — so cache
memory is allocated block-at-a-time, sequences of very different lengths
don't reserve max_len each, and finished sequences return their blocks).
The reference implements it as a CUDA serving kernel
(fluid/operators/fused/block_multi_head_attention_op.cu); TPU-first
redesign: the pool is a [num_blocks, block_size, kv_heads, head_dim] array,
the block table drives jnp gathers/scatters, and XLA fuses the
gather -> attention -> reduce chain — no page-table indirection kernel is
hand-written, the indexed reads ARE the indirection.

Layout note: the reference kernel stores [max_blocks, kv_heads, block_size,
head_dim]; here blocks are [block_size, kv_heads, head_dim]-major so the
gathered view reshapes straight to the [B, S, H, D] attention layout with
no transpose.

Everything is functional and jit-compatible: cache arrays in, cache arrays
out (donate-friendly), shapes static, per-sequence lengths as data.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..analysis import faultinject as _fi

__all__ = ["PagedKVCache", "CowPoolExhausted", "alloc_blocks",
           "read_blocks",
           "paged_write_decode", "paged_write_prefill", "paged_write_mixed",
           "paged_attention_decode", "paged_write_decode_int8",
           "paged_write_prefill_int8", "paged_write_mixed_int8",
           "paged_attention_decode_int8"]


class CowPoolExhausted(RuntimeError):
    """Copy-on-write ran out of free blocks. Copies that were already
    remapped before the pool ran dry ARE applied (their table rows point
    at initialized private blocks), and — because the copy DONATES the
    pools it was handed — the replacement pool list travels on ``.pools``
    so a caller may reclaim blocks and retry against live buffers."""

    def __init__(self, msg, pools):
        super().__init__(msg)
        self.pools = pools

_MON = None  # (state, free-blocks gauge, CoW counter, exhaustion counter)


def _mon():
    global _MON
    if _MON is None:
        from .. import monitor as _m

        _MON = (_m._state,
                _m.gauge("paddle_tpu_kv_free_blocks"),
                _m.counter("paddle_tpu_kv_cow_copies_total"),
                _m.counter("paddle_tpu_kv_pool_exhausted_total"))
    return _MON


class PagedKVCache:
    """Host-side block allocator + the device block pools for ONE layer set.

    The allocator (free-list) is host logic — block grant/free decisions are
    control flow, not device math (the reference's BlockManager is host C++
    too). The pools and tables live on device and flow through jit.
    """

    def __init__(self, num_layers, num_blocks, block_size, kv_heads, head_dim,
                 batch, max_blocks_per_seq, dtype=jnp.bfloat16,
                 quantized=False):
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self.quantized = bool(quantized)
        shape = (num_blocks, block_size, kv_heads, head_dim)
        if quantized:
            # int8 blocks + per-(token, head) fp32 absmax scales: the same
            # halved-KV-bandwidth lever as the dense int8 cache, paged
            sshape = shape[:-1]
            self.k = [jnp.zeros(shape, jnp.int8) for _ in range(num_layers)]
            self.v = [jnp.zeros(shape, jnp.int8) for _ in range(num_layers)]
            self.k_scale = [jnp.zeros(sshape, jnp.float32)
                            for _ in range(num_layers)]
            self.v_scale = [jnp.zeros(sshape, jnp.float32)
                            for _ in range(num_layers)]
        else:
            self.k = [jnp.zeros(shape, dtype) for _ in range(num_layers)]
            self.v = [jnp.zeros(shape, dtype) for _ in range(num_layers)]
        # block 0 is the permanently-reserved NULL block: unassigned table
        # slots point at it, so gathers stay in-bounds without masking reads
        self._free = list(range(num_blocks - 1, 0, -1))
        self.batch = int(batch)
        self._tables_np = np.zeros((batch, max_blocks_per_seq), np.int32)
        self.block_tables = jnp.asarray(self._tables_np)
        # per-block reference counts: >1 after fork_rows (beam search shares
        # prompt blocks); writes go copy-on-write via make_tail_exclusive
        self._refs = np.zeros(num_blocks, np.int32)

    # -- host-side allocator -------------------------------------------------
    def ensure_capacity(self, seq_lens_next):
        """Grant blocks so every sequence can hold seq_lens_next[b] tokens.

        The table lives host-side (numpy mirror); the device copy is
        re-uploaded ONLY when a grant actually happened — most decode steps
        grant nothing (blocks change once per block_size tokens), and a
        per-token host->device upload would sit in the serving hot loop.
        The nothing-to-grant case is detected vectorized up front: it IS
        the serving steady state, and a per-row python loop there costs
        more than the compiled step saves."""
        _sp = _fi.fire("paged_kv.ensure")
        if _sp is not None and _sp.action == "flag":
            # chaos drill: the allocator's typed exhaustion error without
            # touching the free list — the engine's eviction/spill relief
            # must absorb it (a delay spec just slept inside fire())
            raise RuntimeError(
                "paged KV pool exhausted: no free blocks (injected fault; "
                f"pool={self.num_blocks}, block={self.block_size})")
        tables = self._tables_np
        owned = (tables > 0).sum(axis=1)
        need_arr = np.asarray(seq_lens_next)
        needed = -(-np.maximum(need_arr.astype(np.int64), 0)
                   // self.block_size)
        mon = _mon()
        if (needed <= owned).all():
            if mon[0].on:
                mon[1].set(len(self._free))
            return
        changed = False
        for b, need_tok in enumerate(need_arr):
            need = int(-(-int(need_tok) // self.block_size))  # ceil
            while owned[b] < need:
                if not self._free:
                    if mon[0].on:
                        mon[3].inc()
                    if changed:
                        # blocks already granted to earlier rows must reach
                        # the device even on the failure path — a caller
                        # that catches this would otherwise decode against
                        # a stale device table (writes landing in the null
                        # block) while the host mirror says all is granted
                        self.block_tables = jnp.asarray(tables.copy())
                    raise RuntimeError(
                        "paged KV pool exhausted: no free blocks "
                        f"(pool={self.num_blocks}, block={self.block_size})")
                blk = self._free.pop()
                tables[b, owned[b]] = blk
                self._refs[blk] = 1
                owned[b] += 1
                changed = True
        if mon[0].on:
            mon[1].set(len(self._free))
        if changed:
            # upload a COPY: jnp.asarray of an aligned numpy array may be
            # zero-copy on CPU, and an in-flight async step could still be
            # reading the previous device view while the host mirror mutates
            self.block_tables = jnp.asarray(tables.copy())

    def free_sequence(self, b):
        """Drop sequence b's block references; blocks return to the pool
        when their last referencing row lets go."""
        tables = self._tables_np
        for blk in tables[b]:
            if blk > 0:
                self._refs[blk] -= 1
                if self._refs[blk] == 0:
                    self._free.append(int(blk))
        tables[b] = 0
        self.block_tables = jnp.asarray(tables.copy())
        mon = _mon()
        if mon[0].on:
            mon[1].set(len(self._free))

    # -- external references (radix/prefix cache) ----------------------------
    def retain_blocks(self, blocks):
        """Take one extra reference on each block (the prefix cache's pin):
        a retained block survives :meth:`free_sequence` of its original
        owner and only returns to the pool when released."""
        for blk in blocks:
            blk = int(blk)
            if not 0 < blk < self.num_blocks:
                raise ValueError(f"block {blk} out of range")
            if self._refs[blk] <= 0:
                raise ValueError(f"block {blk} is free; cannot retain")
            self._refs[blk] += 1

    def release_blocks(self, blocks):
        """Drop one reference per block (undo of retain_blocks); blocks
        whose last reference goes return to the free pool."""
        freed = 0
        for blk in blocks:
            blk = int(blk)
            self._refs[blk] -= 1
            if self._refs[blk] == 0:
                self._free.append(blk)
                freed += 1
        mon = _mon()
        if mon[0].on:
            mon[1].set(len(self._free))
        return freed

    def adopt_blocks(self, b, blocks):
        """Map shared ``blocks`` into the HEAD of row b's block table (one
        new reference each) — the prefix-cache admission path: row b's
        first ``len(blocks) * block_size`` positions read the shared KV.
        Row b must hold no blocks yet (adoption happens at admission)."""
        tables = self._tables_np
        if (tables[b] > 0).any():
            raise ValueError(f"row {b} already holds blocks")
        if len(blocks) > self.max_blocks_per_seq:
            raise ValueError("shared prefix longer than max_blocks_per_seq")
        for i, blk in enumerate(blocks):
            blk = int(blk)
            if self._refs[blk] <= 0:
                raise ValueError(f"block {blk} is free; cannot adopt")
            tables[b, i] = blk
            self._refs[blk] += 1
        self.block_tables = jnp.asarray(tables.copy())

    # -- host-RAM spill/restore (serving resilience) -------------------------
    def take_blocks(self, n):
        """Pop ``n`` free blocks for a restore (spilled radix prefixes,
        preempted-request KV): each comes back with one reference — the
        restorer owns it. Returns None (taking nothing) when the pool
        lacks headroom, so a restore can degrade to a recompute instead
        of starving live sequences."""
        n = int(n)
        if n <= 0 or len(self._free) < n:
            return None
        blks = [self._free.pop() for _ in range(n)]
        for blk in blks:
            self._refs[blk] = 1
        mon = _mon()
        if mon[0].on:
            mon[1].set(len(self._free))
        return blks

    def place_blocks(self, b, blocks):
        """Map ``blocks`` (owned by the caller via :meth:`take_blocks`)
        into the HEAD of empty row ``b`` — the restore path of a
        preempted request: its spilled KV re-uploads into these blocks
        at the same in-block offsets, so the continuation is bit-exact."""
        tables = self._tables_np
        if (tables[b] > 0).any():
            raise ValueError(f"row {b} already holds blocks")
        if len(blocks) > self.max_blocks_per_seq:
            raise ValueError("restore longer than max_blocks_per_seq")
        for i, blk in enumerate(blocks):
            tables[b, i] = int(blk)
        self.block_tables = jnp.asarray(tables.copy())

    def write_block_contents(self, pools, blocks, contents):
        """Upload host-RAM block contents into pool ``blocks`` (one
        donated scatter): ``contents`` is a per-layer list of pool-leaf
        tuples — ``(k, v)`` for bf16 pools, ``(kq, ks, vq, vs)`` for the
        quantized layout — each numpy array shaped ``[n, block_size,
        ...]`` (block-major on axis 0, exactly like the pools). Index
        vectors pad to a power-of-two length (padding writes zeros into
        the null block — benign) so the jitted upload compiles for
        O(log) distinct shapes, exactly like the CoW copy."""
        n = len(blocks)
        if n == 0:
            return pools
        m = 1
        while m < n:
            m *= 2
        blks = np.zeros(m, np.int32)
        blks[:n] = np.asarray(blocks, np.int32)
        padded = []
        for entry in contents:
            leaves = []
            for arr in entry:
                if m != n:
                    pad = ((0, m - n),) + ((0, 0),) * (arr.ndim - 1)
                    arr = np.pad(arr, pad)
                leaves.append(arr)
            padded.append(tuple(leaves))
        fn = getattr(self, "_restore_jit", None)
        if fn is None:
            @functools.partial(jax.jit, donate_argnums=(0,))
            def fn(pools, blks, vals):
                return [tuple(pl.at[blks].set(c.astype(pl.dtype))
                              for pl, c in zip(entry, cs))
                        for entry, cs in zip(pools, vals)]

            self._restore_jit = fn
        return fn(pools, jnp.asarray(blks), padded)

    def make_positions_exclusive(self, rows, positions, pools):
        """Copy-on-write for the mixed serving step: before row ``rows[i]``
        writes at ``positions[i]``, any targeted block that is SHARED
        (refs > 1 — prefix-cache hits, beam forks) is replaced by a private
        copy in one donated gather/scatter. The generalized, per-row form
        of :meth:`make_tail_exclusive`; plain unshared decode takes the
        cheap all-refs<=1 early exit."""
        _sp = _fi.fire("paged_kv.cow")
        if _sp is not None and _sp.action == "flag":
            # chaos drill: a REAL CowPoolExhausted carrying the live
            # (unconsumed) pools, raised before any copy — the caller's
            # adopt-pools-evict-retry path runs against valid buffers
            raise CowPoolExhausted(
                "paged KV pool exhausted during copy-on-write (injected "
                f"fault; pool={self.num_blocks})", pools)
        if (self._refs <= 1).all():
            return pools
        mon = _mon()
        t = self._tables_np
        rows = np.asarray(rows, np.int64)
        positions = np.asarray(positions, np.int64)
        bidxs = positions // self.block_size
        targets = t[rows, bidxs]
        hot = np.flatnonzero((targets > 0) & (self._refs[targets] > 1))
        pairs = []
        exhausted = False
        for i in hot:
            b, bidx = int(rows[i]), int(bidxs[i])
            phys = int(t[b, bidx])
            if phys > 0 and self._refs[phys] > 1:
                if not self._free:
                    if mon[0].on:
                        mon[3].inc()
                    # raise only AFTER applying the pairs already
                    # remapped: their tables/refs mutations are in, so
                    # skipping their data copy would leave a retrying
                    # caller (they now look unshared) reading
                    # uninitialized KV
                    exhausted = True
                    break
                new = self._free.pop()
                self._refs[new] = 1
                self._refs[phys] -= 1
                t[b, bidx] = new
                pairs.append((phys, new))
        if pairs:
            if mon[0].on:
                mon[2].inc(len(pairs))
                mon[1].set(len(self._free))
            pools = self._cow_apply(pools, pairs)
            self.block_tables = jnp.asarray(t.copy())
        if exhausted:
            raise CowPoolExhausted(
                "paged KV pool exhausted during copy-on-write "
                f"(pool={self.num_blocks})", pools)
        return pools

    # -- copy-on-write sharing (beam search) ---------------------------------
    def fork_rows(self, parent_rows):
        """Every row adopts parent_rows[b]'s block table (shared blocks,
        refcounted) — the paged form of the dense cache's batch-axis beam
        reorder. Writes afterwards must go through make_tail_exclusive."""
        parent_rows = np.asarray(parent_rows, np.int64)
        t = self._tables_np
        new = t[parent_rows].copy()
        if np.array_equal(new, t):
            return   # identity fork (EOS-frozen beams): nothing changes
        # vectorized refcount delta (this runs once per decoded token)
        self._refs -= np.bincount(t[t > 0].ravel(),
                                  minlength=self.num_blocks).astype(np.int32)
        self._refs += np.bincount(new[new > 0].ravel(),
                                  minlength=self.num_blocks).astype(np.int32)
        # blocks nobody references anymore go back to the pool
        for blk in np.unique(t[t > 0]):
            if self._refs[blk] == 0:
                self._free.append(int(blk))
        self._tables_np = new
        self.block_tables = jnp.asarray(new.copy())
        mon = _mon()
        if mon[0].on:
            mon[1].set(len(self._free))

    def _cow_copy_fn(self):
        fn = getattr(self, "_cow_jit", None)
        if fn is None:
            @functools.partial(jax.jit, donate_argnums=(0,))
            def fn(pools, olds, news):
                # donated: XLA scatters the copied blocks in place instead
                # of duplicating every layer's whole pool per CoW event.
                # tree_map covers both pool layouts ((k, v) and the int8
                # (kq, ks, vq, vs)) — every leaf is block-major on axis 0
                return jax.tree_util.tree_map(
                    lambda a: a.at[news].set(a[olds]), pools)

            self._cow_jit = fn
        return fn

    def _cow_apply(self, pools, pairs):
        """Run the donated CoW copy for ``pairs`` of (old, new) blocks.
        The index vectors pad to a power-of-two length so the jitted copy
        compiles for O(log) distinct shapes, not one per batch size —
        padding entries copy the null block onto itself (benign)."""
        n = 1
        while n < len(pairs):
            n *= 2
        olds = np.zeros(n, np.int32)
        news = np.zeros(n, np.int32)
        for i, (o, w) in enumerate(pairs):
            olds[i] = o
            news[i] = w
        return self._cow_copy_fn()(pools, jnp.asarray(olds),
                                   jnp.asarray(news))

    def make_tail_exclusive(self, pos, pools):
        """Copy-on-write: before writing at position `pos`, any row whose
        tail block (pos // block_size) is SHARED gets its own copy of it
        (one donated gather/scatter over the pools). No-op (and cheap)
        when nothing is shared — plain decoding always takes that path."""
        if (self._refs <= 1).all():
            return pools
        mon = _mon()
        bidx = int(pos) // self.block_size
        t = self._tables_np
        pairs = []
        exhausted = False
        for b in range(len(t)):
            phys = int(t[b, bidx])
            if phys > 0 and self._refs[phys] > 1:
                if not self._free:
                    if mon[0].on:
                        mon[3].inc()
                    # apply-then-raise, as in make_positions_exclusive:
                    # already-remapped rows must get their data copy
                    exhausted = True
                    break
                new = self._free.pop()
                self._refs[new] = 1
                self._refs[phys] -= 1
                t[b, bidx] = new
                pairs.append((phys, new))
        if pairs:
            if mon[0].on:
                mon[2].inc(len(pairs))
                mon[1].set(len(self._free))
            pools = self._cow_apply(pools, pairs)
            self.block_tables = jnp.asarray(t.copy())
        if exhausted:
            raise CowPoolExhausted(
                "paged KV pool exhausted during copy-on-write "
                f"(pool={self.num_blocks})", pools)
        return pools


def alloc_blocks(batch, max_len, block_size):
    """Static shape helper: blocks per sequence for a max_len budget."""
    return -(-max_len // block_size)


def read_blocks(pools, blocks):
    """Download pool ``blocks`` to host RAM (the SPILL read): a per-layer
    list of pool-leaf tuples of numpy arrays ``[n, block_size, ...]`` —
    ``(k, v)`` for bf16 pools, the 4-leaf ``(kq, ks, vq, vs)`` for the
    quantized layout. This is a deliberate device→host transfer on the
    resilience path (pool pressure / preemption), never the serving hot
    loop — the spilled bits round-trip exactly, which is what makes
    restore-then-decode bit-identical."""
    blks = jnp.asarray(np.asarray(blocks, np.int32))
    out = []
    for entry in pools:
        out.append(tuple(
            np.asarray(jax.device_get(leaf[blks]))    # graftlint: disable=GL002
            for leaf in entry))
    return out


def _decode_scatter_idx(block_tables, seq_lens, bs):
    """(phys block, in-block offset) for writing one token at seq_lens[b]."""
    pos = seq_lens.astype(jnp.int32)
    blk_idx = pos // bs
    off = pos % bs
    rows = jnp.arange(block_tables.shape[0])
    return block_tables[rows, blk_idx], off


def paged_write_decode(cache_k, cache_v, block_tables, seq_lens, k_new, v_new):
    """Write ONE new token per sequence into its current tail block.

    k_new/v_new: [B, kv_heads, head_dim]; position = seq_lens[b].
    Returns (cache_k, cache_v) with the writes applied (functional)."""
    phys, off = _decode_scatter_idx(block_tables, seq_lens, cache_k.shape[1])
    cache_k = cache_k.at[phys, off].set(k_new.astype(cache_k.dtype))
    cache_v = cache_v.at[phys, off].set(v_new.astype(cache_v.dtype))
    return cache_k, cache_v


def paged_write_mixed(cache_k, cache_v, row_tables, positions, valid,
                      k_new, v_new):
    """Write one token per LANE of a mixed (decode + chunked-prefill) pack.

    ``row_tables`` is the per-lane view ``block_tables[slot_ids]`` — two
    lanes of the same prefill chunk carry the same table row at different
    ``positions``. Padding lanes (``valid`` False) are redirected at an
    out-of-bounds block and DROPPED by the scatter, exactly like prefill
    padding rows (any real block id would clobber its owner)."""
    phys, off = _decode_scatter_idx(row_tables, positions, cache_k.shape[1])
    phys = jnp.where(valid, phys, cache_k.shape[0])
    cache_k = cache_k.at[phys, off].set(k_new.astype(cache_k.dtype),
                                        mode="drop")
    cache_v = cache_v.at[phys, off].set(v_new.astype(cache_v.dtype),
                                        mode="drop")
    return cache_k, cache_v


def paged_write_prefill(cache_k, cache_v, block_tables, seq_lens,
                        k_new, v_new):
    """Write a full prompt per sequence: k_new/v_new [B, S, kv_heads, D],
    token t of sequence b lands at block_tables[b, t // bs] offset t % bs
    (only t < seq_lens[b] rows are written; the rest target the null block
    but are masked by never being read — seq_lens bounds every gather)."""
    phys, off = _prefill_scatter_idx(cache_k, block_tables, seq_lens,
                                     k_new.shape[1])
    cache_k = cache_k.at[phys, off].set(
        _flat_rows(k_new).astype(cache_k.dtype), mode="drop")
    cache_v = cache_v.at[phys, off].set(
        _flat_rows(v_new).astype(cache_v.dtype), mode="drop")
    return cache_k, cache_v


def _prefill_scatter_idx(pool, block_tables, seq_lens, S):
    """Flattened (phys, offset) for writing a [B, S, ...] prompt. Padding
    rows target an OUT-OF-BOUNDS block and are DROPPED by the scatter —
    redirecting them at any real block id (block 0 included) would clobber
    whichever sequence owns that block."""
    B = block_tables.shape[0]
    nb, bs = pool.shape[0], pool.shape[1]
    t = jnp.arange(S)
    blk_idx = t // bs                                   # [S]
    off = t % bs
    phys = block_tables[:, blk_idx]                     # [B, S]
    valid = t[None, :] < seq_lens[:, None]              # [B, S]
    phys = jnp.where(valid, phys, nb)
    return phys.reshape(-1), jnp.tile(off, B)


def _flat_rows(x):
    return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])


def paged_write_decode_int8(kq, ks, vq, vs, block_tables, seq_lens,
                            k_new_q, k_new_s, v_new_q, v_new_s):
    """int8 form of paged_write_decode: values [B, kv, D] int8 plus their
    per-(token, head) scales [B, kv] — same scatter indices, four pools."""
    phys, off = _decode_scatter_idx(block_tables, seq_lens, kq.shape[1])
    return (kq.at[phys, off].set(k_new_q), ks.at[phys, off].set(k_new_s),
            vq.at[phys, off].set(v_new_q), vs.at[phys, off].set(v_new_s))


def paged_write_mixed_int8(kq, ks, vq, vs, row_tables, positions, valid,
                           k_new_q, k_new_s, v_new_q, v_new_s):
    """int8 form of paged_write_mixed: one quantized token per LANE of a
    mixed (decode + chunked-prefill + draft-verify) pack — values
    [T, kv, D] int8 plus per-(token, head) scales [T, kv], the same
    per-lane scatter indices across four pools. Padding lanes (``valid``
    False) redirect at an out-of-bounds block and DROP."""
    phys, off = _decode_scatter_idx(row_tables, positions, kq.shape[1])
    phys = jnp.where(valid, phys, kq.shape[0])
    return (kq.at[phys, off].set(k_new_q, mode="drop"),
            ks.at[phys, off].set(k_new_s, mode="drop"),
            vq.at[phys, off].set(v_new_q, mode="drop"),
            vs.at[phys, off].set(v_new_s, mode="drop"))


def paged_write_prefill_int8(kq, ks, vq, vs, block_tables, seq_lens,
                             k_new_q, k_new_s, v_new_q, v_new_s):
    """int8 form of paged_write_prefill (values [B, S, kv, D] int8 + scales
    [B, S, kv]); padding rows drop via the shared out-of-bounds scatter."""
    phys, off = _prefill_scatter_idx(kq, block_tables, seq_lens,
                                     k_new_q.shape[1])

    def w(pool, new):
        return pool.at[phys, off].set(_flat_rows(new), mode="drop")

    return w(kq, k_new_q), w(ks, k_new_s), w(vq, v_new_q), w(vs, v_new_s)


def paged_attention_decode_int8(q, kq, ks, vq, vs, block_tables, seq_lens,
                                scale=None):
    """One decode step against the int8 paged cache WITHOUT materializing a
    dequantized copy: the per-(token, head) scales fold into the score and
    value einsums. Arithmetic MIRRORS the dense engine's _attend_int8
    op-for-op (QK/PV einsums in q.dtype, fp32 scale fold, divide by
    sqrt(D)) so dense-int8 and paged-int8 stay bit-comparable in bf16 too,
    not just fp32."""
    B, n_q, D = q.shape
    nb, bs, n_kv, _ = kq.shape
    groups = n_q // n_kv
    T = block_tables.shape[1] * bs

    k = kq[block_tables].reshape(B, T, n_kv, D)
    k_s = ks[block_tables].reshape(B, T, n_kv)
    v = vq[block_tables].reshape(B, T, n_kv, D)
    v_s = vs[block_tables].reshape(B, T, n_kv)

    qg = q.reshape(B, n_kv, groups, D)
    logits = jnp.einsum("bhgd,bthd->bhgt", qg, k.astype(q.dtype))
    ct = jnp.promote_types(q.dtype, jnp.float32)
    logits = (logits.astype(ct)
              * jnp.transpose(k_s, (0, 2, 1))[:, :, None, :].astype(ct)
              / (np.sqrt(D) if scale is None else 1.0 / scale))
    t = jnp.arange(T)[None, None, None, :]
    mask = t <= seq_lens[:, None, None, None]
    logits = jnp.where(mask, logits, jnp.asarray(-1e30, logits.dtype))
    probs = jax.nn.softmax(logits, axis=-1)
    pv = (probs * jnp.transpose(v_s, (0, 2, 1))[:, :, None, :].astype(ct)
          ).astype(q.dtype)
    out = jnp.einsum("bhgt,bthd->bhgd", pv, v.astype(q.dtype))
    return out.reshape(B, n_q, D).astype(q.dtype)


def paged_attention_decode(q, cache_k, cache_v, block_tables, seq_lens,
                           scale=None):
    """One decode step of attention against the paged cache.

    q: [B, q_heads, head_dim] (GQA: q_heads a multiple of kv_heads).
    Gathers each sequence's blocks into a [B, T_max, kv, D] view
    (T_max = max_blocks_per_seq * block_size) and masks t <= seq_lens[b]
    (inclusive: the current token was just written at position seq_lens).
    XLA fuses gather + QK + softmax + PV; bandwidth matches the dense cache
    read — the block indirection costs the index arithmetic only."""
    B, n_q, D = q.shape
    nb, bs, n_kv, _ = cache_k.shape
    groups = n_q // n_kv
    T = block_tables.shape[1] * bs

    k = cache_k[block_tables].reshape(B, T, n_kv, D)
    v = cache_v[block_tables].reshape(B, T, n_kv, D)

    if scale is None:
        scale = 1.0 / np.sqrt(D)
    # promote, don't demote: bf16 -> f32 for a stable softmax, f64 stays f64
    ct = jnp.promote_types(q.dtype, jnp.float32)
    qg = q.reshape(B, n_kv, groups, D)
    logits = jnp.einsum("bhgd,bthd->bhgt", qg.astype(ct),
                        k.astype(ct)) * scale
    t = jnp.arange(T)[None, None, None, :]
    mask = t <= seq_lens[:, None, None, None]
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhgt,bthd->bhgd", probs, v.astype(ct))
    return out.reshape(B, n_q, D).astype(q.dtype)
