"""Hybrid-parallel topology: CommunicateTopology + HybridCommunicateGroup.

Reference analog: python/paddle/distributed/fleet/base/topology.py (CommunicateTopology :70,
HybridCommunicateGroup :189, axis order :298). The reference carves pp/mp/sep/sharding/dp
sub-communicators out of the flat rank space and creates one NCCL group per axis slice.

TPU-first redesign: the topology IS a jax.sharding.Mesh. One global ProcessMesh carries all
hybrid axes; every "communicator group" is a view (a sub-mesh / named axis) rather than a
separately-bootstrapped NCCL ring, and XLA lays each axis's collectives onto ICI. The axis
ORDER decides physical locality: the innermost (fastest-varying) axis maps to neighbouring
chips, so `mp` (highest-bandwidth demand) is innermost, then sep, sharding, dp, with `pp`
outermost — matching how the reference orders pp outermost for its slower P2P traffic.
"""
from __future__ import annotations

import collections
import itertools

import numpy as np

from ..collective import Group, new_group
from ..process_mesh import ProcessMesh

# outermost -> innermost; mp innermost = adjacent devices = best ICI for TP collectives
_DEFAULT_ORDER = ["pp", "dp", "sharding", "sep", "mp"]


class CommunicateTopology:
    """Maps the flat rank space onto named hybrid axes (base/topology.py:70)."""

    def __init__(self, hybrid_group_names=None, dims=None):
        self._parallel_names = list(hybrid_group_names or _DEFAULT_ORDER)
        self._dims = list(dims if dims is not None else [1] * len(self._parallel_names))
        if len(self._dims) != len(self._parallel_names):
            raise ValueError("dims must match hybrid_group_names")
        self.coordinate = collections.namedtuple("Coordinate", self._parallel_names)
        ranges = [range(d) for d in self._dims]
        all_coords = [self.coordinate(*c) for c in itertools.product(*ranges)]
        self._coord2rank = {c: i for i, c in enumerate(all_coords)}
        self._rank2coord = {i: c for c, i in self._coord2rank.items()}
        self._world_size = int(np.prod(self._dims))

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world_size

    def get_rank(self, **args):
        return self._coord2rank[self.coordinate(**args)]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        """All ranks whose coordinate on `axis_name` equals `index`."""
        axis = self._parallel_names.index(axis_name)
        return sorted(
            r for c, r in self._coord2rank.items() if c[axis] == index
        )

    def get_dim_num(self, axis_name):
        return self.get_dim(axis_name)

    def get_comm_list(self, axis_name):
        """List of rank-lists: one communicator per slice along `axis_name`."""
        axis = self._parallel_names.index(axis_name)
        other_ranges = [
            range(d) for i, d in enumerate(self._dims) if i != axis
        ]
        comm_list = []
        for other in itertools.product(*other_ranges):
            ranks = []
            for v in range(self._dims[axis]):
                coord = list(other)
                coord.insert(axis, v)
                ranks.append(self._coord2rank[self.coordinate(*coord)])
            comm_list.append(ranks)
        return comm_list

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = self.get_coord(global_rank)
        tf = coord._replace(**kwargs)._asdict()
        return self.get_rank(**tf)


class HybridCommunicateGroup:
    """Per-axis communicator views over the global mesh (base/topology.py:189).

    Single-controller: `global_rank` is which device this controller is reasoning about
    (defaults to 0); the per-axis Group objects enumerate that rank's peers exactly like
    the reference, and `global_mesh` is the ProcessMesh TP/PP/sharding layers annotate
    their tensors over.
    """

    def __init__(self, topology: CommunicateTopology, global_rank=0):
        self._topo = topology
        self.global_rank = int(global_rank)
        self.nranks = topology.world_size()

        self._dp_degree = topology.get_dim("dp")
        self._mp_degree = topology.get_dim("mp")
        self._pp_degree = topology.get_dim("pp")
        self._sharding_degree = topology.get_dim("sharding")
        self._sep_degree = topology.get_dim("sep") if "sep" in topology.get_hybrid_group_names() else 1

        # one ProcessMesh carrying every axis: the GSPMD backbone
        names = topology.get_hybrid_group_names()
        dims = [topology.get_dim(n) for n in names]
        self.global_mesh = ProcessMesh(
            np.arange(self.nranks).reshape(dims), names
        )

        self._groups = {}
        for name in names:
            self._groups[name] = self._build_group(name)
        # fused dp+sep group (reference topology.py:260): gradients of non-sequence-
        # sharded params all-reduce over dp and sep together
        self._dp_sep_group = self._build_fused_group(
            [n for n in ("dp", "sep") if n in names])
        # "check" group = everything except dp (model replicas hold identical data)
        self._check_group = self._build_fused_group(
            [n for n in names if n != "dp"]
        )

    def _ranks_through(self, axis_names):
        """Peers of global_rank along the given axes (others' coords fixed)."""
        coord = self._topo.get_coord(self.global_rank)
        axes = [self._topo.get_hybrid_group_names().index(a) for a in axis_names]
        ranges = [range(self._topo.get_dim(a)) for a in axis_names]
        ranks = []
        for values in itertools.product(*ranges):
            c = list(coord)
            for ax, v in zip(axes, values):
                c[ax] = v
            ranks.append(self._topo.get_rank(**dict(zip(
                self._topo.get_hybrid_group_names(), c))))
        return sorted(ranks)

    def _build_group(self, axis_name):
        return new_group(self._ranks_through([axis_name]))

    def _build_fused_group(self, axis_names):
        return new_group(self._ranks_through(axis_names))

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # -- per-axis accessors (reference API names) ----------------------------
    def _axis_info(self, name):
        group = self._groups[name]
        rank_in_axis = group.ranks.index(self.global_rank)
        return rank_in_axis, group

    def get_data_parallel_rank(self):
        return self._axis_info("dp")[0]

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._groups["dp"]

    def get_data_parallel_group_src_rank(self):
        return self._groups["dp"].ranks[0]

    def get_model_parallel_rank(self):
        return self._axis_info("mp")[0]

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._groups["mp"]

    def get_model_parallel_group_src_rank(self):
        return self._groups["mp"].ranks[0]

    def get_stage_id(self):
        return self._axis_info("pp")[0]

    def get_pipe_parallel_rank(self):
        return self._axis_info("pp")[0]

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._groups["pp"]

    def get_p2p_groups(self):
        return None

    def get_sharding_parallel_rank(self):
        return self._axis_info("sharding")[0]

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._groups["sharding"]

    def get_sharding_parallel_group_src_rank(self):
        return self._groups["sharding"].ranks[0]

    def get_sep_parallel_rank(self):
        return self._axis_info("sep")[0] if "sep" in self._groups else 0

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self):
        return self._groups.get("sep")

    def get_dp_sep_parallel_group(self):
        return self._dp_sep_group

    def get_check_parallel_group(self, sharding=False):
        return self._check_group

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(
            self.global_rank, pp=stage_id, **kwargs
        )

    # -- pipeline neighbour info ---------------------------------------------
    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1


_HYBRID_PARALLEL_GROUP = [None]


def _set_hybrid_parallel_group(hcg):
    _HYBRID_PARALLEL_GROUP[0] = hcg


def get_hybrid_parallel_group():
    return _HYBRID_PARALLEL_GROUP[0]
