"""Ring attention: exact attention over sequence-sharded (context-parallel) inputs.

Reference analog: the reference's long-sequence story is the `sep` topology axis
+ Megatron sequence parallelism (fleet/base/topology.py:199, segment_parallel.py)
— it ships NO ring attention (SURVEY §5 confirms). This module is the TPU-native
extension the sep axis naturally wants: each device holds S/P of the sequence,
k/v blocks rotate around the ring via lax.ppermute (one ICI neighbour hop per
step), and an online-softmax accumulator in fp32 makes the result EXACT — the
memory per device is O(S/P) activations with full-sequence attention semantics
(Ring Attention, Liu et al. 2023; blockwise parallel transformers).

Autodiff: the rotation is pure jax (ppermute has a transpose rule = the reverse
rotation), so jax.vjp of the forward IS the backward ring — gradients flow with
the same one-hop communication pattern. Each ring step is wrapped in
jax.checkpoint so residency stays O(S/P) in the backward too.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..framework.core import Tensor
from ..ops._apply import apply_raw

__all__ = ["ring_attention", "RingAttention"]

_NEG_INF = np.float32(-1e30)


def _ring_step(q, k, v, scale, q_off, k_off, causal, m, l, acc):
    """One online-softmax accumulation of a (q block, k/v block) pair.

    q: (B, Sq, Hq, D); k/v: (B, Sk, Hkv, D) with Hq a multiple of Hkv (GQA) —
    k/v are NOT repeated: a grouped einsum shares each kv head across its query
    group, so ring hops move only the true (small) KV state.
    m/l: (B, Hq, Sq); acc: (B, Hq, Sq, D).
    """
    B, Sq, Hq, D = q.shape
    Hkv = k.shape[2]
    G = Hq // Hkv
    qf = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * scale   # (B, Hq, Sq, D)
    qg = qf.reshape(B, Hkv, G, Sq, D)
    kf = jnp.swapaxes(k, 1, 2).astype(jnp.float32)           # (B, Hkv, Sk, D)
    vf = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, kf)              # (B,Hkv,G,Sq,Sk)
    s = s.reshape(B, Hq, Sq, -1)
    if causal:
        rows = q_off + jnp.arange(s.shape[-2], dtype=jnp.int32)[:, None]
        cols = k_off + jnp.arange(s.shape[-1], dtype=jnp.int32)[None, :]
        s = jnp.where(rows >= cols, s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(-1))
    # guard fully-masked rows: exp(-1e30 - (-1e30)) would be exp(0)=1 garbage
    p_ = jnp.exp(s - m_new[..., None])
    p_ = jnp.where(s <= _NEG_INF / 2, 0.0, p_)
    alpha = jnp.exp(m - m_new)
    alpha = jnp.where(m <= _NEG_INF / 2, 0.0, alpha)
    l_new = l * alpha + p_.sum(-1)
    pg = p_.reshape(B, Hkv, G, Sq, -1)
    upd = jnp.einsum("bhgqk,bhkd->bhgqd", pg, vf).reshape(B, Hq, Sq, D)
    acc_new = acc * alpha[..., None] + upd
    return m_new, l_new, acc_new


def _ring_attention_values(q, k, v, mesh, axis_name="sep", causal=True,
                           scale=None):
    """q/k/v: GLOBAL (B, S, H, D) arrays sharded on S over `axis_name`."""
    p_count = mesh.shape[axis_name]
    D = q.shape[-1]
    s_scale = np.float32(scale if scale is not None else 1.0 / np.sqrt(D))
    perm = [(i, (i + 1) % p_count) for i in range(p_count)]

    def body(q_loc, k_loc, v_loc):
        B, Sl, H, Dh = q_loc.shape
        idx = lax.axis_index(axis_name)
        q_off = idx * Sl
        # accumulators must carry the varying-over-sep type from the start or
        # lax.cond's branches disagree on vma (same discipline as pipelining.py)
        m = lax.pcast(jnp.full((B, H, Sl), _NEG_INF, jnp.float32),
                      (axis_name,), to="varying")
        l = lax.pcast(jnp.zeros((B, H, Sl), jnp.float32),
                      (axis_name,), to="varying")
        acc = lax.pcast(jnp.zeros((B, H, Sl, Dh), jnp.float32),
                        (axis_name,), to="varying")
        k_cur, v_cur = k_loc, v_loc
        step_fn = jax.checkpoint(_ring_step, static_argnums=(6,))
        for step in range(p_count):
            src = (idx - step) % p_count            # original owner of k_cur
            k_off = src * Sl
            if causal:
                # blocks entirely in the future are fully masked — skip their
                # einsums (~half the attention FLOPs); the hop still runs so
                # the ring stays in step
                m, l, acc = lax.cond(
                    k_off <= q_off + Sl - 1,
                    lambda kc, vc, m_, l_, a_, ko: step_fn(
                        q_loc, kc, vc, s_scale, q_off, ko, True, m_, l_, a_),
                    lambda kc, vc, m_, l_, a_, ko: (m_, l_, a_),
                    k_cur, v_cur, m, l, acc, k_off)
            else:
                m, l, acc = step_fn(q_loc, k_cur, v_cur, s_scale, q_off,
                                    k_off, False, m, l, acc)
            if step < p_count - 1:
                # one ICI neighbour hop: block moves to the next rank
                k_cur = lax.ppermute(k_cur, axis_name, perm)
                v_cur = lax.ppermute(v_cur, axis_name, perm)
        out = acc / jnp.maximum(l, 1e-30)[..., None]          # (B, H, Sl, D)
        return jnp.swapaxes(out, 1, 2).astype(q_loc.dtype)    # (B, Sl, H, D)

    spec = P(None, axis_name, None, None)
    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={axis_name})(q, k, v)


def ring_attention(q, k, v, mesh=None, axis_name="sep", causal=True,
                   scale=None):
    """Exact seq-sharded attention; paddle Tensors in/out, tape-differentiable.

    With mesh=None uses the fleet topology's global mesh (requires a sep axis).
    """
    if mesh is None:
        from .fleet.topology import get_hybrid_parallel_group

        hcg = get_hybrid_parallel_group()
        if hcg is None:
            raise ValueError("ring_attention needs a mesh (or fleet.init with "
                             "a sep degree)")
        mesh = hcg.global_mesh.jax_mesh()
    if q.shape[1] % mesh.shape[axis_name] != 0:
        raise ValueError(
            f"sequence length {q.shape[1]} must be divisible by the ring "
            f"size {mesh.shape[axis_name]}")
    if k.shape[1] != q.shape[1] or v.shape[1] != q.shape[1]:
        # block offsets derive from the shared local length; a differing k/v
        # length would silently misalign the causal mask
        raise ValueError(
            f"ring attention is self-attention over ONE sequence: q/k/v "
            f"lengths must match, got {q.shape[1]}/{k.shape[1]}/{v.shape[1]}")

    jfn = _jitted_ring(mesh, axis_name, bool(causal),
                       None if scale is None else float(scale))
    return apply_raw("ring_attention", jfn, [q, k, v])[0]


_RING_CACHE = {}


def _jitted_ring(mesh, axis_name, causal, scale):
    """One jitted ring program per (mesh, axis, causal, scale) — a fresh
    jax.jit wrapper every call would retrace the whole ring per forward."""
    key = (mesh, axis_name, causal, scale)
    if key not in _RING_CACHE:
        _RING_CACHE[key] = jax.jit(functools.partial(
            _ring_attention_values, mesh=mesh, axis_name=axis_name,
            causal=causal, scale=scale))
    return _RING_CACHE[key]


class RingAttention:
    """Layer-ish wrapper selecting the ring for a given mesh/axis."""

    def __init__(self, mesh=None, axis_name="sep", causal=True):
        self.mesh = mesh
        self.axis_name = axis_name
        self.causal = causal

    def __call__(self, q, k, v):
        return ring_attention(q, k, v, mesh=self.mesh,
                              axis_name=self.axis_name, causal=self.causal)
