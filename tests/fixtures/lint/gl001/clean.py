"""GL001 clean sample: traced bodies that stay pure."""
import jax
import jax.numpy as jnp

from paddle_tpu.jit import to_static
from paddle_tpu.ops._apply import defop


@to_static
def pure_forward(x, key):
    # keyed randomness threads through the trace — re-randomized per call
    return x + jax.random.normal(key, x.shape)


@defop("scaled_tanh")
def scaled_tanh(x, scale=1.0):
    return jnp.tanh(x) * scale
