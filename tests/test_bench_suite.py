"""bench_suite.py: the five BASELINE configs must run end-to-end on CPU
(smoke shapes) and emit well-formed result rows. Reference analog: the
configs named in BASELINE.json (LeNet / ResNet-50 AMP / BERT-base DP /
GPT hybrid / LLaMA — the last is bench.py's flagship)."""
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SUITE = os.path.join(ROOT, "bench_suite.py")


def _run(configs, timeout=560):
    env = dict(os.environ)
    env["PADDLE_TPU_PLATFORM"] = "cpu"
    out = subprocess.run(
        [sys.executable, SUITE, "--configs", configs],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=ROOT)
    assert out.returncode == 0, out.stderr[-800:]
    rows = json.loads(out.stdout)
    assert [r["config"] for r in rows] == configs.split(",")
    for r in rows:
        assert "error" not in r, r
        assert r["value"] > 0
    return rows


class TestServingSmoke:
    # fast tier on purpose: `bench_suite.py --smoke serving` is the
    # tier-1-safe invocation of the serving benchmark (ISSUE 5)
    def test_smoke_serving_meets_acceptance(self):
        env = dict(os.environ)
        env["PADDLE_TPU_PLATFORM"] = "cpu"
        env["JAX_PLATFORMS"] = "cpu"
        out = subprocess.run(
            [sys.executable, SUITE, "--smoke", "serving"],
            capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
        assert out.returncode == 0, out.stderr[-800:]
        row = json.loads(out.stdout.strip().splitlines()[-1])
        assert row["config"] == "serving"
        assert row["unit"] == "tokens/s"
        d = row["detail"]
        assert row["value"] == d["serving_tokens_per_sec"] > 0
        # ISSUE 5 acceptance: continuous batching + chunked prefill at
        # >= 2x the static-batch engine's tokens/s, equal batch capacity
        assert d["speedup_vs_static"] >= 2.0, d
        # ... with exact shared-block reuse and a fully warm cache pass
        assert d["warm_tokens_match"] is True
        assert d["prefix_hit_rate"] == 1.0
        assert d["static_tokens_per_sec"] > 0
        for k in ("p50", "p99"):
            assert d["ttft_ms"][k] > 0
            assert d["static_ttft_ms"][k] > 0

    def test_smoke_rejects_unknown_config(self):
        out = subprocess.run(
            [sys.executable, SUITE, "--smoke", "lenet"],
            capture_output=True, text=True, timeout=60, cwd=ROOT)
        assert out.returncode != 0
        assert "serving" in out.stderr


@pytest.mark.slow
class TestBenchSuite:
    def test_lenet_and_bert(self):
        rows = _run("lenet,bert_dp")
        assert rows[0]["unit"] == "images/s"
        assert rows[0]["detail"]["mode"] == "eager"
        assert rows[1]["unit"] == "tokens/s"
        assert rows[1]["detail"]["dp_degree"] == 1

    def test_resnet50_amp(self):
        (row,) = _run("resnet50")
        assert row["detail"]["amp"] in ("O1", "O2")
        assert row["detail"]["step_ms"] > 0

    def test_gpt_hybrid_trains_on_virtual_mesh(self):
        (row,) = _run("gpt_hybrid")
        assert row["detail"]["mesh"].startswith("tp2 x pp2 x sharding2")
        assert row["detail"]["trains"] is True
