"""GL006 clean fixture catalog (dependency-free, loadable by file path)."""

SUBSYSTEMS = ("serving", "dispatch")

NAME_PATTERN = r"^paddle_tpu_(" + "|".join(SUBSYSTEMS) + r")_[a-z][a-z0-9_]*$"

METRICS = {}

SPAN_SUBSYSTEMS = ("serving", "dispatch")

SPAN_PATTERN = (
    r"^(" + "|".join(SPAN_SUBSYSTEMS) + r")(\.[a-z][a-z0-9_]*)+$"
)

SPANS = {
    "serving.request": "Root span of one serving request.",
    "serving.prefill": "Admission prefill.",
    "dispatch.op": "One sampled eager op dispatch.",
}
