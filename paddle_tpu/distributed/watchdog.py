"""Communication watchdog: hang detection for collective operations.

Reference analog: paddle/phi/core/distributed/{nccl_comm_task,
comm_task_manager}.cc — ONE async scanner thread watches all in-flight
collective tasks, aborts on timeout and dumps traces.

TPU-first mapping: XLA owns collective execution, so the watchable boundary is
the host-side blocking wait. `CommWatchdog.watch(desc)` wraps any blocking
section (Task.wait, block_until_ready, TCPStore barriers); a single daemon
scanner checks every in-flight section's age each tick and fires the timeout
callback once per stuck section. Completed sections land in a bounded history
for post-mortem dumps.

With span tracing on (paddle_tpu.monitor.trace), each watched section also
opens a ``comm.wait`` span, and a timeout writes the flight-recorder dump
(open spans + last-N spans + metrics snapshot) to a per-rank file — the
hang-dump workflow of docs/tracing.md.
"""
from __future__ import annotations

import collections
import itertools
import os
import sys
import threading
import time

from ..analysis.sanitizers import new_lock as _new_lock


class WatchdogTimeout(RuntimeError):
    pass


# One optional process-wide watchdog: when set, the eager collectives in
# distributed/collective.py wrap their program dispatch in a watched,
# execution-fenced section, so a hung collective is observed without every
# call site threading a dog through. One slot load when unset.
_DEFAULT = [None]


def set_default_watchdog(dog):
    """Install (or clear, with None) the process-wide watchdog the eager
    collective layer arms itself with. Returns the previous one."""
    prev = _DEFAULT[0]
    _DEFAULT[0] = dog
    return prev


def default_watchdog():
    return _DEFAULT[0]


_TRACE = None


def _trace():
    global _TRACE
    if _TRACE is None:
        from ..monitor import trace as _t

        _TRACE = _t
    return _TRACE


class CommWatchdog:
    def __init__(self, timeout=1800.0, on_timeout=None, max_history=10000,
                 flight_key=None):
        self.timeout = timeout
        self.on_timeout = on_timeout
        # flight-dump path key: a watchdog observing ONE engine/replica
        # dumps to that component's per-replica file, so its dump
        # coalesces with the component's own recovery dump (same path)
        # and never blends with a sibling replica's
        self.flight_key = flight_key
        # graftsan known-lock site: the watchdog's lock is held by user
        # threads (watch enter/exit) AND the scanner — exactly the kind of
        # cross-thread lock the order witness exists for
        self._lock = _new_lock("distributed.watchdog.CommWatchdog")
        self._inflight = {}                         # id -> (desc, start)
        self._ids = itertools.count()
        self.events = collections.deque(maxlen=max_history)  # (desc, start, end)
        self.timed_out = []
        self.last_flight_dump = None     # path of the newest hang dump
        self._stop = threading.Event()
        self._scanner = None

    # -- scanner (comm_task_manager.cc watchdog loop) ------------------------
    def _ensure_scanner(self):
        if self._scanner is None or not self._scanner.is_alive():
            self._stop.clear()
            self._scanner = threading.Thread(target=self._scan_loop,
                                             daemon=True)
            self._scanner.start()

    def _scan_loop(self):
        tick = max(min(1.0, self.timeout / 4.0), 0.01)
        fired = set()
        while not self._stop.wait(tick):
            now = time.monotonic()
            with self._lock:
                inflight = list(self._inflight.items())
                if not inflight:
                    continue
            for wid, (desc, start) in inflight:
                if wid in fired:
                    continue
                if now - start > self.timeout:
                    fired.add(wid)
                    self.timed_out.append(desc)
                    self._flight_dump(desc)
                    try:
                        if self.on_timeout is not None:
                            self.on_timeout(desc, self.dump())
                        else:
                            print(f"[comm watchdog] {desc} exceeded "
                                  f"{self.timeout}s\n{self.dump()}")
                    except Exception as e:  # noqa: BLE001 - a failing
                        # timeout callback (e.g. a recovery with no
                        # restore target) must not kill the scanner —
                        # later hangs still need an observer — but the
                        # failure must not vanish either
                        import traceback

                        print(f"[comm watchdog] on_timeout callback for "
                              f"{desc} raised {type(e).__name__}: {e}\n"
                              f"{traceback.format_exc()}",
                              file=sys.stderr)

    def _flight_dump(self, desc):
        """Write the trace flight recorder (open spans = the hang
        candidates, recent spans, metrics snapshot) to the per-rank file.
        Active when tracing is on or PADDLE_TPU_FLIGHT_DIR is set; a dump
        failure never masks the timeout it documents."""
        try:
            trace = _trace()
            if trace._state.on or os.environ.get("PADDLE_TPU_FLIGHT_DIR"):
                self.last_flight_dump = trace.flight_dump(
                    reason=f"watchdog timeout: {desc} exceeded "
                           f"{self.timeout}s",
                    extra={"watchdog": self.dump()},
                    key=self.flight_key)
        except Exception:  # noqa: BLE001
            pass

    def stop(self):
        self._stop.set()
        if self._scanner is not None:
            self._scanner.join(timeout=5)

    # -- watch sections ------------------------------------------------------
    def watch(self, desc="collective"):
        return _Watch(self, desc)

    def dump(self):
        """Trace dump: in-flight sections first, then recent history."""
        with self._lock:
            now = time.monotonic()
            lines = [f"[comm] {desc}: {(now - start) * 1000:.1f} ms (IN FLIGHT)"
                     for desc, start in self._inflight.values()]
            lines += [f"[comm] {desc}: {(end - start) * 1000:.1f} ms (done)"
                      for desc, start, end in self.events]
            return "\n".join(lines)


class _Watch:
    def __init__(self, dog, desc):
        self._dog = dog
        self._desc = desc
        self._span = None

    def __enter__(self):
        dog = self._dog
        with dog._lock:
            self._id = next(dog._ids)
            dog._inflight[self._id] = (self._desc, time.monotonic())
        trace = _trace()
        if trace._state.on:
            # an OPEN comm.wait span in a flight dump IS the hang candidate
            self._span = trace.start_span("comm.wait",
                                          attrs={"desc": self._desc})
        dog._ensure_scanner()
        return self

    def __exit__(self, exc_type, exc, tb):
        dog = self._dog
        with dog._lock:
            desc, start = dog._inflight.pop(self._id)
            dog.events.append((desc, start, time.monotonic()))
        _trace().end_span(self._span)
        return False
