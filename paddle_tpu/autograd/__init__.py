"""paddle_tpu.autograd — public autograd API.

Reference analog: python/paddle/autograd + fluid/eager engine entry points.
"""
from .tape import (  # noqa: F401
    backward,
    enable_grad,
    grad,
    is_grad_enabled,
    no_grad,
    set_grad_enabled,
)
from .py_layer import PyLayer, PyLayerContext  # noqa: F401


def hessian(func, xs, batch_axis=None):
    """Minimal hessian via double grad."""
    raise NotImplementedError("use paddle_tpu.incubate.autograd for functional transforms")
