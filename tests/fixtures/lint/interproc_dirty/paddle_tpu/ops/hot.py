"""Interprocedural dirty sample: a hot-path function calling an
out-of-scope helper that host-syncs — GL002 fires at the call site."""
import helpers


def hot_read(x):
    return helpers.read_scalar(x)
