"""Collective census: ONE vocabulary of collective ops, shared by the
trainer's ``comm.mesh_step`` spans and graftir's GI001 pass.

Two census surfaces over the same vocabulary:

- :func:`census_hlo` counts collectives in compiler TEXT (StableHLO or
  optimized HLO — both spellings match), the live-program view
  ``MeshParallel.collective_counts`` attaches to every ``comm.mesh_step``
  span (PR 8 embedded a private copy of this regex in
  ``mesh/parallelize.py``; this module is its one home now);
- :func:`census_jaxpr` / :func:`collective_sequence` walk a traced
  jaxpr for collective PRIMITIVES with their axis names — the static
  view GI001 compares across cond branches and while bodies, where a
  per-device divergence in the collective sequence is an SPMD deadlock.

Stdlib-only at import time: the jaxpr walkers duck-type jax's eqn
objects (``eqn.primitive.name`` / ``eqn.params``), so importing this
module never initializes a backend.
"""
from __future__ import annotations

import re

__all__ = ["COLLECTIVE_RE", "COLLECTIVE_PRIMITIVES", "census_hlo",
           "census_lowered", "census_lowered_text", "census_jaxpr",
           "byte_census_jaxpr", "byte_census_hlo", "collective_sequence",
           "iter_subjaxprs"]

# matches both optimized-HLO (all-reduce) and StableHLO
# (stablehlo.all_reduce) spellings — the census reader accepts either
# text form
COLLECTIVE_RE = re.compile(
    r"(all[-_]reduce|all[-_]gather|reduce[-_]scatter|"
    r"collective[-_]permute|all[-_]to[-_]all)")

# the jaxpr-level (primitive) spellings of the same vocabulary; psum is
# HLO all-reduce, psum_scatter is reduce-scatter, ppermute is
# collective-permute. pmean lowers through psum and never appears as its
# own primitive.
COLLECTIVE_PRIMITIVES = {
    "psum": "all_reduce",
    "pmax": "all_reduce",
    "pmin": "all_reduce",
    "all_gather": "all_gather",
    "psum_scatter": "reduce_scatter",
    "reduce_scatter": "reduce_scatter",
    "all_to_all": "all_to_all",
    "ppermute": "collective_permute",
    "pbroadcast": "collective_permute",
}


def census_hlo(text):
    """{canonical-collective: count} over compiler text (StableHLO or
    optimized HLO)."""
    counts = {}
    for m in COLLECTIVE_RE.finditer(text):
        k = m.group(1).replace("-", "_")
        counts[k] = counts.get(k, 0) + 1
    return counts


def census_lowered(lowered, force_compile=False):
    """Census of a ``jit(...).lower(...)`` result. The cheap path parses
    the StableHLO from the trace (manual-axis collectives a shard_map
    body hand-places are explicit ops there); only if that shows nothing
    (everything GSPMD-inserted) — or the caller forces it because the
    program has auto axes GSPMD may insert collectives on — does it pay
    a full AOT compile for the optimized HLO."""
    return census_lowered_text(lowered, force_compile=force_compile)[0]


def census_lowered_text(lowered, force_compile=False):
    """(counts, text) of :func:`census_lowered` — the text is what was
    actually parsed (StableHLO on the cheap path, optimized HLO on the
    compiled one), so byte pricers can reuse it without re-lowering."""
    text = lowered.as_text()
    counts = census_hlo(text)
    if not counts or force_compile:
        text = lowered.compile().as_text()
        counts = census_hlo(text)
    return counts, text


def _axis_names(eqn):
    """Normalized axis-name tuple of one collective eqn (the params
    spelling differs per primitive: psum uses ``axes``, all_gather uses
    ``axis_name``, ...)."""
    for key in ("axes", "axis_name", "axis"):
        if key in eqn.params:
            v = eqn.params[key]
            if isinstance(v, (tuple, list, frozenset, set)):
                return tuple(sorted(str(a) for a in v))
            return (str(v),)
    return ()


def iter_subjaxprs(eqn):
    """(slot, jaxpr) for every sub-jaxpr a call-like eqn carries —
    cond branches, while cond/body, scan/pjit/remat/custom_* bodies,
    shard_map's open jaxpr. Duck-typed: a "jaxpr" is anything with
    ``.eqns``; ClosedJaxpr wrappers are unwrapped."""
    for key, val in eqn.params.items():
        items = val if isinstance(val, (tuple, list)) else (val,)
        for i, item in enumerate(items):
            inner = getattr(item, "jaxpr", item)  # ClosedJaxpr -> Jaxpr
            if hasattr(inner, "eqns"):
                slot = f"{key}[{i}]" if isinstance(val, (tuple, list)) \
                    else key
                yield slot, inner


def collective_sequence(jaxpr):
    """The ORDERED collective signature of a jaxpr: a tuple of
    ``(canonical_name, axis_names)`` pairs, recursing into every
    sub-jaxpr in program order. Two sub-programs that may run on
    different devices of one mesh (cond branches) must produce EQUAL
    sequences or the mesh deadlocks — this is the comparison key."""
    seq = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        canon = COLLECTIVE_PRIMITIVES.get(name)
        if canon is not None:
            seq.append((canon, _axis_names(eqn)))
        for _slot, sub in iter_subjaxprs(eqn):
            seq.extend(collective_sequence(sub))
    return tuple(seq)


def census_jaxpr(jaxpr):
    """{canonical-collective: count} over a traced jaxpr (recursive) —
    the static twin of :func:`census_hlo`. NOTE: a scan/while body's
    collectives count ONCE here (per trace) but run per iteration live."""
    counts = {}
    for name, _axes in collective_sequence(jaxpr):
        counts[name] = counts.get(name, 0) + 1
    return counts


def _aval_bytes(aval):
    """Buffer bytes of one abstract value (duck-typed; 0 for tokens)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * dtype.itemsize


def byte_census_jaxpr(jaxpr):
    """Per-collective BYTE sizes over a traced jaxpr (recursive):
    ``{canonical-collective: {"count": n, "bytes": b}}``, the
    bytes-on-wire prep ROADMAP item 2 asks for.

    ``bytes`` is each collective eqn's per-device PAYLOAD — the larger
    of its operand and result buffer bytes (an ``all_gather``'s output
    is what moves; a ``reduce_scatter``'s input is) as the jaxpr sees
    them: inside a ``shard_map`` body avals are already local, so the
    number is per device, not global. Quantized exchanges are priced
    at their true wire width (an int8/f8 ``all_to_all`` aval is 1
    byte/element). This is payload accounting, not a ring-algorithm
    model (a ring all-reduce moves ~2x its payload); and like
    :func:`census_jaxpr` it counts a scan/while body ONCE per trace
    while the live program pays it per iteration. Collectives GSPMD
    inserts on auto axes exist only post-compile — price those with
    :func:`byte_census_hlo` over the compiled text (the
    ``MeshParallel.collective_bytes`` merge does)."""
    out = {}

    def _visit(j):
        for eqn in j.eqns:
            canon = COLLECTIVE_PRIMITIVES.get(eqn.primitive.name)
            if canon is not None:
                b_in = sum(_aval_bytes(getattr(v, "aval", None))
                           for v in eqn.invars)
                b_out = sum(_aval_bytes(getattr(v, "aval", None))
                            for v in eqn.outvars)
                row = out.setdefault(canon, {"count": 0, "bytes": 0})
                row["count"] += 1
                row["bytes"] += max(b_in, b_out)
            for _slot, sub in iter_subjaxprs(eqn):
                _visit(sub)

    _visit(jaxpr)
    return out


# shaped-type spellings in compiler text: optimized-HLO ``f32[8,16]{1,0}``
# and StableHLO/MLIR ``tensor<8x16xf32>``
_HLO_TYPE_RE = re.compile(
    r"\b(pred|bf16|f8e4m3fn|f8e5m2|f8e4m3|[fsu]\d+)\[([0-9,]*)\]")
_MLIR_TYPE_RE = re.compile(
    r"tensor<((?:\d+x)*)"
    r"(i1|bf16|f8E4M3FN|f8E5M2|[fiu]\d+|ui\d+)>")
_DTYPE_BYTES = {
    "pred": 1, "i1": 1, "s8": 1, "u8": 1, "i8": 1, "ui8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1, "f8E4M3FN": 1, "f8E5M2": 1,
    "bf16": 2, "f16": 2, "s16": 2, "u16": 2, "i16": 2, "ui16": 2,
    "f32": 4, "s32": 4, "u32": 4, "i32": 4, "ui32": 4,
    "f64": 8, "s64": 8, "u64": 8, "i64": 8, "ui64": 8,
}


def _shaped_bytes(line):
    """Buffer bytes of every shaped type spelled on one compiler-text
    line (both HLO and MLIR spellings)."""
    out = []
    for dt, dims in _HLO_TYPE_RE.findall(line):
        n = 1
        for d in dims.split(","):
            if d.strip():
                n *= int(d)
        out.append(n * _DTYPE_BYTES.get(dt, 4))
    for dims, dt in _MLIR_TYPE_RE.findall(line):
        n = 1
        for d in dims.split("x"):
            if d:
                n *= int(d)
        out.append(n * _DTYPE_BYTES.get(dt, 4))
    return out


def byte_census_hlo(text):
    """Per-collective BYTE sizes over compiler TEXT (StableHLO or
    optimized HLO): ``{canonical-collective: {"count", "bytes"}}``.

    This is how collectives invisible to :func:`byte_census_jaxpr` get
    priced — GSPMD-inserted exchanges on auto axes and the collectives a
    routed ``device_put`` reshard lowers to exist only in compiler
    output. Pricing is per matching LINE: the largest shaped type
    spelled on the line (optimized HLO carries the RESULT type inline,
    so an all-gather prices its grown output; StableHLO carries operand
    and result types, so the max mirrors the jaxpr census's
    max(in, out) payload rule). Like every text census this is payload
    accounting of the program text — a line that mentions a collective
    without being one (a metadata string) would be counted; compiler
    output keeps those off the op lines in practice.

    StableHLO REGION ops (``"stablehlo.all_reduce"(%x) ({ ... }) :
    (tensor<..>) -> tensor<..>``) carry their types on the region's
    closing ``}) : ...`` line, several lines after the op name — the
    walker remembers the pending op and prices it from that closer."""
    out = {}

    def _price(name, sizes):
        row = out.setdefault(name, {"count": 0, "bytes": 0})
        row["count"] += 1
        row["bytes"] += max(sizes)

    pending = None
    for line in text.splitlines():
        m = COLLECTIVE_RE.search(line)
        sizes = _shaped_bytes(line)
        if m is not None:
            pending = None
            k = m.group(1).replace("-", "_")
            if sizes:
                _price(k, sizes)
            else:
                pending = k        # a region op: types come at `}) :`
        elif pending is not None and sizes and "}" in line \
                and ":" in line:
            _price(pending, sizes)
            pending = None
    return out
