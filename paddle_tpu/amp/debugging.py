"""AMP / numerics debugging tools.

Reference analog: python/paddle/amp/debugging.py:321 (check_numerics + the
FLAGS_check_nan_inf per-op scanner backed by eager/nan_inf_utils.cc) plus the
operator-stats collection (:480 enable_operator_stats_collection, :559
collect_operator_stats), tensor checker (:653 enable_tensor_checker /
TensorCheckerConfig :173, DebugMode :56) and compare_accuracy (:594).

TPU-first mapping: the per-op hook lives in the op dispatcher
(ops/_apply.py — every defop output is scanned when FLAGS check_nan_inf is on,
the XLA-world stand-in for the CUDA kernel-side scan); this module provides the
user-facing switches, per-op dtype call statistics, and tensor stat utilities.
"""
from __future__ import annotations

import contextlib
from enum import Enum

import numpy as np

import jax.numpy as jnp

from ..framework import flags
from ..framework.core import Tensor

__all__ = [
    "DebugMode",
    "TensorCheckerConfig",
    "check_numerics",
    "check_layer_numerics",
    "enable_operator_stats_collection",
    "disable_operator_stats_collection",
    "collect_operator_stats",
    "enable_tensor_checker",
    "disable_tensor_checker",
    "set_checked_op_list",
    "set_skipped_op_list",
    "compare_accuracy",
]


class DebugMode(Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL_FOR_OVERFLOW = 2
    CHECK_ALL = 3


class TensorCheckerConfig:
    """reference debugging.py:173 — which ops to scan and what to do on hit."""

    def __init__(self, enable=True, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None, skipped_op_list=None,
                 debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = list(checked_op_list or [])
        self.skipped_op_list = list(skipped_op_list or [])
        self.debug_step = debug_step
        self.stack_height_limit = stack_height_limit


_CHECKED_OPS = [None]   # None = all
_SKIPPED_OPS = [set()]


def set_checked_op_list(checked_op_list):
    _CHECKED_OPS[0] = set(checked_op_list) if checked_op_list else None


def set_skipped_op_list(skipped_op_list):
    _SKIPPED_OPS[0] = set(skipped_op_list or [])


def _op_filter(op_name):
    if op_name in _SKIPPED_OPS[0]:
        return False
    if _CHECKED_OPS[0] is not None and op_name not in _CHECKED_OPS[0]:
        return False
    return True


def _scan_op_outputs(name, vals):
    """The tensor checker's per-op scan, invoked through the dispatcher's
    ``_NAN_INF_HOOK`` slot when FLAGS ``check_nan_inf`` is on. Each float
    output runs the same compiled device-side all-finite reduction numsan
    uses (analysis/numerics) — one bool to host per scanned output. For
    always-on step-boundary coverage without the per-op sync, enable the
    numerics sanitizer instead (``PADDLE_TPU_SANITIZE=numerics``)."""
    if not _op_filter(name):
        return
    from ..analysis import numerics as _num

    for v in vals:
        if hasattr(v, "dtype") and jnp.issubdtype(np.dtype(v.dtype),
                                                  jnp.inexact):
            if not _num.all_finite(v):
                if flags.flag("check_nan_inf_level") > 0:
                    print(f"[paddle_tpu] nan/inf detected in output of "
                          f"op {name}")
                else:
                    raise FloatingPointError(
                        f"nan/inf detected in output of op {name}")


def enable_tensor_checker(checker_config: TensorCheckerConfig):
    """Turn on the per-op NaN/Inf scan (reference debugging.py:653)."""
    if not checker_config.enable:
        return
    set_checked_op_list(checker_config.checked_op_list or None)
    set_skipped_op_list(checker_config.skipped_op_list)
    level = (0 if checker_config.debug_mode
             == DebugMode.CHECK_NAN_INF_AND_ABORT else 1)
    flags.set_flags({"check_nan_inf": True, "check_nan_inf_level": level})


def disable_tensor_checker():
    flags.set_flags({"check_nan_inf": False})
    set_checked_op_list(None)
    set_skipped_op_list(None)


def tensor_stats(x):
    """(num_nan, num_inf, num_zero, min, max, mean) of a tensor — the stats row
    the reference prints per offending tensor."""
    v = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    vf = v.astype(jnp.float32)
    finite = jnp.isfinite(vf)
    num_nan = int(jnp.isnan(vf).sum())
    num_inf = int(jnp.isinf(vf).sum())
    num_zero = int((vf == 0).sum())
    safe = jnp.where(finite, vf, 0.0)
    n_finite = int(finite.sum())
    stats = {
        "num_nan": num_nan,
        "num_inf": num_inf,
        "num_zero": num_zero,
        "min": float(jnp.where(finite, vf, jnp.inf).min()) if n_finite else None,
        "max": float(jnp.where(finite, vf, -jnp.inf).max()) if n_finite else None,
        "mean": float(safe.sum() / max(n_finite, 1)) if n_finite else None,
    }
    return stats


def check_numerics(tensor, op_type="", var_name="", debug_mode=None,
                   stack_height_limit=1):
    """Scan one tensor; raise (abort mode) or print stats (reference :361)."""
    stats = tensor_stats(tensor)
    bad = stats["num_nan"] > 0 or stats["num_inf"] > 0
    if bad:
        msg = (f"[check_numerics] op={op_type or '?'} var={var_name or '?'} "
               f"nan={stats['num_nan']} inf={stats['num_inf']} "
               f"zero={stats['num_zero']} min={stats['min']} max={stats['max']}")
        if debug_mode in (None, DebugMode.CHECK_NAN_INF_AND_ABORT):
            raise FloatingPointError(msg)
        print(msg)
    return stats


def check_layer_numerics(func):
    """Decorator: scan a layer's inputs/outputs (reference :78)."""
    import functools

    @functools.wraps(func)
    def wrapper(self, *args, **kwargs):
        for i, a in enumerate(args):
            if isinstance(a, Tensor):
                check_numerics(a, op_type=type(self).__name__,
                               var_name=f"input{i}")
        out = func(self, *args, **kwargs)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        for i, o in enumerate(outs):
            if isinstance(o, Tensor):
                check_numerics(o, op_type=type(self).__name__,
                               var_name=f"output{i}")
        return out

    return wrapper


# -- operator stats ----------------------------------------------------------
_OP_STATS = [None]  # dict: op name -> [fp16, bf16, fp32, other] call counts


def _record_op_call(op_name, out_vals):
    table = _OP_STATS[0]
    if table is None:
        return
    row = table.setdefault(op_name, [0, 0, 0, 0])
    col = 3
    for v in out_vals:
        d = str(getattr(v, "dtype", ""))
        if d == "float16":
            col = 0
            break
        if d == "bfloat16":
            col = 1
            break
        if d == "float32":
            col = 2
            break
    row[col] += 1


def enable_operator_stats_collection():
    """Count op calls by output dtype (reference :480)."""
    _OP_STATS[0] = {}


def disable_operator_stats_collection():
    table = _OP_STATS[0]
    _OP_STATS[0] = None
    if table:
        _print_operator_stats(table)
    return table


def _print_operator_stats(table):
    print("<" + "-" * 86 + ">")
    print(f"{'Op Name':<40} {'FP16':>10} {'BF16':>10} {'FP32':>10} {'Other':>10}")
    for name in sorted(table):
        f16, bf16, f32, other = table[name]
        print(f"{name:<40} {f16:>10} {bf16:>10} {f32:>10} {other:>10}")
    print("<" + "-" * 86 + ">")


@contextlib.contextmanager
def collect_operator_stats():
    """Context form (reference :559)."""
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def operator_stats():
    """Live view of the current collection (None when disabled)."""
    return _OP_STATS[0]


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1, dump_all_tensors=False):
    """Reference :594 compares two runs' tensor dump dirs. The TPU build's
    equivalent workflow is jax's deterministic CPU replay; file-dump comparison
    is not implemented."""
    raise NotImplementedError(
        "compare_accuracy requires the tensor-dump workflow; use "
        "paddle_tpu.amp.debugging.tensor_stats / check_numerics instead")
