"""Static-graph capture hook: program_guard records eager ops for replay.

Reference analog: python/paddle/base/framework.py Program/Block op recording —
under the reference's static mode, layer calls append OpDescs to the active
Program and Executor.run feeds/fetches the graph. TPU-first redesign: the
construction code EXECUTES eagerly on placeholder tensors (shapes with dynamic
dims filled with 1), and every dispatched op is recorded here; Executor.run
replays the recorded sequence through the normal eager dispatcher with the
feed tensors substituted — so the replay builds a fresh autograd tape, layers'
live Parameters are read at replay time (training updates persist across
run() calls), and XLA sees the same ops as dynamic mode.

This module only holds the active-program cell so ops/_apply.py (the hot
path) and static/__init__.py avoid a circular import; the one extra branch
per dispatch is a list-index check.
"""
from __future__ import annotations

_ACTIVE = [None]  # the Program currently recording (static.program_guard)


def active():
    return _ACTIVE[0]


def set_active(program):
    _ACTIVE[0] = program


def record(kind, payload, t_leaves, outputs):
    """Append one dispatched op to the active program (no-op when inactive)."""
    prog = _ACTIVE[0]
    if prog is not None:
        prog._record_op(kind, payload, t_leaves, outputs)
