"""Server-side tables and optimizers for the parameter server.

Reference analog: paddle/fluid/distributed/ps/table/ (memory_dense_table.cc,
memory_sparse_table.cc, sparse accessors with server-side adagrad/adam) —
rebuilt as numpy state machines: the server owns fp32 master copies and the
optimizer state; trainers only ever see parameter values.
"""
from __future__ import annotations

import os
import threading

import numpy as np


class _ServerOptimizer:
    """Server-side rule applied to a table's values. (ps/table accessors.)"""

    def __init__(self, kind="sgd", lr=0.01, beta1=0.9, beta2=0.999,
                 eps=1e-8, weight_decay=0.0, momentum=0.9):
        self.kind = kind
        self.lr = float(lr)
        self.beta1, self.beta2, self.eps = float(beta1), float(beta2), float(eps)
        self.weight_decay = float(weight_decay)  # decoupled (AdamW-style)
        self.momentum = float(momentum)

    def make_state(self, shape):
        if self.kind == "sgd":
            return {}
        if self.kind == "adagrad":
            return {"g2": np.zeros(shape, np.float32)}
        if self.kind == "momentum":
            return {"v": np.zeros(shape, np.float32)}
        if self.kind == "adam":
            return {"m": np.zeros(shape, np.float32),
                    "v": np.zeros(shape, np.float32), "t": 0}
        if self.kind == "summer":  # geo-sgd delta accumulation: w += delta
            return {}
        raise ValueError(f"unknown server optimizer {self.kind!r}")

    def apply(self, value, grad, state, lr=None):
        # lr rides along with every push so trainer-side LR schedulers work
        lr = self.lr if lr is None else float(lr)
        if self.weight_decay:
            value *= 1.0 - lr * self.weight_decay
        if self.kind == "sgd":
            value -= lr * grad
        elif self.kind == "momentum":
            state["v"] = self.momentum * state["v"] + grad
            value -= lr * state["v"]
        elif self.kind == "summer":
            value += grad  # "grad" is a parameter delta in geo mode
        elif self.kind == "adagrad":
            state["g2"] += grad * grad
            value -= lr * grad / (np.sqrt(state["g2"]) + self.eps)
        elif self.kind == "adam":
            state["t"] += 1
            t = state["t"]
            state["m"] = self.beta1 * state["m"] + (1 - self.beta1) * grad
            state["v"] = self.beta2 * state["v"] + (1 - self.beta2) * grad * grad
            mhat = state["m"] / (1 - self.beta1 ** t)
            vhat = state["v"] / (1 - self.beta2 ** t)
            value -= lr * mhat / (np.sqrt(vhat) + self.eps)
        return value


class DenseTable:
    """One dense parameter: fp32 value + optimizer state + sync accumulation.

    Sync protocol (exact synchronous SGD): each trainer pushes one grad per
    step; the table accumulates; when `trainers` grads arrived it averages,
    applies the optimizer, and bumps `version`. Pulls can block on a minimum
    version so every trainer sees the post-step weights.
    """

    def __init__(self, name, init_value, optimizer: _ServerOptimizer,
                 trainers=1, sync=True):
        self.name = name
        self.value = np.asarray(init_value, np.float32).copy()
        self.opt = optimizer
        self.state = optimizer.make_state(self.value.shape)
        self.trainers = int(trainers)
        self.sync = bool(sync)
        self.version = 0
        self._pending = None
        self._pending_count = 0
        self._cv = threading.Condition()

    def push_grad(self, grad, lr=None):
        grad = np.asarray(grad, np.float32)
        with self._cv:
            if not self.sync:
                self.value = self.opt.apply(self.value, grad, self.state, lr)
                self.version += 1
                self._cv.notify_all()
                return self.version
            if self._pending is None:
                self._pending = grad.copy()
            else:
                self._pending += grad
            self._pending_count += 1
            if self._pending_count >= self.trainers:
                avg = self._pending / self._pending_count
                self.value = self.opt.apply(self.value, avg, self.state, lr)
                self._pending = None
                self._pending_count = 0
                self.version += 1
                self._cv.notify_all()
            return self.version

    def set_value(self, value):
        with self._cv:
            self.value = np.asarray(value, np.float32).copy()
            self.version += 1
            self._cv.notify_all()

    def pull(self, min_version=0, timeout=None):
        if timeout is None:
            # sync pulls block until every trainer's push lands; on a loaded
            # single-core box (the CI suite) a peer trainer can be starved
            # for a long time, so the deadlock guard is env-tunable
            timeout = float(os.environ.get("PADDLE_PS_SYNC_TIMEOUT", "60"))
        with self._cv:
            ok = self._cv.wait_for(lambda: self.version >= min_version, timeout)
            if not ok:
                raise TimeoutError(
                    f"dense table {self.name!r}: version {min_version} not "
                    f"reached (at {self.version}) within {timeout}s")
            return self.value.copy(), self.version


class SparseTable:
    """id -> embedding row, lazily initialized, server-side optimizer.

    Reference analog: memory_sparse_table.cc — rows materialize on first pull
    (deterministic per-id uniform init so every server/trainer agrees), grads
    are scatter-accumulated by id then applied row-wise.

    Sync mode mirrors DenseTable: every trainer pushes exactly once per step
    (possibly with zero ids); the merged per-id grads are averaged over the
    trainer count and applied once — order-independent, same effective lr as
    the dense path.
    """

    def __init__(self, name, dim, optimizer: _ServerOptimizer,
                 init_scale=0.01, seed=0, trainers=1, sync=False):
        self.name = name
        self.dim = int(dim)
        self.opt = optimizer
        self.init_scale = float(init_scale)
        self.seed = int(seed)
        self.trainers = int(trainers)
        self.sync = bool(sync)
        self.rows = {}
        self.states = {}
        self._pending = {}
        self._pending_count = 0
        self._lock = threading.Lock()

    def _init_row(self, i):
        rng = np.random.default_rng((self.seed, int(i)))
        return rng.uniform(-self.init_scale, self.init_scale,
                           self.dim).astype(np.float32)

    def pull(self, ids):
        ids = np.asarray(ids, np.int64).ravel()
        out = np.empty((ids.size, self.dim), np.float32)
        with self._lock:
            for k, i in enumerate(ids):
                i = int(i)
                row = self.rows.get(i)
                if row is None:
                    row = self._init_row(i)
                    self.rows[i] = row
                out[k] = row
        return out

    def push_grad(self, ids, grads, lr=None):
        ids = np.asarray(ids, np.int64).ravel()
        grads = np.asarray(grads, np.float32).reshape(ids.size, self.dim)
        # dedupe: accumulate grads per unique id (rows repeated in a batch)
        uniq, inv = np.unique(ids, return_inverse=True)
        acc = np.zeros((uniq.size, self.dim), np.float32)
        np.add.at(acc, inv, grads)
        with self._lock:
            if not self.sync:
                self._apply_locked(uniq, acc, lr, scale=1.0)
                return
            for k, i in enumerate(uniq):
                i = int(i)
                cur = self._pending.get(i)
                self._pending[i] = acc[k] if cur is None else cur + acc[k]
            self._pending_count += 1
            if self._pending_count >= self.trainers:
                if self._pending:
                    pids = np.fromiter(self._pending.keys(), np.int64,
                                       len(self._pending))
                    pacc = np.stack([self._pending[int(i)] for i in pids])
                    self._apply_locked(pids, pacc, lr,
                                       scale=1.0 / self.trainers)
                self._pending = {}
                self._pending_count = 0

    def _apply_locked(self, uniq, acc, lr, scale):
        for k, i in enumerate(uniq):
            i = int(i)
            row = self.rows.get(i)
            if row is None:
                row = self._init_row(i)
            st = self.states.get(i)
            if st is None:
                st = self.opt.make_state((self.dim,))
                self.states[i] = st
            self.rows[i] = self.opt.apply(row, acc[k] * scale, st, lr)

    def n_rows(self):
        with self._lock:
            return len(self.rows)

    def dump(self):
        with self._lock:
            if not self.rows:
                return np.empty(0, np.int64), np.empty((0, self.dim), np.float32)
            ids = np.fromiter(self.rows.keys(), np.int64, len(self.rows))
            vals = np.stack([self.rows[int(i)] for i in ids])
            return ids, vals

    def load(self, ids, vals):
        with self._lock:
            for i, v in zip(np.asarray(ids, np.int64), vals):
                self.rows[int(i)] = np.asarray(v, np.float32).copy()


class SSDSparseTable(SparseTable):
    """Two-tier sparse table: LRU hot rows in memory, cold rows on disk.

    Reference analog: paddle/fluid/distributed/ps/table/ssd_sparse_table.h:63
    (MemorySparseTable subclass whose overflow tier is a rocksdb instance) —
    rebuilt on sqlite3 (stdlib): hot rows live in the in-memory dict exactly
    like SparseTable; when the hot set exceeds `cache_rows`, the least
    recently used rows (value + optimizer state) spill to an on-disk table
    and are transparently faulted back on the next pull/push. `shrink()`
    drops rows whose access count is below a threshold (the reference's
    show-clicks decay pass).
    """

    def __init__(self, name, dim, optimizer: _ServerOptimizer,
                 init_scale=0.01, seed=0, trainers=1, sync=False,
                 cache_rows=100_000, db_path=None):
        super().__init__(name, dim, optimizer, init_scale=init_scale,
                         seed=seed, trainers=trainers, sync=sync)
        import collections
        import sqlite3
        import tempfile

        self.cache_rows = int(cache_rows)
        self._lru = collections.OrderedDict()  # id -> None, most-recent last
        self._access = {}  # id -> access count since last shrink
        self._owns_db = db_path is None
        if db_path is None:
            f = tempfile.NamedTemporaryFile(
                prefix=f"ssd_table_{name}_", suffix=".db", delete=False)
            db_path = f.name
            f.close()
        self.db_path = db_path
        self._db = sqlite3.connect(db_path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS rows ("
            "id INTEGER PRIMARY KEY, val BLOB, state BLOB)")
        self._db.commit()

    # ---- tier plumbing (all called with self._lock held) ----

    def _touch(self, i):
        self._lru.pop(i, None)
        self._lru[i] = None
        self._access[i] = self._access.get(i, 0) + 1

    def _fault_in(self, i):
        """Disk -> memory. Returns the row or None if absent on both tiers."""
        row = self.rows.get(i)
        if row is not None:
            return row
        cur = self._db.execute(
            "SELECT val, state FROM rows WHERE id=?", (i,)).fetchone()
        if cur is None:
            return None
        val = np.frombuffer(cur[0], np.float32).copy()
        self.rows[i] = val
        if cur[1]:
            import pickle

            self.states[i] = pickle.loads(cur[1])
        self._db.execute("DELETE FROM rows WHERE id=?", (i,))
        self._db_dirty = True
        return val

    def _evict_cold(self):
        import pickle

        n_evict = len(self.rows) - self.cache_rows
        if n_evict <= 0:
            return
        batch = []
        for i in list(self._lru):
            if n_evict <= 0:
                break
            row = self.rows.pop(i, None)
            if row is None:
                self._lru.pop(i, None)
                continue
            st = self.states.pop(i, None)
            batch.append((i, row.astype(np.float32).tobytes(),
                          pickle.dumps(st) if st else b""))
            self._lru.pop(i, None)
            n_evict -= 1
        if batch:
            self._db.executemany(
                "INSERT OR REPLACE INTO rows VALUES (?,?,?)", batch)
            self._db_dirty = True

    def _commit(self):
        """Flush fault-in DELETEs / eviction INSERTs: without this, close()
        would roll the implicit transaction back and faulted-in rows would
        resurrect on disk with their pre-fault values."""
        if getattr(self, "_db_dirty", False):
            self._db.commit()
            self._db_dirty = False

    # ---- public surface: same contract as SparseTable ----

    def pull(self, ids):
        ids = np.asarray(ids, np.int64).ravel()
        out = np.empty((ids.size, self.dim), np.float32)
        with self._lock:
            for k, i in enumerate(ids):
                i = int(i)
                row = self._fault_in(i)
                if row is None:
                    row = self._init_row(i)
                    self.rows[i] = row
                self._touch(i)
                out[k] = row
            self._evict_cold()
            self._commit()
        return out

    def _apply_locked(self, uniq, acc, lr, scale):
        for i in uniq:  # fault the whole update set in first
            self._fault_in(int(i))
            self._touch(int(i))
        super()._apply_locked(uniq, acc, lr, scale)
        self._evict_cold()
        self._commit()

    def load(self, ids, vals):
        """Restored rows are authoritative: enter them through the LRU (so
        the cache_rows cap keeps working after a warm restore) and drop any
        stale spilled copy a persistent db_path may still hold."""
        ids = np.asarray(ids, np.int64)
        with self._lock:
            self._db.executemany("DELETE FROM rows WHERE id=?",
                                 [(int(i),) for i in ids])
            for i, v in zip(ids, vals):
                i = int(i)
                self.rows[i] = np.asarray(v, np.float32).copy()
                self._lru.pop(i, None)
                self._lru[i] = None  # recently-restored = recently-used
            self._evict_cold()
            self._db.commit()
            self._db_dirty = False

    def n_rows(self):
        with self._lock:
            n_disk = self._db.execute("SELECT COUNT(*) FROM rows").fetchone()[0]
            return len(self.rows) + n_disk

    def n_hot(self):
        with self._lock:
            return len(self.rows)

    def shrink(self, min_access=1):
        """Drop rows accessed fewer than `min_access` times since the last
        shrink pass; reset access counts. (ssd_sparse_table.cc Shrink.)"""
        with self._lock:
            dead = [i for i in list(self.rows)
                    if self._access.get(i, 0) < min_access]
            for i in dead:
                self.rows.pop(i, None)
                self.states.pop(i, None)
                self._lru.pop(i, None)
            # disk rows keep their pre-eviction access counts in _access
            disk_ids = [r[0] for r in
                        self._db.execute("SELECT id FROM rows").fetchall()]
            dead_disk = [(i,) for i in disk_ids
                         if self._access.get(i, 0) < min_access]
            self._db.executemany("DELETE FROM rows WHERE id=?", dead_disk)
            self._db.commit()
            dead += [i for (i,) in dead_disk]
            self._access = {}
            return len(dead)

    def dump(self):
        with self._lock:
            ids_mem = list(self.rows.keys())
            disk = self._db.execute("SELECT id, val FROM rows").fetchall()
            ids = np.asarray(
                ids_mem + [r[0] for r in disk], np.int64)
            if ids.size == 0:
                return ids, np.empty((0, self.dim), np.float32)
            vals = np.stack(
                [self.rows[i] for i in ids_mem]
                + [np.frombuffer(r[1], np.float32) for r in disk])
            return ids, vals

    def close(self):
        import os as _os

        with self._lock:
            self._commit()
            self._db.close()
            if self._owns_db:  # self-generated temp spill file
                try:
                    _os.unlink(self.db_path)
                except OSError:
                    pass
