"""Global FLAGS registry.

The reference defines ~188 exported FLAGS_* in paddle/common/flags.cc with env-var pickup and
runtime get/set surfaced through paddle.set_flags/get_flags
(python/paddle/base/framework.py:144). Here the registry is a plain dict with typed defaults,
env ingestion at import, and the same public get/set API.
"""
from __future__ import annotations

import os
from typing import Any, Dict

_REGISTRY: Dict[str, dict] = {}
# bumped on every set_flags: caches of traced/compiled programs that baked a
# flag value at trace time (ops/_apply.py's jit-cached backwards) key on this
# so a flag change forces a retrace instead of silently using stale values
_EPOCH = [0]


def epoch() -> int:
    return _EPOCH[0]


def define_flag(name: str, default: Any, doc: str = ""):
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    value = default
    env = os.environ.get(name)
    if env is not None:
        value = _parse(env, type(default))
    _REGISTRY[name] = {"value": value, "default": default, "doc": doc, "type": type(default)}
    return value


def _parse(text: str, ty):
    if ty is bool:
        return text.lower() in ("1", "true", "yes", "on")
    if ty in (int, float):
        return ty(text)
    return text


def set_flags(flags: Dict[str, Any]):
    _EPOCH[0] += 1
    for k, v in flags.items():
        if not k.startswith("FLAGS_"):
            k = "FLAGS_" + k
        if k not in _REGISTRY:
            define_flag(k, v)
        else:
            _REGISTRY[k]["value"] = _parse(v, _REGISTRY[k]["type"]) if isinstance(v, str) else v


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        key = k if k.startswith("FLAGS_") else "FLAGS_" + k
        if key not in _REGISTRY:
            raise KeyError(f"Unknown flag {k}")
        out[k] = _REGISTRY[key]["value"]
    return out


def flag(name: str):
    key = name if name.startswith("FLAGS_") else "FLAGS_" + name
    return _REGISTRY[key]["value"]


def exported_flags() -> Dict[str, dict]:
    return dict(_REGISTRY)


# Core flags (subset of the reference's set that is meaningful on TPU).
define_flag("check_nan_inf", False, "scan op outputs for NaN/Inf after each eager op")
define_flag("check_nan_inf_level", 0, "0: error on nan/inf; >0: print statistics only")
define_flag("use_stride_kernel", True, "allow zero-copy view ops (reshape/slice return views)")
define_flag("eager_delete_tensor_gb", 0.0, "kept for API parity; XLA/PJRT manages memory")
define_flag("allocator_strategy", "auto_growth", "kept for API parity; PJRT allocates HBM")
define_flag("tpu_matmul_precision", "default", "jax matmul precision: default|high|highest")
define_flag("embedding_deterministic", 0, "kept for API parity (determinism is XLA default)")
define_flag("cudnn_deterministic", False, "API parity alias; TPU execution is deterministic")
define_flag("max_inplace_grad_add", 0, "API parity; tape always accumulates functionally")
define_flag("log_level", 0, "verbosity of paddle_tpu host-side logging")
