"""GL008 dirty sample: the three recompile-hazard shapes."""
import jax

from paddle_tpu.jit import to_static
from paddle_tpu.ops._apply import defop


def scale_api(x):
    # per-call registration AND per-call use: a fresh OpDef identity on
    # every call defeats the per-signature vjp cache
    @defop("scale_bad")
    def _op(v):
        return v * 2

    return _op(x)


@jax.jit
def branchy(x, bias):
    # one compiled program per branch outcome = one per distinct shape
    if x.shape[0] > 4:
        return x * 2
    if x.dtype == "float32":
        return x + bias
    return x


@to_static
def padded(x):
    while len(x) > 8:
        x = x[:-1]
    return x


compiled = to_static(lambda v, fn: fn(v))


def run_per_call(x):
    y = compiled(x, lambda v: v + 1)      # repr-keyed lambda: miss per call

    def local_fn(v):
        return v * 3

    return compiled(y, local_fn)          # fresh function object per call
