"""Sparse NN layers (paddle.sparse.nn): Conv2D/3D, SubmConv2D/3D, BatchNorm,
MaxPool3D — gather-scatter formulation validated numerically against the
dense reference computation.

Reference analog: python/paddle/sparse/nn/layer/{conv,norm,pooling}.py and
test/legacy_test/test_sparse_conv_op.py."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.sparse as sparse


def _voxels(shape_spatial, c_in, density=0.3, batch=2, seed=0):
    """Random channels-last sparse volume [N, *spatial, C] + its dense twin."""
    r = np.random.RandomState(seed)
    dense = r.randn(batch, *shape_spatial, c_in).astype("float32")
    mask = r.rand(batch, *shape_spatial) < density
    dense = dense * mask[..., None]
    t = paddle.to_tensor(dense)
    coo = t.to_sparse_coo(1 + len(shape_spatial))  # dense trailing channel
    return coo, dense


def _dense_conv(dense, w, b, stride, padding, ndim):
    """lax cross-correlation on NHWC/NDHWC with kernel [*k, Cin, Cout]."""
    dn = jax.lax.conv_dimension_numbers(
        dense.shape, w.shape,
        ("NHWC", "HWIO", "NHWC") if ndim == 2 else
        ("NDHWC", "DHWIO", "NDHWC"))
    out = jax.lax.conv_general_dilated(
        jnp.asarray(dense), jnp.asarray(w), (stride,) * ndim,
        [(padding, padding)] * ndim, dimension_numbers=dn)
    if b is not None:
        out = out + jnp.asarray(b)
    return np.asarray(out)


class TestSubmConv:
    @pytest.mark.parametrize("ndim", [2, 3])
    def test_matches_dense_conv_at_input_points(self, ndim):
        spatial = (6, 6) if ndim == 2 else (4, 5, 6)
        coo, dense = _voxels(spatial, c_in=3)
        cls = sparse.nn.SubmConv2D if ndim == 2 else sparse.nn.SubmConv3D
        layer = cls(3, 5, kernel_size=3)
        out = layer(coo)
        # same sparsity pattern as the input
        np.testing.assert_array_equal(np.asarray(out._bcoo.indices),
                                      np.asarray(coo._bcoo.indices))
        ref = _dense_conv(dense, layer.weight.numpy(), layer.bias.numpy(),
                          1, 1, ndim)
        idx = np.asarray(coo._bcoo.indices)
        got = np.asarray(out._bcoo.data)
        for row in range(idx.shape[0]):
            point = tuple(idx[row])
            np.testing.assert_allclose(got[row], ref[point], rtol=2e-5,
                                       atol=2e-5)

    def test_stride_rejected(self):
        with pytest.raises(ValueError):
            coo, _ = _voxels((4, 4), c_in=2)
            layer = sparse.nn.SubmConv2D(2, 2, 3, stride=2)
            layer(coo)


class TestSparseConv:
    @pytest.mark.parametrize("ndim,stride,padding", [(2, 1, 1), (2, 2, 0),
                                                     (3, 1, 1), (3, 2, 1)])
    def test_matches_dense_conv(self, ndim, stride, padding):
        spatial = (6, 6) if ndim == 2 else (4, 6, 6)
        coo, dense = _voxels(spatial, c_in=2)
        cls = sparse.nn.Conv2D if ndim == 2 else sparse.nn.Conv3D
        layer = cls(2, 4, kernel_size=3, stride=stride, padding=padding)
        out = layer(coo)
        ref = _dense_conv(dense, layer.weight.numpy(),
                          layer.bias.numpy(), stride, padding, ndim)
        assert tuple(out.shape)[:-1] == ref.shape[:-1]
        idx = np.asarray(out._bcoo.indices)
        got = np.asarray(out._bcoo.data)
        for row in range(idx.shape[0]):
            np.testing.assert_allclose(got[row], ref[tuple(idx[row])],
                                       rtol=2e-5, atol=2e-5)
        # the output pattern covers every position with receptive-field
        # support: dense outputs off the pattern are exactly bias-only
        covered = np.zeros(ref.shape[:-1], bool)
        for row in range(idx.shape[0]):
            covered[tuple(idx[row])] = True
        off_pattern = ref[~covered]
        np.testing.assert_allclose(
            off_pattern, np.broadcast_to(layer.bias.numpy(),
                                         off_pattern.shape), atol=1e-6)


class TestSparseBatchNorm:
    def test_matches_dense_bn_over_points(self):
        coo, _dense = _voxels((4, 4, 4), c_in=3)
        bn = sparse.nn.BatchNorm(3)
        out = bn(coo)
        vals = np.asarray(coo._bcoo.data)
        mean = vals.mean(0)
        var = vals.var(0)
        expect = (vals - mean) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(np.asarray(out._bcoo.data), expect,
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_array_equal(np.asarray(out._bcoo.indices),
                                      np.asarray(coo._bcoo.indices))

    def test_bias_attr_false_disables_beta(self):
        bn = sparse.nn.BatchNorm(3, weight_attr=False, bias_attr=False)
        assert bn._bn.weight is None and bn._bn.bias is None
        assert len(bn.parameters()) == 0
        coo, _ = _voxels((4, 4, 4), c_in=3)
        out = bn(coo)  # affine-free BN still normalizes
        vals = np.asarray(out._bcoo.data)
        np.testing.assert_allclose(vals.mean(0), 0.0, atol=1e-4)

    def test_eval_uses_running_stats(self):
        coo, _ = _voxels((4, 4, 4), c_in=3, seed=1)
        bn = sparse.nn.BatchNorm(3)
        for _ in range(3):
            bn(coo)
        bn.eval()
        out = bn(coo)
        assert np.isfinite(np.asarray(out._bcoo.data)).all()


class TestSparseMaxPool:
    def test_matches_dense_pool_on_present_points(self):
        coo, dense = _voxels((4, 4, 4), c_in=2, density=0.5)
        pool = sparse.nn.MaxPool3D(2, stride=2)
        out = pool(coo)
        # dense reference with -inf at empty voxels (present-points-only max)
        mask = (dense != 0).any(-1, keepdims=True)
        neg = np.where(mask, dense, -np.inf).astype("float32")
        ref = np.asarray(jax.lax.reduce_window(
            jnp.asarray(neg), -jnp.inf, jax.lax.max,
            (1, 2, 2, 2, 1), (1, 2, 2, 2, 1), "VALID"))
        idx = np.asarray(out._bcoo.indices)
        got = np.asarray(out._bcoo.data)
        for row in range(idx.shape[0]):
            np.testing.assert_allclose(got[row], ref[tuple(idx[row])],
                                       rtol=1e-6)


class TestSparseConvNet:
    def test_small_net_forward(self):
        """The reference's typical stack: SubmConv -> BN -> ReLU -> Conv
        (downsample) -> MaxPool, end to end on sparse voxels."""
        coo, _ = _voxels((6, 6, 6), c_in=2, density=0.2)
        net = [sparse.nn.SubmConv3D(2, 8, 3),
               sparse.nn.BatchNorm(8),
               sparse.nn.ReLU(),
               sparse.nn.Conv3D(8, 16, 3, stride=2, padding=1),
               sparse.nn.MaxPool3D(2, stride=2)]
        x = coo
        for layer in net:
            x = layer(x)
        assert x.shape[-1] == 16
        assert np.isfinite(np.asarray(x._bcoo.data)).all()
