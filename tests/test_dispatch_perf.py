"""Eager-dispatch overhead budget (round-2 verdict #5).

The reference keeps the per-op eager hot path in C++ (~us; SURVEY §3.1). Our
path is Python defop dispatch with a lazy, jit-cached vjp — these tests pin
correctness of the caching fast-path and assert the overhead stays bounded so
regressions (e.g. re-introducing per-call jax.vjp retracing) surface in CI.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle


def _floor_us(f, n=60):
    import gc

    f()  # warm: fills the per-signature caches (jit trace on first backward)
    gc.collect()  # a full-suite run leaves collectable garbage that would
    # otherwise bill GC pauses to the dispatch path under test
    ts = []
    for _ in range(7):
        t0 = time.perf_counter()
        for _ in range(n):
            f()
        ts.append((time.perf_counter() - t0) / n * 1e6)
    # min-of-runs: the dispatch cost is the FLOOR; suite-order noise (GC,
    # allocator pressure after hundreds of tests) only ever adds time, and
    # a real regression raises the floor itself
    return min(ts)


class TestDispatchBudget:
    # bounds sit ~2x above the measured medians (round-4: tape-on add
    # ~20us, fwd+bwd ~260us on the 1-core dev box; raw jnp.add alone is
    # ~11us there) so CI noise passes but a 2-3x dispatch regression
    # actually fails (round-4 verdict: the old 100us budget was 5x slack)
    BUDGET_FWD_US = 40
    BUDGET_FWD_BWD_US = 600

    def test_tape_on_forward_budget(self):
        y = paddle.to_tensor(np.random.randn(4, 4).astype("float32"))
        xg = paddle.to_tensor(np.random.randn(4, 4).astype("float32"),
                              stop_gradient=False)
        us = _floor_us(lambda: xg + y)
        assert us < self.BUDGET_FWD_US, f"tape-on add dispatch {us:.0f}us"

    def test_fwd_bwd_budget(self):
        y = paddle.to_tensor(np.random.randn(4, 4).astype("float32"))
        xg = paddle.to_tensor(np.random.randn(4, 4).astype("float32"),
                              stop_gradient=False)

        def fwd_bwd():
            xg.clear_grad()
            (xg + y).sum().backward()

        us = _floor_us(fwd_bwd, 30)
        assert us < self.BUDGET_FWD_BWD_US, f"fwd+bwd {us:.0f}us"


class TestLazyVjpCorrectness:
    """The jit-cached backward must be numerically identical to direct vjp."""

    def test_cached_backward_matches_direct(self):
        r = np.random.RandomState(0)
        xv = r.randn(3, 5).astype("float32")
        yv = r.randn(3, 5).astype("float32")
        x = paddle.to_tensor(xv, stop_gradient=False)
        y = paddle.to_tensor(yv, stop_gradient=False)
        loss = ((x * y).exp() + x / (y.abs() + 1.0)).sum()
        loss.backward()
        import jax
        import jax.numpy as jnp

        def ref(xx, yy):
            return (jnp.exp(xx * yy) + xx / (jnp.abs(yy) + 1.0)).sum()

        gx, gy = jax.grad(ref, argnums=(0, 1))(xv, yv)
        np.testing.assert_allclose(x.grad.numpy(), np.asarray(gx), rtol=1e-5)
        np.testing.assert_allclose(y.grad.numpy(), np.asarray(gy), rtol=1e-5)

    def test_cache_hit_across_calls_same_signature(self):
        from paddle_tpu.ops._apply import _cached_op_fns

        before = _cached_op_fns.cache_info()
        y = paddle.to_tensor(np.random.randn(4, 4).astype("float32"))
        xg = paddle.to_tensor(np.random.randn(4, 4).astype("float32"),
                              stop_gradient=False)
        for _ in range(4):
            (xg + y).sum()
        after = _cached_op_fns.cache_info()
        # repeated identical signatures must be cache hits, not new entries
        assert after.hits - before.hits >= 3

    def test_unhashable_static_arg_falls_back(self):
        # a raw numpy array kwarg leaf is unhashable -> direct-vjp fallback,
        # still correct
        from paddle_tpu.ops._apply import defop

        @defop("_test_unhashable_fallback")
        def _op(x, weights=None):
            return x * weights

        w = np.asarray([2.0, 3.0], "float32")
        x = paddle.to_tensor(np.asarray([1.0, 1.0], "float32"),
                             stop_gradient=False)
        out = _op(x, weights=w)
        out.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), w)

    def test_retain_graph_double_backward(self):
        y = paddle.to_tensor(np.random.randn(4).astype("float32"))
        x = paddle.to_tensor(np.random.randn(4).astype("float32"),
                             stop_gradient=False)
        loss = (x * y).sum()
        loss.backward(retain_graph=True)
        g1 = x.grad.numpy().copy()
        x.clear_grad()
        loss.backward()
        np.testing.assert_allclose(x.grad.numpy(), g1)

    def test_set_flags_invalidates_cached_backward(self):
        """A flag read at trace time (check_nan_inf pathology aside, e.g.
        matmul precision) must not be baked forever into the jitted pullback:
        set_flags bumps the epoch and forces a fresh cache entry."""
        from paddle_tpu.framework import flags
        from paddle_tpu.ops._apply import _cached_op_fns

        y = paddle.to_tensor(np.random.randn(4, 4).astype("float32"))
        xg = paddle.to_tensor(np.random.randn(4, 4).astype("float32"),
                              stop_gradient=False)
        (xg @ y).sum()
        before = _cached_op_fns.cache_info().currsize
        old = flags.flag("tpu_matmul_precision")
        try:
            paddle.set_flags({"FLAGS_tpu_matmul_precision": "highest"})
            (xg @ y).sum()
            after = _cached_op_fns.cache_info().currsize
            assert after > before  # new epoch -> new entry, not a stale hit
        finally:
            paddle.set_flags({"FLAGS_tpu_matmul_precision": old})

    def test_scalar_python_type_does_not_alias_cache(self):
        """hash(True)==hash(1)==hash(1.0): the cache key must include the
        scalar's Python type so bool/int/float specializations stay distinct."""
        from paddle_tpu.ops._apply import defop

        @defop("_test_scalar_type_key")
        def _op(x, flag=0):
            # isinstance-branching op: True and 1 behave differently
            if flag is True:
                return x * 10.0
            return x + float(flag)

        x = paddle.to_tensor(np.ones(2, "float32"), stop_gradient=False)
        a = _op(x, flag=1)
        b = _op(x, flag=True)
        np.testing.assert_allclose(a.numpy(), [2.0, 2.0])
        np.testing.assert_allclose(b.numpy(), [10.0, 10.0])

    def test_cached_vjp_opt_out_flag(self):
        from paddle_tpu.framework import flags

        y = paddle.to_tensor(np.random.randn(3).astype("float32"))
        x = paddle.to_tensor(np.random.randn(3).astype("float32"),
                             stop_gradient=False)
        paddle.set_flags({"FLAGS_eager_cached_vjp": False})
        try:
            (x * y).sum().backward()
            np.testing.assert_allclose(x.grad.numpy(), y.numpy(), rtol=1e-6)
        finally:
            paddle.set_flags({"FLAGS_eager_cached_vjp": True})

    def test_integer_output_float0_cotangent(self):
        # ops with integer outputs (argmax) alongside float outputs must not
        # break the jitted pullback's float0 handling
        x = paddle.to_tensor(np.random.randn(4, 5).astype("float32"),
                             stop_gradient=False)
        out = x.max(axis=1)
        out.sum().backward()
        assert x.grad is not None
        np.testing.assert_allclose(x.grad.numpy().sum(), 4.0, rtol=1e-6)
