"""DistributedStrategy: every hybrid-parallel/optimization knob in one config object.

Reference analog: python/paddle/distributed/fleet/base/distributed_strategy.py (2,826 LoC,
backed by framework/distributed_strategy.proto). The TPU build keeps the same attribute
surface on plain Python state — there is no protobuf round-trip because no C++ pass
pipeline consumes it; the Python wrappers read the knobs directly.
"""
from __future__ import annotations

import copy


_DEFAULT_HYBRID = {
    "dp_degree": 1,
    "mp_degree": 1,
    "pp_degree": 1,
    "sharding_degree": 1,
    "sep_degree": 1,
    "order": ["pp", "dp", "sharding", "sep", "mp"],
    "mp_configs": {},
    "pp_configs": {},
}


class DistributedStrategy:
    def __init__(self):
        # hybrid parallel
        self.hybrid_configs = copy.deepcopy(_DEFAULT_HYBRID)
        # amp
        self.amp = False
        self.amp_configs = {
            "init_loss_scaling": 32768.0,
            "use_dynamic_loss_scaling": True,
            "use_pure_fp16": False,
            "use_bf16": True,  # TPU-first default: bf16 needs no loss scaling
            "custom_white_list": [],
            "custom_black_list": [],
        }
        # recompute
        self.recompute = False
        self.recompute_configs = {"checkpoints": [], "enable_offload": False}
        # sharding (ZeRO)
        self.sharding = False
        self.sharding_configs = {
            "sharding_degree": 1,
            "stage": 1,
            "offload": False,
            "comm_buffer_size_MB": 25,
        }
        # pipeline
        self.pipeline = False
        self.pipeline_configs = {
            "accumulate_steps": 1,
            "micro_batch_size": 1,
            "schedule_mode": "1F1B",
            "p2p_cache_shape": True,
        }
        # misc optimizations (accepted for parity; XLA does the fusion work)
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.localsgd = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.find_unused_parameters = False
        self.gradient_scale_configs = {"scale_strategy": "avg"}
        self.heter_ccl_mode = False
        self.without_graph_optimization = True
        self.tensor_parallel = False
        self.tensor_parallel_configs = {}
        self.a_sync = False
        self.a_sync_configs = {}
        self.auto_tuner = False

    def __setattr__(self, key, value):
        if key == "hybrid_configs" and hasattr(self, "hybrid_configs"):
            merged = copy.deepcopy(_DEFAULT_HYBRID)
            merged.update(value or {})
            object.__setattr__(self, key, merged)
            return
        object.__setattr__(self, key, value)

    @property
    def hybrid_parallel_order(self):
        return list(self.hybrid_configs.get("order", _DEFAULT_HYBRID["order"]))

    def to_dict(self):
        return {k: v for k, v in self.__dict__.items()}

    def __repr__(self):
        lines = ["DistributedStrategy("]
        for k, v in sorted(self.__dict__.items()):
            lines.append(f"  {k}={v!r},")
        lines.append(")")
        return "\n".join(lines)


class Strategy(DistributedStrategy):
    """auto_parallel Strategy (auto_parallel/strategy.py) — same knobs, dot-access groups."""
