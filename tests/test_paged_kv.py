"""Paged (block-table) KV attention: numerics vs the dense path + allocator.

Reference analog: incubate/nn/functional/block_multihead_attention.py (the
CUDA paged serving kernel) — here models/paged_kv.py implements the block
pool with jnp gathers/scatters. The acceptance bar: paged attention must be
numerically identical to dense attention over the same history, for ragged
per-sequence lengths, GQA, and multi-block sequences.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.paged_kv import (
    PagedKVCache, paged_attention_decode, paged_write_decode,
    paged_write_prefill)


def _dense_attention(q, ks, vs):
    """Oracle: fp32 softmax attention of one query over a dense history.
    q [H, D]; ks/vs [T, KV, D]; GQA by head grouping."""
    H, D = q.shape
    KV = ks.shape[1]
    g = H // KV
    qg = q.reshape(KV, g, D).astype(np.float64)
    logits = np.einsum("hgd,thd->hgt", qg, ks.astype(np.float64)) / np.sqrt(D)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    return np.einsum("hgt,thd->hgd", p, vs.astype(np.float64)).reshape(H, D)


class TestAllocator:
    def test_grant_and_exhaust(self):
        c = PagedKVCache(num_layers=1, num_blocks=6, block_size=4,
                         kv_heads=2, head_dim=8, batch=2,
                         max_blocks_per_seq=4)
        c.ensure_capacity([4, 9])   # 1 + 3 blocks (9 tokens @ bs=4)
        t = np.asarray(c.block_tables)
        assert (t[0] > 0).sum() == 1 and (t[1] > 0).sum() == 3
        # distinct physical blocks, none the reserved null block 0
        used = t[t > 0]
        assert len(set(used.tolist())) == len(used)
        with pytest.raises(RuntimeError, match="pool exhausted"):
            c.ensure_capacity([16, 16])  # needs 4+4 > 5 available

    def test_free_returns_blocks(self):
        c = PagedKVCache(num_layers=1, num_blocks=6, block_size=4,
                         kv_heads=2, head_dim=8, batch=2,
                         max_blocks_per_seq=4)
        c.ensure_capacity([8, 8])
        c.free_sequence(0)
        assert (np.asarray(c.block_tables)[0] == 0).all()
        c.ensure_capacity([0, 16])  # reuses the freed blocks
        assert (np.asarray(c.block_tables)[1] > 0).sum() == 4


@pytest.mark.slow
class TestPagedDecodeNumerics:
    def test_paged_equals_dense_ragged_gqa_multiblock(self):
        """Token-by-token paged decode == dense attention, ragged lengths,
        GQA (4 q heads over 2 kv heads), sequences spanning >1 block."""
        rng = np.random.RandomState(0)
        B, n_q, n_kv, D, bs = 3, 4, 2, 8, 4
        steps = 11                                      # 3 blocks at bs=4
        c = PagedKVCache(num_layers=1, num_blocks=16, block_size=bs,
                         kv_heads=n_kv, head_dim=D, batch=B,
                         max_blocks_per_seq=4, dtype=jnp.float32)
        ck, cv = c.k[0], c.v[0]
        # ragged: sequence b starts decoding at offset b (staggered lens)
        lens = np.array([0, 1, 2], np.int32)
        hist_k = [[] for _ in range(B)]
        hist_v = [[] for _ in range(B)]
        # pre-fill the stagger offsets so lens reflect real history
        for b in range(B):
            for _ in range(int(lens[b])):
                kv = rng.randn(n_kv, D).astype("float32")
                vv = rng.randn(n_kv, D).astype("float32")
                hist_k[b].append(kv)
                hist_v[b].append(vv)
        c.ensure_capacity(lens + 1)
        for b in range(B):
            for t, (kv, vv) in enumerate(zip(hist_k[b], hist_v[b])):
                one = jnp.asarray(np.array([t], np.int32))
                ck, cv = paged_write_decode(
                    ck, cv, c.block_tables[b:b + 1], one,
                    jnp.asarray(kv)[None], jnp.asarray(vv)[None])

        cur = lens.copy()
        for step in range(steps):
            c.ensure_capacity(cur + 1)
            q = rng.randn(B, n_q, D).astype("float32")
            k_new = rng.randn(B, n_kv, D).astype("float32")
            v_new = rng.randn(B, n_kv, D).astype("float32")
            ck, cv = paged_write_decode(ck, cv, c.block_tables,
                                        jnp.asarray(cur), jnp.asarray(k_new),
                                        jnp.asarray(v_new))
            out = np.asarray(paged_attention_decode(
                jnp.asarray(q), ck, cv, c.block_tables, jnp.asarray(cur)))
            for b in range(B):
                hist_k[b].append(k_new[b])
                hist_v[b].append(v_new[b])
                want = _dense_attention(q[b], np.stack(hist_k[b]),
                                        np.stack(hist_v[b]))
                np.testing.assert_allclose(out[b], want, rtol=1e-5,
                                           atol=1e-5, err_msg=f"b={b} "
                                           f"step={step}")
            cur += 1

    def test_prefill_write_then_decode_reads_history(self):
        rng = np.random.RandomState(1)
        B, n_kv, D, bs = 2, 2, 8, 4
        S = 6
        c = PagedKVCache(num_layers=1, num_blocks=8, block_size=bs,
                         kv_heads=n_kv, head_dim=D, batch=B,
                         max_blocks_per_seq=3, dtype=jnp.float32)
        lens = np.array([6, 3], np.int32)
        c.ensure_capacity(lens)
        k_pad = rng.randn(B, S, n_kv, D).astype("float32")
        v_pad = rng.randn(B, S, n_kv, D).astype("float32")
        ck, cv = paged_write_prefill(c.k[0], c.v[0], c.block_tables,
                                     jnp.asarray(lens), jnp.asarray(k_pad),
                                     jnp.asarray(v_pad))
        q = rng.randn(B, 4, D).astype("float32")
        k_new = rng.randn(B, n_kv, D).astype("float32")
        v_new = rng.randn(B, n_kv, D).astype("float32")
        ck, cv = paged_write_decode(ck, cv, c.block_tables,
                                    jnp.asarray(lens), jnp.asarray(k_new),
                                    jnp.asarray(v_new))
        out = np.asarray(paged_attention_decode(
            jnp.asarray(q), ck, cv, c.block_tables, jnp.asarray(lens)))
        for b in range(B):
            ks = np.concatenate([k_pad[b, :lens[b]], k_new[b][None]])
            vs = np.concatenate([v_pad[b, :lens[b]], v_new[b][None]])
            want = _dense_attention(q[b], ks, vs)
            np.testing.assert_allclose(out[b], want, rtol=1e-5, atol=1e-5)


@pytest.mark.slow
class TestBlockMultiheadAttentionFunctional:
    """The reference-surface functional over the paged pool (reference
    block_multihead_attention.py:33 contract: varlen qkv rows, reference
    cache layout [nb, kv, bs, d], returns (out, qkv, k_cache, v_cache))."""

    def _setup(self, B=2, n_q=4, n_kv=2, D=8, bs=4, max_blocks=3):
        import paddle_tpu.incubate.nn.functional as IF

        nb = 1 + B * max_blocks
        kc = np.zeros((nb, n_kv, bs, D), "float32")
        vc = np.zeros((nb, n_kv, bs, D), "float32")
        tables = np.zeros((B, max_blocks), "int64")
        nxt = 1
        for b in range(B):
            for j in range(max_blocks):
                tables[b, j] = nxt
                nxt += 1
        return IF, kc, vc, tables

    def test_prefill_then_decode_matches_dense(self):
        IF, kc, vc, tables = self._setup()
        rng = np.random.RandomState(2)
        B, n_q, n_kv, D = 2, 4, 2, 8
        enc = np.array([5, 3], np.int32)
        tok = int(enc.sum())
        qkv = rng.randn(tok, (n_q + 2 * n_kv) * D).astype("float32")

        out, _, kc2, vc2 = IF.block_multihead_attention(
            paddle.to_tensor(qkv), paddle.to_tensor(kc),
            paddle.to_tensor(vc), paddle.to_tensor(enc),
            paddle.to_tensor(np.zeros(B, np.int32)),
            paddle.to_tensor(enc), block_tables=paddle.to_tensor(tables),
            block_size=4)
        out = np.asarray(out.value)

        # dense causal oracle per sequence
        row = 0
        for b in range(B):
            L = int(enc[b])
            rows = qkv[row:row + L].reshape(L, n_q + 2 * n_kv, D)
            qs, ks, vs = rows[:, :n_q], rows[:, n_q:n_q + n_kv], \
                rows[:, n_q + n_kv:]
            for t in range(L):
                want = _dense_attention(qs[t], ks[:t + 1], vs[:t + 1])
                np.testing.assert_allclose(
                    out[row + t].reshape(n_q, D), want, rtol=1e-5,
                    atol=1e-5, err_msg=f"b={b} t={t}")
            row += L

        # one decode step against the written history
        q1 = rng.randn(B, (n_q + 2 * n_kv) * D).astype("float32")
        out2, _, _, _ = IF.block_multihead_attention(
            paddle.to_tensor(q1), kc2, vc2,
            paddle.to_tensor(np.zeros(B, np.int32)),
            paddle.to_tensor(enc),
            paddle.to_tensor(np.ones(B, np.int32)),
            block_tables=paddle.to_tensor(tables), block_size=4)
        out2 = np.asarray(out2.value)
        row = 0
        for b in range(B):
            L = int(enc[b])
            rows = qkv[row:row + L].reshape(L, n_q + 2 * n_kv, D)
            new = q1[b].reshape(n_q + 2 * n_kv, D)
            ks = np.concatenate([rows[:, n_q:n_q + n_kv],
                                 new[None, n_q:n_q + n_kv]])
            vs = np.concatenate([rows[:, n_q + n_kv:],
                                 new[None, n_q + n_kv:]])
            want = _dense_attention(new[:n_q], ks, vs)
            np.testing.assert_allclose(out2[b].reshape(n_q, D), want,
                                       rtol=1e-5, atol=1e-5)
            row += L

    def test_quant_args_rejected(self):
        IF, kc, vc, tables = self._setup()
        with pytest.raises(NotImplementedError, match="cache_k_quant"):
            IF.block_multihead_attention(
                paddle.to_tensor(np.zeros((2, 64), "float32")),
                paddle.to_tensor(kc), paddle.to_tensor(vc),
                paddle.to_tensor(np.zeros(2, np.int32)),
                paddle.to_tensor(np.ones(2, np.int32)),
                paddle.to_tensor(np.ones(2, np.int32)),
                block_tables=paddle.to_tensor(tables),
                cache_k_quant_scales=paddle.to_tensor(
                    np.ones(2, "float32")))


@pytest.mark.slow
class TestPagedDecodeEngine:
    """LlamaDecodeEngine(kv_cache_layout='paged'): the serving engine over
    the block pool must reproduce the dense-cache engine's generation."""

    def _model(self):
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

        paddle.seed(0)
        cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                          intermediate_size=176, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=96)
        return LlamaForCausalLM(cfg)

    def test_paged_generate_matches_dense(self):
        from paddle_tpu.models.llama_decode import LlamaDecodeEngine

        model = self._model()
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 128, (2, 9)).astype("int32")
        dense = LlamaDecodeEngine(model, max_len=64)
        paged = LlamaDecodeEngine(model, max_len=64,
                                  kv_cache_layout="paged", block_size=8)
        out_d = np.asarray(dense.generate(ids, max_new_tokens=20))
        out_p = np.asarray(paged.generate(ids, max_new_tokens=20))
        np.testing.assert_array_equal(out_p, out_d)
        # lazy grant: after 9 + 20 tokens at block 8, each sequence owns
        # ceil(29 / 8) = 4 blocks, not the max_len/8 = 8 worst case
        owned = (np.asarray(paged._pager.block_tables) > 0).sum(axis=1)
        assert (owned == 4).all(), owned

    def test_paged_beam_search_matches_dense_with_block_sharing(self):
        """Beam search over paged blocks: prompt blocks are SHARED across
        beams (refcounted fork) with copy-on-write at divergence — tokens
        and scores must match the dense-cache beam search exactly."""
        from paddle_tpu.models.llama_decode import LlamaDecodeEngine

        # f64: the dense and paged attention paths are bitwise-identical
        # there, so near-tie top-k flips (f32 gather-order noise on a
        # random-weight model) cannot masquerade as failures
        model = self._model().astype("float64")
        rng = np.random.RandomState(4)
        ids = rng.randint(0, 128, (2, 9)).astype("int32")
        dense = LlamaDecodeEngine(model, max_len=64)
        paged = LlamaDecodeEngine(model, max_len=64,
                                  kv_cache_layout="paged", block_size=8)
        td, sd = dense.beam_search(ids, beam_size=3, max_new_tokens=12,
                                   eos_token_id=5, length_penalty=0.5)
        tp, sp = paged.beam_search(ids, beam_size=3, max_new_tokens=12,
                                   eos_token_id=5, length_penalty=0.5)
        np.testing.assert_array_equal(np.asarray(tp), np.asarray(td))
        np.testing.assert_allclose(np.asarray(sp), np.asarray(sd),
                                   rtol=1e-5, atol=1e-6)
        # sharing accounting: every live block is referenced >= once, and
        # the pool books balance (free + referenced == pool size - null)
        refs = paged._pager._refs
        live = int((refs > 0).sum())
        assert live + len(paged._pager._free) == paged._pager.num_blocks - 1

    def test_interleaved_prefills_do_not_cross_wire(self):
        """Each prefill's cache owns its own pager/tables: decoding cache A
        after prefill B must produce the same tokens as an uninterleaved
        run (the cache, not the engine, carries the block state)."""
        from paddle_tpu.models.llama_decode import LlamaDecodeEngine

        model = self._model()
        rng = np.random.RandomState(3)
        ids_a = rng.randint(0, 128, (1, 7)).astype("int32")
        ids_b = rng.randint(0, 128, (1, 5)).astype("int32")

        eng = LlamaDecodeEngine(model, max_len=48,
                                kv_cache_layout="paged", block_size=8)
        want = np.asarray(eng.generate(ids_a, max_new_tokens=8))

        la, ca, pa = eng.prefill(ids_a)
        lb, cb, pb = eng.prefill(ids_b)   # would clobber engine-level state
        toks = [np.asarray(jnp.argmax(la, -1))[..., None].astype("int32")]
        logits, cache = la, ca
        for _ in range(7):
            logits, cache = eng.decode_step(toks[-1], cache, pa)
            pa += 1
            toks.append(np.asarray(jnp.argmax(logits, -1))[..., None]
                        .astype("int32"))
        got = np.concatenate(toks, axis=1)
        np.testing.assert_array_equal(got, want)

    def test_paged_int8_generate_matches_dense_int8(self):
        """Quantized paged blocks: the int8 paged cache must reproduce the
        dense int8 engine's greedy generation (same per-(token, head)
        absmax quantization, paged storage)."""
        from paddle_tpu.models.llama_decode import LlamaDecodeEngine

        model = self._model()
        rng = np.random.RandomState(5)
        ids = rng.randint(0, 128, (2, 9)).astype("int32")
        dense = LlamaDecodeEngine(model, max_len=64, kv_cache_dtype="int8")
        paged = LlamaDecodeEngine(model, max_len=64, kv_cache_dtype="int8",
                                  kv_cache_layout="paged", block_size=8)
        out_d = np.asarray(dense.generate(ids, max_new_tokens=16))
        out_p = np.asarray(paged.generate(ids, max_new_tokens=16))
        np.testing.assert_array_equal(out_p, out_d)


class TestAllocatorInvariants:
    """Property test: under a random workload of grants, frees, forks and
    CoW events, the allocator's books must always balance —
    refs[blk] == number of table cells referencing blk, the free list is
    disjoint from referenced blocks, and free + live == pool - null."""

    def _check(self, c):
        t = c._tables_np
        counts = np.bincount(t[t > 0].ravel(), minlength=c.num_blocks)
        live = np.flatnonzero(counts)
        np.testing.assert_array_equal(c._refs[live], counts[live],
                                      err_msg="refcount != table count")
        assert (c._refs[counts == 0] == 0).all(), \
            "nonzero refs on unreferenced blocks"
        free = set(c._free)
        assert free.isdisjoint(set(live.tolist())), "freed live block"
        assert len(free) + len(live) == c.num_blocks - 1, (
            len(free), len(live), c.num_blocks)
        assert 0 not in free, "null block entered the free list"

    def test_retained_blocks_survive_owner_eviction(self):
        """The prefix cache's pin: a retained block stays allocated when
        its producing sequence frees, can be adopted by a new row, and
        only returns to the pool once every reference lets go."""
        c = PagedKVCache(num_layers=1, num_blocks=8, block_size=4,
                         kv_heads=2, head_dim=8, batch=2,
                         max_blocks_per_seq=4)
        c.ensure_capacity([8, 0])
        shared = [int(b) for b in c._tables_np[0] if b > 0]
        c.retain_blocks(shared)                     # the cache's pin
        c.free_sequence(0)                          # owner evicted
        assert all(c._refs[b] == 1 for b in shared)
        assert not set(shared) & set(c._free), "pinned block freed"
        c.adopt_blocks(1, shared)                   # new request shares
        assert all(c._refs[b] == 2 for b in shared)
        np.testing.assert_array_equal(
            np.asarray(c.block_tables)[1, :2], shared)
        c.free_sequence(1)
        assert c.release_blocks(shared) == len(shared)  # pin released
        assert set(shared) <= set(c._free)

    def test_retain_free_block_rejected(self):
        c = PagedKVCache(num_layers=1, num_blocks=8, block_size=4,
                         kv_heads=2, head_dim=8, batch=2,
                         max_blocks_per_seq=4)
        with pytest.raises(ValueError, match="free"):
            c.retain_blocks([3])
        with pytest.raises(ValueError, match="out of range"):
            c.retain_blocks([0])

    def test_adopt_requires_empty_row(self):
        c = PagedKVCache(num_layers=1, num_blocks=8, block_size=4,
                         kv_heads=2, head_dim=8, batch=2,
                         max_blocks_per_seq=4)
        c.ensure_capacity([4, 4])
        blk = int(c._tables_np[0, 0])
        with pytest.raises(ValueError, match="already holds"):
            c.adopt_blocks(1, [blk])

    def test_cow_under_pool_exhaustion(self):
        """make_positions_exclusive must raise (not corrupt) when a shared
        write target needs a copy and the pool has no free block."""
        c = PagedKVCache(num_layers=1, num_blocks=3, block_size=4,
                         kv_heads=1, head_dim=2, batch=2,
                         max_blocks_per_seq=2, dtype=jnp.float32)
        c.ensure_capacity([4, 0])          # row 0 owns block A
        blk = int(c._tables_np[0, 0])
        c.retain_blocks([blk])             # shared: refs == 2
        c.ensure_capacity([4, 4])          # row 1 takes the LAST free block
        pools = (c.k[0], c.v[0])
        with pytest.raises(RuntimeError, match="copy-on-write"):
            c.make_positions_exclusive([0], [3], pools)
        # books stay balanced: the failed CoW granted nothing
        assert c._refs[blk] == 2 and not c._free

    def test_cow_partial_exhaustion_applies_completed_copies(self):
        """When the pool runs dry mid-CoW, the copies already remapped
        must still receive their DATA (their rows now look unshared, so
        a retrying caller would otherwise read uninitialized KV), and
        the donated-in pools' replacement must ride the exception."""
        from paddle_tpu.models.paged_kv import CowPoolExhausted

        c = PagedKVCache(num_layers=1, num_blocks=5, block_size=4,
                         kv_heads=1, head_dim=2, batch=3,
                         max_blocks_per_seq=2, dtype=jnp.float32)
        c.ensure_capacity([4, 4, 0])       # rows 0 and 1 own one block each
        b0, b1 = int(c._tables_np[0, 0]), int(c._tables_np[1, 0])
        c.retain_blocks([b0, b1])          # both shared (refs == 2)
        c.ensure_capacity([4, 4, 4])       # row 2: ONE free block remains
        k = c.k[0].at[b0].set(7.0).at[b1].set(9.0)
        pools = (k, c.v[0])
        with pytest.raises(CowPoolExhausted, match="copy-on-write") as ei:
            c.make_positions_exclusive([0, 1], [3, 3], pools)
        # row 0's copy was remapped before exhaustion: its new private
        # block must CONTAIN block b0's data, and the books must show
        # exactly one transfer of ownership
        new0 = int(c._tables_np[0, 0])
        assert new0 != b0 and c._refs[b0] == 1 and c._refs[new0] == 1
        assert (np.asarray(ei.value.pools[0][new0]) == 7.0).all()
        # row 1 never got a block: still shared, retryable
        assert int(c._tables_np[1, 0]) == b1 and c._refs[b1] == 2

    def test_positions_exclusive_copies_once_per_block(self):
        """Two lanes writing the SAME shared block (a prefill chunk
        spanning it) trigger exactly one copy."""
        c = PagedKVCache(num_layers=1, num_blocks=8, block_size=4,
                         kv_heads=1, head_dim=2, batch=2,
                         max_blocks_per_seq=4, dtype=jnp.float32)
        c.ensure_capacity([8, 0])
        blk = int(c._tables_np[0, 1])      # row 0's second block
        c.retain_blocks([blk])
        free0 = len(c._free)
        pools = (c.k[0], c.v[0])
        pools = c.make_positions_exclusive([0, 0], [5, 6], pools)
        assert len(c._free) == free0 - 1
        assert int(c._tables_np[0, 1]) != blk
        assert c._refs[blk] == 1           # only the pin remains

    def test_random_workload_books_balance(self):
        rng = np.random.RandomState(0)
        B, bs, max_blocks = 6, 4, 5
        c = PagedKVCache(num_layers=1, num_blocks=B * max_blocks + 1,
                         block_size=bs, kv_heads=1, head_dim=2, batch=B,
                         max_blocks_per_seq=max_blocks, dtype=jnp.float32)
        pools = [(c.k[0], c.v[0])]
        lens = np.zeros(B, np.int64)
        self._check(c)
        for step in range(200):
            op = rng.randint(4)
            if op == 0:                        # grow a random row
                b = rng.randint(B)
                if lens[b] + 1 < bs * max_blocks:
                    lens[b] += 1
                    c.ensure_capacity(lens)
            elif op == 1:                      # free a random row
                b = rng.randint(B)
                c.free_sequence(b)
                lens[b] = 0
            elif op == 2:                      # fork from random parents
                parents = rng.randint(0, B, B)
                c.fork_rows(parents)
                lens = lens[parents]
            else:                              # CoW at a random position
                pos = int(lens.max()) if lens.max() > 0 else 0
                c.ensure_capacity(np.maximum(lens, pos + 1)
                                  * (lens > 0))
                pools = [c.make_tail_exclusive(pos, pools[0])]
            self._check(c)


class TestQuantizedSpillRoundTrip:
    """ISSUE 7 satellite: the spill read/restore path must carry the
    quantized pool layout's FOUR leaves per layer (int8 K/V values +
    fp32 per-(token, head) scales) bit-exactly — read_blocks downloads
    whatever leaf tuple the pool holds, write_block_contents scatters it
    back, and no leaf may be dropped, reordered, or recast."""

    def _pool(self, quantized, blocks=9, bs=4, layers=2):
        return PagedKVCache(num_layers=layers, num_blocks=blocks,
                            block_size=bs, kv_heads=2, head_dim=8,
                            batch=2, max_blocks_per_seq=4,
                            dtype=jnp.float32, quantized=quantized)

    def test_quantized_roundtrip_bit_exact(self):
        from paddle_tpu.models.paged_kv import read_blocks
        c = self._pool(True)
        pools = list(zip(c.k, c.k_scale, c.v, c.v_scale))
        rng = np.random.RandomState(0)
        blks = [2, 5, 7]                      # 3 blocks: pads to 4 inside
        vshape = (len(blks),) + tuple(c.k[0].shape[1:])
        sshape = (len(blks),) + tuple(c.k_scale[0].shape[1:])
        want = [tuple(
            rng.randint(-128, 128, vshape).astype(np.int8) if i % 2 == 0
            else rng.rand(*sshape).astype(np.float32)
            for i in range(4)) for _ in range(2)]
        pools = c.write_block_contents(pools, blks, want)
        got = read_blocks(pools, blks)
        assert len(got) == 2
        for wl, gl in zip(want, got):
            assert len(gl) == 4               # (kq, ks, vq, vs)
            for w, g in zip(wl, gl):
                assert g.dtype == w.dtype
                np.testing.assert_array_equal(g, w)

    def test_quantized_roundtrip_leaves_other_blocks_alone(self):
        from paddle_tpu.models.paged_kv import read_blocks
        c = self._pool(True, layers=1)
        pools = [(c.k[0], c.k_scale[0], c.v[0], c.v_scale[0])]
        rng = np.random.RandomState(1)
        vshape = (1,) + tuple(c.k[0].shape[1:])
        sshape = (1,) + tuple(c.k_scale[0].shape[1:])
        content = [(rng.randint(-128, 128, vshape).astype(np.int8),
                    rng.rand(*sshape).astype(np.float32),
                    rng.randint(-128, 128, vshape).astype(np.int8),
                    rng.rand(*sshape).astype(np.float32))]
        pools = c.write_block_contents(pools, [3], content)
        # the power-of-two padding wrote only the null block; every
        # other real block stays zero
        others = [b for b in range(1, c.num_blocks) if b != 3]
        for leaf in read_blocks(pools, others)[0]:
            assert not leaf.any()

    def test_full_precision_roundtrip_still_two_leaves(self):
        from paddle_tpu.models.paged_kv import read_blocks
        c = self._pool(False, layers=1)
        pools = [(c.k[0], c.v[0])]
        rng = np.random.RandomState(2)
        shape = (2,) + tuple(c.k[0].shape[1:])
        content = [(rng.rand(*shape).astype(np.float32),
                    rng.rand(*shape).astype(np.float32))]
        pools = c.write_block_contents(pools, [1, 4], content)
        got = read_blocks(pools, [1, 4])
        assert len(got[0]) == 2
        for w, g in zip(content[0], got[0]):
            np.testing.assert_array_equal(g, w)
