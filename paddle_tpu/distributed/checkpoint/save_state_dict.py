"""Sharded checkpoint save.

Reference analog: python/paddle/distributed/checkpoint/save_state_dict.py:48
(async save queue) / :135 (save_state_dict — each rank writes its unique local
shards plus a coordinator metadata file).

TPU-first mapping: a GSPMD array already knows its shard layout
(jax.Array.addressable_shards carries per-device index + replica_id), so "which
ranks own which unique shard" falls out of the sharding instead of a dist_attr
walk. Each process writes exactly its addressable replica-0 shards into one
``.distcp.npz`` container + one per-process metadata JSON; the loader merges all
metadata files, making the format identical for single-controller tests and
true multi-host runs (no cross-process gather needed at save time).
"""
from __future__ import annotations

import os
import threading

import numpy as np

import jax

from ...framework.core import Tensor
from .metadata import LocalTensorIndex, LocalTensorMetadata, Metadata

_ASYNC_THREADS = []


def flatten_state_dict(state_dict, prefix=()):
    """Nested dict -> flat { 'a/b/c': leaf }; records the original path."""
    flat, mapping = {}, {}
    for key, val in state_dict.items():
        path = prefix + (str(key),)
        if isinstance(val, dict):
            sub_flat, sub_map = flatten_state_dict(val, path)
            flat.update(sub_flat)
            mapping.update(sub_map)
        else:
            name = "/".join(path)
            flat[name] = val
            mapping[name] = path
    return flat, mapping


def unflatten_state_dict(flat, mapping):
    nested = {}
    for name, val in flat.items():
        path = mapping.get(name, (name,))
        cur = nested
        for part in path[:-1]:
            cur = cur.setdefault(part, {})
        cur[path[-1]] = val
    return nested


def _storable(local: np.ndarray) -> np.ndarray:
    """npz round-trips only native dtypes; ml_dtypes (bfloat16, fp8) come back as
    opaque void — store their bit pattern as a same-width uint instead (the
    logical dtype is recorded in LocalTensorMetadata.dtype)."""
    if local.dtype.kind == "V":
        return local.view(f"u{local.dtype.itemsize}")
    return local


def _as_jax_array(value):
    if isinstance(value, Tensor):
        return value.value
    if isinstance(value, jax.Array):
        return value
    return None


def _process_rank():
    return jax.process_index()


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    async_save=False):
    """Write each tensor's unique shards under `path` (flat-shard format).

    Every process participates; the data files and metadata are keyed by
    process index so concurrent writers never collide.
    """
    os.makedirs(path, exist_ok=True)
    flat, mapping = flatten_state_dict(state_dict)
    rank = _process_rank()

    arrays = {}          # npz key -> np.ndarray
    md = Metadata(flat_mapping=mapping)
    data_file = f"{rank}_0.distcp.npz"

    n = 0
    for name, value in flat.items():
        arr = _as_jax_array(value)
        if arr is None:
            # python scalar / numpy leaf: rank 0 owns it
            if rank == coordinator_rank:
                key = f"t{n}"
                n += 1
                np_val = np.asarray(value)
                offset = (0,) * np_val.ndim
                arrays[key] = np_val
                md.state_dict_metadata.setdefault(name, []).append(
                    LocalTensorMetadata(offset, tuple(np_val.shape),
                                        str(np_val.dtype)))
                md.storage_metadata[LocalTensorIndex(name, offset)] = \
                    f"{data_file}::{key}"
                md.global_shapes[name] = tuple(np_val.shape)
            continue
        md.global_shapes[name] = tuple(arr.shape)
        for shard in arr.addressable_shards:
            if shard.replica_id != 0:
                continue  # replicas saved once, by their replica-0 owner
            offset = tuple(
                (sl.start or 0) for sl in shard.index) if shard.index else ()
            local = np.asarray(shard.data)
            key = f"t{n}"
            n += 1
            arrays[key] = _storable(local)
            md.state_dict_metadata.setdefault(name, []).append(
                LocalTensorMetadata(offset, tuple(local.shape), str(local.dtype)))
            md.storage_metadata[LocalTensorIndex(name, offset)] = \
                f"{data_file}::{key}"

    world = jax.process_count()

    def _write():
        if arrays:
            np.savez(os.path.join(path, data_file), **arrays)
        with open(os.path.join(path, f"{rank}.metadata.json"), "w") as f:
            f.write(md.to_json())
        if rank == coordinator_rank:
            # manifest pins which rank files belong to THIS save: re-saving into
            # a dir previously written by more processes must not let the loader
            # merge the stale extra-rank shards
            import json

            with open(os.path.join(path, "checkpoint.manifest.json"), "w") as f:
                json.dump({"world_size": world}, f)

    if async_save:
        holder = {"error": None}

        def _guarded():
            try:
                _write()
            except BaseException as e:  # surfaced by wait_async_save
                holder["error"] = e

        t = threading.Thread(target=_guarded, daemon=True)
        t.start()
        _ASYNC_THREADS.append((t, holder))
    else:
        _write()


def wait_async_save():
    """Join outstanding async save threads and re-raise any write failure
    (a silently lost checkpoint is only discovered at restore time otherwise)."""
    errors = []
    while _ASYNC_THREADS:
        t, holder = _ASYNC_THREADS.pop()
        t.join()
        if holder["error"] is not None:
            errors.append(holder["error"])
    if errors:
        raise errors[0]
