"""Loss functionals.

Reference analog: python/paddle/nn/functional/loss.py (~25 losses over phi kernels,
softmax_with_cross_entropy at its core). cross_entropy uses a numerically-stable fused
log_softmax+gather form — the shape XLA fuses into one kernel.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...framework.core import Tensor
from ...ops._apply import defop


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


@defop("cross_entropy", amp_category="black")
def _cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",  # noqa: A002
                   soft_label=False, axis=-1, label_smoothing=0.0):
    logp = jax.nn.log_softmax(input, axis=axis)
    if soft_label:
        soft = label
        if label_smoothing > 0.0:
            k = input.shape[axis]
            soft = soft * (1 - label_smoothing) + label_smoothing / k
        loss = -jnp.sum(soft * logp, axis=axis)
        return _reduce(loss, reduction)
    lbl = label
    if lbl.ndim == input.ndim and lbl.shape[axis] == 1:
        lbl = jnp.squeeze(lbl, axis)
    lbl = lbl.astype(jnp.int32)
    valid = lbl != ignore_index
    safe = jnp.where(valid, lbl, 0)
    picked = jnp.take_along_axis(logp, safe[..., None] if axis in (-1, input.ndim - 1)
                                 else jnp.expand_dims(safe, axis), axis=axis)
    picked = jnp.squeeze(picked, axis)
    if label_smoothing > 0.0:
        k = input.shape[axis]
        smooth_term = jnp.mean(logp, axis=axis)
        nll = -(1 - label_smoothing) * picked - label_smoothing * smooth_term
    else:
        nll = -picked
    if weight is not None:
        w = weight[safe]
        nll = nll * w
        nll = jnp.where(valid, nll, 0.0)
        if reduction == "mean":
            return jnp.sum(nll) / jnp.maximum(jnp.sum(jnp.where(valid, w, 0.0)), 1e-12)
    else:
        nll = jnp.where(valid, nll, 0.0)
        if reduction == "mean":
            return jnp.sum(nll) / jnp.maximum(jnp.sum(valid.astype(nll.dtype)), 1.0)
    return _reduce(nll, reduction)


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",  # noqa: A002
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    if not use_softmax:
        # input is already a probability distribution; loss = NLL of log-probs
        return nll_loss(input.log(), label, weight, ignore_index, reduction)
    return _cross_entropy(input, label, weight, ignore_index=int(ignore_index),
                          reduction=reduction, soft_label=bool(soft_label), axis=int(axis),
                          label_smoothing=float(label_smoothing))


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = _cross_entropy(logits, label, None, ignore_index=int(ignore_index),
                          reduction="none", soft_label=bool(soft_label), axis=int(axis))
    from .activation import softmax as softmax_fn

    from ...ops.manipulation import unsqueeze

    loss = unsqueeze(loss, [int(axis)]) if not soft_label else loss
    if return_softmax:
        return loss, softmax_fn(logits, axis=axis)
    return loss


@defop("nll_loss_op", amp_category="black")
def _nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):  # noqa: A002
    lbl = label.astype(jnp.int32)
    valid = lbl != ignore_index
    safe = jnp.where(valid, lbl, 0)
    picked = jnp.take_along_axis(input, safe[:, None] if input.ndim == 2
                                 else jnp.expand_dims(safe, 1), axis=1)
    picked = jnp.squeeze(picked, 1)
    loss = -picked
    if weight is not None:
        w = weight[safe]
        loss = loss * w
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(valid, w, 0.0)), 1e-12)
    loss = jnp.where(valid, loss, 0.0)
    return _reduce(loss, reduction)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):  # noqa: A002
    return _nll_loss(input, label, weight, ignore_index=int(ignore_index), reduction=reduction)


@defop("mse_loss")
def _mse_loss(input, label, reduction="mean"):  # noqa: A002
    return _reduce(jnp.square(input - label), reduction)


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return _mse_loss(input, label, reduction=reduction)


@defop("l1_loss")
def _l1_loss(input, label, reduction="mean"):  # noqa: A002
    return _reduce(jnp.abs(input - label), reduction)


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return _l1_loss(input, label, reduction=reduction)


@defop("smooth_l1_loss")
def _smooth_l1(input, label, delta=1.0, reduction="mean"):  # noqa: A002
    d = jnp.abs(input - label)
    loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return _reduce(loss, reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    return _smooth_l1(input, label, delta=float(delta), reduction=reduction)


@defop("huber_loss")
def _huber(input, label, delta=1.0, reduction="mean"):  # noqa: A002
    d = jnp.abs(input - label)
    loss = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
    return _reduce(loss, reduction)


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):  # noqa: A002
    return _huber(input, label, delta=float(delta), reduction=reduction)


@defop("bce_loss", amp_category="black")
def _bce(input, label, weight=None, reduction="mean"):  # noqa: A002
    eps = 1e-12
    loss = -(label * jnp.log(jnp.maximum(input, eps))
             + (1 - label) * jnp.log(jnp.maximum(1 - input, eps)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):  # noqa: A002
    return _bce(input, label, weight, reduction=reduction)


@defop("bce_with_logits", amp_category="black")
def _bce_logits(logit, label, weight=None, pos_weight=None, reduction="mean"):
    max_val = jnp.maximum(-logit, 0.0)
    if pos_weight is not None:
        log_w = (pos_weight - 1.0) * label + 1.0
        loss = (1 - label) * logit + log_w * (jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val)
    else:
        loss = (1 - label) * logit + jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    return _bce_logits(logit, label, weight, pos_weight, reduction=reduction)


def sigmoid_cross_entropy_with_logits(logit, label, normalize=False, ignore_index=-100):
    out = _bce_logits(logit, label, None, None, reduction="none")
    return out


@defop("kl_div", amp_category="black")
def _kl_div(input, label, reduction="mean", log_target=False):  # noqa: A002
    if log_target:
        loss = jnp.exp(label) * (label - input)
    else:
        loss = label * (jnp.log(jnp.maximum(label, 1e-12)) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


def kl_div(input, label, reduction="mean", log_target=False, name=None):  # noqa: A002
    return _kl_div(input, label, reduction=reduction, log_target=bool(log_target))


@defop("margin_ranking")
def _margin_ranking(input, other, label, margin=0.0, reduction="mean"):  # noqa: A002
    loss = jnp.maximum(0.0, -label * (input - other) + margin)
    return _reduce(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):  # noqa: A002
    return _margin_ranking(input, other, label, margin=float(margin), reduction=reduction)


@defop("hinge_embedding")
def _hinge_embedding(input, label, margin=1.0, reduction="mean"):  # noqa: A002
    loss = jnp.where(label == 1.0, input, jnp.maximum(0.0, margin - input))
    return _reduce(loss, reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):  # noqa: A002
    return _hinge_embedding(input, label, margin=float(margin), reduction=reduction)


@defop("cosine_embedding")
def _cosine_embedding(input1, input2, label, margin=0.0, reduction="mean"):
    cos = jnp.sum(input1 * input2, -1) / jnp.maximum(
        jnp.linalg.norm(input1, axis=-1) * jnp.linalg.norm(input2, axis=-1), 1e-12
    )
    loss = jnp.where(label == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
    return _reduce(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    return _cosine_embedding(input1, input2, label, margin=float(margin), reduction=reduction)


@defop("triplet_margin")
def _triplet_margin(anchor, positive, negative, margin=1.0, p=2.0, eps=1e-6, swap=False,
                    reduction="mean"):
    def dist(a, b):
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a - b) + eps, p), -1), 1.0 / p)

    dp = dist(anchor, positive)
    dn = dist(anchor, negative)
    if swap:
        dn = jnp.minimum(dn, dist(positive, negative))
    return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,  # noqa: A002
                        swap=False, reduction="mean", name=None):
    return _triplet_margin(input, positive, negative, margin=float(margin), p=float(p),
                           eps=float(epsilon), swap=bool(swap), reduction=reduction)


@defop("soft_margin")
def _soft_margin(input, label, reduction="mean"):  # noqa: A002
    return _reduce(jnp.log1p(jnp.exp(-label * input)), reduction)


def soft_margin_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return _soft_margin(input, label, reduction=reduction)


@defop("multi_label_soft_margin")
def _mlsm(input, label, weight=None, reduction="mean"):  # noqa: A002
    loss = -(label * jax.nn.log_sigmoid(input) + (1 - label) * jax.nn.log_sigmoid(-input))
    if weight is not None:
        loss = loss * weight
    return _reduce(jnp.mean(loss, -1), reduction)


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean", name=None):  # noqa: A002
    return _mlsm(input, label, weight, reduction=reduction)


@defop("poisson_nll")
def _poisson_nll(input, label, log_input=True, full=False, epsilon=1e-8, reduction="mean"):  # noqa: A002
    if log_input:
        loss = jnp.exp(input) - label * input
    else:
        loss = input - label * jnp.log(input + epsilon)
    if full:
        stirling = label * jnp.log(jnp.maximum(label, 1.0)) - label + 0.5 * jnp.log(
            2 * np.pi * jnp.maximum(label, 1.0)
        )
        loss = loss + jnp.where(label > 1, stirling, 0.0)
    return _reduce(loss, reduction)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,  # noqa: A002
                     reduction="mean", name=None):
    return _poisson_nll(input, label, log_input=bool(log_input), full=bool(full),
                        epsilon=float(epsilon), reduction=reduction)


@defop("gaussian_nll")
def _gaussian_nll(input, label, variance, full=False, epsilon=1e-6, reduction="mean"):  # noqa: A002
    var = jnp.maximum(variance, epsilon)
    loss = 0.5 * (jnp.log(var) + jnp.square(input - label) / var)
    if full:
        loss = loss + 0.5 * np.log(2 * np.pi)
    return _reduce(loss, reduction)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6, reduction="mean",  # noqa: A002
                      name=None):
    return _gaussian_nll(input, label, variance, full=bool(full), epsilon=float(epsilon),
                         reduction=reduction)


def square_error_cost(input, label):  # noqa: A002
    return _mse_loss(input, label, reduction="none")


# module level, not inside log_loss: a defop inside the function body
# would re-register on every call (registry churn + a fresh OpDef identity
# defeating the per-signature vjp cache) and never reach docs/ops.md (GL003)
@defop("log_loss_op")
def _log_loss_op(input, label, epsilon=1e-4):  # noqa: A002
    return -label * jnp.log(input + epsilon) - (1 - label) * jnp.log(1 - input + epsilon)


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    return _log_loss_op(input, label, epsilon=float(epsilon))


@defop("ctc_loss_op", amp_category="black")
def _ctc_loss_op(log_probs, labels, input_lengths, label_lengths, blank=0):
    # log_probs: (T, N, C) paddle layout
    T, N, C = log_probs.shape
    L = labels.shape[1]
    S = 2 * L + 1
    lbl = labels.astype(jnp.int32)
    ext = jnp.full((N, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(lbl)
    neg_inf = -1e30

    # alpha init
    alpha0 = jnp.full((N, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(log_probs[0, jnp.arange(N), blank])
    first_lbl = log_probs[0, jnp.arange(N), ext[:, 1]]
    alpha0 = alpha0.at[:, 1].set(jnp.where(label_lengths > 0, first_lbl, neg_inf))

    same_as_prev2 = jnp.concatenate(
        [jnp.zeros((N, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1
    )

    def step(alpha, t):
        a_shift1 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
        a_shift2 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
        a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
        merged = jnp.logaddexp(alpha, jnp.logaddexp(a_shift1, a_shift2))
        emit = log_probs[t][jnp.arange(N)[:, None], ext]
        new_alpha = merged + emit
        new_alpha = jnp.where(t < input_lengths[:, None], new_alpha, alpha)
        return new_alpha, None

    alphaT, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
    end_idx = 2 * label_lengths
    last = alphaT[jnp.arange(N), end_idx]
    last2 = jnp.where(end_idx - 1 >= 0, alphaT[jnp.arange(N), jnp.maximum(end_idx - 1, 0)],
                      neg_inf)
    ll = jnp.logaddexp(last, last2)
    return -ll


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean",
             norm_by_times=False):
    """CTC via the standard forward algorithm under lax.scan (reference:
    nn/functional/loss.py ctc_loss over warpctc)."""
    loss = _ctc_loss_op(log_probs, labels, input_lengths, label_lengths, blank=int(blank))
    if reduction == "mean":
        from ...ops.reduction import mean as mean_op
        from ...ops.math import divide

        return mean_op(divide(loss, label_lengths.astype(loss.dtype)))
    if reduction == "sum":
        from ...ops.reduction import sum as sum_op

        return sum_op(loss)
    return loss


@defop("dice_loss_op")
def _dice_loss_op(input, label, epsilon=1e-5):  # noqa: A002
    lbl = jax.nn.one_hot(label.squeeze(-1), input.shape[-1], dtype=input.dtype)
    red = tuple(range(1, input.ndim))
    inter = jnp.sum(input * lbl, axis=red)
    union = jnp.sum(input, axis=red) + jnp.sum(lbl, axis=red)
    return jnp.mean(1.0 - (2 * inter + epsilon) / (union + epsilon))


def dice_loss(input, label, epsilon=1e-5, name=None):  # noqa: A002
    return _dice_loss_op(input, label, epsilon=float(epsilon))
