"""Parameter-server stack: tables, RPC service, fleet PS flow.

Mirrors the reference's PS test strategy (SURVEY §4 harness A:
test_dist_base.py TestDistBase spawns pserver+trainer processes and compares
losses). Here: servers run as in-process daemon threads (the service is pure
numpy+sockets — no device state), trainers as threads for sync-SGD exactness
and as a real subprocess pair for the fleet env-contract flow.
"""
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _start_servers(n):
    from paddle_tpu.distributed.ps import PSServer

    servers = [PSServer("127.0.0.1:0").start() for _ in range(n)]
    return servers, [s.endpoint for s in servers]


class TestTables:
    def test_dense_sync_averages_and_versions(self):
        from paddle_tpu.distributed.ps.tables import DenseTable, _ServerOptimizer

        t = DenseTable("w", np.zeros(3), _ServerOptimizer("sgd", lr=1.0),
                       trainers=2, sync=True)
        t.push_grad(np.array([2.0, 0.0, 0.0]))
        assert t.version == 0  # waiting for trainer 2
        t.push_grad(np.array([0.0, 2.0, 0.0]))
        assert t.version == 1
        val, ver = t.pull(min_version=1)
        np.testing.assert_allclose(val, [-1.0, -1.0, 0.0])  # avg grad applied

    def test_sparse_sync_merges_once_order_independent(self):
        from paddle_tpu.distributed.ps.tables import SparseTable, _ServerOptimizer

        t = SparseTable("emb", 2, _ServerOptimizer("sgd", lr=1.0),
                        init_scale=0.0, trainers=2, sync=True)
        t.push_grad([1], np.full((1, 2), 2.0))
        np.testing.assert_allclose(t.pull([1]), 0.0)  # held until trainer 2
        t.push_grad(np.zeros(0, np.int64), np.zeros((0, 2)))  # empty push counts
        np.testing.assert_allclose(t.pull([1]), -1.0)  # avg over 2 trainers

    def test_push_lr_overrides_table_default(self):
        from paddle_tpu.distributed.ps.tables import DenseTable, _ServerOptimizer

        t = DenseTable("w", np.zeros(1), _ServerOptimizer("sgd", lr=0.01),
                       trainers=1, sync=True)
        t.push_grad(np.ones(1), lr=1.0)  # scheduler-provided lr wins
        np.testing.assert_allclose(t.pull(1)[0], [-1.0])

    def test_sparse_dedupe_and_lazy_init(self):
        from paddle_tpu.distributed.ps.tables import SparseTable, _ServerOptimizer

        t = SparseTable("emb", 4, _ServerOptimizer("sgd", lr=1.0), init_scale=0.0)
        rows = t.pull([5, 5, 9])
        assert rows.shape == (3, 4)
        np.testing.assert_allclose(rows, 0.0)
        g = np.ones((3, 4), np.float32)
        t.push_grad([5, 5, 9], g)  # id 5 appears twice -> accumulated
        rows2 = t.pull([5, 9])
        np.testing.assert_allclose(rows2[0], -2.0)
        np.testing.assert_allclose(rows2[1], -1.0)
        assert t.n_rows() == 2


class TestSSDSparseTable:
    """Two-tier (memory + sqlite) sparse table. Reference analog:
    paddle/fluid/distributed/ps/table/ssd_sparse_table.h:63."""

    def _table(self, tmp_path, cache_rows=4, kind="sgd", lr=1.0):
        from paddle_tpu.distributed.ps.tables import (
            SSDSparseTable, _ServerOptimizer)

        return SSDSparseTable(
            "emb", 2, _ServerOptimizer(kind, lr=lr), init_scale=0.0,
            cache_rows=cache_rows, db_path=str(tmp_path / "t.db"))

    def test_eviction_spills_and_faults_back(self, tmp_path):
        t = self._table(tmp_path, cache_rows=4)
        t.push_grad(np.arange(8), np.ones((8, 2), np.float32))
        assert t.n_rows() == 8
        assert t.n_hot() <= 4  # LRU spilled the overflow to disk
        # faulting a cold row back returns the trained value, not a re-init
        np.testing.assert_allclose(t.pull([0]), -1.0)

    def test_optimizer_state_survives_eviction(self, tmp_path):
        t = self._table(tmp_path, cache_rows=2, kind="adagrad", lr=1.0)
        ids = np.arange(6)
        t.push_grad(ids, np.ones((6, 2), np.float32))
        t.push_grad(ids, np.ones((6, 2), np.float32))
        # adagrad: step1 = -1/sqrt(1), step2 = -1/sqrt(2); identical for every
        # row only if each row's g2 state followed it across the disk tier
        expect = -(1.0 + 1.0 / np.sqrt(2.0 + 1e-8))
        got = t.pull(ids)
        np.testing.assert_allclose(got, expect, rtol=1e-5)

    def test_dump_covers_both_tiers(self, tmp_path):
        t = self._table(tmp_path, cache_rows=3)
        t.push_grad(np.arange(10), np.ones((10, 2), np.float32))
        ids, vals = t.dump()
        assert sorted(ids.tolist()) == list(range(10))
        np.testing.assert_allclose(vals, -1.0)

    def test_shrink_drops_cold_rows(self, tmp_path):
        t = self._table(tmp_path, cache_rows=100)
        t.pull(np.arange(8))
        t.shrink()  # resets access counts; all rows had 1 access -> survive
        assert t.n_rows() == 8
        t.pull([0, 1])  # touch only two rows
        dropped = t.shrink(min_access=1)
        assert dropped == 6
        assert t.n_rows() == 2

    def test_warm_restore_respects_cache_cap(self, tmp_path):
        """load()ed rows enter the LRU: the hot set stays bounded and the
        restored values remain evictable (code-review r4 finding)."""
        t = self._table(tmp_path, cache_rows=2)
        t.load(np.arange(10), np.full((10, 2), 7.0, np.float32))
        assert t.n_hot() <= 2
        assert t.n_rows() == 10
        np.testing.assert_allclose(t.pull([3]), 7.0)  # faults back from disk

    def test_persistent_db_restart_no_stale_shadow(self, tmp_path):
        """Restart on the same db_path + warm restore must not double-count
        or let stale spilled rows shadow the restored values."""
        t = self._table(tmp_path, cache_rows=2)
        t.push_grad(np.arange(6), np.ones((6, 2), np.float32))
        ids, vals = t.dump()
        t.close()
        t2 = self._table(tmp_path, cache_rows=2)
        t2.load(ids, vals)
        assert t2.n_rows() == 6
        ids2, vals2 = t2.dump()
        assert sorted(ids2.tolist()) == list(range(6))
        np.testing.assert_allclose(vals2, -1.0)

    def test_shrink_keeps_accessed_rows_on_disk_tier(self, tmp_path):
        t = self._table(tmp_path, cache_rows=2)
        t.pull(np.arange(6))  # all 6 accessed; 4 evicted to disk
        assert t.n_hot() == 2
        t.shrink(min_access=1)  # accessed-then-evicted rows must survive
        assert t.n_rows() == 6


class TestHeterPSCache:
    """Device-resident hot-row cache over the PS tables. Reference analog:
    paddle/fluid/framework/fleet/heter_ps/ (PSGPU hashtable cache)."""

    def _setup(self, capacity=8):
        from paddle_tpu.distributed.ps import HeterPSCache, PSClient

        servers, eps = _start_servers(1)
        c = PSClient(eps, trainer_id=0, trainers=1)
        c.register_sparse("emb", 3, opt_cfg={"kind": "sgd", "lr": 1.0},
                          init_scale=0.0)
        return servers, c, HeterPSCache(c, "emb", 3, capacity=capacity)

    def test_hits_stay_on_device(self):
        servers, c, cache = self._setup()
        try:
            r1 = cache.pull([1, 2, 3])
            assert cache.stats["misses"] == 3
            r2 = cache.pull([1, 2, 3, 2])
            assert cache.stats["misses"] == 3  # all hits, no new RPC
            assert r2.shape == (4, 3)
            np.testing.assert_allclose(np.asarray(r1), 0.0)
        finally:
            c.close()
            for s in servers:
                s.shutdown()

    def test_grads_accumulate_and_flush_applies_server_side(self):
        servers, c, cache = self._setup()
        try:
            ids = [5, 6]
            cache.pull(ids)
            cache.push_grad(ids, np.ones((2, 3)))
            cache.push_grad(ids, np.ones((2, 3)))  # accumulates on device
            # not yet on the server
            np.testing.assert_allclose(c.pull_sparse("emb", ids), 0.0)
            cache.flush()
            # server stepped once with the summed grad (sgd lr=1 -> -2)
            np.testing.assert_allclose(c.pull_sparse("emb", ids), -2.0)
            # the cache now serves the stepped values device-side
            np.testing.assert_allclose(np.asarray(cache.pull(ids)), -2.0)
        finally:
            c.close()
            for s in servers:
                s.shutdown()

    def test_eviction_under_capacity_pressure(self):
        servers, c, cache = self._setup(capacity=4)
        try:
            cache.pull([0, 1, 2, 3])
            cache.pull([10, 11])  # evicts two LRU clean slots
            assert cache.n_resident() == 4
            assert cache.stats["evictions"] == 2
            # evicted rows re-fetch correctly
            np.testing.assert_allclose(np.asarray(cache.pull([0])), 0.0)
        finally:
            c.close()
            for s in servers:
                s.shutdown()

    def test_forced_flush_uses_trainer_lr(self):
        """An eviction-forced flush must apply the lr the grads were pushed
        under, not the table's registered default (review r4 finding)."""
        servers, c, cache = self._setup(capacity=2)
        try:
            cache.pull([1, 2])
            cache.push_grad([1, 2], np.ones((2, 3)), lr=0.5)
            cache.pull([3])  # forces flush: must ride lr=0.5, not table 1.0
            np.testing.assert_allclose(c.pull_sparse("emb", [1, 2]), -0.5)
        finally:
            c.close()
            for s in servers:
                s.shutdown()

    def test_all_dirty_forces_flush_before_evict(self):
        servers, c, cache = self._setup(capacity=2)
        try:
            cache.pull([1, 2])
            cache.push_grad([1, 2], np.ones((2, 3)))
            cache.pull([3])  # both slots dirty -> flush, then evict
            assert cache.stats["flushes"] == 1
            np.testing.assert_allclose(c.pull_sparse("emb", [1, 2]), -1.0)
        finally:
            c.close()
            for s in servers:
                s.shutdown()


class TestService:
    def test_dense_roundtrip_and_partition(self):
        from paddle_tpu.distributed.ps import PSClient

        servers, eps = _start_servers(2)
        try:
            c = PSClient(eps, trainer_id=0, trainers=1)
            for name in ["a", "b", "c", "d"]:
                c.register_dense(name, np.full(2, 7.0),
                                 opt_cfg={"kind": "sgd", "lr": 0.5}, sync=False)
            c.push_dense("a", np.ones(2))
            val, ver = c.pull_dense("a", 1)
            np.testing.assert_allclose(val, 6.5)
            stats = c.stat()
            n_dense = sum(len(s["dense"]) for s in stats)
            assert n_dense == 4  # all tables live somewhere, each exactly once
            c.close()
        finally:
            for s in servers:
                s.shutdown()

    def test_sparse_sharding_across_servers(self):
        from paddle_tpu.distributed.ps import PSClient

        servers, eps = _start_servers(2)
        try:
            c = PSClient(eps, trainer_id=0, trainers=1)
            c.register_sparse("emb", 3, opt_cfg={"kind": "sgd", "lr": 1.0},
                              init_scale=0.0)
            ids = np.array([0, 1, 2, 3, 7])
            rows = c.pull_sparse("emb", ids)
            assert rows.shape == (5, 3)
            c.push_sparse("emb", ids, np.ones((5, 3)))
            rows2 = c.pull_sparse("emb", ids)
            np.testing.assert_allclose(rows2, -1.0)
            stats = c.stat()
            per_server = [s["sparse"]["emb"] for s in stats]
            assert sorted(per_server) == [2, 3]  # even/odd id split
            c.close()
        finally:
            for s in servers:
                s.shutdown()

    def test_ssd_table_through_service(self, tmp_path):
        """table_cfg={"type": "ssd"} selects the disk-tier table server-side."""
        from paddle_tpu.distributed.ps import PSClient

        servers, eps = _start_servers(1)
        try:
            c = PSClient(eps, trainer_id=0, trainers=1)
            c.register_sparse(
                "emb", 3, opt_cfg={"kind": "sgd", "lr": 1.0}, init_scale=0.0,
                table_cfg={"type": "ssd", "cache_rows": 2,
                           "db_path": str(tmp_path / "emb.db")})
            ids = np.arange(8)
            c.push_sparse("emb", ids, np.ones((8, 3)))
            # hot tier holds 2 rows; the rest round-trip through sqlite
            np.testing.assert_allclose(c.pull_sparse("emb", ids), -1.0)
            assert servers[0]._sparse["emb"].n_hot() <= 2
            assert servers[0]._sparse["emb"].n_rows() == 8
            c.close()
        finally:
            for s in servers:
                s.shutdown()

    def test_save_load_roundtrip(self, tmp_path):
        from paddle_tpu.distributed.ps import PSClient

        servers, eps = _start_servers(1)
        try:
            c = PSClient(eps)
            c.register_dense("w", np.arange(4, dtype=np.float32), sync=False)
            c.register_sparse("emb", 2, opt_cfg={"kind": "sgd", "lr": 1.0},
                              init_scale=0.0)
            c.push_sparse("emb", [3], -np.ones((1, 2)))
            c.save(str(tmp_path))
            c.push_dense("w", np.full(4, 100.0))
            c.load(str(tmp_path))
            val, _ = c.pull_dense("w")
            np.testing.assert_allclose(val, np.arange(4))
            np.testing.assert_allclose(c.pull_sparse("emb", [3]), 1.0)
            c.close()
        finally:
            for s in servers:
                s.shutdown()

    def test_wire_refuses_arbitrary_pickles(self):
        """The PS wire must not be a remote-code-execution vector."""
        import pickle
        import socket
        import struct

        from paddle_tpu.distributed.ps import service

        servers, eps = _start_servers(1)
        try:
            class Evil:
                def __reduce__(self):
                    return (os.system, ("true",))

            host, port = eps[0].rsplit(":", 1)
            sock = socket.create_connection((host, int(port)), timeout=10)
            body = pickle.dumps((service._CMD_PUSH_DENSE, ("w", Evil(), None)))
            sock.sendall(struct.pack("<I", len(body)) + body)
            (n,) = struct.unpack("<I", service._recv_exact(sock, 4))
            status, reply = pickle.loads(service._recv_exact(sock, n))
            # the connection survives but the payload must be refused…
            assert status == 1 or "refuses" in str(reply)
            sock.close()
        finally:
            for s in servers:
                s.shutdown()

    def test_warm_start_from_saved_shards(self, tmp_path):
        from paddle_tpu.distributed.ps import PSClient, PSServer

        servers, eps = _start_servers(1)
        try:
            c = PSClient(eps)
            c.register_dense("w", np.zeros(3), sync=False)
            c.push_dense("w", -np.ones(3), lr=1.0)
            c.register_sparse("emb", 2, opt_cfg={"kind": "sgd", "lr": 1.0},
                              init_scale=0.0)
            c.push_sparse("emb", [4], -np.ones((1, 2)))
            c.save(str(tmp_path))
            c.close()
        finally:
            for s in servers:
                s.shutdown()
        # a fresh server on the SAME endpoint warm-starts from the shard file
        host_port = eps[0]
        warm = PSServer(host_port, warm_dir=str(tmp_path)).start()
        try:
            c2 = PSClient([warm.endpoint])
            c2.register_dense("w", np.full(3, 99.0), sync=False)  # init ignored
            val, _ = c2.pull_dense("w")
            np.testing.assert_allclose(val, 1.0)
            c2.register_sparse("emb", 2, init_scale=0.0)
            np.testing.assert_allclose(c2.pull_sparse("emb", [4]), 1.0)
            c2.close()
        finally:
            warm.shutdown()

    def test_two_trainer_sync_sgd_exact(self):
        """Two trainer threads = exact synchronous SGD on least squares."""
        from paddle_tpu.distributed.ps import PSClient

        servers, eps = _start_servers(2)
        rng = np.random.default_rng(0)
        X = rng.normal(size=(32, 4)).astype(np.float32)
        w_true = np.array([1.0, -2.0, 3.0, 0.5], np.float32)
        y = X @ w_true
        halves = [(X[:16], y[:16]), (X[16:], y[16:])]
        results = {}

        def trainer(tid):
            c = PSClient(eps, trainer_id=tid, trainers=2)
            c.register_dense("w", np.zeros(4), opt_cfg={"kind": "sgd", "lr": 0.1},
                             sync=True)
            w, ver = c.pull_dense("w", 0)
            Xi, yi = halves[tid]
            for _ in range(200):
                grad = 2 * Xi.T @ (Xi @ w - yi) / len(yi)
                c.push_dense("w", grad)
                w, ver = c.pull_dense("w", ver + 1)
            results[tid] = w
            c.close()

        ts = [threading.Thread(target=trainer, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        try:
            np.testing.assert_allclose(results[0], results[1])  # bit-identical
            np.testing.assert_allclose(results[0], w_true, atol=1e-3)
        finally:
            for s in servers:
                s.shutdown()


class TestFleetPS:
    def test_ps_optimizer_and_embedding_end_to_end(self):
        """fleet facade in PS mode: dense params + DistributedEmbedding learn."""
        import paddle_tpu as paddle
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.fleet.role_maker import (Role,
                                                             UserDefinedRoleMaker)
        from paddle_tpu.distributed.ps import DistributedEmbedding

        servers, eps = _start_servers(1)
        try:
            rm = UserDefinedRoleMaker(is_collective=False, current_id=0,
                                      role=Role.WORKER, worker_num=1,
                                      worker_endpoints=["127.0.0.1:1"],
                                      server_endpoints=eps)
            strategy = fleet.DistributedStrategy()
            strategy.a_sync = False
            fleet.init(role_maker=rm, strategy=strategy)
            assert fleet.is_worker() and not fleet.is_server()

            class RecModel(paddle.nn.Layer):
                def __init__(self):
                    super().__init__()
                    self.emb = DistributedEmbedding(100, 8, name="emb_t",
                                                    init_scale=0.0)
                    self.fc = paddle.nn.Linear(8, 1)

                def forward(self, ids):
                    return self.fc(self.emb(ids).mean(axis=1))

            model = fleet.distributed_model(RecModel())
            opt = fleet.distributed_optimizer(
                paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=model.parameters()))
            ids = paddle.to_tensor(np.array([[1, 2, 3], [4, 5, 6]], np.int64))
            target = paddle.to_tensor(np.array([[1.0], [-1.0]], np.float32))
            losses = []
            for _ in range(30):
                out = model(ids)
                loss = ((out - target) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(loss.numpy()))
            assert losses[-1] < 0.1 * losses[0]
            stat = fleet.init_worker().stat()[0]
            assert stat["sparse"]["emb_t"] == 6  # only seen ids materialized
            fleet.stop_worker()
        finally:
            for s in servers:
                s.shutdown()

    def test_geo_mode_converges(self):
        import paddle_tpu as paddle
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.fleet.role_maker import (Role,
                                                             UserDefinedRoleMaker)

        servers, eps = _start_servers(1)
        try:
            rm = UserDefinedRoleMaker(is_collective=False, current_id=0,
                                      role=Role.WORKER, worker_num=1,
                                      worker_endpoints=["127.0.0.1:1"],
                                      server_endpoints=eps)
            strategy = fleet.DistributedStrategy()
            strategy.a_sync = True
            strategy.a_sync_configs = {"k_steps": 4}
            fleet.init(role_maker=rm, strategy=strategy)
            lin = paddle.nn.Linear(3, 1)
            fleet.distributed_model(lin)
            opt = fleet.distributed_optimizer(
                paddle.optimizer.SGD(learning_rate=0.05,
                                     parameters=lin.parameters()))
            X = paddle.to_tensor(np.random.default_rng(1)
                                 .normal(size=(16, 3)).astype(np.float32))
            y = (X * paddle.to_tensor(np.array([2.0, -1.0, 0.5], np.float32))) \
                .sum(axis=1, keepdim=True)
            losses = []
            for _ in range(40):
                loss = ((lin(X) - y) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(loss.numpy()))
            assert losses[-1] < 0.05 * losses[0]
            fleet.stop_worker()
        finally:
            for s in servers:
                s.shutdown()


class TestPSSubprocess:
    def test_server_and_trainer_processes(self, tmp_path):
        """The reference env contract: TRAINING_ROLE=PSERVER/TRAINER processes."""
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        server_ep = f"127.0.0.1:{port}"

        server_code = (
            "from paddle_tpu.distributed import fleet\n"
            "fleet.init(is_collective=False)\n"
            "assert fleet.is_server()\n"
            "fleet.init_server()\n"
            "fleet.run_server()\n"
        )
        trainer_code = (
            "import os\n"
            "import numpy as np\n"
            "import paddle_tpu as paddle\n"
            "from paddle_tpu.distributed import fleet\n"
            "fleet.init(is_collective=False)\n"
            "assert not fleet.is_server()\n"
            "tid = int(os.environ['PADDLE_TRAINER_ID'])\n"
            "lin = paddle.nn.Linear(2, 1)\n"
            "fleet.distributed_model(lin)\n"
            "opt = fleet.distributed_optimizer(paddle.optimizer.SGD(\n"
            "    learning_rate=0.1, parameters=lin.parameters()))\n"
            "X = paddle.to_tensor(np.eye(2, dtype=np.float32))\n"
            "y = paddle.to_tensor(np.array([[1.0], [2.0]], np.float32))\n"
            "first = last = None\n"
            "for _ in range(25):\n"
            "    loss = ((lin(X) - y) ** 2).mean()\n"
            "    loss.backward(); opt.step(); opt.clear_grad()\n"
            "    v = float(loss.numpy())\n"
            "    first = v if first is None else first; last = v\n"
            "assert last < 0.2 * first, (first, last)\n"
            "fleet.stop_worker()\n"
            "print(f'TRAINER_OK w={np.asarray(lin.weight.numpy()).ravel().tolist()}')\n"
        )
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.update({
            "PADDLE_PSERVERS_IP_PORT_LIST": server_ep,
            "PADDLE_TRAINERS_NUM": "2",
            "PADDLE_TPU_PLATFORM": "cpu",
            "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        })
        senv = dict(env, TRAINING_ROLE="PSERVER", POD_IP="127.0.0.1",
                    PADDLE_PORT=str(port))
        sp = subprocess.Popen([sys.executable, "-c", server_code], env=senv,
                              cwd=REPO, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT)
        tps = []
        try:
            for tid in range(2):
                tenv = dict(env, TRAINING_ROLE="TRAINER",
                            PADDLE_TRAINER_ID=str(tid))
                tps.append(subprocess.Popen(
                    [sys.executable, "-c", trainer_code], env=tenv, cwd=REPO,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    text=True))
            outs = []
            for tp in tps:
                out, _ = tp.communicate(timeout=300)
                assert tp.returncode == 0, out
                assert "TRAINER_OK" in out
                outs.append(out.strip().splitlines()[-1])
            assert outs[0] == outs[1]  # sync SGD: identical final weights
            sp.wait(timeout=30)  # stop_worker shuts the server down
        finally:
            for p in tps + [sp]:
                if p.poll() is None:
                    p.kill()
