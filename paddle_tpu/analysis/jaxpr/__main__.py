"""``python -m paddle_tpu.analysis.jaxpr`` — the graftir CLI.

(``tools/ir_report.py`` is the same surface without importing jax at
module load: it parses arguments first, then defers here.)
"""
import sys

from . import main

if __name__ == "__main__":
    sys.exit(main())
