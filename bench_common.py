"""Shared machinery for bench.py (flagship) and bench_suite.py (BASELINE
configs): the tunnel-safe execution fence, the donated fused train step, and
the chunk-forced timing loop. The PERF.md round-4 tunnel rules live HERE and
only here: block_until_ready is not an execution fence over the tunneled
backend (fetch one element instead), and long unforced donated chains are
pathologically slow (force every couple of steps)."""
from __future__ import annotations

import time


def force(x):
    """Execution barrier that works on tunneled PJRT backends where
    block_until_ready returns before execution: fetching a value is the only
    reliable fence. Fetches ONE element (downloads over the tunnel run at
    ~MB/s, so device_get of a whole activation would dominate the timing)."""
    import jax
    import jax.numpy as jnp

    leaf = jax.tree_util.tree_leaves(x)[0]
    jax.device_get(jnp.ravel(leaf)[:1])
    jax.block_until_ready(leaf)  # real barrier on non-tunneled backends


def build_step(model, optimizer, loss_fn):
    """One donated fused train step (fwd+bwd+optimizer) with functional state
    threading over the live Layer/Optimizer objects.

    Returns (jitted_step, state_fn, params):
      jitted_step(param_values, acc_values, master_values, *batch)
        -> (loss_value, new_params, new_accs, new_masters)
      state_fn() -> the current (params, accs, masters) value lists
      params    -> the live Parameter objects (rebind after the run with
                   p._replace_value since the step donates their buffers)

    ``loss_fn(model, *batch_tensors)`` returns the scalar loss Tensor.
    """
    import jax
    import jax.numpy as jnp

    from paddle_tpu.framework import random as rng
    from paddle_tpu.framework.core import Tensor

    params = [p for _, p in model.named_parameters()]
    for p in params:
        if id(p) not in optimizer._accumulators:
            optimizer._accumulators[id(p)] = optimizer._init_state(p)
        if (optimizer._use_master_weights
                and id(p) not in optimizer._master_weights):
            optimizer._master_weights[id(p)] = p.value.astype(jnp.float32)
    acc_keys = [sorted(optimizer._accumulators[id(p)].keys()) for p in params]
    use_masters = optimizer._use_master_weights

    def train_step(param_values, acc_values, master_values, *batch):
        with rng.trace_key(jax.random.PRNGKey(0)):
            saved_p = [(p, p._value) for p in params]
            saved_a = {id(p): dict(optimizer._accumulators[id(p)])
                       for p in params}
            saved_m = dict(optimizer._master_weights)
            try:
                for p, v in zip(params, param_values):
                    p._replace_value(v)
                for p, ks, vs in zip(params, acc_keys, acc_values):
                    for k, v in zip(ks, vs):
                        optimizer._accumulators[id(p)][k] = v
                if use_masters:
                    for p, mv in zip(params, master_values):
                        optimizer._master_weights[id(p)] = mv
                loss = loss_fn(model, *[Tensor(b) for b in batch])
                loss.backward()
                optimizer.step()
                optimizer.clear_grad()
                new_p = [p._value for p in params]
                new_a = [[optimizer._accumulators[id(p)][k] for k in ks]
                         for p, ks in zip(params, acc_keys)]
                new_m = ([optimizer._master_weights[id(p)] for p in params]
                         if use_masters else master_values)
                return loss.value, new_p, new_a, new_m
            finally:
                for p, v in saved_p:
                    p._replace_value(v)
                for p in params:
                    optimizer._accumulators[id(p)] = saved_a[id(p)]
                optimizer._master_weights = saved_m

    jitted = jax.jit(train_step, donate_argnums=(0, 1, 2))

    def state_fn():
        pv = [p.value for p in params]
        av = [[optimizer._accumulators[id(p)][k] for k in ks]
              for p, ks in zip(params, acc_keys)]
        mv = ([optimizer._master_weights[id(p)] for p in params]
              if use_masters else [])
        return pv, av, mv

    return jitted, state_fn, params


def timed_loop(step, state0, batch, iters, force_every=2, log=None):
    """Warm (compile + 1 step), then time ``iters`` steps forcing every
    ``force_every`` steps (shallow queue — tunnel rule). Returns
    (seconds_per_step, final_state, final_loss_device_value)."""
    pv, av, mv = state0
    if log is not None:
        log("compiling + executing first step...")
    t_w = time.perf_counter()
    loss, pv, av, mv = step(pv, av, mv, *batch)
    force(loss)
    if log is not None:
        log(f"warm (compile + step 1) done in {time.perf_counter() - t_w:.1f}s")
    t0 = time.perf_counter()
    done = 0
    while done < iters:
        n = min(force_every, iters - done)
        for _ in range(n):
            loss, pv, av, mv = step(pv, av, mv, *batch)
        force(loss)
        done += n
        if log is not None:
            log(f"step {done}/{iters} forced "
                f"({(time.perf_counter() - t0) / done * 1e3:.1f} ms/step avg)")
    dt = (time.perf_counter() - t0) / iters
    return dt, (pv, av, mv), loss
