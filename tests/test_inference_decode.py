"""Inference serving path (round-2 verdict #10): KV-cache decode engine parity
+ Predictor AOT warmup cache. Reference: fluid/inference/api/
analysis_predictor.cc's role, TPU-natively (one compiled decode executable).
"""
import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.llama_decode import LlamaDecodeEngine


def _model(layers=2, heads=4, kv=2, hidden=32, maxlen=32):
    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=64, hidden_size=hidden,
                      intermediate_size=hidden * 2, num_hidden_layers=layers,
                      num_attention_heads=heads, num_key_value_heads=kv,
                      max_position_embeddings=maxlen)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m, cfg


class TestDecodeEngine:
    def test_greedy_matches_full_recompute_generate(self):
        model, _ = _model()
        r = np.random.RandomState(0)
        ids = paddle.to_tensor(r.randint(0, 64, (2, 5)).astype("int64"))
        slow = model.generate(ids, max_new_tokens=8).numpy()[:, 5:]
        eng = LlamaDecodeEngine(model, max_len=32)
        fast = np.asarray(eng.generate(ids, max_new_tokens=8))
        np.testing.assert_array_equal(slow, fast)

    def test_gqa_and_mha_variants(self):
        for kv in (1, 2, 4):
            model, _ = _model(kv=kv)
            r = np.random.RandomState(kv)
            ids = paddle.to_tensor(r.randint(0, 64, (1, 4)).astype("int64"))
            slow = model.generate(ids, max_new_tokens=5).numpy()[:, 4:]
            fast = np.asarray(LlamaDecodeEngine(model, max_len=16)
                              .generate(ids, max_new_tokens=5))
            np.testing.assert_array_equal(slow, fast)

    def test_prefill_logits_match_forward(self):
        model, _ = _model()
        r = np.random.RandomState(1)
        ids_np = r.randint(0, 64, (3, 7)).astype("int64")
        full = model(paddle.to_tensor(ids_np)).numpy()[:, -1]
        eng = LlamaDecodeEngine(model, max_len=16)
        logits, cache, pos = eng.prefill(ids_np)
        assert pos == 7
        np.testing.assert_allclose(np.asarray(logits), full,
                                   rtol=1e-4, atol=1e-4)

    def test_step_is_one_compiled_program(self):
        model, _ = _model()
        eng = LlamaDecodeEngine(model, max_len=16)
        ids = np.random.RandomState(0).randint(0, 64, (1, 3)).astype("int32")
        logits, cache, pos = eng.prefill(ids)
        tok = np.asarray(logits.argmax(-1)).astype("int32")[:, None]
        logits, cache = eng.decode_step(tok, cache, pos)
        # the SAME jitted callable serves every later step (AOT executable);
        # the cache is donated each step, so it chains forward
        before = eng._step_jit._cache_size()
        logits, cache = eng.decode_step(tok, cache, pos + 1)
        logits, cache = eng.decode_step(tok, cache, pos + 2)
        assert eng._step_jit._cache_size() == before == 1


class TestPredictorWarmup:
    def test_warmup_shapes_precompiled(self, tmp_path):
        import paddle_tpu.nn as nn
        from paddle_tpu import inference, jit
        from paddle_tpu.jit.api import InputSpec

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        prefix = str(tmp_path / "model")
        jit.save(net, prefix, input_spec=[InputSpec([None, 8], "float32")])

        cfg = inference.Config(prefix)
        cfg.exp_set_warmup_shapes([(1, 8), (4, 8)])
        pred = inference.create_predictor(cfg)
        assert pred._warmed_shapes == [(1, 8), (4, 8)]
        out = pred.run([np.ones((4, 8), "float32")])
        assert out[0].shape == (4, 4)


class TestSamplingDecode:
    def test_temperature_topk_topp_sampling(self):
        model, cfg = _model()
        eng = LlamaDecodeEngine(model, max_len=32)
        ids = np.random.RandomState(0).randint(0, 64, (2, 4)).astype("int64")
        a = np.asarray(eng.generate(ids, max_new_tokens=6, temperature=0.8,
                                    top_k=10, top_p=0.9, seed=1))
        b = np.asarray(eng.generate(ids, max_new_tokens=6, temperature=0.8,
                                    top_k=10, top_p=0.9, seed=1))
        c = np.asarray(eng.generate(ids, max_new_tokens=6, temperature=0.8,
                                    top_k=10, top_p=0.9, seed=2))
        assert a.shape == (2, 6)
        np.testing.assert_array_equal(a, b)       # same seed, same draw
        assert (a != c).any()                     # different seed differs
        assert (a >= 0).all() and (a < 64).all()

    def test_top_k_one_equals_greedy(self):
        model, _ = _model()
        eng = LlamaDecodeEngine(model, max_len=32)
        ids = np.random.RandomState(1).randint(0, 64, (1, 4)).astype("int64")
        greedy = np.asarray(eng.generate(ids, max_new_tokens=5))
        topk1 = np.asarray(eng.generate(ids, max_new_tokens=5,
                                        temperature=1.0, top_k=1))
        np.testing.assert_array_equal(greedy, topk1)


class TestBatchP2PAndStream:
    def test_batch_isend_irecv_roundtrip(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.collective import p2p_rank

        t = paddle.to_tensor(np.arange(4, dtype="float32"))
        out = paddle.zeros([4])
        with p2p_rank(0):
            tasks = dist.batch_isend_irecv([dist.P2POp(dist.isend, t, 1)])
        with p2p_rank(1):
            tasks += dist.batch_isend_irecv([dist.P2POp(dist.irecv, out, 0)])
        for tk in tasks:
            tk.wait()
        np.testing.assert_allclose(out.numpy(), t.numpy())

    def test_p2pop_validates_op(self):
        import paddle_tpu.distributed as dist

        with pytest.raises(ValueError):
            dist.P2POp(print, paddle.zeros([1]), 0)

    def test_stream_namespace(self):
        import paddle_tpu.distributed as dist

        x = paddle.to_tensor(np.ones(8, "float32"))
        dist.stream.all_reduce(x, use_calc_stream=True)
        assert np.isfinite(x.numpy()).all()

    def test_scatter_object_list(self):
        import paddle_tpu.distributed as dist

        objs = [None]
        dist.scatter_object_list(objs, [{"k": 7}], src=0)
        assert objs == [{"k": 7}]


class TestBeamSearch:
    def test_beam_one_equals_greedy(self):
        model, _ = _model()
        r = np.random.RandomState(1)
        ids = paddle.to_tensor(r.randint(0, 64, (2, 5)).astype("int64"))
        eng = LlamaDecodeEngine(model, max_len=32)
        greedy = np.asarray(eng.generate(ids, max_new_tokens=8))
        beams, scores = eng.beam_search(ids, beam_size=1, max_new_tokens=8)
        np.testing.assert_array_equal(np.asarray(beams)[:, 0], greedy)
        assert np.isfinite(np.asarray(scores)).all()

    def test_beam_scores_are_exact_sequence_logprobs(self):
        """Every returned beam's score must equal the teacher-forced
        log-probability of its token sequence under the model — the
        property beam search actually guarantees. (This replaces the old
        "best-of-4 >= greedy" assertion, which beam search does NOT
        guarantee: the greedy prefix is pruned whenever K other partial
        hypotheses outscore it mid-search — the classic beam-search
        non-monotonicity, observed at this very seed where beam-2 scores
        below beam-1 and beam-8 above it. See docs/COVERAGE.md.)"""
        model, _ = _model()
        r = np.random.RandomState(2)
        ids_np = r.randint(0, 64, (2, 4)).astype("int64")
        eng = LlamaDecodeEngine(model, max_len=32)
        beams4, s4 = eng.beam_search(paddle.to_tensor(ids_np), beam_size=4,
                                     max_new_tokens=6)
        beams4, s4 = np.asarray(beams4), np.asarray(s4)
        assert beams4.shape == (2, 4, 6)
        # sorted best-first, and all K hypotheses per row are distinct
        assert (np.diff(s4, axis=1) <= 1e-6).all()
        for b in range(2):
            assert len({tuple(row) for row in beams4[b]}) == 4
            for k in range(4):
                seq = np.concatenate([ids_np[b], beams4[b, k]])
                logits = model(
                    paddle.to_tensor(seq[None].astype("int64"))
                ).numpy()[0].astype(np.float64)
                lse = np.log(np.exp(
                    logits - logits.max(-1, keepdims=True)).sum(-1)) \
                    + logits.max(-1)
                S = ids_np.shape[1]
                want = sum(logits[S - 1 + t, beams4[b, k, t]]
                           - lse[S - 1 + t] for t in range(6))
                np.testing.assert_allclose(s4[b, k], want, atol=2e-3)

    def test_eos_freezes_beams(self):
        model, _ = _model()
        r = np.random.RandomState(3)
        ids = paddle.to_tensor(r.randint(0, 64, (1, 4)).astype("int64"))
        eng = LlamaDecodeEngine(model, max_len=32)
        eos = 7
        beams, scores = eng.beam_search(ids, beam_size=3, max_new_tokens=8,
                                        eos_token_id=eos,
                                        length_penalty=0.6)
        b = np.asarray(beams)[0]
        for row in b:
            hit = np.where(row == eos)[0]
            if hit.size:  # after the first EOS, only EOS follows (frozen)
                assert (row[hit[0]:] == eos).all()
        assert np.isfinite(np.asarray(scores)).all()

    def test_zero_new_tokens_is_empty(self):
        model, _ = _model()
        ids = paddle.to_tensor(np.zeros((2, 3), "int64"))
        eng = LlamaDecodeEngine(model, max_len=32)
        beams, scores = eng.beam_search(ids, beam_size=2, max_new_tokens=0)
        assert np.asarray(beams).shape == (2, 2, 0)
        assert np.asarray(scores).shape == (2, 2)


class TestInt8KVCache:
    """kv_cache_dtype='int8': per-(token, head) absmax-quantized KV cache —
    half the KV HBM footprint/bandwidth (the decode bottleneck)."""

    def test_greedy_matches_fp_cache(self):
        model, _ = _model()
        r = np.random.RandomState(5)
        ids = paddle.to_tensor(r.randint(0, 64, (2, 6)).astype("int64"))
        fp = LlamaDecodeEngine(model, max_len=32)
        q8 = LlamaDecodeEngine(model, max_len=32, kv_cache_dtype="int8")
        out_fp = np.asarray(fp.generate(ids, max_new_tokens=10))
        out_q8 = np.asarray(q8.generate(ids, max_new_tokens=10))
        # int8 kv introduces <1% logit error; greedy paths stay aligned on
        # this scale of model
        assert (out_fp == out_q8).mean() >= 0.9

    def test_prefill_logits_close(self):
        import jax

        model, _ = _model()
        r = np.random.RandomState(6)
        ids = paddle.to_tensor(r.randint(0, 64, (2, 8)).astype("int64"))
        fp = LlamaDecodeEngine(model, max_len=32)
        q8 = LlamaDecodeEngine(model, max_len=32, kv_cache_dtype="int8")
        a = np.asarray(jax.device_get(fp.prefill(ids)[0]), np.float32)
        b = np.asarray(jax.device_get(q8.prefill(ids)[0]), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-9)
        assert rel < 0.05, rel

    def test_cache_is_int8_and_half_size(self):
        model, _ = _model()
        fp = LlamaDecodeEngine(model, max_len=32)
        q8 = LlamaDecodeEngine(model, max_len=32, kv_cache_dtype="int8")
        c_fp = fp.init_cache(batch=2)
        c_q8 = q8.init_cache(batch=2)
        k_q, k_s, v_q, v_s = c_q8[0]
        assert k_q.dtype == np.int8 and v_q.dtype == np.int8
        assert k_s.shape == k_q.shape[:-1]  # one scale per (token, head)
        bytes_fp = sum(a.nbytes for a in c_fp[0])
        bytes_q8 = sum(a.nbytes for a in c_q8[0])
        # fp32 on CPU (bf16 on TPU): int8 + fp32 scales must be well under
        # half of fp32 and ~ (D+4)/(2D) of a bf16 cache
        assert bytes_q8 < 0.55 * bytes_fp, (bytes_q8, bytes_fp)

    def test_quantization_known_values(self):
        import jax.numpy as jnp

        x = jnp.asarray(np.array(
            [[[[1.0, -2.0, 0.5, 4.0]]],      # absmax 4 -> scale 4/127
             [[[0.0, 0.0, 0.0, 0.0]]]],      # all-zero row -> floor scale
            np.float32))
        q, s = LlamaDecodeEngine._quantize_kv(x)
        np.testing.assert_allclose(np.asarray(s)[0, 0, 0], 4.0 / 127.0)
        np.testing.assert_array_equal(
            np.asarray(q)[0, 0, 0], np.round(
                np.array([1.0, -2.0, 0.5, 4.0]) / (4.0 / 127.0)))
        assert np.asarray(q)[0, 0, 0, 3] == 127  # absmax maps to full scale
        np.testing.assert_array_equal(np.asarray(q)[1, 0, 0], 0)
        # dequantization error bounded by scale/2 per element
        deq = np.asarray(q, np.float32)[0, 0, 0] * np.asarray(s)[0, 0, 0]
        assert np.abs(deq - np.array([1.0, -2.0, 0.5, 4.0])).max() \
            <= (4.0 / 127.0) / 2 + 1e-7

    def test_beam_search_on_int8_cache(self):
        model, _ = _model()
        r = np.random.RandomState(7)
        ids = paddle.to_tensor(r.randint(0, 64, (1, 5)).astype("int64"))
        q8 = LlamaDecodeEngine(model, max_len=32, kv_cache_dtype="int8")
        beams, scores = q8.beam_search(ids, beam_size=3, max_new_tokens=6)
        assert np.asarray(beams).shape == (1, 3, 6)
        assert np.isfinite(np.asarray(scores)).all()

    def test_unknown_dtype_rejected(self):
        model, _ = _model()
        with pytest.raises(ValueError, match="kv_cache_dtype"):
            LlamaDecodeEngine(model, kv_cache_dtype="fp4")


class TestGenerateEOS:
    def test_eos_freezes_rows_and_pads(self):
        model, _ = _model()
        r = np.random.RandomState(9)
        ids = paddle.to_tensor(r.randint(0, 64, (2, 5)).astype("int64"))
        eng = LlamaDecodeEngine(model, max_len=32)
        base = np.asarray(eng.generate(ids, max_new_tokens=8))
        # pick the token row 0 emits at step 2 as the "eos" and regenerate:
        # everything after that step in row 0 must be eos
        eos = int(base[0, 2])
        out = np.asarray(eng.generate(ids, max_new_tokens=8,
                                      eos_token_id=eos))
        assert out.shape == (2, 8)
        hit = np.where(out[0] == eos)[0]
        assert hit.size and (out[0, hit[0]:] == eos).all()
        # prefix before the first eos matches the unconstrained run
        np.testing.assert_array_equal(out[0, :hit[0]], base[0, :hit[0]])
