"""Multiprocess DataLoader workers with shared-memory batch transport.

Reference analog: python/paddle/io/dataloader/dataloader_iter.py:154,368
(_DataLoaderIterMultiProcess — per-worker index queues, shared-memory tensor
transport via core._convert_to_shared_memory, reorder by receive index, worker
liveness watch) and worker.py (_worker_loop, WorkerInfo).

TPU-first note: workers run PYTHON transform code — numpy/PIL augmentation that
is GIL-bound under the thread pool — in forked processes; they must never touch
jax (the forked XLA runtime is not fork-safe). The worker refuses device-Tensor
samples with a clear error instead of hanging inside XLA. Arrays travel through
multiprocessing.shared_memory segments: the worker writes bytes once, the queue
carries only a descriptor, and the parent copies out and unlinks.
"""
from __future__ import annotations

import multiprocessing
import os
import queue as queue_mod
import time
import traceback
from multiprocessing import shared_memory

import numpy as np


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed=None):  # noqa: A002
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed

    def __repr__(self):
        return (f"WorkerInfo(id={self.id}, num_workers={self.num_workers})")


_WORKER_INFO = [None]  # set inside forked worker processes


def get_worker_info():
    return _WORKER_INFO[0]


# -- shared-memory packing ---------------------------------------------------
_SHM_TAG = "__paddle_tpu_shm__"


def _pack(obj, segments):
    """Replace ndarrays in a collated batch tree with shm descriptors."""
    if isinstance(obj, np.ndarray) and obj.nbytes > 0:
        shm = shared_memory.SharedMemory(create=True, size=obj.nbytes)
        segments.append(shm)  # appended FIRST so a later failure can clean up
        view = np.ndarray(obj.shape, obj.dtype, buffer=shm.buf)
        view[...] = obj
        return (_SHM_TAG, shm.name, obj.shape, str(obj.dtype))
    if isinstance(obj, dict):
        return {k: _pack(v, segments) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        packed = [_pack(v, segments) for v in obj]
        return packed if isinstance(obj, list) else tuple(packed)
    return obj


def _unpack(obj):
    """Reconstruct ndarrays from shm descriptors (copy out, close + unlink)."""
    if isinstance(obj, tuple) and len(obj) == 4 and obj[0] == _SHM_TAG:
        _, name, shape, dtype = obj
        shm = shared_memory.SharedMemory(name=name)
        try:
            arr = np.array(
                np.ndarray(shape, np.dtype(dtype), buffer=shm.buf))
        finally:
            shm.close()
            try:
                shm.unlink()
            except FileNotFoundError:
                pass
        return arr
    if isinstance(obj, dict):
        return {k: _unpack(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unpack(v) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_unpack(v) for v in obj)
    return obj


def _contains_device_tensor(obj):
    """Type-name check only — must not import jax in the worker."""
    tname = type(obj).__name__
    if tname in ("Tensor", "Parameter", "ArrayImpl"):
        return True
    if isinstance(obj, dict):
        return any(_contains_device_tensor(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return any(_contains_device_tensor(v) for v in obj)
    return False


def _disown_and_close(segments, unlink=False):
    for shm in segments:
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:
            pass
        try:
            shm.close()
            if unlink:
                shm.unlink()
        except Exception:
            pass
    segments.clear()


def _worker_loop(dataset, collate_fn, index_queue, result_queue, worker_id,
                 num_workers, use_shared_memory, worker_init_fn, base_seed,
                 ring_name=None):
    """Body of one forked worker (reference worker.py _worker_loop)."""
    _WORKER_INFO[0] = WorkerInfo(worker_id, num_workers, dataset,
                                 seed=(base_seed + worker_id
                                       if base_seed is not None else None))
    parent_pid = os.getppid()  # the REAL parent; may legitimately be pid 1
    ring = None
    if ring_name is not None:
        try:
            from .native_shm import ShmRing

            ring = ShmRing(ring_name)
        except Exception:
            ring = None  # fall back to the per-array SharedMemory path
    if base_seed is not None:
        import random

        np.random.seed((base_seed + worker_id) % (2 ** 31))
        # python's random too: forked workers otherwise share the parent's
        # Mersenne state and draw identical augmentation streams
        random.seed(base_seed + worker_id)
    try:
        if worker_init_fn is not None:
            worker_init_fn(worker_id)
    except Exception:  # noqa: BLE001
        result_queue.put(("error", -1, traceback.format_exc()))
        return
    while True:
        job = index_queue.get()
        if job is None:
            return
        seq, indices = job
        segments = []
        try:
            samples = [dataset[i] for i in indices]
            if _contains_device_tensor(samples):
                raise TypeError(
                    "dataset returned device Tensors inside a forked worker; "
                    "forked children must not touch jax — use a numpy-returning "
                    "dataset or set DataLoader(use_shared_memory=False) for the "
                    "thread fallback")
            batch = collate_fn(samples)
            if _contains_device_tensor(batch):
                raise TypeError(
                    "collate_fn produced device Tensors inside a forked "
                    "worker; forked children must not touch jax — collate to "
                    "numpy (the parent stages to device) or set "
                    "DataLoader(use_shared_memory=False)")
            if ring is not None:
                # native transport: one memcpy into the shared ring instead of
                # per-array shm segments / pickled pipe chunks
                import pickle

                # out-of-band buffers: array bytes go to the ring RAW instead
                # of being copied into the pickle stream first
                oob = []
                header = pickle.dumps((seq, batch), protocol=5,
                                      buffer_callback=oob.append)
                frames = [header] + [b.raw() for b in oob]
                try:
                    total = sum(len(f) + 16 for f in frames)
                    if total + 16 > ring.capacity:
                        raise ValueError("batch exceeds ring")

                    def push_frame(f):
                        while not ring.push(f, timeout=1.0):
                            # parent gone (reparented away from the ORIGINAL
                            # parent) or shutdown sentinel: stop retrying so
                            # join can proceed
                            if os.getppid() != parent_pid:
                                return False
                            try:
                                job2 = index_queue.get_nowait()
                            except queue_mod.Empty:
                                continue
                            if job2 is None:
                                return False
                            index_queue.put(job2)  # keep for after this push
                        return True

                    if not all(push_frame(f) for f in frames):
                        return
                    result_queue.put(("ring", seq, (worker_id, len(oob))))
                    continue
                except ValueError:
                    pass  # batch larger than the ring: per-array shm fallback
            if use_shared_memory:
                payload = _pack(batch, segments)
                result_queue.put(("ok", seq, payload))
                # only after a successful put does the parent own cleanup:
                # deregister from this worker's resource tracker (so worker
                # exit doesn't warn about names the parent unlinks) and close.
                # If the worker is killed BEFORE the put, the tracker still
                # owns the segments and reclaims them at exit.
                _disown_and_close(segments)
            else:
                result_queue.put(("ok", seq, batch))
        except Exception:  # noqa: BLE001 — surfaced in the parent
            _disown_and_close(segments, unlink=True)  # reclaim partial packs
            result_queue.put(("error", seq, traceback.format_exc()))


class MultiprocessBatchLoader:
    """Order-preserving fan-out of index batches over forked workers.

    Reusable across epochs (reference persistent_workers): call ``epoch(it)``
    per pass; ``shutdown()`` when done.
    """

    _POLL_S = 2.0  # liveness check cadence while waiting on results

    def __init__(self, dataset, collate_fn, num_workers,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, base_seed=None,
                 ring_capacity=64 << 20):
        self._ctx = multiprocessing.get_context("fork")
        self._index_queues = [self._ctx.Queue() for _ in range(num_workers)]
        self._result_queue = self._ctx.Queue()
        self._timeout = timeout or None
        self._num_workers = num_workers
        self._max_outstanding = num_workers * max(prefetch_factor, 2)
        self._send_seq = 0
        self._recv_seq = 0
        # native shared-memory rings (one SPSC ring per worker) when the C++
        # transport compiled; workers fall back per-batch when a batch exceeds
        # the ring, and entirely when attach fails
        self._rings = {}
        ring_names = [None] * num_workers
        if use_shared_memory:
            try:
                from .native_shm import ShmRing, available

                if available():
                    uid = f"{os.getpid()}_{id(self) & 0xFFFFFF:x}"
                    for wid in range(num_workers):
                        name = f"/pt_dl_{uid}_{wid}"
                        self._rings[wid] = ShmRing(
                            name, capacity=ring_capacity, create=True)
                        ring_names[wid] = name
            except Exception:
                self._rings = {}
                ring_names = [None] * num_workers
        self._workers = [
            self._ctx.Process(
                target=_worker_loop,
                args=(dataset, collate_fn, self._index_queues[wid],
                      self._result_queue, wid, num_workers, use_shared_memory,
                      worker_init_fn, base_seed, ring_names[wid]),
                daemon=True)
            for wid in range(num_workers)
        ]
        for p in self._workers:
            p.start()
        self._closed = False

    def _check_alive(self):
        dead = [i for i, p in enumerate(self._workers) if not p.is_alive()]
        if dead:
            self.shutdown()
            raise RuntimeError(
                f"DataLoader worker(s) {dead} exited unexpectedly "
                "(killed or crashed before reporting)")

    def _get_result(self):
        """result_queue.get with liveness polling so a dead worker raises
        instead of blocking forever (reference watchdog semantics)."""
        deadline = (time.monotonic() + self._timeout
                    if self._timeout is not None else None)
        while True:
            try:
                return self._result_queue.get(timeout=self._POLL_S)
            except queue_mod.Empty:
                self._check_alive()
                if deadline is not None and time.monotonic() > deadline:
                    self.shutdown()
                    raise TimeoutError(
                        f"DataLoader worker timed out after {self._timeout}s "
                        "(stuck transform?)") from None

    def epoch(self, batch_indices_iter):
        """Yield collated batches for one pass over the given index batches."""
        if self._closed:
            raise RuntimeError("MultiprocessBatchLoader already shut down")
        if getattr(self, "_epoch_active", False):
            # two interleaved epochs would steal each other's results off the
            # shared result queue and hang on sequence numbers the other took
            raise RuntimeError(
                "a previous epoch over this worker pool is still active; "
                "finish it (or use a second DataLoader) before starting "
                "another pass")
        self._epoch_active = True
        it = iter(batch_indices_iter)
        outstanding = 0
        reorder = {}

        def feed():
            nonlocal it, outstanding
            while outstanding < self._max_outstanding and it is not None:
                try:
                    indices = next(it)
                except StopIteration:
                    it = None
                    return
                wid = self._send_seq % self._num_workers
                self._index_queues[wid].put((self._send_seq, list(indices)))
                self._send_seq += 1
                outstanding += 1

        try:
            feed()
            while outstanding > 0:
                while self._recv_seq in reorder:
                    batch = reorder.pop(self._recv_seq)
                    self._recv_seq += 1
                    outstanding -= 1
                    feed()
                    yield batch
                if outstanding == 0:
                    break
                status, seq, payload = self._get_result()
                if status == "error":
                    self.shutdown()
                    raise RuntimeError(
                        f"DataLoader worker failed:\n{payload}")
                if status == "ring":
                    import pickle

                    wid, n_oob = payload
                    ring = self._rings[wid]
                    frames = []
                    for _ in range(1 + n_oob):
                        blob = ring.pop(timeout=self._timeout or 300)
                        if blob is None:
                            self.shutdown()
                            raise TimeoutError(
                                "ring marker arrived but payload never did "
                                f"(worker {wid})")
                        frames.append(blob)
                    # out-of-band reconstruct: arrays view the popped frames
                    ring_seq, batch = pickle.loads(frames[0],
                                                   buffers=frames[1:])
                    if ring_seq != seq:  # SPSC FIFO: marker order == data order
                        self.shutdown()
                        raise RuntimeError(
                            f"ring transport desynchronized: marker seq {seq} "
                            f"!= payload seq {ring_seq} (worker {wid})")
                    reorder[seq] = batch
                else:
                    reorder[seq] = _unpack(payload)
        except GeneratorExit:
            # consumer abandoned the epoch mid-way: outstanding results would
            # desynchronize seq bookkeeping; tear the pool down
            self.shutdown()
            raise
        finally:
            self._epoch_active = False

    def shutdown(self):
        if self._closed:
            return
        self._closed = True
        for q in self._index_queues:
            try:
                q.put(None)
            except (ValueError, OSError):
                pass
        for p in self._workers:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        # drain in-flight results and unlink their shm segments; a feeder
        # thread may still be flushing, so poll with a short timeout until
        # the pipe stays empty
        empty_rounds = 0
        while empty_rounds < 2:
            try:
                status, _, payload = self._result_queue.get(timeout=0.2)
                if status == "ok":
                    _unpack(payload)
            except queue_mod.Empty:
                empty_rounds += 1
        for ring in self._rings.values():
            try:
                ring.close()
                ring.unlink()
            except Exception:
                pass
        self._rings = {}

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass


def fork_available():
    return os.name == "posix" and "fork" in multiprocessing.get_all_start_methods()
