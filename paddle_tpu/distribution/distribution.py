"""Probability distributions (paddle.distribution).

Reference analog: python/paddle/distribution/ — ~30 distribution classes over
the Distribution base (distribution.py: sample/rsample/log_prob/prob/entropy/
kl_divergence), the KL registry (kl.py) and transforms (transform.py).

TPU-first design: every density/statistic is a pure tape-tracked op composition
over Tensors (differentiable through log_prob for variational objectives, and
reparameterized `rsample` wherever the reference provides it); sampling draws
from the framework's global PRNG stream (jax.random under the hood) so compiled
and eager paths share one RNG discipline.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .. import ops
from ..framework import random as rng
from ..framework.core import Tensor

__all__ = ["Distribution", "register_kl", "kl_divergence"]

_TWO_PI = float(2.0 * np.pi)


def _t(x, dtype="float32"):
    if isinstance(x, Tensor):
        return x
    return Tensor(jnp.asarray(np.asarray(x, dtype)))


def _shape(*tensors):
    s = ()
    for t in tensors:
        s = np.broadcast_shapes(s, tuple(t.shape))
    return s


class Distribution:
    """Base class (reference distribution.py Distribution)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        try:
            return self.rsample(shape).detach()
        except NotImplementedError:
            return self._sample(shape)

    def rsample(self, shape=()):
        raise NotImplementedError

    def _sample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return ops.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)

    def _extend(self, shape):
        return tuple(shape) + self._batch_shape + self._event_shape


# -- KL registry (reference kl.py) -------------------------------------------
_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    def deco(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn

    return deco


def kl_divergence(p, q):
    for (cp, cq), fn in _KL_REGISTRY.items():
        if isinstance(p, cp) and isinstance(q, cq):
            return fn(p, q)
    raise NotImplementedError(
        f"no KL(p||q) registered for ({type(p).__name__}, {type(q).__name__})")
