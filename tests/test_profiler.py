"""Profiler tests: state machine, scheduler, chrome trace export, timer,
and an import guard over every paddle_tpu submodule (VERDICT r1 Weak #4)."""
import importlib
import json
import os
import pkgutil

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.profiler as profiler
from paddle_tpu.profiler import (
    Profiler, ProfilerState, ProfilerTarget, RecordEvent, SortedKeys,
    benchmark, export_chrome_tracing, make_scheduler,
)


def _walk_submodules():
    import paddle_tpu

    names = []
    for mod in pkgutil.walk_packages(paddle_tpu.__path__, prefix="paddle_tpu."):
        names.append(mod.name)
    return names


@pytest.mark.parametrize("name", _walk_submodules())
def test_every_submodule_imports(name):
    importlib.import_module(name)


def test_make_scheduler_states():
    sch = make_scheduler(closed=1, ready=1, record=2, repeat=1, skip_first=1)
    states = [sch(i) for i in range(7)]
    assert states == [
        ProfilerState.CLOSED,            # skip_first
        ProfilerState.CLOSED,            # closed
        ProfilerState.READY,             # ready
        ProfilerState.RECORD,            # record
        ProfilerState.RECORD_AND_RETURN,  # last record step
        ProfilerState.CLOSED,            # repeat exhausted
        ProfilerState.CLOSED,
    ]


def test_make_scheduler_validates():
    with pytest.raises(ValueError):
        make_scheduler(closed=1, ready=0, record=0)


def test_profiler_records_train_step_and_exports(tmp_path):
    traces = []

    def on_ready(prof):
        prof.export(str(tmp_path / f"trace_{prof.step_num}.json"))
        traces.append(prof.step_num)

    model = paddle.nn.Linear(8, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    sch = make_scheduler(closed=0, ready=1, record=2, repeat=1)
    with Profiler(targets=[ProfilerTarget.CPU], scheduler=sch,
                  on_trace_ready=on_ready) as p:
        for _ in range(4):
            with RecordEvent("fwd_bwd"):
                x = paddle.randn([2, 8])
                loss = model(x).mean()
                loss.backward()
            with RecordEvent("optimizer"):
                opt.step()
                opt.clear_grad()
            p.step(num_samples=2)
    assert traces, "on_trace_ready never fired"
    files = list(tmp_path.glob("trace_*.json"))
    assert files
    doc = json.loads(files[0].read_text())
    names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert "fwd_bwd" in names and "optimizer" in names
    assert any(n.startswith("ProfileStep#") for n in names)


def test_record_event_outside_profiler_is_noop():
    with RecordEvent("orphan"):
        pass  # must not raise or leak


def test_export_chrome_tracing_handler(tmp_path):
    d = str(tmp_path / "logs")
    handler = export_chrome_tracing(d, worker_name="w0")
    with Profiler(targets=[ProfilerTarget.CPU], on_trace_ready=handler) as p:
        with RecordEvent("span"):
            pass
        p.step()
    assert any(f.startswith("w0") for f in os.listdir(d))


def test_summary_prints(capsys):
    with Profiler(targets=[ProfilerTarget.CPU]) as p:
        with RecordEvent("alpha"):
            pass
        p.step()
    p.summary(sorted_by=SortedKeys.CPUTotal)
    out = capsys.readouterr().out
    assert "alpha" in out and "Calls" in out


def test_event_tree_self_time():
    """Nested spans: the parent's SELF time excludes children (reference
    event-tree analysis, profiler_statistic.py EventSummary)."""
    import time as _time

    from paddle_tpu.profiler.profiler_statistic import (
        _walk, build_event_tree, gather_tree_stats,
    )

    with Profiler(targets=[ProfilerTarget.CPU]) as p:
        with RecordEvent("outer"):
            with RecordEvent("inner"):
                _time.sleep(0.02)
            _time.sleep(0.005)
        p.step()
    res = p._last_result
    nodes = list(_walk(build_event_tree(res.events)))
    outer = [n for n in nodes if n.event.name == "outer"]
    assert outer and outer[0].children, "inner must nest under outer"
    assert outer[0].children[0].event.name == "inner"
    stats, selfs = gather_tree_stats(res.events)
    assert selfs["outer"] < stats["outer"].total_ns  # children excluded
    assert stats["inner"].total_ns > 15e6            # ~20ms
    assert selfs["outer"] < 15e6                     # outer self ~5ms


def test_summary_has_overview_and_self_column(capsys):
    with Profiler(targets=[ProfilerTarget.CPU]) as p:
        with RecordEvent("top"):
            with RecordEvent("nested"):
                pass
        p.step()
    p.summary()
    out = capsys.readouterr().out
    assert "Overview Summary" in out
    assert "Self(" in out and "nested" in out


def test_load_profiler_result_roundtrip(tmp_path):
    path = str(tmp_path / "t.json")
    with Profiler(targets=[ProfilerTarget.CPU]) as p:
        with RecordEvent("roundtrip"):
            pass
        p.step()
    p.export(path)
    res = profiler.load_profiler_result(path)
    assert any(e.name == "roundtrip" for e in res.events)


def test_timer_benchmark_and_step_info():
    bm = benchmark()
    bm.begin()
    for _ in range(3):
        bm.before_reader()
        bm.after_reader()
        bm.step(num_samples=4)
    info = bm.step_info("samples")
    assert "batch_cost" in info and "ips" in info
    bm.end()


def test_profiler_step_info():
    with Profiler(targets=[ProfilerTarget.CPU]) as p:
        p.step(num_samples=8)
        assert isinstance(p.step_info(), str)


def test_tuple_scheduler():
    p = Profiler(targets=[ProfilerTarget.CPU], scheduler=(1, 3))
    got = [p._scheduler(i) for i in range(4)]
    assert got[1] in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
    assert got[2] == ProfilerState.RECORD_AND_RETURN
    assert got[3] == ProfilerState.CLOSED


def test_xplane_clock_normalization_drops_glitches_keeps_bursts():
    """jax 0.4.37's CPU tracer stamps a few events without the session
    base; _normalize_clock must drop only such glitch-sized minorities and
    keep (and NOT re-anchor away) genuine multi-burst activity."""
    from paddle_tpu.profiler.xplane import _normalize_clock

    def ev(t):
        return {"start_ns": float(t), "dur_ns": 1.0}

    # 4 glitch events near 0, real cluster ~9s later -> glitches dropped
    base = 9_000_000_000
    events = [ev(i) for i in range(4)] + [ev(base + i * 1000)
                                          for i in range(500)]
    kept = _normalize_clock(events)
    assert len(kept) == 500
    assert kept[0]["start_ns"] == 0          # anchored on the real cluster
    assert kept[-1]["start_ns"] == 499 * 1000

    # two REAL bursts 8s apart (both well above glitch size): keep both,
    # true gap preserved
    events = [ev(i * 1000) for i in range(300)] \
        + [ev(8_000_000_000 + i * 1000) for i in range(300)]
    kept = _normalize_clock(events)
    assert len(kept) == 600
    assert kept[300]["start_ns"] == 8_000_000_000


def test_merged_host_device_trace_lenet_step(tmp_path, monkeypatch):
    """VERDICT r4 #10 acceptance: ONE chrome trace containing host defop
    spans AND the XLA device kernel spans (clock-translated), plus a per-op
    device-time table. PADDLE_TPU_PROFILER_FORCE_XLA drives the same merge
    path the TPU uses (reference chrometracing_logger.cc one-timeline
    merge)."""
    monkeypatch.setenv("PADDLE_TPU_PROFILER_FORCE_XLA", "1")
    monkeypatch.setenv("PADDLE_TPU_TRACE_DIR", str(tmp_path))

    model = paddle.vision.models.LeNet()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    results = []
    sch = make_scheduler(closed=0, ready=1, record=1, repeat=1)
    with Profiler(targets=[ProfilerTarget.CPU, ProfilerTarget.TPU],
                  scheduler=sch,
                  on_trace_ready=lambda p: results.append(p._last_result)) as p:
        for _ in range(3):
            x = paddle.randn([2, 1, 28, 28])
            y = paddle.to_tensor(np.array([1, 2], "int64"))
            loss = paddle.nn.functional.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            p.step()
    assert results
    res = results[0]

    # per-op device-time table (reference profiler_statistic device view)
    rows = res.device_op_stats()
    assert rows, "no device events parsed from the xplane trace"
    assert all(r["calls"] >= 1 and r["total_ns"] > 0 for r in rows)
    assert abs(sum(r["ratio"] for r in rows) - 1.0) < 1e-6

    # ONE json: host defop spans + device kernel spans, distinct pids
    out = str(tmp_path / "merged.json")
    res.save(out)
    doc = json.loads(open(out).read())
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    host_ops = [e for e in evs if e["name"].startswith("op::")]
    dev_ops = [e for e in evs if e.get("cat") == "DeviceOp"]
    assert host_ops, "host defop spans missing from the merged trace"
    assert dev_ops, "device kernel spans missing from the merged trace"
    assert {e["pid"] for e in host_ops}.isdisjoint({e["pid"] for e in dev_ops})
    # clock translation puts the device spans inside the host window (wide
    # margin: the anchor is taken right after start_trace returns)
    host_lo = min(e["ts"] for e in host_ops)
    host_hi = max(e["ts"] + e["dur"] for e in host_ops)
    dev_mid = sorted(e["ts"] for e in dev_ops)[len(dev_ops) // 2]
    assert host_lo - 2e6 < dev_mid < host_hi + 2e6

    # summary renders the device table
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        p.summary()
    assert "Device Op Summary" in buf.getvalue()


def test_load_profiler_result_skips_merged_device_events(tmp_path):
    """A merged trace (host + DeviceOp spans, exactly what
    ProfilerResult.save writes when XLA tracing was active) must round-trip
    through load_profiler_result without KeyError: the loader reconstructs
    the host side, skips device cats, and tolerates unknown cats."""
    out = str(tmp_path / "merged_roundtrip.json")
    doc = {"traceEvents": [
        {"name": "span", "cat": "PythonUserDefined", "ph": "X",
         "ts": 10.0, "dur": 5.0, "pid": 1, "tid": 1, "args": {"step": 0}},
        {"name": "fusion.1", "cat": "DeviceOp", "ph": "X",
         "ts": 11.0, "dur": 2.0, "pid": 900000, "tid": 1,
         "args": {"hlo_module": "jit_step"}},
        {"name": "mystery", "cat": "SomeFutureCat", "ph": "X",
         "ts": 12.0, "dur": 1.0, "pid": 1, "tid": 1, "args": {}},
    ]}
    with open(out, "w") as f:
        json.dump(doc, f)
    res = profiler.load_profiler_result(out)
    names = {e.name for e in res.events}
    assert "span" in names
    assert "fusion.1" not in names            # device spans skipped
    assert "mystery" in names                 # unknown cat -> UserDefined
    from paddle_tpu.profiler.profiler import TracerEventType

    mystery = [e for e in res.events if e.name == "mystery"][0]
    assert mystery.event_type is TracerEventType.UserDefined
