"""The repo's flaky-budget helper: retry a wall-clock-sensitive smoke
assertion up to N times.

Tier-1 runs on shared CPU runners, so any assertion comparing two measured
wall clocks (serving speedup vs static, chaos goodput ratio, spec speedup)
can lose a run to scheduler contention. The discipline (PR 6/7): every run
must pass its own HARD bounds (bit-exactness, typed-rejection counts —
asserted inside the bench worker, a non-zero exit fails immediately), and
only the wall-clock RATIO gets up to three attempts.
"""


def retry_smoke(run, accept, attempts=3):
    """Call ``run()`` up to ``attempts`` times until ``accept(result)`` is
    truthy; returns the last result (the caller asserts on it, so the final
    failure message shows the real measured values)."""
    result = None
    for _ in range(attempts):
        result = run()
        if accept(result):
            break
    return result
