"""Functional image ops on HWC numpy arrays (python/paddle/vision/transforms/functional.py)."""
from __future__ import annotations

import math
import numbers

import numpy as np

from ...framework.core import Tensor


def _np(img):
    if isinstance(img, Tensor):
        return img.numpy()
    return np.asarray(img)


def to_tensor(pic, data_format="CHW"):
    arr = _np(pic)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.dtype == np.uint8:
        arr = arr.astype("float32") / 255.0
    else:
        arr = arr.astype("float32")
    if data_format == "CHW":
        arr = np.transpose(arr, (2, 0, 1))
    return Tensor(arr)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    tensor_in = isinstance(img, Tensor)
    arr = _np(img).astype("float32")
    mean = np.asarray(mean, "float32").reshape(-1)
    std = np.asarray(std, "float32").reshape(-1)
    channels = arr.shape[0] if data_format == "CHW" else arr.shape[-1]
    if mean.size != channels:
        # scalar stats expanded to 3 by the Normalize ctor must still apply to
        # single-channel images (MNIST pipelines), not broadcast-stack them
        if np.unique(mean).size == 1 and np.unique(std).size == 1:
            mean = mean[:1].repeat(channels)
            std = std[:1].repeat(channels)
        else:
            raise ValueError(
                f"normalize: {mean.size}-channel mean/std vs {channels}-channel "
                "image")
    if data_format == "CHW":
        arr = (arr - mean[:, None, None]) / std[:, None, None]
    else:
        arr = (arr - mean) / std
    return Tensor(arr) if tensor_in else arr


def resize(img, size, interpolation="bilinear"):
    arr = _np(img)
    h, w = arr.shape[0], arr.shape[1]
    if isinstance(size, int):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = size
    if (oh, ow) == (h, w):
        return arr
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[:, :, None]
    if interpolation == "nearest":
        ri = (np.arange(oh) * h / oh).astype(int).clip(0, h - 1)
        ci = (np.arange(ow) * w / ow).astype(int).clip(0, w - 1)
        out = arr[ri][:, ci]
    else:  # bilinear
        ry = (np.arange(oh) + 0.5) * h / oh - 0.5
        rx = (np.arange(ow) + 0.5) * w / ow - 0.5
        y0 = np.floor(ry).astype(int).clip(0, h - 1)
        y1 = (y0 + 1).clip(0, h - 1)
        x0 = np.floor(rx).astype(int).clip(0, w - 1)
        x1 = (x0 + 1).clip(0, w - 1)
        wy = (ry - y0).clip(0, 1)[:, None, None]
        wx = (rx - x0).clip(0, 1)[None, :, None]
        a = arr.astype("float32")
        out = ((a[y0][:, x0] * (1 - wy) * (1 - wx)) + (a[y0][:, x1] * (1 - wy) * wx)
               + (a[y1][:, x0] * wy * (1 - wx)) + (a[y1][:, x1] * wy * wx))
        if arr.dtype == np.uint8:
            out = np.round(out).clip(0, 255).astype(np.uint8)
        else:
            out = out.astype(arr.dtype)
    if squeeze:
        out = out[:, :, 0]
    return out


def crop(img, top, left, height, width):
    arr = _np(img)
    return arr[top:top + height, left:left + width]


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    arr = _np(img)
    h, w = arr.shape[0], arr.shape[1]
    th, tw = output_size
    i = int(round((h - th) / 2.0))
    j = int(round((w - tw) / 2.0))
    return crop(arr, i, j, th, tw)


def hflip(img):
    return _np(img)[:, ::-1].copy()


def vflip(img):
    return _np(img)[::-1].copy()


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _np(img)
    if isinstance(padding, int):
        pl = pr = pt = pb = padding
    elif len(padding) == 2:
        pl = pr = padding[0]
        pt = pb = padding[1]
    else:
        pl, pt, pr, pb = padding
    pads = [(pt, pb), (pl, pr)] + [(0, 0)] * (arr.ndim - 2)
    if padding_mode == "constant":
        return np.pad(arr, pads, mode="constant", constant_values=fill)
    return np.pad(arr, pads, mode={"edge": "edge", "reflect": "reflect",
                                   "symmetric": "symmetric"}[padding_mode])


def rotate(img, angle, interpolation="nearest", expand=False, center=None, fill=0):
    arr = _np(img)
    h, w = arr.shape[0], arr.shape[1]
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    yy, xx = np.mgrid[0:h, 0:w]
    ys = cy + (yy - cy) * cos - (xx - cx) * sin
    xs = cx + (yy - cy) * sin + (xx - cx) * cos
    yi = np.round(ys).astype(int)
    xi = np.round(xs).astype(int)
    valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
    out = np.full_like(arr, fill)
    out[valid] = arr[yi[valid], xi[valid]]
    return out


def to_grayscale(img, num_output_channels=1):
    arr = _np(img).astype("float32")
    if arr.ndim == 3 and arr.shape[2] >= 3:
        g = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
    else:
        g = arr if arr.ndim == 2 else arr[..., 0]
    g = g.astype(_np(img).dtype)
    if num_output_channels == 3:
        return np.stack([g, g, g], -1)
    return g[..., None] if _np(img).ndim == 3 else g


def adjust_brightness(img, brightness_factor):
    arr = _np(img)
    out = arr.astype("float32") * brightness_factor
    if arr.dtype == np.uint8:
        return out.clip(0, 255).astype(np.uint8)
    return out.astype(arr.dtype)


def adjust_contrast(img, contrast_factor):
    arr = _np(img)
    mean = arr.astype("float32").mean()
    out = (arr.astype("float32") - mean) * contrast_factor + mean
    if arr.dtype == np.uint8:
        return out.clip(0, 255).astype(np.uint8)
    return out.astype(arr.dtype)


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5] turns) via RGB→HSV→RGB.

    Reference: python/paddle/vision/transforms/functional_cv2.py adjust_hue
    (cv2 HSV roundtrip); same math on float channels here."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError(f"hue_factor {hue_factor} is not in [-0.5, 0.5]")
    arr = _np(img)
    if abs(hue_factor) < 1e-6 or arr.ndim != 3 or arr.shape[2] < 3:
        return arr
    dtype = arr.dtype
    x = arr.astype("float32")
    scale = 255.0 if dtype == np.uint8 else 1.0
    x = x / scale
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    maxc = np.max(x[..., :3], axis=-1)
    minc = np.min(x[..., :3], axis=-1)
    v = maxc
    c = maxc - minc
    s = np.where(maxc > 0, c / np.maximum(maxc, 1e-12), 0.0)
    cc = np.maximum(c, 1e-12)
    h = np.where(maxc == r, ((g - b) / cc) % 6.0,
                 np.where(maxc == g, (b - r) / cc + 2.0, (r - g) / cc + 4.0))
    h = np.where(c == 0, 0.0, h) / 6.0                      # hue in [0,1) turns
    h = (h + hue_factor) % 1.0
    # HSV → RGB
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype("int32") % 6
    r2 = np.choose(i, [v, q, p, p, t, v])
    g2 = np.choose(i, [t, v, v, q, p, p])
    b2 = np.choose(i, [p, p, t, v, v, q])
    out = np.stack([r2, g2, b2], axis=-1) * scale
    if arr.shape[2] > 3:                                    # preserve alpha etc.
        out = np.concatenate([out, arr[..., 3:].astype("float32")], axis=-1)
    if dtype == np.uint8:
        return out.round().clip(0, 255).astype(np.uint8)
    return out.astype(dtype)


def adjust_saturation(img, saturation_factor):
    """functional.adjust_saturation: blend with the grayscale image."""
    arr = np.asarray(img)
    dtype = arr.dtype
    f = arr.astype("float32")
    gray = (0.299 * f[..., 0] + 0.587 * f[..., 1]
            + 0.114 * f[..., 2])[..., None]
    out = gray + saturation_factor * (f[..., :3] - gray)
    if arr.shape[-1] > 3:
        out = np.concatenate([out, f[..., 3:]], axis=-1)
    if dtype == np.uint8:
        return out.round().clip(0, 255).astype(np.uint8)
    return out.astype(dtype)


def erase(img, i, j, h, w, v, inplace=False):
    """functional.erase: fill the (i:i+h, j:j+w) region with v."""
    arr = np.asarray(img)
    out = arr if inplace else arr.copy()
    out[i:i + h, j:j + w] = np.asarray(v, out.dtype)
    return out


def _affine_matrix(angle, translate, scale, shear, center):
    angle = math.radians(angle)
    sx, sy = (math.radians(s) for s in
              (shear if isinstance(shear, (list, tuple)) else (shear, 0.0)))
    cx, cy = center
    # RSS = rotate * shear * scale (torchvision/paddle parameterization)
    a = math.cos(angle - sy) / math.cos(sy)
    b = -math.cos(angle - sy) * math.tan(sx) / math.cos(sy) - math.sin(angle)
    c = math.sin(angle - sy) / math.cos(sy)
    d = -math.sin(angle - sy) * math.tan(sx) / math.cos(sy) + math.cos(angle)
    m = np.array([[a, b, 0.0], [c, d, 0.0]]) * scale
    m[0, 2] = translate[0] + cx - m[0, 0] * cx - m[0, 1] * cy
    m[1, 2] = translate[1] + cy - m[1, 0] * cx - m[1, 1] * cy
    return m


def affine(img, angle, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="nearest", fill=0, center=None):
    """functional.affine: rotation/translation/scale/shear warp (scipy
    map_coordinates backend; order 0/1 for nearest/bilinear)."""
    from scipy import ndimage

    arr = np.asarray(img)
    h, w = arr.shape[:2]
    if center is None:
        center = ((w - 1) * 0.5, (h - 1) * 0.5)
    m = _affine_matrix(angle, translate, scale, shear, center)
    # output pixel -> input pixel: invert the 2x3 matrix
    inv = np.linalg.inv(np.vstack([m, [0, 0, 1]]))[:2]
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    coords = np.round(np.stack(
        [inv[1, 0] * xs + inv[1, 1] * ys + inv[1, 2],
         inv[0, 0] * xs + inv[0, 1] * ys + inv[0, 2]]), 6)
    order = 1 if interpolation in ("bilinear", 1) else 0
    chans = [ndimage.map_coordinates(arr[..., ch].astype("float32"), coords,
                                     order=order, cval=float(fill))
             for ch in range(arr.shape[2])] if arr.ndim == 3 else \
        [ndimage.map_coordinates(arr.astype("float32"), coords, order=order,
                                 cval=float(fill))]
    out = np.stack(chans, axis=-1) if arr.ndim == 3 else chans[0]
    return out.round().clip(0, 255).astype(arr.dtype) \
        if arr.dtype == np.uint8 else out.astype(arr.dtype)


def _perspective_coeffs(startpoints, endpoints):
    a = []
    b = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        a.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        a.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        b += [sx, sy]
    res, *_ = np.linalg.lstsq(np.array(a, "float64"),
                              np.array(b, "float64"), rcond=None)
    return res  # 8 homography coefficients (maps OUTPUT -> INPUT)


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    """functional.perspective: 4-point homography warp."""
    from scipy import ndimage

    arr = np.asarray(img)
    h, w = arr.shape[:2]
    co = _perspective_coeffs(startpoints, endpoints)
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    den = co[6] * xs + co[7] * ys + 1.0
    # snap numerical noise (±1e-15 around integer grid points) so borders
    # aren't misclassified as out-of-range and filled with cval
    in_x = np.round((co[0] * xs + co[1] * ys + co[2]) / den, 6)
    in_y = np.round((co[3] * xs + co[4] * ys + co[5]) / den, 6)
    coords = np.stack([in_y, in_x])
    order = 1 if interpolation in ("bilinear", 1) else 0
    chans = [ndimage.map_coordinates(arr[..., ch].astype("float32"), coords,
                                     order=order, cval=float(fill))
             for ch in range(arr.shape[2])] if arr.ndim == 3 else \
        [ndimage.map_coordinates(arr.astype("float32"), coords, order=order,
                                 cval=float(fill))]
    out = np.stack(chans, axis=-1) if arr.ndim == 3 else chans[0]
    return out.round().clip(0, 255).astype(arr.dtype) \
        if arr.dtype == np.uint8 else out.astype(arr.dtype)
