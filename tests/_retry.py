"""The repo's ONE flaky-budget gate for wall-clock-sensitive smokes.

Tier-1 runs on shared CPU runners, so any assertion comparing two measured
wall clocks (serving speedup vs static, chaos goodput ratio, spec speedup,
train-chaos recovery latency) can lose a run to scheduler contention. The
discipline (PR 6/7, hardened here): every run must pass its own HARD
bounds (bit-exactness, typed-rejection counts, recovery correctness —
asserted inside the bench worker), while the wall-clock bars route through
THIS module instead of per-test retry tuning:

- :func:`retry_smoke` re-runs an attempt whether the accept predicate
  fails OR the attempt itself raises — a worker whose in-process
  wall-clock bound tripped under contention (non-zero exit -> the runner
  asserts and raises) consumes a retry instead of failing the test on its
  first unlucky run (the PR 7 flake);
- :func:`wall_clock_floor` is the contention-aware floor: the full bar on
  a quiet runner, a relaxed-but-still-meaningful bar when the machine is
  oversubscribed (load average per core above ``threshold``); tests
  assert against the SAME floor the accept predicate used, so the bar and
  the gate can never drift apart;
- attempt counts scale with contention too (3 quiet, 5 oversubscribed).
"""
import os


def contention_factor():
    """Runnable load per core (1-minute load average / cpu count); > 1
    means more runnable work than cores. 0.0 where loadavg is
    unavailable."""
    try:
        load = os.getloadavg()[0]
    except (AttributeError, OSError):
        return 0.0
    return load / max(os.cpu_count() or 1, 1)


def contended(threshold=1.5):
    """True when the runner is oversubscribed past ``threshold`` runnable
    threads per core — the regime where wall-clock ratios stop measuring
    the code under test."""
    return contention_factor() > threshold


def wall_clock_floor(base, relaxed, threshold=1.5):
    """The single contention-aware floor for a wall-clock bar: ``base``
    on a quiet runner, ``relaxed`` on an oversubscribed one. Use the SAME
    returned value in the retry accept predicate and the final assert."""
    return relaxed if contended(threshold) else base


def retry_smoke(run, accept, attempts=None):
    """Call ``run()`` until ``accept(result)`` is truthy, up to
    ``attempts`` times (default 3; 5 when the runner is contended). A
    raising attempt (a bench worker's own in-process wall-clock bound
    tripping exits non-zero and the runner asserts) consumes a retry; the
    LAST attempt's raise propagates, and the last result is returned even
    when not accepted so the caller's assert shows the real measured
    values."""
    if attempts is None:
        attempts = 5 if contended() else 3
    result = None
    for i in range(attempts):
        last = i == attempts - 1
        try:
            result = run()
        except Exception:
            if last:
                raise
            continue
        if accept(result):
            break
    return result
